"""One-command real-data parity runner (PARITY.md "Reference targets
awaiting a data mount").

Usage::

    FEDML_DATA_DIR=/mnt/fedml_data python tools/parity_run.py [--gate NAME]
    python tools/parity_run.py --dry-run        # synthetic smoke, no mount

For every gate whose dataset is present under the mount, runs the
benchmark-shaped config end-to-end (the same configs as
tests/test_parity.py::TestRealDataGates, thresholds from the reference
benchmark tables: doc/en/simulation/benchmark/BENCHMARK_MPI.md:9,99-108)
and APPENDS a result row to PARITY.md, so the measured-parity record
accretes run over run.  With no mount (or --dry-run) each gate executes a
tiny synthetic-shape version to prove the runner itself end-to-end, and
nothing is appended.
"""

from __future__ import annotations

import argparse
import datetime
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# gate -> (dataset, config builder kwargs, threshold, reference citation)
GATES = {
    "mnist_lr_200_rounds": dict(
        dataset="mnist", model="lr", clients=(1000, 10), rounds=200,
        batch=10, lr=0.03, threshold=0.75,
        ref="BENCHMARK_MPI.md:9 (target >75)",
    ),
    "cifar10_resnet56_trajectory": dict(
        dataset="cifar10", model="resnet56", clients=(10, 10), rounds=50,
        batch=64, lr=0.1, threshold=0.35,
        ref="BENCHMARK_MPI.md:101 (50-round trajectory toward 93.19 IID)",
    ),
    "femnist_cnn": dict(
        dataset="femnist", model="cnn", clients=(200, 10), rounds=100,
        batch=20, lr=0.03, threshold=0.60,
        ref="BENCHMARK_simulation.md (fed EMNIST + CNN, 84.9 full-scale)",
    ),
}


def _cfg(gate: str, g: dict, data_dir: str, synthetic: bool) -> dict:
    rounds = 2 if synthetic else g["rounds"]
    clients = (8, 4) if synthetic else g["clients"]
    batch = min(g["batch"], 16) if synthetic else g["batch"]
    return {
        "common_args": {"training_type": "simulation", "random_seed": 0,
                        "run_id": f"parity-run-{gate}"},
        "data_args": {"dataset": g["dataset"],
                      "data_cache_dir": "" if synthetic else data_dir,
                      "partition_method": "hetero", "partition_alpha": 0.5,
                      "synthetic_train_size": 512},
        "model_args": {"model": g["model"]},
        "train_args": {"federated_optimizer": "FedAvg",
                       "client_num_in_total": clients[0],
                       "client_num_per_round": clients[1],
                       "comm_round": rounds, "epochs": 1,
                       "batch_size": batch, "client_optimizer": "sgd",
                       "learning_rate": g["lr"]},
        "validation_args": {"frequency_of_the_test": max(rounds // 2, 1)},
        "comm_args": {"backend": "XLA"},
    }


def _run(cfg: dict) -> dict:
    import fedml_tpu
    from fedml_tpu.arguments import Arguments
    from fedml_tpu.simulation.simulator import create_simulator

    args = fedml_tpu.init(Arguments.from_dict(cfg).validate(),
                          should_init_logs=False)
    device = fedml_tpu.device.get_device(args)
    dataset, out_dim = fedml_tpu.data.load(args)
    model = fedml_tpu.models.create(args, out_dim)
    return create_simulator(args, device, dataset, model).run()


def _dataset_mounted(name: str, data_dir: str) -> bool:
    from fedml_tpu.data.loaders import try_load_real

    try:
        return try_load_real(name, data_dir) is not None
    except Exception:
        return False


def _append_parity(rows: list) -> None:
    stamp = datetime.datetime.now(datetime.timezone.utc).strftime("%Y-%m-%d %H:%MZ")
    path = os.path.join(REPO, "PARITY.md")
    with open(path, "a") as f:
        f.write(f"\n## Real-data parity run — {stamp}\n\n")
        f.write("| Gate | Threshold | Measured | Status | Reference |\n")
        f.write("|---|---|---|---|---|\n")
        for gate, thr, acc, ok, ref in rows:
            f.write(f"| {gate} | >={thr} | {acc:.4f} | "
                    f"{'pass' if ok else 'FAIL'} | {ref} |\n")
    print(f"appended {len(rows)} result row(s) to PARITY.md")


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--gate", action="append",
                    help="run only this gate (repeatable); default: all")
    ap.add_argument("--dry-run", action="store_true",
                    help="synthetic smoke of every gate; nothing appended")
    args = ap.parse_args()

    data_dir = os.environ.get("FEDML_DATA_DIR", os.path.join(REPO, "fedml_data"))
    if args.gate:
        unknown = [g for g in args.gate if g not in GATES]
        if unknown:
            # every requested name must resolve: a silently-dropped typo
            # would leave a gate unmeasured while PARITY.md looks complete
            print(f"unknown gate(s) {unknown}; known: {sorted(GATES)}")
            return 2
    gates = {k: v for k, v in GATES.items()
             if not args.gate or k in args.gate}

    if args.dry_run:
        # dry-run must work with no TPU/tunnel at all: force CPU before any
        # jax import (same policy as tests/conftest.py)
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    else:
        # real capture: probe the backend in a SUBPROCESS first (a failed
        # in-process init is cached by jax) — bench.py's outage-riding loop
        import bench

        if not bench._wait_for_backend():
            print("backend unavailable; aborting parity run")
            return 1

    rows, failures = [], 0
    for gate, g in gates.items():
        synthetic = args.dry_run or not _dataset_mounted(g["dataset"], data_dir)
        mode = "synthetic dry-run" if synthetic else f"REAL data ({data_dir})"
        print(f"== {gate}: {mode} ==")
        metrics = _run(_cfg(gate, g, data_dir, synthetic))
        acc = float(metrics.get("test_acc", 0.0))
        if synthetic:
            print(f"   dry-run completed (acc {acc:.4f}; threshold not applied)")
            continue
        ok = acc >= g["threshold"]
        failures += 0 if ok else 1
        print(f"   acc {acc:.4f} vs threshold {g['threshold']}: "
              f"{'pass' if ok else 'FAIL'}")
        rows.append((gate, g["threshold"], acc, ok, g["ref"]))
    if rows:
        _append_parity(rows)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
