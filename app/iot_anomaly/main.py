"""One-line app entry: `python main.py --cf fedml_config.yaml`."""

import os
import sys

import fedml_tpu

if __name__ == "__main__":
    if "--cf" not in sys.argv and "--yaml_config_file" not in sys.argv:
        sys.argv += ["--cf", os.path.join(os.path.dirname(__file__), "fedml_config.yaml")]
    fedml_tpu.run_simulation()
