"""Test harness: force an 8-device virtual CPU mesh regardless of outer env.

This is how "multi-node" is tested without hardware (SURVEY.md §4 implication):
every sharding/collective test runs over 8 virtual devices on one host; the
driver separately dry-runs the multi-chip path via __graft_entry__.

The outer environment pins JAX_PLATFORMS=axon (a single tunneled TPU chip)
and a sitecustomize imports jax before this file runs, so setting env vars is
not enough: we must also update jax.config and deregister the axon backend
factory (its PJRT init can block the whole process if the tunnel is busy —
unit tests must never touch it).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    from jax._src import xla_bridge as _xb

    _xb._backend_factories.pop("axon", None)
except Exception:  # pragma: no cover - jax internals may move
    pass

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_singletons():
    """Security/DP singletons are process-global; isolate tests."""
    yield
    from fedml_tpu.core.dp.fedml_differential_privacy import FedMLDifferentialPrivacy
    from fedml_tpu.core.security.fedml_attacker import FedMLAttacker
    from fedml_tpu.core.security.fedml_defender import FedMLDefender

    FedMLDifferentialPrivacy._instance = None
    FedMLAttacker._attacker_instance = None
    FedMLDefender._defender_instance = None


@pytest.fixture
def rng():
    return np.random.RandomState(0)
