"""Test harness: force an 8-device virtual CPU mesh regardless of outer env.

This is how "multi-node" is tested without hardware (SURVEY.md §4 implication):
every sharding/collective test runs over 8 virtual devices on one host; the
driver separately dry-runs the multi-chip path via __graft_entry__.

The outer environment pins JAX_PLATFORMS=axon (a single tunneled TPU chip)
and a sitecustomize imports jax before this file runs, so setting env vars is
not enough: we must also update jax.config and deregister the axon backend
factory (its PJRT init can block the whole process if the tunnel is busy —
unit tests must never touch it).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# Load the shared force-CPU helper WITHOUT importing the fedml_tpu package:
# `from fedml_tpu.utils.platform import ...` would execute fedml_tpu/__init__
# (and its full import graph) before the axon backend is deregistered — any
# future module-level jax.devices()/jnp constant there would then touch the
# TPU tunnel and wedge the suite.
import importlib.util as _ilu  # noqa: E402

_spec = _ilu.spec_from_file_location(
    "_fedml_tpu_platform_util",
    os.path.join(os.path.dirname(__file__), os.pardir, "fedml_tpu", "utils", "platform.py"),
)
_mod = _ilu.module_from_spec(_spec)
_spec.loader.exec_module(_mod)
_mod.force_cpu_backend()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_singletons():
    """Security/DP singletons are process-global; isolate tests."""
    yield
    from fedml_tpu.core.dp.fedml_differential_privacy import FedMLDifferentialPrivacy
    from fedml_tpu.core.security.fedml_attacker import FedMLAttacker
    from fedml_tpu.core.security.fedml_defender import FedMLDefender

    FedMLDifferentialPrivacy._instance = None
    FedMLAttacker._attacker_instance = None
    FedMLDefender._defender_instance = None


@pytest.fixture
def rng():
    return np.random.RandomState(0)
