"""End-to-end SP simulator tests (the reference's smoke-matrix equivalent,
SURVEY.md §4 — but in-process and on the virtual CPU mesh)."""

import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.arguments import Arguments


def _args(**over):
    base = {
        "common_args": {"training_type": "simulation", "random_seed": 0, "run_id": "t"},
        "data_args": {
            "dataset": "mnist",
            "data_cache_dir": "",
            "partition_method": "hetero",
            "partition_alpha": 0.5,
            "synthetic_train_size": 1200,
        },
        "model_args": {"model": "lr"},
        "train_args": {
            "federated_optimizer": "FedAvg",
            "client_num_in_total": 8,
            "client_num_per_round": 4,
            "comm_round": 3,
            "epochs": 1,
            "batch_size": 32,
            "client_optimizer": "sgd",
            "learning_rate": 0.1,
        },
        "validation_args": {"frequency_of_the_test": 2},
        "comm_args": {"backend": "sp"},
    }
    args = Arguments.from_dict(base)
    for k, v in over.items():
        setattr(args, k, v)
    return args.validate()


def _run(args):
    from fedml_tpu import FedMLRunner, data, device, models

    args = fedml_tpu.init(args, should_init_logs=False)
    dev = device.get_device(args)
    dataset, out_dim = data.load(args)
    model = models.create(args, out_dim)
    runner = FedMLRunner(args, dev, dataset, model)
    return runner.run()


class TestSPFedAvg:
    def test_lr_mnist_learns(self):
        metrics = _run(_args())
        assert metrics["test_acc"] > 0.5  # synthetic mnist is separable; random = 0.1

    @pytest.mark.heavy
    def test_cnn_runs(self):
        args = _args(model="cnn", comm_round=1, client_num_per_round=2, synthetic_train_size=400)
        metrics = _run(args)
        assert "test_acc" in metrics

    def test_deterministic_given_seed(self):
        m1 = _run(_args(comm_round=2))
        m2 = _run(_args(comm_round=2))
        assert m1["test_acc"] == m2["test_acc"]
        assert m1["test_loss"] == m2["test_loss"]

    def test_fedavg_with_defense_runs(self):
        args = _args(comm_round=2)
        args.enable_defense = True
        args.defense_type = "coordinate_wise_median"
        metrics = _run(args)
        assert "test_acc" in metrics

    def test_fedavg_with_cdp_runs(self):
        args = _args(comm_round=2)
        args.enable_dp = True
        args.dp_type = "cdp"
        args.epsilon = 100.0
        args.delta = 1e-5
        args.mechanism_type = "gaussian"
        metrics = _run(args)
        assert "test_acc" in metrics


class TestDataLayer:
    def test_reference_shaped_tuple(self):
        args = _args()
        dataset, class_num = fedml_tpu.data.load(args)
        (tn, te, tg, teg, local_num, local_train, local_test, cn) = dataset
        assert class_num == 10 and cn == 10
        assert sum(local_num.values()) == tn
        assert set(local_train.keys()) == set(range(8))
        x0, y0 = local_train[0]
        assert len(x0) == len(y0) == local_num[0]

    def test_unknown_dataset_raises(self):
        args = _args()
        args.dataset = "nope"
        with pytest.raises(ValueError):
            fedml_tpu.data.load(args)
