"""Algorithm zoo on the XLA fast path: in-mesh strategies must match the
single-process server math (reference ``simulation/mpi/{fedopt,fednova,...}``
semantics) exactly.

Each test runs the compiled in-mesh simulator for 2 rounds, then replays the
same rounds on the host with an INDEPENDENT formulation: per-client calls to
the engine's local_train plus the explicit published update rule (the same
formulas the sp implementations use), and asserts the final global variables
match."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.arguments import Arguments
from fedml_tpu.ml.engine.train import build_local_train
from fedml_tpu.parallel.mesh import create_fl_mesh
from fedml_tpu.simulation.xla.fed_sim import XLASimulator

pytestmark = pytest.mark.heavy  # long XLA compiles; see pytest.ini

N_CLIENTS = 4
ROUNDS = 2


def _args(**over):
    args = Arguments.from_dict(
        {
            "common_args": {"training_type": "simulation", "random_seed": 0, "run_id": "zoo"},
            "data_args": {
                "dataset": "mnist",
                "data_cache_dir": "",
                "partition_method": "homo",
                "synthetic_train_size": 640,
            },
            "model_args": {"model": "lr"},
            "train_args": {
                "federated_optimizer": "FedAvg",
                "client_num_in_total": N_CLIENTS,
                "client_num_per_round": N_CLIENTS,
                "comm_round": ROUNDS,
                "epochs": 1,
                "batch_size": 32,
                "client_optimizer": "sgd",
                "learning_rate": 0.1,
            },
            "validation_args": {"frequency_of_the_test": 100},
            "comm_args": {"backend": "XLA"},
        }
    )
    for k, v in over.items():
        setattr(args, k, v)
    return args.validate()


class Replay:
    """Capture the in-mesh run's schedules, then drive a host-side replay
    with identical data slices and rng streams."""

    def __init__(self, **over):
        args = fedml_tpu.init(_args(**over), should_init_logs=False)
        dataset, out_dim = fedml_tpu.data.load(args)
        model = fedml_tpu.models.create(args, out_dim)
        self.args, self.model = args, model
        self.sim = XLASimulator(args, dataset, model, mesh=create_fl_mesh(4))
        self.w0 = self.sim.variables
        self.schedules = []
        orig = self.sim._schedule

        def capture(sampled):
            ids, real = orig(sampled)
            self.schedules.append((np.asarray(ids), np.asarray(real)))
            return ids, real

        self.sim._schedule = capture

    def run_sim(self):
        self.sim.train()
        return self.sim.variables

    def local_results(self, round_idx, w_global, grad_hook=None, extras=None):
        """Per-client engine runs for one round, in schedule order.
        Returns [(cid, n_i, LocalTrainResult)] for real clients."""
        sim, args = self.sim, self.args
        fn = build_local_train(self.model, args, int(args.batch_size), sim.padded_n,
                               grad_hook=grad_hook)
        ids, real = self.schedules[round_idx]
        counts = np.where(real > 0, np.asarray(sim.client_counts)[ids], 0)
        rng = jax.random.PRNGKey(int(args.random_seed) + 11)
        for _ in range(round_idx + 1):
            rng, sub = jax.random.split(rng)
        rngs = jax.random.split(jax.random.fold_in(sub, round_idx), len(ids))
        out = []
        for slot, cid in enumerate(ids):
            if counts[slot] == 0:
                continue
            idx_row = np.asarray(sim.client_idx[cid])
            x = jnp.asarray(np.asarray(sim.x_all)[idx_row])
            y = jnp.asarray(np.asarray(sim.y_all)[idx_row])
            extra = None if extras is None else extras[int(cid)]
            res = fn(w_global, x, y, int(counts[slot]), rngs[slot], extra=extra)
            out.append((int(cid), float(counts[slot]), res))
        return out


def assert_trees_close(a, b, rtol=2e-4, atol=2e-5):
    jax.tree_util.tree_map(
        lambda x, y: np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                                rtol=rtol, atol=atol),
        a, b,
    )


def wavg(results, like):
    tot = sum(n for _, n, _ in results)
    return jax.tree_util.tree_map(
        lambda *leaves: sum(
            n * l.astype(jnp.float32) for (_, n, _), l in zip(results, leaves)
        ) / tot,
        *[r.variables for _, _, r in results],
    )


class TestXLAZoo:
    def test_fedopt_matches_host_math(self):
        import optax

        from fedml_tpu.simulation.sp.fedopt.fedopt_api import make_server_optimizer

        rp = Replay(federated_optimizer="FedOpt", server_optimizer="adam", server_lr=0.05)
        got = rp.run_sim()

        tx = make_server_optimizer(rp.args)
        w = rp.w0
        opt_state = tx.init(w["params"])
        for r in range(ROUNDS):
            results = rp.local_results(r, w)
            avg = wavg(results, w)
            pseudo = jax.tree_util.tree_map(
                lambda p, a: p - a, w["params"], avg["params"]
            )
            updates, opt_state = tx.update(pseudo, opt_state, w["params"])
            w = dict(avg, params=optax.apply_updates(w["params"], updates))
        assert_trees_close(got, w)

    def test_fednova_matches_host_math(self):
        rp = Replay(federated_optimizer="FedNova")
        got = rp.run_sim()

        w = rp.w0
        for r in range(ROUNDS):
            results = rp.local_results(r, w)
            tot = sum(n for _, n, _ in results)
            taus = [max(float(res.steps), 1.0) for _, _, res in results]
            ps = [n / tot for _, n, _ in results]
            tau_eff = sum(p * t for p, t in zip(ps, taus))
            d = jax.tree_util.tree_map(jnp.zeros_like, w)
            for (cid, n, res), p, tau in zip(results, ps, taus):
                d = jax.tree_util.tree_map(
                    lambda acc, g, wi: acc + p * (g - wi) / tau, d, w, res.variables
                )
            w = jax.tree_util.tree_map(lambda g, di: g - tau_eff * di, w, d)
        assert_trees_close(got, w)

    def test_scaffold_matches_host_math(self):
        rp = Replay(federated_optimizer="SCAFFOLD")
        lr = float(rp.args.learning_rate)
        got = rp.run_sim()

        def hook(grads, params, anchor, extra):
            c_i, c = extra
            return jax.tree_util.tree_map(lambda g, ci, cg: g - ci + cg, grads, c_i, c)

        zeros_p = jax.tree_util.tree_map(jnp.zeros_like, rp.w0["params"])
        w = rp.w0
        c_server = zeros_p
        c_clients = {i: zeros_p for i in range(N_CLIENTS)}
        for r in range(ROUNDS):
            extras = {i: (c_clients[i], c_server) for i in range(N_CLIENTS)}
            results = rp.local_results(r, w, grad_hook=hook, extras=extras)
            dc_sum = zeros_p
            for cid, n, res in results:
                K = max(float(res.steps), 1.0)
                new_ci = jax.tree_util.tree_map(
                    lambda ci, cg, wg, wi: ci - cg + (wg - wi) / (K * lr),
                    c_clients[cid], c_server, w["params"], res.variables["params"],
                )
                dc_sum = jax.tree_util.tree_map(
                    lambda s, n_, o: s + (n_ - o), dc_sum, new_ci, c_clients[cid]
                )
                c_clients[cid] = new_ci
            w = wavg(results, w)
            c_server = jax.tree_util.tree_map(
                lambda c, d: c + d / N_CLIENTS, c_server, dc_sum
            )
        assert_trees_close(got, w)
        # server control variate state must match too
        assert_trees_close(rp.sim.server_state, c_server)

    def test_feddyn_matches_host_math(self):
        rp = Replay(federated_optimizer="FedDyn", feddyn_alpha=0.1)
        alpha = 0.1
        got = rp.run_sim()

        def hook(grads, params, anchor, extra):
            return jax.tree_util.tree_map(
                lambda g, h, p, a: g - h + alpha * (p - a), grads, extra, params, anchor
            )

        zeros_p = jax.tree_util.tree_map(jnp.zeros_like, rp.w0["params"])
        w = rp.w0
        h_clients = {i: zeros_p for i in range(N_CLIENTS)}
        for r in range(ROUNDS):
            extras = {i: h_clients[i] for i in range(N_CLIENTS)}
            results = rp.local_results(r, w, grad_hook=hook, extras=extras)
            for cid, n, res in results:
                h_clients[cid] = jax.tree_util.tree_map(
                    lambda h, wi, wg: h - alpha * (wi - wg),
                    h_clients[cid], res.variables["params"], w["params"],
                )
            avg = wavg(results, w)
            h_mean = jax.tree_util.tree_map(
                lambda *hs: sum(hs) / N_CLIENTS, *h_clients.values()
            )
            params = jax.tree_util.tree_map(
                lambda p, h: p - h / alpha, avg["params"], h_mean
            )
            w = dict(avg, params=params)
        assert_trees_close(got, w)

    def test_async_buffered_matches_host_math(self):
        # 8 clients, 4 per round: participation varies, so staleness kicks in
        rp = Replay(federated_optimizer="Async_FedAvg", client_num_in_total=8,
                    client_num_per_round=4, async_alpha=0.6, async_beta=0.5,
                    synthetic_train_size=1280)
        got = rp.run_sim()

        w = rp.w0
        last = {}
        for r in range(ROUNDS):
            results = rp.local_results(r, w)
            K = len(results)
            delta = jax.tree_util.tree_map(jnp.zeros_like, w)
            for cid, n, res in results:
                stale = r - last.get(cid, r)
                a_i = 0.6 / (1.0 + stale) ** 0.5
                delta = jax.tree_util.tree_map(
                    lambda d, wi, wg: d + a_i * (wi - wg), delta, res.variables, w
                )
            for cid, _, _ in results:
                last[cid] = r
            w = jax.tree_util.tree_map(lambda g, d: g + d / K, w, delta)
        assert_trees_close(got, w)

    def test_fednova_krum_composition_matches_host(self):
        """Defense x ext-aggregating algorithm: the in-mesh security tail
        (ext_from_rows over the defended row space) must equal the sp
        composition — defend_before_aggregation filters the update list,
        taus follow the survivors, FedNova aggregates them
        (sp/fednova/fednova_api.py server_update)."""
        from fedml_tpu.core.security.fedml_defender import FedMLDefender

        FedMLDefender._defender_instance = None
        d = FedMLDefender.get_instance()
        try:
            # hetero partition: distinguishable client updates (a homo
            # split of the tiny synthetic set yields EXACT krum-score ties,
            # where host argsort and jnp argsort may break differently)
            # 8 clients (not the default 4): with n=4 and byz=1 the krum
            # score degenerates to the single nearest-neighbour distance,
            # which ties EXACTLY for mutual nearest neighbours — host and
            # stacked argsort may break the tie differently.  n=8 sums 5
            # distances per score; ties vanish.
            rp = Replay(federated_optimizer="FedNova", enable_defense=True,
                        defense_type="krum", byzantine_client_num=1,
                        partition_method="hetero", partition_alpha=0.5,
                        client_num_in_total=8, client_num_per_round=8,
                        synthetic_train_size=1280)
            d.init(rp.args)
            got = rp.run_sim()

            w = rp.w0
            for r in range(ROUNDS):
                results = rp.local_results(r, w)
                updates = [(n, res.variables) for _, n, res in results]
                tau_by_id = {
                    id(p): max(float(res.steps), 1.0)
                    for (_, _, res), (_, p) in zip(results, updates)
                }
                survivors = d.defend_before_aggregation(updates, w)
                taus = [tau_by_id.get(id(p), 1.0) for _, p in survivors]
                tot = sum(n for n, _ in survivors)
                ps = [n / tot for n, _ in survivors]
                tau_eff = sum(p * t for p, t in zip(ps, taus))
                dsum = jax.tree_util.tree_map(jnp.zeros_like, w)
                for (n, wi), p, tau in zip(survivors, ps, taus):
                    dsum = jax.tree_util.tree_map(
                        lambda acc, g, v: acc + p * (g - v) / tau, dsum, w, wi
                    )
                w = jax.tree_util.tree_map(
                    lambda g, di: g - tau_eff * di, w, dsum
                )
            assert_trees_close(got, w)
        finally:
            FedMLDefender._defender_instance = None

    def test_async_krum_composition_matches_host(self):
        """Same composition for the buffered-async strategy: survivors keep
        their own staleness discounts, k drops to the surviving count."""
        from fedml_tpu.core.security.fedml_defender import FedMLDefender

        FedMLDefender._defender_instance = None
        d = FedMLDefender.get_instance()
        try:
            # 6 sampled per round (krum scores sum 3 distances: no
            # mutual-NN exact ties; see the FedNova test above)
            rp = Replay(federated_optimizer="Async_FedAvg",
                        client_num_in_total=8, client_num_per_round=6,
                        async_alpha=0.6, async_beta=0.5,
                        synthetic_train_size=1280,
                        enable_defense=True, defense_type="krum",
                        byzantine_client_num=1,
                        partition_method="hetero", partition_alpha=0.5)
            d.init(rp.args)
            got = rp.run_sim()

            w = rp.w0
            last = {}
            for r in range(ROUNDS):
                results = rp.local_results(r, w)
                updates = [(n, res.variables) for _, n, res in results]
                cid_by_id = {id(p): cid for (cid, _, _), (_, p)
                             in zip(results, updates)}
                survivors = d.defend_before_aggregation(updates, w)
                K = len(survivors)
                delta = jax.tree_util.tree_map(jnp.zeros_like, w)
                for _, wi in survivors:
                    stale = r - last.get(cid_by_id[id(wi)], r)
                    a_i = 0.6 / (1.0 + stale) ** 0.5
                    delta = jax.tree_util.tree_map(
                        lambda dl, v, wg: dl + a_i * (v - wg), delta, wi, w
                    )
                # host_round_end marks EVERY participant (survivor or not)
                for cid, _, _ in results:
                    last[cid] = r
                w = jax.tree_util.tree_map(lambda g, dl: g + dl / K, w, delta)
            assert_trees_close(got, w)
        finally:
            FedMLDefender._defender_instance = None

    def test_unsupported_zoo_algorithm_fails_loud(self):
        # XLASimulator owns only the shared FedAvg-family round; every
        # structurally-distinct optimizer (turbo/GAN/NAS/gossip/...) has its
        # own mesh program reached through SimulatorXLA's dispatch.  Handed
        # such an optimizer DIRECTLY, XLASimulator must refuse rather than
        # silently run plain FedAvg.
        args = fedml_tpu.init(_args(federated_optimizer="turbo_aggregate"), should_init_logs=False)
        dataset, out_dim = fedml_tpu.data.load(args)
        model = fedml_tpu.models.create(args, out_dim)
        with pytest.raises(NotImplementedError, match="in-mesh"):
            XLASimulator(args, dataset, model, mesh=create_fl_mesh(4))

    def test_scaffold_learns(self):
        rp = Replay(federated_optimizer="SCAFFOLD", comm_round=4,
                    frequency_of_the_test=2, partition_method="hetero",
                    partition_alpha=0.5, synthetic_train_size=1600,
                    client_num_in_total=16, client_num_per_round=8)
        metrics = rp.sim.train()
        assert metrics["test_acc"] > 0.5
