"""Model/data zoo widening + misc parity (SURVEY.md §2.4/§2.9): task
trainer/aggregator factories, EfficientNet, centralized baseline, cross-silo
split util, TCP (TRPC-slot) backend."""

import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.arguments import Arguments

pytestmark = pytest.mark.heavy  # long XLA compiles; see pytest.ini


def _args(**over):
    base = {
        "common_args": {"training_type": "simulation", "random_seed": 0, "run_id": "z"},
        "data_args": {"dataset": "mnist", "data_cache_dir": "",
                      "partition_method": "homo", "synthetic_train_size": 320},
        "model_args": {"model": "lr"},
        "train_args": {
            "federated_optimizer": "FedAvg",
            "client_num_in_total": 4,
            "client_num_per_round": 2,
            "comm_round": 2,
            "epochs": 1,
            "batch_size": 32,
            "client_optimizer": "sgd",
            "learning_rate": 0.1,
        },
        "validation_args": {"frequency_of_the_test": 1},
        "comm_args": {"backend": "sp"},
    }
    args = Arguments.from_dict(base)
    for k, v in over.items():
        setattr(args, k, v)
    return args.validate()


class TestTaskFactories:
    def test_trainer_creator_dispatch(self):
        from fedml_tpu.ml.trainer.cls_trainer import ModelTrainerCLS
        from fedml_tpu.ml.trainer.nwp_trainer import ModelTrainerNWP
        from fedml_tpu.ml.trainer.tag_trainer import ModelTrainerTAGPred
        from fedml_tpu.ml.trainer.trainer_creator import create_model_trainer

        assert isinstance(create_model_trainer(None, _args()), ModelTrainerCLS)
        assert isinstance(
            create_model_trainer(None, _args(dataset="shakespeare")), ModelTrainerNWP
        )
        assert isinstance(
            create_model_trainer(None, _args(dataset="stackoverflow_lr")), ModelTrainerTAGPred
        )

    def test_nwp_fedavg_learns_tokens(self):
        args = _args(dataset="shakespeare", model="rnn_fedshakespeare",
                     synthetic_train_size=256, learning_rate=0.5, comm_round=3)
        args = fedml_tpu.init(args, should_init_logs=False)
        from fedml_tpu import FedMLRunner, data, models

        dataset, out_dim = data.load(args)
        model = models.create(args, out_dim)
        metrics = FedMLRunner(args, None, dataset, model).run()
        # markov corpus: well above uniform-vocab chance (1/90 ~= 0.011)
        assert metrics["test_acc"] > 0.025

    def test_tagpred_fedavg_runs(self):
        args = _args(dataset="stackoverflow_lr", model="lr",
                     synthetic_train_size=256, comm_round=2)
        args = fedml_tpu.init(args, should_init_logs=False)
        from fedml_tpu import FedMLRunner, data, models

        dataset, out_dim = data.load(args)
        model = models.create(args, out_dim)
        metrics = FedMLRunner(args, None, dataset, model).run()
        assert "test_acc" in metrics


class TestModels:
    def test_efficientnet_forward(self):
        import jax
        import jax.numpy as jnp

        from fedml_tpu.models.efficientnet import EfficientNet

        m = EfficientNet(num_classes=10)
        x = jnp.zeros((2, 32, 32, 3))
        params = m.init(jax.random.PRNGKey(0), x)
        out = m.apply(params, x)
        assert out.shape == (2, 10)

    def test_hub_key(self):
        from fedml_tpu import models

        m = models.create(_args(model="efficientnet", dataset="cifar10"), 10)
        assert m.__class__.__name__ == "EfficientNet"


class TestCentralizedBaseline:
    def test_centralized_beats_chance(self):
        from fedml_tpu.centralized import CentralizedTrainer

        args = _args(synthetic_train_size=512, comm_round=2)
        args = fedml_tpu.init(args, should_init_logs=False)
        trainer = CentralizedTrainer(args)
        metrics = trainer.train()
        assert metrics["test_acc"] > 0.8


class TestCrossSiloSplit:
    def test_split_preserves_all_samples(self):
        from fedml_tpu.data.data_loader_cross_silo import split_data_for_dist_trainers

        x = np.arange(100).reshape(100, 1)
        y = np.arange(100)
        shards = split_data_for_dist_trainers((x, y), 3)
        assert len(shards) == 3
        assert sum(len(sy) for _, sy in shards) == 100
        np.testing.assert_array_equal(np.concatenate([sy for _, sy in shards]), y)


class TestTCPBackend:
    def test_round_protocol_over_tcp(self):
        """1 server + 2 clients complete FedAvg rounds over raw TCP (the
        TRPC-slot backend), same protocol/topology as loopback/gRPC."""
        from test_cross_silo import _run_topology

        history = _run_topology("TRPC", "cs-tcp", comm_extra={"trpc_base_port": 29690})
        assert len(history) == 2
        assert history[-1]["test_acc"] > 0.2
