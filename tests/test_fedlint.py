"""tools/fedlint.py wired into tier-1: the unified static-analysis plane.

Golden fixtures under tests/fixtures/fedlint/ pin each analyzer to exact
(line, rule) findings; the pragma/baseline suppression contract, the JSON
report schema, and the CLI exit codes are locked here; and the self-lint
test makes `fedlint` clean on fedml_tpu/ a machine-enforced invariant with
an EMPTY baseline — race-* and ack-* findings may never be baselined, only
fixed or carried on a justified inline pragma.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(REPO_ROOT, "tools")
FIXTURES = os.path.join(REPO_ROOT, "tests", "fixtures", "fedlint")

sys.path.insert(0, TOOLS)

from _analysis_loader import load_analysis  # noqa: E402

analysis = load_analysis()


def _lint_fixture(name, analyzers=None):
    """All findings for one fixture file, as (lineno, rule_id) pairs."""
    src = analysis.SourceFile(os.path.join(FIXTURES, name))
    found = analysis.analyze_file(
        src, analyzers or analysis.passes.build_analyzers(), root=FIXTURES
    )
    return sorted((f.lineno, f.rule) for f in found)


# ---------------------------------------------------------------- fixtures


def test_race_fixture_exact_findings():
    assert _lint_fixture("race_seeded.py") == [
        (19, "race-unannotated-shared"),
        (28, "race-cross-thread-write"),
    ]


def test_race_clean_fixture_is_clean():
    """Same shape as the seeded fixture, but every shared write is either
    lock-guarded or ownership-annotated — zero findings."""
    assert _lint_fixture("race_clean.py") == []


def test_ack_fixture_exact_findings():
    assert _lint_fixture("ack_early.py") == [(10, "ack-before-journal")]


def test_ack_ok_fixture_is_clean():
    """journal append, deferred_ack_scope ticket, and dispatch hand-off all
    count as the durability marker preceding the ack."""
    assert _lint_fixture("ack_ok.py") == []


def test_purity_fixture_exact_findings():
    assert _lint_fixture("purity_impure.py") == [
        (19, "purity-wall-clock"),
        (20, "purity-host-rng"),
        (21, "purity-host-numpy"),
        (22, "purity-unsorted-dict"),
        (29, "purity-donated-reuse"),
    ]


def test_server_opt_fixture_exact_findings():
    """The sharded-server-state satellite: a pseudo-gradient tree_map in
    the same function as an optax apply is a host server-optimizer round
    tail — those belong to core/aggregate.host_server_round_update or the
    sharded round plane.  A bare delta fold (client_delta) stays clean."""
    assert _lint_fixture("agg_server_opt.py") == [
        (17, "agg-server-opt-host"),
        (24, "agg-server-opt-host"),
    ]


def test_server_opt_seams_are_exempt():
    """The rule's seam list: the sp/fedopt reference, the round plane, and
    the in-mesh strategies may spell the tail; everyone else may not."""
    from fedml_tpu.core.analysis.passes.legacy import AggAnalyzer

    a = AggAnalyzer()
    src_path = os.path.join(FIXTURES, "agg_server_opt.py")
    text = open(src_path).read()
    for seam in ("fedml_tpu/simulation/sp/fedopt/fedopt_api.py",
                 "fedml_tpu/parallel/agg_plane.py",
                 "fedml_tpu/simulation/xla/algorithms.py"):
        src = analysis.SourceFile(os.path.join(REPO_ROOT, seam), text=text)
        assert a._server_opt_findings(src) == []


def test_alias_dodge_fixture_exact_findings():
    """The satellite regression: aliased imports (``from os import fsync as
    f``, ``import msgpack as mp``, ``import numpy.random as nr``) were
    invisible to the old grep linters; the import map resolves them."""
    assert _lint_fixture("alias_dodge.py") == [
        (18, "perf-stray-fsync"),
        (19, "perf-hot-codec"),
        (20, "rng-global-rng"),
    ]


def test_mesh_stale_fixture_exact_findings():
    """The elastic-remesh satellite: a compiled-program cache fetched in a
    scope that never references mesh_key/mesh_fingerprint would execute a
    stale program against re-sharded buffers after a resize.  The keyed
    counterparts in the same fixture (key built from the fingerprint in
    the fetching function or an enclosing one) stay clean."""
    assert _lint_fixture("mesh_stale.py") == [
        (20, "mesh-stale-program"),
        (27, "mesh-stale-program"),
        (47, "mesh-stale-program"),
    ]


def test_sec_fallback_fixture_exact_findings():
    """The security-plane satellite: host aggregation folds over client
    payloads in core/security|core/dp|core/mpc must either move onto the
    compiled plane (parallel/sec_plane, core/mpc/inmesh) or carry a
    justified retained-oracle pragma.  The payload-inspection loop, the
    jnp-marked tree_map, and the pragma'd oracle stay clean."""
    assert _lint_fixture("sec_fallback.py") == [
        (25, "sec-host-fallback"),
        (32, "sec-host-fallback"),
        (40, "sec-host-fallback"),
    ]


def test_hierarchy_seam_fixture_exact_findings():
    """The hierarchy satellite: partial-reduction entry points
    (partial_fold / partial_reduce / combine_partials / block_partial)
    outside core/hierarchy + core/aggregate.py + parallel/agg_plane.py
    are findings — a second reduction site can pick its own block order
    or total and break the tree/flat bit-identity contract.  The
    plan-delegating call and the pragma'd oracle stay clean."""
    assert _lint_fixture("hier_partial.py") == [
        (22, "hierarchy-reduce-seam"),
        (26, "hierarchy-reduce-seam"),
        (32, "hierarchy-reduce-seam"),
    ]


def test_chunk_seam_fixture_exact_findings():
    """The chunked-upload satellite: chunk wire-vocabulary literals
    (header keys / message types) parsed, subscripted, or compared — and
    framing entry points (ChunkReassembler / build_chunks / split_payload)
    invoked — outside core/distributed/chunking.py + core/ingest.py are
    findings: a second chunk-parsing site forks the resume protocol and
    the replay exactly-once accounting.  The constant-importing
    comparison and the pragma'd probe stay clean."""
    assert _lint_fixture("chunk_seam.py") == [
        (21, "chunk-reassembly-seam"),
        (25, "chunk-reassembly-seam"),
        (31, "chunk-reassembly-seam"),
    ]


def test_health_seam_fixture_exact_findings():
    """The health-plane satellite: hand-rolled liveness bookkeeping —
    a heartbeat timestamp stored through a clock call (plain name,
    attribute, or subscript) or ``is_alive()`` polled on a
    ``threading.Thread`` — outside core/obs/health.py is a finding: a
    second liveness site runs on the wall clock instead of the injected
    one and its expiry never reaches the status machine or the flight
    dumps.  The non-Thread ``is_alive()`` (a process health check), the
    round-number ``last_seen_round`` store, and the justified pragma
    stay clean."""
    assert _lint_fixture("health_seam.py") == [
        (17, "health-seam"),
        (22, "health-seam"),
        (27, "health-seam"),
        (30, "health-seam"),
    ]


def test_legacy_shims_catch_alias_dodges():
    """The four legacy CLIs ride the same AST passes now, so the alias
    dodges are caught through the old entry points too."""
    import lint_perf
    import lint_rng

    path = os.path.join(FIXTURES, "alias_dodge.py")
    perf = lint_perf.lint_file(path)
    assert [(lineno, kind) for _, lineno, kind, _ in perf] == [
        (18, "per-record fsync outside the durability seam"),
        (19, "hot-path msgpack codec outside the seams"),
    ]
    rng = lint_rng.lint_file(path)
    assert [lineno for _, lineno, _ in rng] == [20]


# ------------------------------------------------------- pragma semantics


def _one_file(tmp_path, text):
    p = tmp_path / "case.py"
    p.write_text(text)
    return analysis.SourceFile(str(p))


_RACY = (
    "import threading\n"
    "class Pump:\n"
    "    def __init__(self):\n"
    "        self.active = False\n"
    "    def start(self):\n"
    "        self.active = True  {pragma}\n"
    "        threading.Thread(target=self._worker).start()\n"
    "    def _worker(self):\n"
    "        while self.active:\n"
    "            pass\n"
)


def test_justified_pragma_suppresses_race_rule(tmp_path):
    src = _one_file(
        tmp_path,
        _RACY.format(pragma="# fedlint: allow[race-unannotated-shared] — set-before-start"),
    )
    kept = analysis.analyze_file(src, [analysis.passes.ThreadOwnershipAnalyzer()])
    assert kept == []


def test_bare_pragma_does_not_suppress_race_rule(tmp_path):
    """race-*/ack-* rules require a justification: a bare allow pragma
    leaves the finding standing and stamps it with a note."""
    src = _one_file(
        tmp_path,
        _RACY.format(pragma="# fedlint: allow[race-unannotated-shared]"),
    )
    kept = analysis.analyze_file(src, [analysis.passes.ThreadOwnershipAnalyzer()])
    assert [f.rule for f in kept] == ["race-unannotated-shared"]
    assert "justification" in kept[0].note


def test_bare_pragma_suppresses_ordinary_rule(tmp_path):
    src = _one_file(
        tmp_path,
        "import os\ndef flush(fd):\n    os.fsync(fd)  # fedlint: allow[perf-stray-fsync]\n",
    )
    kept = analysis.analyze_file(src, [analysis.passes.PerfAnalyzer()])
    assert kept == []


def test_legacy_pragma_still_honored(tmp_path):
    """Existing ``# lint_perf: allow`` pragmas in the tree keep working."""
    src = _one_file(
        tmp_path,
        "import os\ndef flush(fd):\n    os.fsync(fd)  # lint_perf: allow (durability seam)\n",
    )
    kept = analysis.analyze_file(src, [analysis.passes.PerfAnalyzer()])
    assert kept == []


# ------------------------------------------------------ baseline contract


def test_baseline_suppresses_ordinary_finding(tmp_path):
    src = _one_file(tmp_path, "import os\ndef flush(fd):\n    os.fsync(fd)\n")
    entry = {
        "rule": "perf-stray-fsync",
        "path": "case.py",
        "source": "os.fsync(fd)",
    }
    baseline = analysis.Baseline([entry])
    kept = analysis.analyze_file(src, [analysis.passes.PerfAnalyzer()], baseline=baseline)
    assert kept == []
    assert baseline.rejected == []


def test_baseline_rejects_race_and_ack_entries():
    """The acceptance gate: race-* and ack-* findings can never hide in the
    baseline file — entries are rejected at load and never written back."""
    entries = [
        {"rule": "race-cross-thread-write", "path": "a.py", "source": "self.x = 1"},
        {"rule": "ack-before-journal", "path": "b.py", "source": "ack(msg)"},
        {"rule": "perf-stray-fsync", "path": "c.py", "source": "os.fsync(fd)"},
    ]
    baseline = analysis.Baseline(entries)
    assert sorted(e["rule"] for e in baseline.rejected) == [
        "ack-before-journal",
        "race-cross-thread-write",
    ]


def test_baseline_render_never_writes_race_or_ack():
    """--write-baseline can't smuggle them back in either."""
    findings = [
        analysis.Finding("races", "race-cross-thread-write", "/x/a.py", 3, "m", "self.x = 1"),
        analysis.Finding("perf", "perf-stray-fsync", "/x/c.py", 5, "m", "os.fsync(fd)"),
    ]
    rendered = json.loads(analysis.Baseline.render(findings, "/x"))
    assert [e["rule"] for e in rendered["entries"]] == ["perf-stray-fsync"]


def test_shipped_baseline_is_empty():
    with open(os.path.join(TOOLS, "fedlint_baseline.json")) as f:
        shipped = json.load(f)
    assert shipped["entries"] == []


# ----------------------------------------------------- CLI + JSON schema


def _run_cli(*argv):
    return subprocess.run(
        [sys.executable, os.path.join(TOOLS, "fedlint.py"), *argv],
        capture_output=True,
        text=True,
        cwd=REPO_ROOT,
    )


def test_cli_findings_exit_1_and_advisory_exit_0():
    strict = _run_cli("--root", FIXTURES, "--no-baseline")
    assert strict.returncode == 1
    advisory = _run_cli("--root", FIXTURES, "--no-baseline", "--advisory")
    assert advisory.returncode == 0


def test_cli_json_schema_is_stable():
    """chaos_check and CI consume --json; the shape is a contract."""
    proc = _run_cli("--root", FIXTURES, "--no-baseline", "--json")
    report = json.loads(proc.stdout)
    assert report["version"] == 1
    assert sorted(report.keys()) == [
        "baseline_rejected",
        "counts",
        "findings",
        "root",
        "suppressed",
        "version",
    ]
    assert report["counts"]["findings"] == len(report["findings"]) == 29
    first = report["findings"][0]
    assert sorted(first.keys()) >= ["analyzer", "line", "message", "path", "rule", "source"]
    assert {f["rule"] for f in report["findings"]} >= {
        "race-unannotated-shared",
        "ack-before-journal",
        "purity-donated-reuse",
        "mesh-stale-program",
        "sec-host-fallback",
        "hierarchy-reduce-seam",
        "chunk-reassembly-seam",
        "health-seam",
    }


def test_cli_select_and_ignore():
    """--select/--ignore pick whole analyzers by name."""
    proc = _run_cli("--root", FIXTURES, "--no-baseline", "--json", "--select", "ack")
    report = json.loads(proc.stdout)
    assert [f["rule"] for f in report["findings"]] == ["ack-before-journal"]
    proc = _run_cli("--root", FIXTURES, "--no-baseline", "--json", "--ignore", "ack")
    report = json.loads(proc.stdout)
    assert report["findings"] and "ack-before-journal" not in {
        f["rule"] for f in report["findings"]
    }
    bogus = _run_cli("--root", FIXTURES, "--select", "not-an-analyzer")
    assert bogus.returncode != 0


# ------------------------------------------------------------- self-lint


def test_library_tree_is_fedlint_clean():
    """The machine-enforced contract: the whole plane — all eleven
    analyzers — is clean on fedml_tpu/ with zero baseline entries."""
    proc = _run_cli()
    assert proc.returncode == 0, proc.stdout + proc.stderr
