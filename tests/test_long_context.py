"""Long-context stack: ring attention over the virtual 8-device mesh,
sequence-parallel transformer, pallas flash-attention kernel (interpret mode).
The capability SURVEY.md §5 lists as absent in the reference and the brief
requires first-class."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.models.transformer import TransformerConfig, TransformerLM, causal_attention
from fedml_tpu.ops.flash_attention import flash_attention, reference_attention
from fedml_tpu.parallel.mesh import create_mesh
from fedml_tpu.parallel.ring_attention import ring_attention

pytestmark = pytest.mark.heavy  # long XLA compiles; see pytest.ini


def _qkv(B=2, L=64, H=4, D=16, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (B, L, H, D)
    return tuple(jax.random.normal(k, shape, jnp.float32) * 0.5 for k in ks)


@pytest.fixture(scope="module")
def sp_mesh():
    return create_mesh((8,), ("sp",))


class TestRingAttention:
    def test_matches_full_attention_causal(self, sp_mesh):
        q, k, v = _qkv()
        full = reference_attention(q, k, v, causal=True)
        ring = ring_attention(q, k, v, sp_mesh, axis_name="sp", causal=True)
        np.testing.assert_allclose(np.asarray(ring), np.asarray(full), atol=2e-5)

    def test_matches_full_attention_noncausal(self, sp_mesh):
        q, k, v = _qkv(seed=3)
        full = reference_attention(q, k, v, causal=False)
        ring = ring_attention(q, k, v, sp_mesh, axis_name="sp", causal=False)
        np.testing.assert_allclose(np.asarray(ring), np.asarray(full), atol=2e-5)

    def test_grad_flows(self, sp_mesh):
        q, k, v = _qkv(L=32, seed=5)

        def loss_ring(q):
            return jnp.sum(ring_attention(q, k, v, sp_mesh) ** 2)

        def loss_full(q):
            return jnp.sum(reference_attention(q, k, v) ** 2)

        g_ring = jax.grad(loss_ring)(q)
        g_full = jax.grad(loss_full)(q)
        np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_full), atol=5e-4)


class TestSequenceParallelTransformer:
    def test_forward_matches_single_device(self, sp_mesh):
        from fedml_tpu.parallel.seq_parallel import sp_apply, sp_init

        cfg = TransformerConfig(vocab_size=128, d_model=64, n_heads=4, n_layers=2,
                                d_ff=128, max_seq_len=64)
        params = sp_init(cfg, seed=0)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0, 128)

        single = TransformerLM(cfg).apply(params, tokens)
        sp = sp_apply(cfg, params, tokens, sp_mesh)
        np.testing.assert_allclose(np.asarray(sp), np.asarray(single), atol=3e-4)

    def test_sp_training_step_decreases_loss(self, sp_mesh):
        import optax

        from fedml_tpu.parallel.seq_parallel import sp_init, sp_loss_fn

        cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64)
        params = sp_init(cfg, seed=0)
        loss_fn = sp_loss_fn(cfg, sp_mesh)
        tokens = jax.random.randint(jax.random.PRNGKey(2), (2, 32), 0, 64)
        targets = jnp.roll(tokens, -1, axis=1)
        tx = optax.adam(1e-2)
        opt = tx.init(params)
        grad_fn = jax.jit(jax.value_and_grad(lambda p: loss_fn(p, tokens, targets)))
        l0, grads = grad_fn(params)
        for _ in range(5):
            l, grads = grad_fn(params)
            updates, opt = tx.update(grads, opt, params)
            params = optax.apply_updates(params, updates)
        l_end, _ = grad_fn(params)
        assert float(l_end) < float(l0)


class TestFlashAttentionKernel:
    @pytest.mark.parametrize("causal", [True, False])
    def test_kernel_matches_reference(self, causal):
        q, k, v = _qkv(B=1, L=64, H=2, D=16, seed=7)
        ref = reference_attention(q, k, v, causal=causal)
        out = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16,
                              interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_kernel_single_block(self):
        q, k, v = _qkv(B=1, L=16, H=1, D=8, seed=9)
        ref = reference_attention(q, k, v, causal=True)
        out = flash_attention(q, k, v, causal=True, block_q=16, block_k=16,
                              interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_kernel_grad_matches_reference(self):
        """custom_vjp: jax.grad through the kernel == grad through reference."""
        q, k, v = _qkv(B=1, L=32, H=2, D=8, seed=11)

        def loss_flash(q, k, v):
            return jnp.sum(
                flash_attention(q, k, v, True, 16, 16, True) ** 2
            )

        def loss_ref(q, k, v):
            return jnp.sum(reference_attention(q, k, v, causal=True) ** 2)

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)

    @pytest.mark.parametrize("causal", [True, False])
    def test_kernel_grad_ragged_and_noncausal(self, causal):
        """The pallas backward kernels must keep exact gradients through the
        internal pad-to-block path (dead lse rows, padded key tails) and for
        both mask modes."""
        q, k, v = _qkv(B=1, L=24, H=2, D=8, seed=17)

        def loss_flash(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal, 16, 16, True) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(reference_attention(q, k, v, causal=causal) ** 2)

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)

    def test_ragged_length_padded(self):
        """L not divisible by block size is padded internally."""
        q, k, v = _qkv(B=1, L=24, H=2, D=8, seed=13)
        ref = reference_attention(q, k, v, causal=True)
        out = flash_attention(q, k, v, causal=True, block_q=16, block_k=16,
                              interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
        # non-causal must also exclude padded keys
        refn = reference_attention(q, k, v, causal=False)
        outn = flash_attention(q, k, v, causal=False, block_q=16, block_k=16,
                               interpret=True)
        np.testing.assert_allclose(np.asarray(outn), np.asarray(refn), atol=2e-5)

    def test_mismatched_block_sizes(self):
        """block_q != block_k where the smaller does not divide the padded
        length: geometry must pad to a common multiple, not silently truncate
        one grid axis (keys never folded in / rows never written)."""
        q, k, v = _qkv(B=1, L=32, H=1, D=8, seed=19)
        for bq, bk in ((32, 24), (24, 32)):
            ref = reference_attention(q, k, v, causal=False)
            out = flash_attention(q, k, v, causal=False, block_q=bq,
                                  block_k=bk, interpret=True)
            np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                       atol=2e-5, err_msg=f"bq={bq} bk={bk}")

    def test_transformer_with_flash_attention(self):
        """The kernel slots in as the transformer's attention_fn."""
        from functools import partial

        cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64)
        attn = lambda q, k, v: flash_attention(q, k, v, causal=True, block_q=16,
                                               block_k=16, interpret=True)
        tokens = jax.random.randint(jax.random.PRNGKey(3), (1, 32), 0, 64)
        params = TransformerLM(cfg).init(jax.random.PRNGKey(0), tokens)
        base = TransformerLM(cfg).apply(params, tokens)
        flash = TransformerLM(cfg, attention_fn=attn).apply(params, tokens)
        np.testing.assert_allclose(np.asarray(flash), np.asarray(base), atol=3e-4)


class TestRingPlusPallas:
    """The composed design: ppermute moves K/V shards around the ring, the
    pallas block-update kernel (flash_shard_update) folds each shard into
    the running online-softmax state per chip."""

    @pytest.mark.parametrize("causal", [True, False])
    def test_ring_with_pallas_blocks_matches_reference(self, sp_mesh, causal):
        from functools import partial

        from fedml_tpu.parallel.ring_attention import (
            pallas_block_attend,
            ring_attention,
        )

        q, k, v = _qkv(B=1, L=64, H=2, D=16, seed=23)
        full = reference_attention(q, k, v, causal=causal)
        ring = ring_attention(
            q, k, v, sp_mesh, axis_name="sp", causal=causal,
            block_fn=partial(pallas_block_attend, block_q=8, block_k=8,
                             interpret=True),
        )
        np.testing.assert_allclose(np.asarray(ring), np.asarray(full), atol=2e-5)

    def test_shard_update_matches_block_attend(self):
        """One shard fold: the kernel must reproduce _block_attend exactly,
        including carried state from a previous fold."""
        from fedml_tpu.ops.flash_attention import flash_shard_update
        from fedml_tpu.parallel.ring_attention import _block_attend

        q, k, v = _qkv(B=2, L=32, H=2, D=8, seed=29)
        k2, v2 = k + 0.1, v - 0.1
        q_pos = jnp.arange(32)
        k_pos = jnp.arange(32) + 32  # a later shard (partially masked causal)
        B, L, H, D = q.shape
        m0 = jnp.full((B, H, L), -jnp.inf, jnp.float32)
        l0 = jnp.zeros((B, H, L), jnp.float32)
        o0 = jnp.zeros((B, L, H, D), jnp.float32)
        # first fold: the local shard
        m1, l1, o1 = _block_attend(q, k, v, q_pos, q_pos, True, m0, l0, o0)
        # second fold via BOTH paths, carrying the first fold's state
        ref = _block_attend(q, k2, v2, q_pos, k_pos, True, m1, l1, o1)
        got = flash_shard_update(q, k2, v2, q_pos, k_pos, m1, l1, o1,
                                 causal=True, block_q=8, block_k=8,
                                 interpret=True)
        for a, b in zip(got, ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)

    def test_ring_with_pallas_blocks_is_trainable(self, sp_mesh):
        """jax.grad flows through the composed path (custom_vjp recompute
        through the canonical shard update) and matches the full-attention
        gradient."""
        from functools import partial

        from fedml_tpu.parallel.ring_attention import (
            pallas_block_attend,
            ring_attention,
        )

        q, k, v = _qkv(B=1, L=32, H=2, D=8, seed=31)
        bf = partial(pallas_block_attend, block_q=8, block_k=8, interpret=True)

        def loss_ring(q):
            return jnp.sum(ring_attention(q, k, v, sp_mesh, block_fn=bf) ** 2)

        def loss_full(q):
            return jnp.sum(reference_attention(q, k, v) ** 2)

        g_ring = jax.grad(loss_ring)(q)
        g_full = jax.grad(loss_full)(q)
        np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_full),
                                   atol=5e-4)
