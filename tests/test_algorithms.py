"""Algorithm zoo smoke + learning tests (each algorithm runs e2e and learns
or at least executes its protocol faithfully)."""

import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.arguments import Arguments


def _args(optimizer, **over):
    args = Arguments.from_dict(
        {
            "common_args": {"training_type": "simulation", "random_seed": 0, "run_id": "alg"},
            "data_args": {
                "dataset": "mnist",
                "data_cache_dir": "",
                "partition_method": "hetero",
                "partition_alpha": 0.5,
                "synthetic_train_size": 800,
            },
            "model_args": {"model": "lr"},
            "train_args": {
                "federated_optimizer": optimizer,
                "client_num_in_total": 6,
                "client_num_per_round": 3,
                "comm_round": 3,
                "epochs": 1,
                "batch_size": 32,
                "client_optimizer": "sgd",
                "learning_rate": 0.1,
            },
            "validation_args": {"frequency_of_the_test": 2},
            "comm_args": {"backend": "sp"},
        }
    )
    for k, v in over.items():
        setattr(args, k, v)
    return args.validate()


def _run(args):
    args = fedml_tpu.init(args, should_init_logs=False)
    dataset, out_dim = fedml_tpu.data.load(args)
    model = fedml_tpu.models.create(args, out_dim)
    runner = fedml_tpu.FedMLRunner(args, None, dataset, model)
    return runner.run()


LEARNERS = [
    ("FedOpt", {"server_optimizer": "adam", "server_lr": 0.03}),
    ("FedProx", {"proximal_mu": 0.1}),
    ("FedNova", {}),
    ("FedSGD", {"comm_round": 10}),
    ("SCAFFOLD", {}),
    ("FedDyn", {}),
    ("HierarchicalFL", {"group_num": 2, "group_comm_round": 1}),
    ("decentralized_fl", {"comm_round": 2}),
    ("turbo_aggregate", {"ta_group_num": 2}),
    ("Async_FedAvg", {"comm_round": 6}),
]


@pytest.mark.parametrize("opt,extra", LEARNERS)
def test_algorithm_learns(opt, extra):
    metrics = _run(_args(opt, **extra))
    assert metrics.get("test_acc", 0) > 0.4, metrics


def test_vertical_fl():
    args = _args("classical_vertical", comm_round=60, dataset="synthetic")
    metrics = _run(args)
    assert metrics["test_acc"] > 0.5


def test_split_nn():
    metrics = _run(_args("split_nn", comm_round=2, client_num_in_total=3))
    assert metrics["test_acc"] > 0.4


@pytest.mark.heavy
def test_fedgan_runs():
    metrics = _run(_args("FedGAN", comm_round=2, client_num_in_total=3,
                         client_num_per_round=2, synthetic_train_size=300))
    assert "d_fake_score" in metrics


def test_fednova_uses_step_counts():
    """FedNova must record tau per client each round."""
    from fedml_tpu.simulation.sp.fednova.fednova_api import FedNovaAPI

    args = fedml_tpu.init(_args("FedNova"), should_init_logs=False)
    dataset, out_dim = fedml_tpu.data.load(args)
    model = fedml_tpu.models.create(args, out_dim)
    api = FedNovaAPI(args, None, dataset, model)
    api.train()
    assert len(api._round_taus) == int(args.client_num_per_round)
    assert all(t >= 1 for t in api._round_taus)


def test_turbo_aggregate_matches_fedavg_modulo_masks():
    """Mask telescoping must cancel: TA result == plain weighted mean."""
    import jax
    import jax.numpy as jnp

    from fedml_tpu.simulation.sp.turboaggregate.ta_api import TurboAggregateAPI

    args = fedml_tpu.init(_args("turbo_aggregate", ta_group_num=3), should_init_logs=False)
    dataset, out_dim = fedml_tpu.data.load(args)
    model = fedml_tpu.models.create(args, out_dim)
    api = TurboAggregateAPI(args, None, dataset, model)
    from fedml_tpu.core.aggregate import weighted_mean

    ups = [(2.0, jax.tree_util.tree_map(lambda v: v + i, api.w_global)) for i in range(4)]
    got = api.server_update(list(ups))
    want = weighted_mean(ups)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4),
        got, want,
    )


def test_vertical_fl_nuswide():
    """NUS-WIDE is the reference's canonical VFL dataset
    (data/NUS_WIDE/nus_wide_dataset.py two-party loader): multi-hot labels
    collapse to the dominant concept for the guest's softmax.  The dataset
    tuple here carries REAL multi-hot [N, L] labels (the synthetic taglr
    fallback ships int labels, which would skip the collapse branch)."""
    import numpy as np

    from fedml_tpu.simulation.sp.classical_vertical_fl.vfl_api import VerticalFLAPI

    rng = np.random.RandomState(0)
    n_tr, n_te, d, L = 640, 160, 20, 5
    protos = np.random.RandomState(7).randn(L, d).astype(np.float32) * 2

    def _mk(n, seed):
        r = np.random.RandomState(seed)
        dom = r.randint(0, L, n)
        x = protos[dom] + 0.5 * r.randn(n, d).astype(np.float32)
        y = np.zeros((n, L), np.float32)
        y[np.arange(n), dom] = 1.0
        extra = r.rand(n, L) < 0.2  # co-occurring secondary concepts
        y = np.clip(y + extra * 0.0, 0, 1)  # dominant stays unique
        return x, y

    x_tr, y_tr = _mk(n_tr, 1)
    x_te, y_te = _mk(n_te, 2)
    args = _args("classical_vertical", comm_round=60, dataset="nuswide")
    dataset = (n_tr, n_te, (x_tr, y_tr), (x_te, y_te), {}, {}, {}, L)
    api = VerticalFLAPI(args, None, dataset)
    assert api.y_tr.ndim == 1  # multi-hot collapsed to concept indices
    metrics = api.train()
    assert metrics["test_acc"] > 0.6, metrics
