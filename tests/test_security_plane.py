"""The compiled defense & privacy plane (``parallel/sec_plane``,
``core/mpc/inmesh``, ``core/mpc/dropout``).

Four strata:

* **Compiled == host, bitwise** — the parity matrix: every in-mesh defense
  crossed with every server policy, modes checkerboarded so each defense
  and each policy is exercised under both ``mean`` and ``sum``; the fused
  staged round program on the 8-device mesh must agree BIT-FOR-BIT with
  :func:`~fedml_tpu.parallel.sec_plane.host_secure_round_update` (the same
  stage/fold/tail closures as three separately-jitted host programs).
* **DP determinism** — the counter-based noise stream is a pure function of
  (seed, round, client): identical inputs replay identical noise, the
  round/client counters actually move the stream, sigma is a RUNTIME
  scalar (no recompile between sigma values), and a 4→2 device remesh
  regenerates bitwise-identical noise.
* **Finite-field properties** — M31 residue ops: the compiled scan equals
  the host loop in ANY summation order (exact integer math), add/sub
  round-trip, boundary residues, and out-of-range rejection.
* **SecAgg dropout chaos** (the ``secagg_dropout`` leg of
  ``tools/chaos_check.py``) — a client dropped mid-upload plus a server
  kill mid-round: the restored round unmasks BIT-IDENTICALLY to the
  uninterrupted one with exactly-once duplicate accounting, and below the
  reconstruction threshold the round aborts instead of emitting garbage.
"""

from __future__ import annotations

import jax
import numpy as np
import pytest

from fedml_tpu.core.mpc.dropout import SecAggRound
from fedml_tpu.core.mpc.field import FIELD_PRIME
from fedml_tpu.core.mpc.inmesh import (
    field_add,
    field_sub,
    field_sum,
    reset_kernels,
)
from fedml_tpu.parallel.agg_plane import (
    _ROUND_PROGRAMS,
    ShardedRoundPlane,
    reset_planes,
)
from fedml_tpu.parallel.mesh import create_round_mesh, set_visible_devices
from fedml_tpu.parallel.sec_plane import (
    PLANE_DEFENSES,
    host_secure_round_update,
    reset_host_programs,
)


@pytest.fixture(autouse=True)
def _plane_hygiene():
    """Planes, round programs, host-oracle programs, and field kernels are
    process-cached; device visibility is process-global.  Leave all clean."""
    set_visible_devices(None)
    reset_planes()
    reset_host_programs()
    reset_kernels()
    yield
    set_visible_devices(None)
    reset_planes()
    reset_host_programs()
    reset_kernels()


def _tree(seed=0):
    rng = np.random.default_rng(seed)
    return {"w": rng.normal(size=(6, 4)).astype(np.float32),
            "b": rng.normal(size=(4,)).astype(np.float32)}


def _updates(n, seed=1):
    rng = np.random.default_rng(seed)
    return [(float(i + 1),
             {"w": rng.normal(size=(6, 4)).astype(np.float32),
              "b": rng.normal(size=(4,)).astype(np.float32)})
            for i in range(n)]


def _assert_bit_identical(a, b):
    fa, ta = jax.tree_util.tree_flatten(a)
    fb, tb = jax.tree_util.tree_flatten(b)
    assert ta == tb
    for x, y in zip(fa, fb):
        assert np.asarray(x).tobytes() == np.asarray(y).tobytes()


# ---------------------------------------------------------------------------
# Compiled == host: the defense x policy parity matrix
# ---------------------------------------------------------------------------

_DEFENSES = [
    ("norm_clip", 1.5),
    ("krum", 1, 1),
    ("krum", 1, 3),       # multi-Krum, m survivors
    ("trimmed_mean", 0.2),
]
_POLICIES = [
    ("fedavg",),
    ("sgd", 0.9, 0.0),
    ("adam", 0.1, 0.9),
    ("yogi", 0.1, 0.9),
    ("adagrad", 0.1, 0.0),
]
_DP = ("gaussian", 1.0, 0)

# every (defense, policy) pair, modes checkerboarded: each defense and each
# policy sees both mean and sum without doubling the compile bill
_MATRIX = [(d, p, ("mean", "sum")[(i + j) % 2])
           for i, d in enumerate(_DEFENSES)
           for j, p in enumerate(_POLICIES)]


class TestCompiledHostParity:
    """The tentpole acceptance claim: with the security stages active the
    fused round program agrees bitwise with the retained host oracle."""

    @pytest.mark.parametrize(
        "defense,policy,mode", _MATRIX,
        ids=[f"{d[0]}-{p[0]}-{m}" for d, p, m in _MATRIX])
    def test_defense_policy_parity_bitwise(self, defense, policy, mode):
        params, updates = _tree(10), _updates(6, seed=11)
        plane = ShardedRoundPlane(policy=policy, defense=defense, dp=_DP)
        got = plane.round_update(params, updates, mode=mode, round_idx=3,
                                 client_ids=list(range(6)), dp_sigma=0.7)
        want, _, _ = host_secure_round_update(
            params, updates, mode=mode, policy=policy, defense=defense,
            dp=_DP, round_idx=3, client_ids=np.arange(6), dp_sigma=0.7)
        _assert_bit_identical(got, want)

    def test_dp_only_stage_parity_bitwise(self):
        """DP without a defense filter still stages bitwise."""
        params, updates = _tree(12), _updates(5, seed=13)
        plane = ShardedRoundPlane(policy=("fedavg",), dp=("laplace", 2.0, 9))
        got = plane.round_update(params, updates, round_idx=1,
                                 client_ids=[3, 1, 4, 1, 5], dp_sigma=0.3)
        want, _, _ = host_secure_round_update(
            params, updates, dp=("laplace", 2.0, 9), round_idx=1,
            client_ids=np.asarray([3, 1, 4, 1, 5]), dp_sigma=0.3)
        _assert_bit_identical(got, want)

    def test_every_plane_defense_has_a_matrix_row(self):
        """_DEFENSES tracks PLANE_DEFENSES — growing the plane without
        growing the parity matrix is a silent coverage hole."""
        kinds = {d[0] for d in _DEFENSES}
        assert kinds == {"norm_clip", "krum", "trimmed_mean"}
        assert len(PLANE_DEFENSES) == 4  # krum + multi_krum share a stage


# ---------------------------------------------------------------------------
# DP determinism: counter-based noise, runtime sigma, remesh stability
# ---------------------------------------------------------------------------

class TestDPDeterminism:
    def test_dp_noise_counter_deterministic(self):
        """Same (seed, round, client) -> same noise, bitwise; moving either
        counter moves the stream."""
        params, updates = _tree(20), _updates(4, seed=21)
        kw = dict(dp=("gaussian", 1.0, 7), dp_sigma=0.5,
                  client_ids=np.asarray([2, 5, 8, 11]))
        a, _, _ = host_secure_round_update(params, updates, round_idx=4, **kw)
        b, _, _ = host_secure_round_update(params, updates, round_idx=4, **kw)
        _assert_bit_identical(a, b)
        c, _, _ = host_secure_round_update(params, updates, round_idx=5, **kw)
        assert np.asarray(a["w"]).tobytes() != np.asarray(c["w"]).tobytes()
        kw["client_ids"] = np.asarray([2, 5, 8, 12])
        d, _, _ = host_secure_round_update(params, updates, round_idx=4, **kw)
        assert np.asarray(a["w"]).tobytes() != np.asarray(d["w"]).tobytes()

    def test_dp_sigma_is_runtime_not_a_cache_key(self):
        """Budget decay (the accountant shrinking sigma round over round)
        must never force a recompile: two sigmas, one program."""
        params, updates = _tree(22), _updates(4, seed=23)
        plane = ShardedRoundPlane(policy=("fedavg",), dp=_DP)
        out1 = plane.round_update(params, updates, round_idx=0,
                                  client_ids=[0, 1, 2, 3], dp_sigma=0.5)
        n_progs = len(_ROUND_PROGRAMS)
        out2 = plane.round_update(out1, updates, round_idx=1,
                                  client_ids=[0, 1, 2, 3], dp_sigma=0.125)
        assert len(_ROUND_PROGRAMS) == n_progs
        assert np.asarray(out2["w"]).dtype == np.float32

    def test_dp_determinism_under_remesh_4_to_2(self):
        """The remesh-stability claim: shrinking the mesh 4 -> 2 devices
        regenerates bitwise-identical DP noise (the counter-based stream
        depends on (seed, round, client), never on topology) — and both
        topologies match the unsharded host oracle."""
        params, updates = _tree(24), _updates(4, seed=25)
        kw = dict(round_idx=6, client_ids=[1, 3, 5, 7], dp_sigma=0.9)
        mesh4 = create_round_mesh(clients=1, model=4,
                                  devices=jax.devices()[:4])
        mesh2 = create_round_mesh(clients=1, model=2,
                                  devices=jax.devices()[:2])
        p4 = ShardedRoundPlane(mesh=mesh4, policy=("adam", 0.1, 0.9),
                               defense=("norm_clip", 2.0), dp=_DP)
        p2 = ShardedRoundPlane(mesh=mesh2, policy=("adam", 0.1, 0.9),
                               defense=("norm_clip", 2.0), dp=_DP)
        out4 = p4.round_update(params, updates, **kw)
        out2 = p2.round_update(params, updates, **kw)
        _assert_bit_identical(out4, out2)
        want, _, _ = host_secure_round_update(
            params, updates, policy=("adam", 0.1, 0.9),
            defense=("norm_clip", 2.0), dp=_DP, round_idx=6,
            client_ids=np.asarray([1, 3, 5, 7]), dp_sigma=0.9)
        _assert_bit_identical(out4, want)


# ---------------------------------------------------------------------------
# Finite-field properties (core/mpc/inmesh vs the host loop)
# ---------------------------------------------------------------------------

class TestFiniteField:
    def _residues(self, n, shape, seed):
        rng = np.random.default_rng(seed)
        return rng.integers(0, int(FIELD_PRIME), size=(n,) + shape,
                            dtype=np.int64)

    def test_field_sum_matches_host_loop_any_order(self):
        """Exact integer math: the compiled scan equals the per-client host
        fold under every permutation of the stack."""
        stack = self._residues(7, (5,), seed=30)
        host = np.zeros((5,), np.int64)
        for v in stack:
            host = np.mod(host + v, FIELD_PRIME)
        rng = np.random.default_rng(31)
        for _ in range(4):
            perm = rng.permutation(len(stack))
            assert np.array_equal(field_sum(stack[perm]), host)

    def test_field_add_sub_round_trip_and_boundaries(self):
        a = self._residues(1, (9,), seed=32)[0]
        b = self._residues(1, (9,), seed=33)[0]
        assert np.array_equal(field_sub(field_add(a, b), b), a)
        # boundary residues: p-1 + p-1 wraps, x - 0 is identity, 0 - x wraps
        top = np.full((3,), int(FIELD_PRIME) - 1, np.int64)
        zero = np.zeros((3,), np.int64)
        assert np.array_equal(field_add(top, top),
                              np.mod(top + top, FIELD_PRIME))
        assert np.array_equal(field_sub(a, np.zeros_like(a)), a)
        assert np.array_equal(field_sub(zero, top),
                              np.mod(-top, FIELD_PRIME))

    def test_field_ops_reject_non_residues(self):
        bad_hi = np.asarray([int(FIELD_PRIME)], np.int64)
        bad_lo = np.asarray([-1], np.int64)
        for bad in (bad_hi, bad_lo):
            with pytest.raises(ValueError, match="residues"):
                field_sum(bad[None, :])
            with pytest.raises(ValueError, match="residues"):
                field_add(bad, np.zeros_like(bad))


# ---------------------------------------------------------------------------
# SecAgg dropout chaos (the chaos_check `secagg_dropout` leg)
# ---------------------------------------------------------------------------

def _client_vecs(n, dim=32, seed=40):
    rng = np.random.default_rng(seed)
    return [rng.normal(size=(dim,)) for _ in range(n)]


def _expected_aggregate(rnd, vecs, survivors):
    """The ground truth: the plain field sum of the SURVIVORS' quantized
    vectors, dequantized — what a fault-free round over exactly the
    survivors would produce."""
    total = np.zeros_like(rnd.quantize(vecs[0]))
    for s in survivors:
        total = np.mod(total + rnd.quantize(vecs[s]), FIELD_PRIME)
    from fedml_tpu.core.mpc.secagg import transform_finite_to_tensor
    return transform_finite_to_tensor(total, FIELD_PRIME, q_bits=rnd.q_bits)


@pytest.mark.parametrize("plane", ["host", "compiled"])
def test_secagg_dropout_unmask_bit_identical(plane):
    """Two clients dropped mid-upload: the survivor shares reconstruct the
    dropped DH secrets, the uncancelled masks strip, and the aggregate is
    BITWISE the plain field sum of the survivors' unmasked residues."""
    n, vecs = 6, _client_vecs(6)
    rnd = SecAggRound(n_clients=n, threshold=4, seed=5, plane=plane)
    for i in range(n):
        if i in (2, 5):
            continue  # dropped: their payloads never arrive
        rnd.submit(i, rnd.client_payload(i, vecs[i]))
    assert rnd.dropped == [2, 5]
    got = rnd.unmask()
    want = _expected_aggregate(rnd, vecs, rnd.survivors)
    assert got.tobytes() == want.tobytes()


def test_secagg_dropout_server_kill_mid_round_bit_identical():
    """The chaos leg: a duplicate retransmit, then a server kill between
    submissions, then a dropout — the restored round unmasks bit-identical
    to an uninterrupted one, with exactly-once duplicate accounting."""
    n, vecs = 5, _client_vecs(5, seed=41)
    payloads = None

    def play(rnd, kill=False):
        nonlocal payloads
        if payloads is None:
            payloads = [rnd.client_payload(i, vecs[i]) for i in range(n)]
        rnd.submit(0, payloads[0])
        rnd.submit(1, payloads[1])
        assert not rnd.submit(1, payloads[1])  # chaos retransmit: dropped
        if kill:
            rnd = SecAggRound.from_state(rnd.export_state())  # server kill
        rnd.submit(3, payloads[3])
        assert not rnd.submit(3, payloads[3])  # post-restore retransmit
        rnd.submit(4, payloads[4])
        # client 2 dropped mid-upload: its payload never lands
        assert rnd.dropped == [2]
        assert rnd.dup_submissions == 2  # exactly-once across the kill
        return rnd.unmask()

    ref = play(SecAggRound(n_clients=n, threshold=3, seed=9))
    got = play(SecAggRound(n_clients=n, threshold=3, seed=9), kill=True)
    assert got.tobytes() == ref.tobytes()
    want = _expected_aggregate(
        SecAggRound(n_clients=n, threshold=3, seed=9), vecs, [0, 1, 3, 4])
    assert got.tobytes() == want.tobytes()


def test_secagg_dropout_host_and_compiled_planes_agree():
    """Field math is exact on both planes, so the unmasked aggregates are
    bitwise equal — secagg_plane=compiled can never drift."""
    n, vecs = 4, _client_vecs(4, seed=42)

    def run(plane):
        rnd = SecAggRound(n_clients=n, threshold=3, seed=2, plane=plane)
        for i in range(n):
            if i != 1:
                rnd.submit(i, rnd.client_payload(i, vecs[i]))
        return rnd.unmask()

    assert run("host").tobytes() == run("compiled").tobytes()


def test_secagg_dropout_below_threshold_aborts():
    """Fewer than ``threshold`` survivors: the masks are information-
    theoretically unrecoverable — the round must raise, not emit garbage."""
    rnd = SecAggRound(n_clients=5, threshold=4, seed=1)
    vecs = _client_vecs(5, seed=43)
    for i in (0, 2, 4):
        rnd.submit(i, rnd.client_payload(i, vecs[i]))
    with pytest.raises(ValueError, match="threshold"):
        rnd.unmask()
