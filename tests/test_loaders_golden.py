"""Golden-fixture parser tests: every real-format parser family in
fedml_tpu/data/loaders.py run against COMMITTED on-disk bytes
(tests/fixtures/golden, written by tools/make_golden_fixtures.py with
stdlib/PIL writers independent of the parsers), asserting the exact
arrays.  Severs parser correctness from any dataset mount — a format
regression fails here, not on the first real-data run.

Expected values are re-derived in-test from the fixtures' seeds and the
documented normalization, NOT by calling the parsers (no self-testing)."""

from __future__ import annotations

import os

import numpy as np
import pytest

from fedml_tpu.data import loaders

GOLD = os.path.join(os.path.dirname(os.path.abspath(__file__)), "fixtures", "golden")


def _gold(name: str) -> str:
    path = os.path.join(GOLD, name)
    assert os.path.isdir(path), (
        f"missing fixture dir {path}; run tools/make_golden_fixtures.py")
    return path


class TestMnistIdx:
    def test_exact_arrays_plain_and_gz(self):
        r = np.random.RandomState(10)
        xt = r.randint(0, 256, (10, 28, 28)).astype(np.uint8)
        yt = r.randint(0, 10, (10,)).astype(np.uint8)
        xe = r.randint(0, 256, (4, 28, 28)).astype(np.uint8)
        ye = r.randint(0, 10, (4,)).astype(np.uint8)

        out = loaders.load_mnist_idx(_gold("mnist"))
        assert out is not None
        gxt, gyt, gxe, gye = out
        assert gxt.shape == (10, 28, 28, 1) and gxt.dtype == np.float32
        np.testing.assert_array_equal(gxt[..., 0], xt.astype(np.float32) / 255.0)
        np.testing.assert_array_equal(gyt, yt.astype(np.int32))
        # test split is gzipped on disk: exercises the .gz opener
        np.testing.assert_array_equal(gxe[..., 0], xe.astype(np.float32) / 255.0)
        np.testing.assert_array_equal(gye, ye.astype(np.int32))

    def test_partial_cache_falls_back(self, tmp_path):
        # only images, no labels: must return None (synthetic fallback)
        import shutil

        shutil.copy(os.path.join(_gold("mnist"), "train-images-idx3-ubyte"),
                    tmp_path / "train-images-idx3-ubyte")
        assert loaders.load_mnist_idx(str(tmp_path)) is None


class TestCifarPickle:
    def test_exact_arrays_and_batch_order(self):
        r = np.random.RandomState(11)
        raw = {}
        for name, n in (("data_batch_1", 3), ("data_batch_2", 3), ("test_batch", 2)):
            raw[name] = (r.randint(0, 256, (n, 3072)).astype(np.uint8),
                         r.randint(0, 10, (n,)))
        out = loaders.load_cifar_pickle(_gold("cifar10"))
        assert out is not None
        xt, yt, xe, ye = out
        assert xt.shape == (6, 32, 32, 3) and xe.shape == (2, 32, 32, 3)
        exp_xt = np.concatenate([
            raw["data_batch_1"][0], raw["data_batch_2"][0]
        ]).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1).astype(np.float32) / 255.0
        np.testing.assert_array_equal(xt, exp_xt)
        np.testing.assert_array_equal(
            yt, np.concatenate([raw["data_batch_1"][1], raw["data_batch_2"][1]]))
        np.testing.assert_array_equal(
            xe, raw["test_batch"][0].reshape(-1, 3, 32, 32)
                  .transpose(0, 2, 3, 1).astype(np.float32) / 255.0)
        np.testing.assert_array_equal(ye, raw["test_batch"][1])


class TestLeafJson:
    def test_exact_arrays_and_mnist_reshape(self):
        r = np.random.RandomState(12)
        tr_x, tr_y = [], []
        for u in ("f_00", "f_01"):
            tr_x.append(np.asarray(r.rand(3, 784).round(6), np.float32))
            tr_y.append(r.randint(0, 62, (3,)).astype(np.int32))
        te_x = np.asarray(r.rand(2, 784).round(6), np.float32)
        te_y = r.randint(0, 62, (2,)).astype(np.int32)

        out = loaders.load_leaf_json(_gold("femnist"))
        assert out is not None
        xt, yt, xe, ye = out
        # 784-wide LEAF x reshapes to NHWC
        assert xt.shape == (6, 28, 28, 1) and xe.shape == (2, 28, 28, 1)
        np.testing.assert_allclose(
            xt.reshape(6, 784), np.concatenate(tr_x), rtol=0, atol=0)
        np.testing.assert_array_equal(yt, np.concatenate(tr_y))
        np.testing.assert_allclose(xe.reshape(2, 784), te_x, rtol=0, atol=0)
        np.testing.assert_array_equal(ye, te_y)


class TestImageFolder:
    def test_cinic_png_exact(self):
        r = np.random.RandomState(13)
        imgs = {}
        for split in ("train", "valid"):
            for cname in ("airplane", "automobile"):
                for i in range(2):
                    imgs[(split, cname, i)] = r.randint(0, 256, (32, 32, 3)).astype(np.uint8)
        out = loaders.load_image_folder(_gold("cinic10"))
        assert out is not None
        xt, yt, xe, ye = out
        assert xt.shape == (4, 32, 32, 3)
        # sorted class order: airplane=0, automobile=1; files img0, img1
        exp = np.stack([
            imgs[("train", "airplane", 0)], imgs[("train", "airplane", 1)],
            imgs[("train", "automobile", 0)], imgs[("train", "automobile", 1)],
        ]).astype(np.float32) / 255.0
        np.testing.assert_array_equal(xt, exp)  # PNG is lossless
        np.testing.assert_array_equal(yt, [0, 0, 1, 1])
        np.testing.assert_array_equal(ye, [0, 0, 1, 1])
        assert xe.shape == (4, 32, 32, 3)


class TestCsvLabeled:
    def test_exact_arrays_named_label_column(self):
        r = np.random.RandomState(14)
        tr = [(r.rand(3).round(4), r.randint(0, 2)) for _ in range(8)]
        te = [(r.rand(3).round(4), r.randint(0, 2)) for _ in range(3)]
        out = loaders.load_csv_labeled(_gold("uci"))
        assert out is not None
        xt, yt, xe, ye = out
        np.testing.assert_allclose(xt, np.stack([f for f, _ in tr]).astype(np.float32),
                                   rtol=1e-6)
        np.testing.assert_array_equal(yt, [y for _, y in tr])
        np.testing.assert_allclose(xe, np.stack([f for f, _ in te]).astype(np.float32),
                                   rtol=1e-6)
        np.testing.assert_array_equal(ye, [y for _, y in te])


class TestLandmarksCsv:
    def test_labels_exact_pixels_close(self):
        # JPEG is lossy: labels/shapes are exact, pixels within jpeg error
        # (fixtures are smooth gradients, so the bound is tight)
        raws = []
        for i in range(4):
            g = (np.add.outer(np.arange(32) * 4, np.arange(32) * 3) + i * 20) % 256
            raws.append(np.stack([g, (g + 40) % 256, (g + 90) % 256], -1)
                        .astype(np.uint8))
        out = loaders.load_landmarks_csv(_gold("gld23k"))
        assert out is not None
        xt, yt, xe, ye = out
        assert xt.shape == (3, 32, 32, 3) and xe.shape == (1, 32, 32, 3)
        np.testing.assert_array_equal(yt, [0, 1, 2])
        np.testing.assert_array_equal(ye, [0])
        for got, raw in zip(xt, raws[:3]):
            assert np.abs(got - raw.astype(np.float32) / 255.0).mean() < 0.05


class TestNusWide:
    def test_exact_multihot_and_features(self):
        r = np.random.RandomState(16)
        lab = {}
        for nm in ("sky", "water"):
            lab[(nm, "Train")] = r.randint(0, 2, (6,))
            lab[(nm, "Test")] = r.randint(0, 2, (3,))
        feat_tr = r.rand(6, 4).round(6)
        feat_te = r.rand(3, 4).round(6)
        out = loaders.load_nuswide(_gold("nuswide"))
        assert out is not None
        xt, yt, xe, ye = out
        np.testing.assert_allclose(xt, feat_tr.astype(np.float32), atol=1e-6)
        np.testing.assert_allclose(xe, feat_te.astype(np.float32), atol=1e-6)
        # names sorted: sky, water
        np.testing.assert_array_equal(
            yt, np.stack([lab[("sky", "Train")], lab[("water", "Train")]], 1))
        np.testing.assert_array_equal(
            ye, np.stack([lab[("sky", "Test")], lab[("water", "Test")]], 1))


class TestFetsNifti:
    def test_mid_slice_channels_and_seg_mapping(self):
        r = np.random.RandomState(17)
        vols = {}
        for s in ("FeTS21_001", "FeTS21_002"):
            for mod, dt in (("_t1", np.int16), ("_t1ce", np.int16),
                            ("_t2", np.int16), ("_flair", np.int16),
                            ("_seg", np.uint8)):
                shape = (8, 8, 4)
                if mod == "_seg":
                    vols[(s, mod)] = r.choice([0, 1, 2, 4], size=shape).astype(dt)
                else:
                    vols[(s, mod)] = r.randint(0, 1000, shape).astype(dt)

        def expect_slice(vol, size=32):
            sl = vol[:, :, vol.shape[2] // 2].astype(np.float32)
            iy = np.linspace(0, sl.shape[0] - 1, size).astype(int)
            ix = np.linspace(0, sl.shape[1] - 1, size).astype(int)
            return sl[np.ix_(iy, ix)]

        out = loaders.load_fets_nifti(_gold("fets2021"))
        assert out is not None
        xt, yt, xe, ye = out
        # 2 subjects, 80/20 -> 1 train / 1 test, sorted subject order
        assert xt.shape == (1, 32, 32, 3) and xe.shape == (1, 32, 32, 3)
        # channel order: t1ce, t1, t2 (flair dropped as 4th)
        for ci, mod in enumerate(("_t1ce", "_t1", "_t2")):
            sl = expect_slice(vols[("FeTS21_001", mod)])
            denom = sl.max() - sl.min()
            np.testing.assert_allclose(
                xt[0, :, :, ci], (sl - sl.min()) / (denom if denom > 0 else 1.0),
                atol=1e-6)
        exp_mask = expect_slice(vols[("FeTS21_001", "_seg")]).astype(np.int32)
        np.testing.assert_array_equal(yt[0], np.where(exp_mask >= 2, 2, exp_mask))


class TestEdgeCasePool:
    def test_pools_grouped_by_shape_exact(self):
        r = np.random.RandomState(18)
        ardis = r.randint(0, 256, (5, 28, 28, 1)).astype(np.uint8)
        southwest = r.rand(4, 32, 32, 3).astype(np.float32)
        pools = loaders.load_edge_case_pool(_gold("edge_case"))
        assert pools is not None
        assert set(pools) == {(28, 28, 1), (32, 32, 3)}
        np.testing.assert_array_equal(pools[(28, 28, 1)],
                                      ardis.astype(np.float32) / 255.0)
        np.testing.assert_array_equal(pools[(32, 32, 3)], southwest)


class TestTryLoadRealDispatch:
    @pytest.mark.parametrize("name,fixture", [
        ("mnist", "mnist"),
        ("cifar10", "cifar10"),
        ("femnist", "femnist"),
        ("cinic10", "cinic10"),
        ("uci", "uci"),
        ("gld23k", "gld23k"),
        ("nuswide", "nuswide"),
        ("fets2021", "fets2021"),
    ])
    def test_dispatch_finds_each_family(self, name, fixture, tmp_path):
        # mount layout: cache_dir/<dataset>/... exactly as a user would
        import shutil

        shutil.copytree(_gold(fixture), tmp_path / name)
        out = loaders.try_load_real(name, str(tmp_path))
        assert out is not None and len(out) == 4
