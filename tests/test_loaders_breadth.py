"""Mounted-file parsers beyond MNIST/CIFAR/LEAF (reference
``data/{ImageNet,Landmarks,NUS_WIDE,FeTS2021,edge_case_examples}``):
each test fabricates files in the real on-disk layout and checks the
parser round-trips them."""

import gzip
import os
import pickle
import struct

import numpy as np
import pytest

from fedml_tpu.data import loaders


def _write_png(path, arr):
    from PIL import Image

    Image.fromarray(arr.astype(np.uint8)).save(path)


class TestImageNetFolder:
    def test_train_val_wnid_layout(self, tmp_path):
        rng = np.random.RandomState(0)
        for split, per in (("train", 3), ("val", 2)):
            for wnid in ("n01440764", "n01443537"):
                d = tmp_path / split / wnid
                d.mkdir(parents=True)
                for i in range(per):
                    _write_png(d / f"{wnid}_{i}.JPEG".replace("JPEG", "jpeg"),
                               rng.randint(0, 255, (48, 48, 3)))
        out = loaders.load_imagenet_folder(str(tmp_path), size=32)
        assert out is not None
        xt, yt, xe, ye = out
        assert xt.shape == (6, 32, 32, 3) and xe.shape == (4, 32, 32, 3)
        assert set(yt) == {0, 1} and xt.max() <= 1.0


class TestLandmarksCSV:
    def test_mapping_csv_plus_images(self, tmp_path):
        rng = np.random.RandomState(0)
        (tmp_path / "images").mkdir()
        rows_train, rows_test = [], []
        for i in range(6):
            img_id = f"img{i:03d}"
            _write_png(tmp_path / "images" / f"{img_id}.jpg",
                       rng.randint(0, 255, (32, 32, 3)))
            (rows_train if i < 4 else rows_test).append(
                (f"user{i % 2}", img_id, i % 3)
            )
        for name, rows in (("mini_gld_train_split.csv", rows_train),
                           ("mini_gld_test.csv", rows_test)):
            with open(tmp_path / name, "w") as f:
                f.write("user_id,image_id,class\n")
                for u, im, c in rows:
                    f.write(f"{u},{im},{c}\n")
        out = loaders.load_landmarks_csv(str(tmp_path))
        assert out is not None
        xt, yt, xe, ye = out
        assert len(xt) == 4 and len(xe) == 2
        assert list(yt) == [0, 1, 2, 0]


class TestNUSWide:
    def test_features_and_multilabel(self, tmp_path):
        lab = tmp_path / "Groundtruth" / "TrainTestLabels"
        feat = tmp_path / "Low_Level_Features"
        lab.mkdir(parents=True), feat.mkdir()
        rng = np.random.RandomState(0)
        n_tr, n_te = 10, 4
        for name in ("animal", "sky"):
            np.savetxt(lab / f"Labels_{name}_Train.txt", rng.randint(0, 2, n_tr), fmt="%d")
            np.savetxt(lab / f"Labels_{name}_Test.txt", rng.randint(0, 2, n_te), fmt="%d")
        for block, d in (("CH", 3), ("EDH", 2)):
            np.savetxt(feat / f"Normalized_{block}_Train_x.dat", rng.rand(n_tr, d))
            np.savetxt(feat / f"Normalized_{block}_Test_x.dat", rng.rand(n_te, d))
        out = loaders.load_nuswide(str(tmp_path))
        assert out is not None
        xt, yt, xe, ye = out
        assert xt.shape == (10, 5) and yt.shape == (10, 2)  # 3+2 feature dims
        assert xe.shape == (4, 5) and ye.shape == (4, 2)
        assert set(np.unique(yt)) <= {0.0, 1.0}


def _write_nifti(path, vol, dtype_code=16, np_dtype=np.float32):
    hdr = bytearray(352)
    struct.pack_into("<i", hdr, 0, 348)
    dims = (vol.ndim,) + vol.shape + (1,) * (7 - vol.ndim)
    struct.pack_into("<8h", hdr, 40, *dims)
    struct.pack_into("<h", hdr, 70, dtype_code)
    struct.pack_into("<f", hdr, 108, 352.0)
    data = np.asarray(vol, np_dtype).flatten(order="F").tobytes()
    op = gzip.open if str(path).endswith(".gz") else open
    with op(str(path), "wb") as f:
        f.write(bytes(hdr) + data)


class TestFeTSNifti:
    def test_brats_subject_layout(self, tmp_path):
        rng = np.random.RandomState(0)
        for s in range(4):
            d = tmp_path / f"FeTS21_{s:03d}"
            d.mkdir()
            for mod in ("t1", "t1ce", "t2"):
                _write_nifti(d / f"FeTS21_{s:03d}_{mod}.nii.gz",
                             rng.rand(20, 22, 8).astype(np.float32))
            seg = rng.choice([0, 1, 2, 4], size=(20, 22, 8))
            _write_nifti(d / f"FeTS21_{s:03d}_seg.nii.gz", seg,
                         dtype_code=4, np_dtype=np.int16)
        out = loaders.load_fets_nifti(str(tmp_path))
        assert out is not None
        xt, yt, xe, ye = out
        assert xt.shape == (3, 32, 32, 3) and yt.shape == (3, 32, 32)
        assert xe.shape == (1, 32, 32, 3)
        assert set(np.unique(np.concatenate([yt, ye]))) <= {0, 1, 2}
        assert 0.0 <= xt.min() and xt.max() <= 1.0

    def test_nifti_roundtrip_fortran_order(self, tmp_path):
        vol = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
        _write_nifti(tmp_path / "v.nii", vol)
        back = loaders._read_nifti(str(tmp_path / "v.nii"))
        assert back.shape == (2, 3, 4)
        assert np.array_equal(back, vol)


class TestEdgeCasePool:
    def test_pickled_pools_group_by_shape(self, tmp_path):
        rng = np.random.RandomState(0)
        a = rng.randint(0, 255, (5, 8, 8, 3)).astype(np.uint8)
        b = {"data": rng.rand(3, 8, 8, 3).astype(np.float32)}
        mnist_shaped = rng.rand(4, 28, 28, 1).astype(np.float32)  # ARDIS next
        with open(tmp_path / "southwest_train.pkl", "wb") as f:  # to Southwest
            pickle.dump(a, f)
        with open(tmp_path / "southwest_test.pkl", "wb") as f:
            pickle.dump(b, f)
        with open(tmp_path / "ardis_7.pkl", "wb") as f:
            pickle.dump(mnist_shaped, f)
        pools = loaders.load_edge_case_pool(str(tmp_path))
        assert pools[(8, 8, 3)].shape == (8, 8, 8, 3)
        assert pools[(28, 28, 1)].shape == (4, 28, 28, 1)
        assert pools[(8, 8, 3)].max() <= 1.0

    def test_attacker_injects_mounted_pool(self, tmp_path):
        import jax.numpy as jnp

        from fedml_tpu.arguments import Arguments
        from fedml_tpu.core.security.fedml_attacker import FedMLAttacker

        rng = np.random.RandomState(0)
        pool = np.full((4, 6, 6, 1), 0.5, np.float32)
        with open(tmp_path / "edge.pkl", "wb") as f:
            pickle.dump(pool, f)
        args = Arguments.from_dict({"common_args": {}, "train_args": {}})
        args.enable_attack = True
        args.attack_type = "edge_case_backdoor"
        args.byzantine_client_num = 1
        args.attack_client_num = 1
        args.client_num_in_total = 2
        args.target_class = 9
        args.poison_fraction = 0.5
        args.edge_case_dir = str(tmp_path)
        atk = FedMLAttacker.get_instance()
        atk.init(args)
        x = jnp.zeros((10, 6, 6, 1))
        y = jnp.zeros((10,), jnp.int32)
        px, py = atk.poison_dataset(x, y)
        n_poisoned = int((py == 9).sum())
        assert n_poisoned == 5  # frac * len
        assert float(px.max()) == 0.5  # pool pixels injected
