"""core/mlops: sinks, metrics, events, status FSM, sys stats, log daemon
(reference core/mlops parity, offline-first)."""

import logging
import os
import time

import pytest

from fedml_tpu.core import mlops
from fedml_tpu.core.mlops import (
    ClientStatus,
    FanoutSink,
    InMemorySink,
    JsonlFileSink,
    MLOpsProfilerEvent,
    MLOpsRuntimeLogDaemon,
    MLOpsStatus,
    ServerStatus,
    SysStats,
)


class _Args:
    def __init__(self, **kw):
        self.__dict__.update(kw)


@pytest.fixture(autouse=True)
def _reset_mlops():
    yield
    mlops.finish()
    MLOpsStatus._instance = None


def test_facade_noop_until_init():
    mlops.log({"acc": 1.0})  # must not raise
    assert not mlops.enabled()


def test_facade_log_round_and_status(tmp_path):
    mem = InMemorySink()
    mlops.init(_Args(run_id="r1", rank=0, log_file_dir=str(tmp_path)), FanoutSink([mem]))
    assert mlops.enabled()
    mlops.log({"acc": 0.9})
    mlops.log_round_info(10, 3)
    mlops.log_training_status(ClientStatus.INITIALIZING, edge_id=1)
    mlops.log_aggregation_status(ServerStatus.STARTING)
    mlops.event("train", event_started=True)
    mlops.event("train", event_started=False)
    topics = {t for t, _ in mem.records}
    assert {"train_metric", "round_info", "client_status", "server_status", "event"} <= topics
    # the JSONL file sink wrote the same records
    files = [f for f in os.listdir(tmp_path) if f.startswith("mlops_")]
    assert files and os.path.getsize(tmp_path / files[0]) > 0


def test_status_fsm_rejects_illegal_transition():
    st = MLOpsStatus.get_instance()
    st.set_client_status(5, ClientStatus.INITIALIZING)
    st.set_client_status(5, ClientStatus.TRAINING)
    st.set_client_status(5, ClientStatus.FINISHED)
    with pytest.raises(ValueError):
        st.set_client_status(5, ClientStatus.TRAINING)  # FINISHED is terminal


def test_profiler_event_duration():
    mem = InMemorySink()
    prof = MLOpsProfilerEvent("r", 0, FanoutSink([mem]))
    with prof.trace("span"):
        time.sleep(0.01)
    ev = mem.by_topic("event")
    assert ev[0]["phase"] == "started" and ev[1]["phase"] == "ended"
    assert ev[1]["duration_s"] >= 0.01


def test_sys_stats_schema():
    info = SysStats().produce_info()
    assert "system_memory_total" in info and "cpu_utilization" in info
    assert isinstance(info["devices"], list)


def test_log_daemon_ships_chunks(tmp_path):
    log_path = str(tmp_path / "run.log")
    mem = InMemorySink()
    daemon = MLOpsRuntimeLogDaemon(
        log_path, FanoutSink([mem]), chunk_lines=2, poll_interval_s=0.01
    ).start()
    with open(log_path, "w") as f:
        for i in range(5):
            f.write(f"line {i}\n")
    deadline = time.time() + 5
    while daemon.lines_shipped < 5 and time.time() < deadline:
        time.sleep(0.02)
    daemon.stop()
    chunks = mem.by_topic("log_chunk")
    shipped = [ln for c in chunks for ln in c["lines"]]
    assert shipped == [f"line {i}" for i in range(5)]


def test_broker_sink_roundtrip():
    from fedml_tpu.core.distributed.communication.mqtt_s3.broker import (
        BrokerClient,
        LocalBroker,
    )
    from fedml_tpu.core.mlops.sinks import BrokerSink

    broker = LocalBroker().start()
    got = []
    sub = BrokerClient("127.0.0.1", broker.port, on_message=lambda t, p: got.append((t, p)))
    sub.subscribe("fedml_mlops/run9/#")
    time.sleep(0.05)
    sink = BrokerSink("127.0.0.1", broker.port, "run9")
    sink.emit("train_metric", {"loss": 0.5})
    deadline = time.time() + 5
    while not got and time.time() < deadline:
        time.sleep(0.02)
    sink.close()
    sub.disconnect()
    broker.stop()
    assert got and got[0][0] == "fedml_mlops/run9/train_metric" and got[0][1]["loss"] == 0.5


class TestXLAProfilerCapture:
    def test_enable_profiler_writes_trace(self, tmp_path):
        """args.enable_profiler captures a TensorBoard-viewable XLA trace of
        the compiled round (the TPU-first half of the reference's profiler
        event reporting)."""
        import os

        import fedml_tpu
        from fedml_tpu.arguments import Arguments
        from fedml_tpu.simulation.xla.fed_sim import XLASimulator

        args = Arguments.from_dict({
            "common_args": {"training_type": "simulation", "random_seed": 0,
                            "run_id": "prof"},
            "data_args": {"dataset": "mnist", "data_cache_dir": "",
                          "partition_method": "homo", "synthetic_train_size": 128},
            "model_args": {"model": "lr"},
            "train_args": {"federated_optimizer": "FedAvg",
                           "client_num_in_total": 4, "client_num_per_round": 4,
                           "comm_round": 1, "epochs": 1, "batch_size": 16,
                           "client_optimizer": "sgd", "learning_rate": 0.1},
            "validation_args": {"frequency_of_the_test": 0},
            "comm_args": {"backend": "XLA"},
        }).validate()
        args.enable_profiler = True
        args.profiler_dir = str(tmp_path / "trace")
        args = fedml_tpu.init(args, should_init_logs=False)
        dataset, out_dim = fedml_tpu.data.load(args)
        model = fedml_tpu.models.create(args, out_dim)
        XLASimulator(args, dataset, model).train()
        dumped = []
        for root, _, files in os.walk(args.profiler_dir):
            dumped += [f for f in files if f.endswith((".pb", ".json.gz", ".xplane.pb"))]
        assert dumped, "no trace files captured"
