"""E2E secure-aggregation scenario tests (reference
smoke_test_cross_silo_lightsecagg_linux.yml analog, in-process)."""

from __future__ import annotations

import numpy as np
import jax

import fedml_tpu
from fedml_tpu.arguments import Arguments
from fedml_tpu.core.distributed.communication.loopback import LoopbackHub


def _args(run_id: str, n_clients: int = 3, rounds: int = 2):
    return Arguments.from_dict({
        "common_args": {"training_type": "cross_silo", "random_seed": 0, "run_id": run_id},
        "data_args": {"dataset": "synthetic", "data_cache_dir": "", "partition_method": "homo",
                      "synthetic_train_size": 240},
        "model_args": {"model": "lr"},
        "train_args": {
            "federated_optimizer": "FedAvg",
            "client_num_in_total": n_clients,
            "client_num_per_round": n_clients,
            "comm_round": rounds,
            "epochs": 1,
            "batch_size": 16,
            "client_optimizer": "sgd",
            "learning_rate": 0.1,
        },
        "validation_args": {"frequency_of_the_test": 1},
        "comm_args": {"backend": "LOOPBACK"},
    }).validate()


def _dataset_fn(args):
    return fedml_tpu.data.load(args)


def _model_fn(args, out_dim):
    return fedml_tpu.models.create(args, out_dim)


def test_secagg_cross_silo():
    LoopbackHub.reset()
    args = fedml_tpu.init(_args("sa-1"), should_init_logs=False)
    from fedml_tpu.cross_silo.secagg import run_secagg_topology_in_threads

    history = run_secagg_topology_in_threads(args, _dataset_fn, _model_fn)
    assert len(history) == 2
    assert history[-1]["test_acc"] > 0.2  # learns despite masking


def test_secagg_matches_plain_fedavg():
    """Masked aggregation must equal plain weighted FedAvg up to quantization."""
    LoopbackHub.reset()
    args = fedml_tpu.init(_args("sa-2", n_clients=2, rounds=1), should_init_logs=False)
    from fedml_tpu.cross_silo.secagg import run_secagg_topology_in_threads

    history = run_secagg_topology_in_threads(args, _dataset_fn, _model_fn)

    # plain SP FedAvg with identical config/seeds
    LoopbackHub.reset()
    args2 = fedml_tpu.init(_args("sa-2b", n_clients=2, rounds=1), should_init_logs=False)
    args2.training_type = "simulation"
    args2.backend = "sp"
    dataset, out_dim = fedml_tpu.data.load(args2)
    model = fedml_tpu.models.create(args2, out_dim)
    from fedml_tpu.simulation.sp.fedavg.fedavg_api import FedAvgAPI

    api = FedAvgAPI(args2, None, dataset, model)
    plain = api.train()
    # same data, same seed, same rounds -> accuracies should be very close
    assert abs(history[-1]["test_acc"] - plain["test_acc"]) < 0.05


def test_lightsecagg_no_dropout():
    LoopbackHub.reset()
    args = fedml_tpu.init(_args("lsa-1"), should_init_logs=False)
    args.lsa_privacy_t = 1
    args.lsa_threshold_u = 2
    from fedml_tpu.cross_silo.lightsecagg import run_lightsecagg_topology_in_threads

    history = run_lightsecagg_topology_in_threads(args, _dataset_fn, _model_fn)
    assert len(history) == 2
    assert history[-1]["test_acc"] > 0.2


def test_lightsecagg_with_dropout():
    """Client 2 drops after the sub-mask exchange; aggregation still completes
    from the surviving 2 of 3 clients (u=2)."""
    LoopbackHub.reset()
    args = fedml_tpu.init(_args("lsa-2", rounds=1), should_init_logs=False)
    args.lsa_privacy_t = 1
    args.lsa_threshold_u = 2
    from fedml_tpu.cross_silo.lightsecagg import run_lightsecagg_topology_in_threads

    history = run_lightsecagg_topology_in_threads(args, _dataset_fn, _model_fn, drop_ranks=[2])
    assert len(history) == 1
    assert history[-1]["test_acc"] > 0.15


def test_q_bits_bound_respects_signed_field():
    """The quantize-bits guard must bound n * 2^q by the SIGNED usable range
    (p-1)/2 ~ 2^30 — transform_finite_to_tensor decodes the upper half of the
    field as negatives, so a sum whose magnitude crosses half the field
    sign-flips silently.  For 2 clients (2-bit headroom) the limit is 28."""
    import pytest

    from fedml_tpu.cross_silo.secagg.sa_fedml_api import _check_q_bits

    assert _check_q_bits(28, 2) == 28
    with pytest.raises(ValueError):
        _check_q_bits(29, 2)  # would fit 31 bits but not the signed range
    # growing the cohort costs headroom bits
    assert _check_q_bits(23, 100) == 23
    with pytest.raises(ValueError):
        _check_q_bits(24, 100)
