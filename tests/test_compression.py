"""Update compression (core/compression.py — reference utils/compression.py:
NoneCompressor, TopK, EF-TopK, Quantization, QSGD) and its cross-silo
delta-upload wiring."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fedml_tpu.core.compression import (
    _MARKER,
    compress_update,
    decompress_update,
    is_compressed,
    maybe_decompress_update,
    qsgd_leaf,
    quantize_leaf,
    topk_k,
    topk_leaf,
    wire_bytes,
)


class TestLeafKernels:
    def test_topk_keeps_largest_magnitudes(self):
        x = jnp.asarray([0.1, -5.0, 0.2, 3.0, -0.05, 1.0])
        values, idx = topk_leaf(x, ratio=0.5)
        assert sorted(np.abs(np.asarray(values)).tolist(), reverse=True) == [5.0, 3.0, 1.0]
        assert set(np.asarray(idx).tolist()) == {1, 3, 5}

    def test_quantize_preserves_sign_and_bounds(self):
        x = jnp.asarray(np.random.RandomState(0).randn(100).astype(np.float32))
        q = quantize_leaf(x, bits=8)
        assert np.all(np.sign(q) * np.sign(x) >= 0)  # sign preserved (or zero)
        assert float(jnp.abs(q - x).max()) < float(jnp.linalg.norm(x)) / 255 + 1e-6

    def test_qsgd_unbiased_in_expectation(self):
        x = jnp.asarray(np.random.RandomState(1).randn(64).astype(np.float32))
        keys = jax.random.split(jax.random.PRNGKey(0), 600)
        qs = np.stack([np.asarray(qsgd_leaf(x, 4, k, is_biased=False)) for k in keys])
        np.testing.assert_allclose(qs.mean(axis=0), np.asarray(x), atol=0.08)


class TestPytreeAPI:
    def _tree(self):
        rng = np.random.RandomState(0)
        return {"layer": {"kernel": jnp.asarray(rng.randn(8, 4), jnp.float32),
                          "bias": jnp.asarray(rng.randn(4), jnp.float32)}}

    @pytest.mark.parametrize("method", ["none", "topk", "quantize", "qsgd"])
    def test_roundtrip_structure(self, method):
        import pickle

        tree = self._tree()
        payload, _ = compress_update(tree, method, ratio=0.25, bits=8,
                                     key=jax.random.PRNGKey(0))
        assert is_compressed(payload)
        out = maybe_decompress_update(pickle.loads(pickle.dumps(payload)))
        assert (jax.tree_util.tree_structure(out)
                == jax.tree_util.tree_structure(tree))
        for a, b in zip(jax.tree_util.tree_leaves(out), jax.tree_util.tree_leaves(tree)):
            assert a.shape == b.shape

    def test_error_feedback_recovers_dropped_mass(self):
        """EF property: the sum of transmitted updates converges to the sum
        of true updates (dropped mass is carried forward, not lost)."""
        rng = np.random.RandomState(0)
        true_sum = np.zeros(50, np.float32)
        sent_sum = np.zeros(50, np.float32)
        residuals = None
        for t in range(30):
            update = {"w": jnp.asarray(rng.randn(50), jnp.float32)}
            true_sum += np.asarray(update["w"])
            payload, residuals = compress_update(update, "eftopk", ratio=0.2,
                                                 residuals=residuals)
            sent_sum += np.asarray(decompress_update(payload)["w"])
        # residual = exactly the gap between truth and what was transmitted
        gap = true_sum - sent_sum
        np.testing.assert_allclose(gap, np.asarray(residuals["w"]), atol=1e-4)
        # and it stays bounded (mass is carried, not accumulated unboundedly)
        assert np.abs(gap).max() < 6.0

    def test_plain_topk_drops_mass(self):
        update = {"w": jnp.asarray(np.arange(1, 11, dtype=np.float32))}
        payload, res = compress_update(update, "topk", ratio=0.2)
        assert res is None
        out = decompress_update(payload)
        assert float(out["w"].sum()) == 10.0 + 9.0  # only the top 2 survive


class TestTopkKBoundaries:
    """Pins for the half-up k rule ``max(1, int(ratio*n + 0.5))``.

    The edge tier's codec negotiation prices a top-k forward from this
    exact k, so the rule is part of the wire contract: banker's rounding
    (``int(round(...))``) would keep a DIFFERENT fraction of .5-boundary
    leaves depending on parity and platform."""

    @pytest.mark.parametrize("ratio,n,expected", [
        (0.5, 1, 1),
        (0.5, 3, 2),
        (0.5, 5, 3),      # round(2.5) == 2 under banker's — the pin
        (0.05, 50, 3),    # round(2.5) again, at the default ratio
        (0.05, 10, 1),
        (0.1, 100, 10),
        (0.001, 100, 1),  # never below one entry
        (1.0, 7, 7),
    ])
    def test_half_up_boundary_pins(self, ratio, n, expected):
        assert topk_k(ratio, n) == expected

    def test_monotone_in_both_arguments(self):
        ks = [topk_k(0.3, n) for n in range(1, 200)]
        assert ks == sorted(ks)
        ks = [topk_k(r, 97) for r in np.linspace(0.01, 1.0, 50)]
        assert ks == sorted(ks)

    def test_topk_leaf_keeps_exactly_k(self):
        for n in (1, 3, 5, 17, 64):
            x = jnp.asarray(np.random.RandomState(n).randn(n), jnp.float32)
            values, idx = topk_leaf(x, ratio=0.5)
            assert values.shape[0] == idx.shape[0] == topk_k(0.5, n)

    def test_indices_stay_int32_below_the_guard(self):
        """The int64 top-k index guard: normal leaves ship the narrow
        dtype (half the index bytes); only leaves past 2^31-1 entries
        widen — and ``wire_bytes`` prices whichever dtype actually rode."""
        _, idx = topk_leaf(jnp.arange(100, dtype=jnp.float32), ratio=0.1)
        assert np.asarray(idx).dtype == np.int32
        # a hand-built wide-index payload is billed at 8 bytes per index
        narrow = {_MARKER: "topk", "treedef": None, "leaves": [
            (np.ones(4, np.float32), np.arange(4, dtype=np.int32), (8,),
             "float32")]}
        wide = {_MARKER: "topk", "treedef": None, "leaves": [
            (np.ones(4, np.float32), np.arange(4, dtype=np.int64), (8,),
             "float32")]}
        assert wire_bytes(narrow) == 4 * 4 + 4 * 4
        assert wire_bytes(wide) == 4 * 4 + 4 * 8


class TestRoundTripProperties:
    """Scheme-by-scheme round-trip laws plus the ``wire_bytes`` honesty
    contract the hierarchy's codec negotiation depends on."""

    def _tree(self, seed=0):
        rng = np.random.RandomState(seed)
        return {"layer": {"kernel": jnp.asarray(rng.randn(32, 16), jnp.float32),
                          "bias": jnp.asarray(rng.randn(16), jnp.float32)}}

    def _dense_bytes(self, tree):
        return sum(np.asarray(l).size * np.asarray(l).dtype.itemsize
                   for l in jax.tree_util.tree_leaves(tree))

    def test_none_is_lossless_and_full_price(self):
        tree = self._tree()
        payload, res = compress_update(tree, "none")
        assert res is None
        out = decompress_update(payload)
        for a, b in zip(jax.tree_util.tree_leaves(out),
                        jax.tree_util.tree_leaves(tree)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert wire_bytes(payload) == self._dense_bytes(tree)
        # raw pytrees price the same as their 'none' wrapping
        assert wire_bytes(tree) == wire_bytes(payload)

    @pytest.mark.parametrize("method", ["topk", "eftopk"])
    @pytest.mark.parametrize("ratio", [0.05, 0.25, 0.5, 1.0])
    def test_topk_exact_on_survivors_zero_elsewhere(self, method, ratio):
        tree = self._tree(1)
        payload, _ = compress_update(tree, method, ratio=ratio)
        out = decompress_update(payload)
        for a, b in zip(jax.tree_util.tree_leaves(out),
                        jax.tree_util.tree_leaves(tree)):
            a, b = np.asarray(a), np.asarray(b)
            kept = a != 0
            # survivors are bit-exact, everything else is exactly zero
            np.testing.assert_array_equal(a[kept], b[kept])
            k = topk_k(ratio, b.size)
            assert kept.sum() == k
            # and the survivors really are the top-k magnitudes
            if (~kept).any():
                assert np.abs(b[kept]).min() >= np.abs(b[~kept]).max()

    @pytest.mark.parametrize("ratio", [0.05, 0.25, 0.5])
    def test_topk_wire_bytes_scale_with_k(self, ratio):
        tree = self._tree(2)
        payload, _ = compress_update(tree, "topk", ratio=ratio)
        expected = sum(
            topk_k(ratio, np.asarray(l).size) * (4 + 4)  # f32 value + i32 idx
            for l in jax.tree_util.tree_leaves(tree))
        assert wire_bytes(payload) == expected
        # at ratio 0.5 the 4-byte index per 4-byte value exactly ties the
        # dense price — the break-even the codec negotiation must see
        if ratio < 0.5:
            assert wire_bytes(payload) < self._dense_bytes(tree)
        else:
            assert wire_bytes(payload) == self._dense_bytes(tree)

    @pytest.mark.parametrize("method", ["quantize", "qsgd"])
    def test_quantized_bounded_error_dense_price(self, method):
        tree = self._tree(3)
        payload, res = compress_update(tree, method, bits=8,
                                       key=jax.random.PRNGKey(7))
        assert res is None
        out = decompress_update(payload)
        for a, b in zip(jax.tree_util.tree_leaves(out),
                        jax.tree_util.tree_leaves(tree)):
            a, b = np.asarray(a), np.asarray(b)
            norm = np.linalg.norm(b.reshape(-1))
            # one quantization level of error, norm-scaled (qsgd's biased
            # scale only shrinks magnitudes, never grows the error bound)
            assert np.abs(a - b).max() <= norm / 255 + norm + 1e-6
            assert np.all(np.sign(a) * np.sign(b) >= 0)
        assert wire_bytes(payload) == self._dense_bytes(tree)

    def test_qsgd_reproducible_under_same_key(self):
        tree = self._tree(4)
        p1, _ = compress_update(tree, "qsgd", key=jax.random.PRNGKey(11))
        p2, _ = compress_update(tree, "qsgd", key=jax.random.PRNGKey(11))
        for a, b in zip(p1["leaves"], p2["leaves"]):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_decompress_rejects_unknown_scheme(self):
        with pytest.raises(ValueError, match="unknown compression"):
            compress_update(self._tree(), "gzip")
        with pytest.raises(ValueError, match="unknown compression"):
            decompress_update({_MARKER: "gzip", "treedef": None,
                               "leaves": []})
        with pytest.raises(ValueError, match="unknown compression"):
            wire_bytes({_MARKER: "gzip", "leaves": []})


@pytest.mark.heavy
class TestCrossSiloCompressed:
    def test_eftopk_round_trip_over_loopback(self):
        import fedml_tpu  # noqa: F401  (import order: init singletons)
        from tests.test_cross_silo import _run_topology
        from fedml_tpu.core.distributed.communication.loopback import LoopbackHub

        LoopbackHub.reset()
        history = _run_topology("LOOPBACK", "cs-comp",
                                comm_extra={"compression": "eftopk",
                                            "compression_ratio": 0.3})
        assert history, "no eval rounds recorded"
        assert 0.0 <= history[-1]["test_acc"] <= 1.0
        # compression must not break learning on a separable problem
        assert history[-1]["test_acc"] > 0.5
