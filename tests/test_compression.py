"""Update compression (core/compression.py — reference utils/compression.py:
NoneCompressor, TopK, EF-TopK, Quantization, QSGD) and its cross-silo
delta-upload wiring."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fedml_tpu.core.compression import (
    compress_update,
    decompress_update,
    is_compressed,
    maybe_decompress_update,
    qsgd_leaf,
    quantize_leaf,
    topk_leaf,
)


class TestLeafKernels:
    def test_topk_keeps_largest_magnitudes(self):
        x = jnp.asarray([0.1, -5.0, 0.2, 3.0, -0.05, 1.0])
        values, idx = topk_leaf(x, ratio=0.5)
        assert sorted(np.abs(np.asarray(values)).tolist(), reverse=True) == [5.0, 3.0, 1.0]
        assert set(np.asarray(idx).tolist()) == {1, 3, 5}

    def test_quantize_preserves_sign_and_bounds(self):
        x = jnp.asarray(np.random.RandomState(0).randn(100).astype(np.float32))
        q = quantize_leaf(x, bits=8)
        assert np.all(np.sign(q) * np.sign(x) >= 0)  # sign preserved (or zero)
        assert float(jnp.abs(q - x).max()) < float(jnp.linalg.norm(x)) / 255 + 1e-6

    def test_qsgd_unbiased_in_expectation(self):
        x = jnp.asarray(np.random.RandomState(1).randn(64).astype(np.float32))
        keys = jax.random.split(jax.random.PRNGKey(0), 600)
        qs = np.stack([np.asarray(qsgd_leaf(x, 4, k, is_biased=False)) for k in keys])
        np.testing.assert_allclose(qs.mean(axis=0), np.asarray(x), atol=0.08)


class TestPytreeAPI:
    def _tree(self):
        rng = np.random.RandomState(0)
        return {"layer": {"kernel": jnp.asarray(rng.randn(8, 4), jnp.float32),
                          "bias": jnp.asarray(rng.randn(4), jnp.float32)}}

    @pytest.mark.parametrize("method", ["none", "topk", "quantize", "qsgd"])
    def test_roundtrip_structure(self, method):
        import pickle

        tree = self._tree()
        payload, _ = compress_update(tree, method, ratio=0.25, bits=8,
                                     key=jax.random.PRNGKey(0))
        assert is_compressed(payload)
        out = maybe_decompress_update(pickle.loads(pickle.dumps(payload)))
        assert (jax.tree_util.tree_structure(out)
                == jax.tree_util.tree_structure(tree))
        for a, b in zip(jax.tree_util.tree_leaves(out), jax.tree_util.tree_leaves(tree)):
            assert a.shape == b.shape

    def test_error_feedback_recovers_dropped_mass(self):
        """EF property: the sum of transmitted updates converges to the sum
        of true updates (dropped mass is carried forward, not lost)."""
        rng = np.random.RandomState(0)
        true_sum = np.zeros(50, np.float32)
        sent_sum = np.zeros(50, np.float32)
        residuals = None
        for t in range(30):
            update = {"w": jnp.asarray(rng.randn(50), jnp.float32)}
            true_sum += np.asarray(update["w"])
            payload, residuals = compress_update(update, "eftopk", ratio=0.2,
                                                 residuals=residuals)
            sent_sum += np.asarray(decompress_update(payload)["w"])
        # residual = exactly the gap between truth and what was transmitted
        gap = true_sum - sent_sum
        np.testing.assert_allclose(gap, np.asarray(residuals["w"]), atol=1e-4)
        # and it stays bounded (mass is carried, not accumulated unboundedly)
        assert np.abs(gap).max() < 6.0

    def test_plain_topk_drops_mass(self):
        update = {"w": jnp.asarray(np.arange(1, 11, dtype=np.float32))}
        payload, res = compress_update(update, "topk", ratio=0.2)
        assert res is None
        out = decompress_update(payload)
        assert float(out["w"].sum()) == 10.0 + 9.0  # only the top 2 survive


@pytest.mark.heavy
class TestCrossSiloCompressed:
    def test_eftopk_round_trip_over_loopback(self):
        import fedml_tpu  # noqa: F401  (import order: init singletons)
        from tests.test_cross_silo import _run_topology
        from fedml_tpu.core.distributed.communication.loopback import LoopbackHub

        LoopbackHub.reset()
        history = _run_topology("LOOPBACK", "cs-comp",
                                comm_extra={"compression": "eftopk",
                                            "compression_ratio": 0.3})
        assert history, "no eval rounds recorded"
        assert 0.0 <= history[-1]["test_acc"] <= 1.0
        # compression must not break learning on a separable problem
        assert history[-1]["test_acc"] > 0.5
