"""tools/lint_perf.py wired into tier-1: with the staged ingest pipeline
in place (PR 10), per-record ``os.fsync`` belongs to the checkpoint
durability seam and msgpack (de)serialization to the journal framer and
the zero-copy decoder — and the linter itself must actually catch
violations, because a lint that can't fail is not a gate."""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))

import lint_perf


def test_library_tree_is_clean():
    """The machine-enforced contract: no hot path pays a private fsync or
    a dispatcher-thread msgpack codec outside the seams."""
    assert lint_perf.main([]) == 0


def test_catches_stray_fsync_and_hot_codec(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import os\n"
        "from flax.serialization import msgpack_restore\n"
        "def persist(f, blob):\n"
        "    f.write(blob)\n"
        "    os.fsync(f.fileno())\n"
        "    return msgpack_restore(blob)\n"
    )
    violations = lint_perf.lint_file(str(bad))
    assert [(lineno, kind) for _, lineno, kind, _ in violations] == [
        (5, "per-record fsync outside the durability seam"),
        (6, "hot-path msgpack codec outside the seams"),
    ]
    assert lint_perf.main(["--root", str(tmp_path)]) == 1


def test_catches_raw_msgpack_module_calls(tmp_path):
    f = tmp_path / "codec.py"
    f.write_text(
        "import msgpack\n"
        "def decode(blob):\n"
        "    return msgpack.unpackb(blob, raw=False)\n"
        "def encode(tree):\n"
        "    return msgpack.packb(tree)\n"
        "def serialize(tree):\n"
        "    return msgpack_serialize(tree)\n"
    )
    kinds = [kind for _, _, kind, _ in lint_perf.lint_file(str(f))]
    assert kinds == ["hot-path msgpack codec outside the seams"] * 3


def test_pragma_allows_approved_seam(tmp_path):
    f = tmp_path / "seam.py"
    f.write_text(
        "import os\n"
        "def sync(f):\n"
        "    os.fsync(f.fileno())  # lint_perf: allow\n"
    )
    assert lint_perf.lint_file(str(f)) == []
    assert lint_perf.main(["--root", str(tmp_path)]) == 0


def test_seam_owners_are_exempt(tmp_path):
    # checkpoint (durability + framing), ingest (zero-copy decode) and
    # core/obs (export file integrity) ARE the seams
    body = ("import os, msgpack\n"
            "def go(f, blob):\n"
            "    os.fsync(f.fileno())\n"
            "    return msgpack.unpackb(blob)\n")
    obs_dir = tmp_path / "core" / "obs"
    obs_dir.mkdir(parents=True)
    for rel in (("core", "checkpoint.py"), ("core", "ingest.py"),
                ("core", "obs", "flight.py")):
        f = tmp_path.joinpath(*rel)
        f.write_text(body)
        assert lint_perf.lint_file(str(f)) == []
    assert lint_perf.main(["--root", str(tmp_path)]) == 0


def test_docstrings_and_comments_do_not_false_positive(tmp_path):
    f = tmp_path / "prose.py"
    f.write_text(
        '"""Never call os.fsync(...) per record; msgpack_restore(blob) is\n'
        'reserved for the checkpoint seam."""\n'
        "# the old code ran os.fsync() and msgpack.unpackb() right here\n"
        "MSG = 'route decodes through ZeroCopyDecoder, not msgpack_restore(b)'\n"
    )
    assert lint_perf.lint_file(str(f)) == []


def test_lookalike_names_are_not_flagged(tmp_path):
    f = tmp_path / "good.py"
    f.write_text(
        "def my_os_fsync(fd):\n"
        "    pass\n"
        "def run(self, blob):\n"
        "    self.os.fsync = None\n"          # attribute chain, not os.fsync
        "    tree = self.msgpack_restore(blob)\n"  # method, not the codec
        "    return my_os_fsync(0)\n"
    )
    assert lint_perf.lint_file(str(f)) == []
