"""Backdoor / edge-case backdoor / DLG attacks and Soteria / WBC defenses
(reference ``core/security/{attack,defense}``), including paired tests that a
defense measurably reduces its paired attack's effect."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.core.security import attack_funcs as A
from fedml_tpu.core.security import defense_funcs as F
from fedml_tpu.core.security.fedml_attacker import FedMLAttacker
from fedml_tpu.core.security.fedml_defender import FedMLDefender


class _Args:
    def __init__(self, **kw):
        for k, v in kw.items():
            setattr(self, k, v)


def _tiny_updates(key, n=8, dim=6, spread=0.01):
    """n benign updates clustered around ones."""
    keys = jax.random.split(key, n)
    return [
        (10.0, {"params": {"dense": {"kernel": jnp.ones((dim,)) + spread * jax.random.normal(k, (dim,))}}})
        for k in keys
    ]


def _kernel(update):
    return update["params"]["dense"]["kernel"]


class TestBackdoorAttack:
    def test_pattern_stamps_and_relabels(self):
        x = jnp.zeros((10, 8, 8, 3))
        y = jnp.arange(10) % 5 + 1
        px, py = A.poison_backdoor(x, y, target_class=0, fraction=0.5,
                                   key=jax.random.PRNGKey(0), size=3, value=2.8)
        poisoned = np.flatnonzero(np.asarray(py) == 0)
        assert len(poisoned) == 5
        for i in poisoned:
            assert float(px[i, 0, 0, 0]) == pytest.approx(2.8)
        clean = np.flatnonzero(np.asarray(py) != 0)
        for i in clean:
            assert float(jnp.abs(px[i]).max()) == 0.0

    def test_alie_stays_in_range_but_biases(self):
        updates = _tiny_updates(jax.random.PRNGKey(1))
        attacked = A.alie_attack(updates, [0, 1], num_std=1.5)
        benign = jnp.stack([_kernel(p) for _, p in updates[2:]])
        mal = _kernel(attacked[0][1])
        mean, std = benign.mean(0), benign.std(0)
        # inside mean +/- 2*std of the benign cloud (evades range checks) ...
        assert bool(jnp.all(jnp.abs(mal - mean) <= 2.0 * std + 1e-6))
        # ... but consistently below the mean (the bias direction)
        assert bool(jnp.all(mal <= mean))

    def test_alie_clip_mode_bounds_poisoned_update(self):
        updates = _tiny_updates(jax.random.PRNGKey(6))
        # malicious client 0 trained a wildly poisoned update
        n0, p0 = updates[0]
        updates[0] = (n0, jax.tree_util.tree_map(lambda t: t + 100.0, p0))
        attacked = A.alie_attack(updates, [0], num_std=1.5, mode="clip")
        benign = jnp.stack([_kernel(p) for _, p in updates[1:]])
        mean, std = benign.mean(0), benign.std(0)
        mal = _kernel(attacked[0][1])
        assert bool(jnp.all(mal <= mean + 1.5 * std + 1e-6))
        # benign clients untouched
        np.testing.assert_allclose(
            np.asarray(_kernel(attacked[3][1])), np.asarray(_kernel(updates[3][1]))
        )

    def test_alie_shifts_mean_vs_trimmed_mean_recovers(self):
        """Paired: coordinate-wise trimmed mean cuts an aggressive (z=3) ALIE
        pair's pull on the average.  (At small z ALIE sits inside the benign
        cloud and evades selection defenses — that is the attack's point.)"""
        updates = _tiny_updates(jax.random.PRNGKey(2), n=8)
        attacked = A.alie_attack(updates, [0, 1], num_std=3.0)
        benign_mean = jnp.stack([_kernel(p) for _, p in updates[2:]]).mean(0)
        naive_mean = jnp.stack([_kernel(p) for _, p in attacked]).mean(0)
        def_mean = _kernel(F.coordinate_wise_trimmed_mean(attacked, 0.25))
        assert float(jnp.linalg.norm(def_mean - benign_mean)) < float(
            jnp.linalg.norm(naive_mean - benign_mean)
        )


class TestEdgeCaseBackdoor:
    def test_selects_low_confidence_tail(self):
        logits = jnp.array([[9.0, 0.0], [0.1, 0.0], [5.0, 0.0], [0.2, 0.1]])
        idx = np.asarray(A.select_edge_cases(logits, fraction=0.5))
        assert set(idx.tolist()) == {1, 3}

    def test_poison_edge_cases_relabels_only_tail(self):
        x = jnp.zeros((4, 2))
        y = jnp.array([0, 0, 0, 0])
        logits = jnp.array([[9.0, 0.0], [0.1, 0.0], [5.0, 0.0], [0.2, 0.1]])
        _, py = A.poison_edge_cases(x, y, logits, target_class=1, fraction=0.5)
        assert np.asarray(py).tolist() == [0, 1, 0, 1]

    def test_projection_evades_naive_norm_check_but_clipping_defends(self):
        """Paired: scaled push projected into the eps-ball passes a norm gate;
        norm_diff_clipping still shrinks its effect on the average."""
        updates = _tiny_updates(jax.random.PRNGKey(3), n=4)
        # global model at the benign cluster center: benign deltas are tiny
        g = jax.tree_util.tree_map(jnp.ones_like, updates[0][1])
        pushed = A.model_replacement(updates[0][1], g, scale=50.0)
        proj = A.project_to_norm_ball(pushed, g, eps=3.0)
        d = jnp.linalg.norm(_kernel(proj) - _kernel(g))
        assert float(d) <= 3.0 + 1e-4
        attacked = [(updates[0][0], proj)] + updates[1:]
        benign_mean = jnp.stack([_kernel(p) for _, p in updates[1:]]).mean(0)
        naive_mean = jnp.stack([_kernel(p) for _, p in attacked]).mean(0)
        clipped = F.norm_diff_clipping(attacked, g, norm_bound=0.1)
        def_mean = jnp.stack([_kernel(p) for _, p in clipped]).mean(0)
        assert float(jnp.linalg.norm(def_mean - benign_mean)) < float(
            jnp.linalg.norm(naive_mean - benign_mean)
        )

    def test_poison_local_data_only_for_malicious(self):
        att = FedMLAttacker.get_instance()
        att.init(_Args(enable_attack=True, attack_type="backdoor",
                       byzantine_client_num=2, target_class=0,
                       poison_fraction=1.0, random_seed=0))
        bad = set(att.get_byzantine_idxs(8))
        good = next(i for i in range(8) if i not in bad)
        x = jnp.zeros((6, 8, 8, 3))
        y = jnp.ones((6,), jnp.int32)
        bx, by = att.poison_local_data(next(iter(bad)), 8, x, y)
        assert np.asarray(by).tolist() == [0] * 6  # relabeled
        assert float(jnp.abs(bx).max()) > 0  # trigger stamped
        gx, gy = att.poison_local_data(good, 8, x, y)
        assert np.asarray(gy).tolist() == [1] * 6
        assert float(jnp.abs(gx).max()) == 0.0

    def test_attacker_dispatch_edge_case(self):
        att = FedMLAttacker.get_instance()
        att.init(_Args(enable_attack=True, attack_type="edge_case_backdoor",
                       byzantine_client_num=1, attack_scale=50.0,
                       attack_norm_bound=2.0, random_seed=0))
        updates = _tiny_updates(jax.random.PRNGKey(4), n=4)
        g = jax.tree_util.tree_map(jnp.zeros_like, updates[0][1])
        out = att.attack_model(updates, g)
        idx = att.get_byzantine_idxs(4)[0]
        d = jnp.linalg.norm(_kernel(out[idx][1]) - _kernel(g))
        assert float(d) <= 2.0 + 1e-4


class _TinyNet(nn.Module):
    features: int = 8
    classes: int = 4

    def setup(self):
        self.fc1 = nn.Dense(self.features)
        self.classifier = nn.Dense(self.classes)

    def representation(self, x):
        h = x.reshape((x.shape[0], -1)) if x.ndim > 2 else x
        return nn.relu(self.fc1(h))

    def __call__(self, x, train: bool = False):
        return self.classifier(self.representation(x))


class TestDLGAndSoteria:
    def _setup(self):
        model = _TinyNet()
        x = jax.random.normal(jax.random.PRNGKey(5), (2, 6))
        y = jnp.array([1, 3])
        variables = model.init(jax.random.PRNGKey(0), x)
        return model, dict(variables), x, y

    def _client_step(self, model, variables, x, y, lr=0.1):
        import optax

        def loss(params):
            logits = model.apply(dict(variables, params=params), x)
            return jnp.mean(
                optax.softmax_cross_entropy_with_integer_labels(logits, y)
            )

        g = jax.grad(loss)(variables["params"])
        new = jax.tree_util.tree_map(lambda p, gr: p - lr * gr, variables["params"], g)
        return dict(variables, params=new)

    def test_dlg_reconstructs_better_than_noise(self):
        model, variables, x, y = self._setup()
        client = self._client_step(model, variables, x, y)
        x_rec, _ = A.dlg_attack(model, variables, client, x.shape, 4,
                                jax.random.PRNGKey(7), lr_client=0.1,
                                steps=300, lr_attack=0.05)
        base = jax.random.normal(jax.random.PRNGKey(8), x.shape)

        def best_match_mse(rec):
            # permutation-invariant: best assignment of reconstructed rows
            d = jnp.sum((rec[:, None, :] - x[None, :, :]) ** 2, axis=-1)
            return float(jnp.minimum(
                d[0, 0] + d[1, 1], d[0, 1] + d[1, 0]
            )) / x.size

        assert best_match_mse(x_rec) < best_match_mse(base)

    def test_soteria_degrades_dlg_reconstruction(self):
        """Paired: pruning the representation-layer delta raises DLG error."""
        model, variables, x, y = self._setup()
        client = self._client_step(model, variables, x, y)

        defender = FedMLDefender.get_instance()
        defender.init(_Args(enable_defense=True, defense_type="soteria",
                            soteria_percentile=75.0,
                            soteria_layer=("fc1", "kernel"), random_seed=0))
        defender.register_soteria_probe(
            lambda xi: model.apply(variables, xi[None], method=_TinyNet.representation)[0],
            x,
        )
        defended = defender.defend_before_aggregation([(2.0, client)], variables)
        x_def, _ = A.dlg_attack(model, variables, defended[0][1], x.shape, 4,
                                jax.random.PRNGKey(7), lr_client=0.1,
                                steps=300, lr_attack=0.05)
        x_rec, _ = A.dlg_attack(model, variables, client, x.shape, 4,
                                jax.random.PRNGKey(7), lr_client=0.1,
                                steps=300, lr_attack=0.05)
        mse_plain = float(jnp.mean((x_rec - x) ** 2))
        mse_def = float(jnp.mean((x_def - x) ** 2))
        assert mse_def > mse_plain

    def test_attacker_reconstruct_dispatch(self):
        model, variables, x, y = self._setup()
        client = self._client_step(model, variables, x, y)
        att = FedMLAttacker.get_instance()
        att.init(_Args(enable_attack=True, attack_type="dlg", random_seed=0,
                       learning_rate=0.1, dlg_steps=50, dlg_lr=0.05))
        rec = att.reconstruct_data(model, variables, client, x.shape, 4)
        assert rec is not None and rec[0].shape == x.shape


class TestWBC:
    def test_perturbs_only_persistent_space(self):
        key = jax.random.PRNGKey(9)
        prev = {"w": jnp.zeros((6,))}
        # coords 0-2 moved a lot since last round; 3-5 barely moved
        update = {"w": jnp.array([5.0, -4.0, 6.0, 1e-4, -1e-4, 0.0])}
        out = F.wbc_perturb(update, prev, key, strength=1.0, lr=0.1)
        moved = np.asarray(out["w"]) - np.asarray(update["w"])
        assert np.allclose(moved[:3], 0.0)  # fast coords untouched
        assert np.any(moved[3:] != 0.0)  # persistent space perturbed

    def test_defender_dispatch_stateful(self):
        defender = FedMLDefender.get_instance()
        defender.init(_Args(enable_defense=True, defense_type="wbc",
                            wbc_strength=1.0, wbc_lr=0.1, random_seed=0))
        u1 = _tiny_updates(jax.random.PRNGKey(10), n=3)
        g = jax.tree_util.tree_map(jnp.zeros_like, u1[0][1])
        out1 = defender.defend_before_aggregation(u1, g)
        # round 1: no history yet -> passthrough
        for (_, a), (_, b) in zip(u1, out1):
            assert np.allclose(np.asarray(_kernel(a)), np.asarray(_kernel(b)))
        u2 = _tiny_updates(jax.random.PRNGKey(11), n=3)
        out2 = defender.defend_before_aggregation(u2, g)
        changed = any(
            not np.allclose(np.asarray(_kernel(a)), np.asarray(_kernel(b)))
            for (_, a), (_, b) in zip(u2, out2)
        )
        assert changed  # round 2: perturbation active

    def test_wbc_bounds_hidden_poison_persistence(self):
        """Paired: a small persistent poison (hiding in slow coordinates) is
        disrupted by WBC noise while large benign motion is preserved."""
        key = jax.random.PRNGKey(12)
        prev = {"w": jnp.ones((100,))}
        poison = jnp.zeros((100,)).at[:50].set(1e-6)  # persistent tiny push
        update = {"w": prev["w"] + poison}
        out = F.wbc_perturb(update, prev, key, strength=1.0, lr=0.1)
        # the poisoned (slow) coords get noise of magnitude >> the poison
        delta = np.abs(np.asarray(out["w"]) - np.asarray(update["w"]))[:50]
        assert np.median(delta) > 1e-3


class TestSoteriaMask:
    def test_mask_zeros_low_sensitivity(self):
        scores = jnp.array([0.1, 5.0, 3.0, 0.2, 9.0])
        mask = F.soteria_mask(scores, percentile=40.0)
        assert np.asarray(mask).tolist() == [0.0, 1.0, 1.0, 0.0, 1.0]

    def test_apply_masks_only_target_layer(self):
        g = {"params": {"fc1": {"kernel": jnp.zeros((2, 3))},
                        "classifier": {"kernel": jnp.ones((3, 2))}}}
        u = {"params": {"fc1": {"kernel": jnp.ones((2, 3))},
                        "classifier": {"kernel": 2.0 * jnp.ones((3, 2))}}}
        mask = jnp.array([1.0, 0.0, 1.0])
        out = F.soteria_apply(u, g, mask, ("fc1", "kernel"))
        # feature axis (last) masked on the defended layer's delta
        np.testing.assert_allclose(
            np.asarray(out["params"]["fc1"]["kernel"])[0], [1.0, 0.0, 1.0]
        )
        np.testing.assert_allclose(np.asarray(out["params"]["classifier"]["kernel"]), 2.0)


class TestRevealLabelsHeadPath:
    """reveal_labels_from_update's explicit head_path (mirroring the
    defender-side soteria_layer knob): at >= 10 layers the lexicographic
    flatten order puts Dense_10 before Dense_2, so the 'last bias' heuristic
    stops pointing at the output layer — the attack needs the head named."""

    NUM_CLASSES = 10
    LR = 0.1

    def _eleven_layer_update(self):
        """Params for Dense_0..Dense_10 where BOTH Dense_5 and Dense_10 have
        (10,)-shaped biases, and a client update whose head-bias gradient is
        negative exactly for classes {2, 7}; the decoy Dense_5 bias moves
        negative for classes {0, 1} instead."""
        rng = np.random.RandomState(0)
        widths = [32, 28, 24, 20, 16, self.NUM_CLASSES, 18, 14, 12, 16,
                  self.NUM_CLASSES]  # Dense_5 is the decoy, Dense_10 the head
        params, update = {}, {}
        in_dim = 8
        for i, w in enumerate(widths):
            name = f"Dense_{i}"
            kernel = rng.randn(in_dim, w).astype(np.float32)
            bias = rng.randn(w).astype(np.float32)
            k_grad = 0.01 * rng.randn(in_dim, w).astype(np.float32)
            b_grad = np.abs(rng.randn(w)).astype(np.float32) * 0.1 + 0.01
            if i == 10:  # head: present classes have NEGATIVE bias grad
                b_grad[[2, 7]] = -0.5
            if i == 5:  # decoy points the heuristic at the wrong classes
                b_grad[[0, 1]] = -0.5
            params[name] = {"kernel": kernel, "bias": bias}
            update[name] = {"kernel": kernel - self.LR * k_grad,
                            "bias": bias - self.LR * b_grad}
            in_dim = w
        return {"params": params}, {"params": update}

    def test_explicit_head_path_recovers_labels(self):
        variables, update = self._eleven_layer_update()
        for head in (("Dense_10", "bias"), "Dense_10/bias"):  # tuple or "/"-joined
            order, present = A.reveal_labels_from_update(
                variables, update, self.NUM_CLASSES, lr_client=self.LR,
                head_path=head)
            assert sorted(np.asarray(order)[:2].tolist()) == [2, 7]
            assert np.asarray(present).nonzero()[0].tolist() == [2, 7]

    def test_heuristic_is_fooled_at_eleven_layers(self):
        """Documents WHY the knob exists: on the same model the fallback
        heuristic lands on the decoy layer and names the wrong classes."""
        variables, update = self._eleven_layer_update()
        _, present = A.reveal_labels_from_update(
            variables, update, self.NUM_CLASSES, lr_client=self.LR)
        assert np.asarray(present).nonzero()[0].tolist() == [0, 1]

    def test_bad_head_path_raises(self):
        variables, update = self._eleven_layer_update()
        with pytest.raises(ValueError, match="not found"):
            A.reveal_labels_from_update(variables, update, self.NUM_CLASSES,
                                        head_path=("Dense_99", "bias"))
        with pytest.raises(ValueError, match="BIAS"):
            A.reveal_labels_from_update(variables, update, self.NUM_CLASSES,
                                        head_path=("Dense_10", "kernel"))
