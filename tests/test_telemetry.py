"""The cross-host telemetry plane (``fedml_tpu.core.obs.telemetry``).

Three strata, mirroring the plane's contract:

* **Unit** — EXACT sequence accounting on the client ring + server
  merger: a retransmitted message re-carries the same blob and dedups
  record-for-record; a dropped blob shows up as a counted gap (never a
  retry); ring overflow surfaces as a gap of exactly ``dropped_total``;
  a delayed blob arriving after the window passed is dropped as dups;
  garbage blobs count ``bad_blobs`` and never raise.
* **Graft** — remote span records re-emitted by the merger carry the
  same deterministic ids the live tracer would mint, so they land inside
  the locally reconstructed round tree (``remote: True``), and metric
  records merge as ``client``-labeled registry series.
* **Chaos** — the acceptance claim: the full drop + duplicate + delay +
  reset plan and a server kill + restart, run WITH telemetry enabled,
  converge to the bit-identical final model of a telemetry-off run, the
  merged trees still pass ``--assert-closed``, and the grafted
  client-side sub-spans are present.  Reuses the harnesses from
  ``test_fault_tolerance`` and ``test_obs``.

Plus golden-record coverage for the report side: ``Trace.clients()``
straggler classification (compute / network / deferred) and the
``trace_report --diff`` regression exit contract.
"""

from __future__ import annotations

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))

import trace_report

import test_fault_tolerance as _ft
import test_obs as _to
from fedml_tpu.core import obs
from fedml_tpu.core.distributed.communication.loopback import LoopbackHub
from fedml_tpu.core.distributed.communication.message import Message
from fedml_tpu.core.obs import MetricsRegistry, telemetry
from fedml_tpu.core.obs.telemetry import ClientTelemetry, TelemetryMerger
from fedml_tpu.core.obs.trace import round_root_ctx, span_id_for, trace_id_for


@pytest.fixture(autouse=True)
def _obs_hygiene():
    yield
    obs.shutdown()
    obs.registry().reset()


RUN = "tel-unit"


def _cap(node=1, capacity=telemetry.DEFAULT_RING_CAPACITY):
    return ClientTelemetry(node, RUN, capacity=capacity)


def _fill(cap, n, round_idx=0, start_seq=0):
    for i in range(n):
        cap.record_span(f"phase{i}", 0.01, round_idx=round_idx,
                        seq=start_seq + i)


def _upload(sender=1):
    return Message("send_model_to_server", sender, 0)


# ---------------------------------------------------------------------------
# Unit: exact sequence accounting
# ---------------------------------------------------------------------------

class TestExactAccounting:
    def test_attach_absorb_counts_every_record_once(self):
        cap = _cap()
        _fill(cap, 3)
        cap.record_counter("comm.bytes_sent", 100.0)
        cap.record_gauge("proc.rss_bytes", 1.0)
        assert cap.pending() == 5
        msg = _upload()
        nbytes = cap.attach(msg)
        assert nbytes > 0 and cap.pending() == 0
        assert cap.blobs_sent == 1 and cap.bytes_sent == nbytes
        merger = TelemetryMerger()
        assert merger.absorb(msg) == 5
        assert merger.counters() == {
            "telemetry_blobs_merged": 1,
            "telemetry_records_merged": 5,
            "telemetry_dup_records": 0,
            "telemetry_gap_records": 0,
            "telemetry_bad_blobs": 0,
            "telemetry_bytes_total": nbytes,
        }

    def test_retransmitted_message_dedups_record_for_record(self):
        # the retransmitter resends the SAME Message object, so the same
        # blob arrives twice: every record must be counted as a dup,
        # none applied twice
        cap = _cap()
        _fill(cap, 4)
        msg = _upload()
        cap.attach(msg)
        merger = TelemetryMerger()
        assert merger.absorb(msg) == 4
        assert merger.absorb(msg) == 0
        c = merger.counters()
        assert c["telemetry_blobs_merged"] == 2
        assert c["telemetry_records_merged"] == 4
        assert c["telemetry_dup_records"] == 4
        assert c["telemetry_gap_records"] == 0

    def test_dropped_blob_is_a_counted_gap_never_a_retry(self):
        cap = _cap()
        merger = TelemetryMerger()
        _fill(cap, 3)
        m1 = _upload()
        cap.attach(m1)
        assert merger.absorb(m1) == 3        # window now expects q=3
        _fill(cap, 2, start_seq=3)
        assert cap.drain() is not None        # this blob is "lost in flight"
        _fill(cap, 4, start_seq=5)
        m3 = _upload()
        cap.attach(m3)
        assert merger.absorb(m3) == 4
        c = merger.counters()
        assert c["telemetry_gap_records"] == 2   # exactly the lost blob
        assert c["telemetry_records_merged"] == 7
        assert c["telemetry_dup_records"] == 0

    def test_first_blob_seeds_the_window(self):
        # a drop BEFORE the merger has seen the node at all is invisible:
        # the first observed seq seeds the window, no false gap
        cap = _cap()
        _fill(cap, 3)
        assert cap.drain() is not None        # lost before first contact
        _fill(cap, 2, start_seq=3)
        msg = _upload()
        cap.attach(msg)
        merger = TelemetryMerger()
        assert merger.absorb(msg) == 2
        assert merger.counters()["telemetry_gap_records"] == 0

    def test_ring_overflow_accounts_exactly_as_gap(self):
        cap = _cap(capacity=4)
        merger = TelemetryMerger()
        _fill(cap, 2)
        m1 = _upload()
        cap.attach(m1)
        merger.absorb(m1)                     # window seeded, expects q=2
        _fill(cap, 6, start_seq=2)            # 2 records age out client-side
        assert cap.dropped_total == 2
        m2 = _upload()
        cap.attach(m2)
        assert merger.absorb(m2) == 4
        c = merger.counters()
        assert c["telemetry_gap_records"] == cap.dropped_total == 2
        assert c["telemetry_dup_records"] == 0

    def test_delayed_stale_blob_is_dropped_as_dups(self):
        # a delayed flush arriving AFTER a later piggyback already moved
        # the window is entirely behind it: dropped as dups, not applied
        cap = _cap()
        _fill(cap, 3)
        early = cap.drain()
        _fill(cap, 2, start_seq=3)
        late = _upload()
        cap.attach(late)
        merger = TelemetryMerger()
        merger.absorb(late)                   # q3-4 arrive first (seeds at 3)
        assert merger.merge(early) == 0       # q0-2 arrive delayed
        c = merger.counters()
        assert c["telemetry_dup_records"] == 3
        assert c["telemetry_records_merged"] == 2

    def test_bad_blob_counts_and_never_raises(self):
        merger = TelemetryMerger()
        assert merger.merge(b"\x00garbage") == 0
        assert merger.counters()["telemetry_bad_blobs"] == 1
        # a message with no blob, and one with a non-bytes payload
        assert merger.absorb(_upload()) == 0
        junk = _upload()
        junk.add_params(telemetry.TELEMETRY_KEY, "not-bytes")
        assert merger.absorb(junk) == 0
        assert merger.counters()["telemetry_blobs_merged"] == 0

    def test_interleaved_nodes_keep_independent_windows(self):
        a, b = _cap(node=1), _cap(node=2)
        merger = TelemetryMerger()
        for cap in (a, b):
            _fill(cap, 2)
            m = _upload(cap.node)
            cap.attach(m)
            assert merger.absorb(m) == 2
        # node 1 loses a blob; node 2 must not inherit the gap
        _fill(a, 2, start_seq=2)
        assert a.drain() is not None
        _fill(a, 1, start_seq=4)
        _fill(b, 3, start_seq=2)
        for cap in (a, b):
            m = _upload(cap.node)
            cap.attach(m)
            merger.absorb(m)
        assert merger.counters()["telemetry_gap_records"] == 2
        assert merger.counters()["telemetry_records_merged"] == 8

    def test_flush_message_contract(self):
        cap = _cap()
        assert cap.flush_message(1, 0) is None      # nothing pending
        _fill(cap, 3)
        assert cap.flush_due(0.0) is False           # piggyback-only mode
        assert cap.flush_due(3600.0) is False        # interval not elapsed
        assert cap.flush_due(1e-9) is True
        m = cap.flush_message(1, 0)
        assert m is not None and m.get_type() == telemetry.TOPIC_TELEMETRY
        # flush messages carry no round_idx: the fault seam can target the
        # topic but round-scoped rules must never match them
        assert m.get("round_idx") is None
        merger = TelemetryMerger()
        assert merger.absorb(m) == 3


# ---------------------------------------------------------------------------
# Graft: remote spans + client-labeled metric merge
# ---------------------------------------------------------------------------

class TestGraft:
    def test_remote_spans_reemit_with_deterministic_ids(self):
        emitted = []
        merger = TelemetryMerger(emit=lambda t, r: emitted.append((t, dict(r))))
        cap = _cap(node=1)
        tctx = cap.record_span("client.train", 1.5, round_idx=2, seq=4,
                               client=7)
        cap.record_span("client.train.step", 1.4, parent=tctx,
                        round_idx=2, seq=4)
        msg = _upload()
        cap.attach(msg)
        assert merger.absorb(msg) == 2
        assert [t for t, _ in emitted] == [
            "span_start", "span_end", "span_start", "span_end"]
        root = round_root_ctx(RUN, 2)
        train_start = emitted[0][1]
        assert train_start["remote"] is True
        assert train_start["trace_id"] == root.trace_id
        assert train_start["parent_span_id"] == root.span_id
        assert train_start["span_id"] == span_id_for(
            root.trace_id, "client.train", 1, 4)
        assert train_start["client"] == 7 and train_start["round_idx"] == 2
        assert emitted[1][1]["duration_s"] == 1.5
        step_start = emitted[2][1]
        assert step_start["parent_span_id"] == train_start["span_id"]
        # the measured train time is readable as the pacing/staleness hint
        assert merger.train_seconds(1) == 1.5
        assert merger.train_seconds(99) is None

    def test_remote_spans_graft_into_a_closed_local_tree(self):
        collected = []
        merger = TelemetryMerger(
            emit=lambda t, r: collected.append(dict(r, topic=t)))
        cap = _cap(node=1)
        tctx = cap.record_span("client.train", 0.5, round_idx=0, seq=0)
        cap.record_span("client.train.step", 0.4, parent=tctx, round_idx=0)
        msg = _upload()
        cap.attach(msg)
        merger.absorb(msg)
        root = round_root_ctx(RUN, 0)
        local = [
            {"topic": "span_start", "trace_id": root.trace_id,
             "span_id": root.span_id, "name": "round", "node": 0,
             "round_idx": 0, "ts": 10.0},
            {"topic": "span_end", "trace_id": root.trace_id,
             "span_id": root.span_id, "name": "round", "duration_s": 1.0,
             "ts": 11.0},
        ]
        tr = trace_report.build_traces(local + collected)[root.trace_id]
        assert tr.problems() == []
        names = {sn.name for sn in tr.spans.values()}
        assert {"round", "client.train", "client.train.step"} <= names
        remote = [sn for sn in tr.spans.values()
                  if (sn.start or {}).get("remote") is True]
        assert len(remote) == 2

    def test_metric_records_merge_as_client_labeled_series(self):
        reg = MetricsRegistry()
        merger = TelemetryMerger(registry=reg)
        cap = _cap(node=3)
        cap.record_counter("comm.bytes_sent", 100.0, labels={"link": "up"})
        cap.record_counter("comm.bytes_sent", 50.0, labels={"link": "up"})
        cap.record_gauge("proc.rss_bytes", 2048.0)
        cap.record_gauge("proc.rss_bytes", 4096.0)  # gauges: last wins
        msg = _upload(3)
        cap.attach(msg)
        assert merger.absorb(msg) == 4
        by_metric = {(r["metric"], tuple(sorted(r["labels"].items()))): r
                     for r in reg.export()}
        counter = by_metric[("comm.bytes_sent",
                             (("client", "3"), ("link", "up")))]
        assert counter["value"] == 150.0       # deltas merge additively
        gauge = by_metric[("proc.rss_bytes", (("client", "3"),))]
        assert gauge["value"] == 4096.0
        # merge bookkeeping mirrors into the same registry
        assert ("telemetry.records_merged", ()) in by_metric


# ---------------------------------------------------------------------------
# Report: clients() classification + --diff golden sets
# ---------------------------------------------------------------------------

def _attributed_round(run_id, round_idx, phases, mode=None):
    """One closed round with named child phases (``{name: seconds}``)."""
    tid = trace_id_for(run_id, round_idx)
    root = span_id_for(tid, "round", 0, 0)
    start = {"topic": "span_start", "trace_id": tid, "span_id": root,
             "name": "round", "node": 0, "round_idx": round_idx, "ts": 10.0}
    if mode:
        start["mode"] = mode
    recs = [start]
    t = 10.0
    for name, dur in phases.items():
        sid = span_id_for(tid, name, 0, 0)
        recs.append({"topic": "span_start", "trace_id": tid, "span_id": sid,
                     "name": name, "node": 0, "parent_span_id": root,
                     "ts": t})
        recs.append({"topic": "span_end", "trace_id": tid, "span_id": sid,
                     "name": name, "duration_s": dur, "ts": t + dur})
        t += dur
    recs.append({"topic": "span_end", "trace_id": tid, "span_id": root,
                 "name": "round", "duration_s": t - 10.0, "ts": t})
    return recs


def _client_leg(recs, tid, root, node, train_s, upload_s, t0,
                upload_child_s=0.0):
    sid = span_id_for(tid, "client.train", node, 0)
    recs += [{"topic": "span_start", "trace_id": tid, "span_id": sid,
              "name": "client.train", "node": node, "parent_span_id": root,
              "ts": t0},
             {"topic": "span_end", "trace_id": tid, "span_id": sid,
              "name": "client.train", "duration_s": train_s,
              "ts": t0 + train_s}]
    up = span_id_for(tid, "upload", node, 0)
    t1 = t0 + train_s
    recs += [{"topic": "span_start", "trace_id": tid, "span_id": up,
              "name": "upload", "node": node, "parent_span_id": root,
              "ts": t1},
             {"topic": "span_end", "trace_id": tid, "span_id": up,
              "name": "upload", "duration_s": upload_s, "ts": t1 + upload_s}]
    if upload_child_s > 0:
        ch = span_id_for(tid, "journal.append", node, 0)
        recs += [{"topic": "span_start", "trace_id": tid, "span_id": ch,
                  "name": "journal.append", "node": node,
                  "parent_span_id": up, "ts": t1},
                 {"topic": "span_end", "trace_id": tid, "span_id": ch,
                  "name": "journal.append", "duration_s": upload_child_s,
                  "ts": t1 + upload_child_s}]


class TestClientsTable:
    def test_sync_compute_vs_network_classes(self):
        run = "cl-sync"
        tid = trace_id_for(run, 0)
        root = span_id_for(tid, "round", 0, 0)
        recs = [{"topic": "span_start", "trace_id": tid, "span_id": root,
                 "name": "round", "node": 0, "round_idx": 0, "ts": 10.0}]
        # node 1: compute-bound; node 2: network-bound (upload self-time
        # excludes the nested server-side journal work)
        _client_leg(recs, tid, root, 1, train_s=1.0, upload_s=0.1, t0=10.0,
                    upload_child_s=0.06)
        _client_leg(recs, tid, root, 2, train_s=0.1, upload_s=0.9, t0=10.0)
        recs.append({"topic": "span_end", "trace_id": tid, "span_id": root,
                     "name": "round", "duration_s": 2.0, "ts": 12.0})
        tr = trace_report.build_traces(recs)[tid]
        rows = {row["client"]: row for row in tr.clients()}
        assert rows[1]["class"] == "compute"
        assert rows[1]["network_s"] == pytest.approx(0.04)  # 0.1 - 0.06
        assert rows[2]["class"] == "network"
        assert rows[2]["deferred_s"] == 0.0     # sync: nothing deferred
        assert tr.is_async() is False

    def test_async_deferred_class(self):
        run = "cl-async"
        tid = trace_id_for(run, 0)
        root = span_id_for(tid, "round", 0, 0)
        recs = [{"topic": "span_start", "trace_id": tid, "span_id": root,
                 "name": "round", "node": 0, "round_idx": 0, "ts": 10.0,
                 "mode": "async_buffered"}]
        # trained fast, uploaded fast, but the report landed 1.9s after the
        # cycle opened: the unexplained residency is buffer deferral
        _client_leg(recs, tid, root, 5, train_s=0.1, upload_s=0.05, t0=11.75)
        recs.append({"topic": "span_end", "trace_id": tid, "span_id": root,
                     "name": "round", "duration_s": 2.0, "ts": 12.0})
        tr = trace_report.build_traces(recs)[tid]
        (row,) = tr.clients()
        assert row["client"] == 5 and row["class"] == "deferred"
        assert row["deferred_s"] == pytest.approx(1.75, abs=1e-6)

    def test_clients_table_rides_the_cli(self, tmp_path, capsys):
        run = "cl-cli"
        tid = trace_id_for(run, 0)
        root = span_id_for(tid, "round", 0, 0)
        recs = [{"topic": "span_start", "trace_id": tid, "span_id": root,
                 "name": "round", "node": 0, "round_idx": 0, "ts": 10.0}]
        _client_leg(recs, tid, root, 1, train_s=0.5, upload_s=0.1, t0=10.0)
        recs.append({"topic": "span_end", "trace_id": tid, "span_id": root,
                     "name": "round", "duration_s": 1.0, "ts": 11.0})
        p = tmp_path / "run.jsonl"
        p.write_text("\n".join(json.dumps(r) for r in recs) + "\n")
        assert trace_report.main([str(p), "--clients"]) == 0
        out = capsys.readouterr().out
        assert "compute_s" in out and "class" in out
        # and the JSON payload carries the same table
        payload = trace_report.trace_payload(
            trace_report.build_traces(recs)[tid], 2.0)
        assert payload["clients"][0]["class"] == "compute"


class TestDiff:
    def _write(self, path, phases):
        recs = _attributed_round(os.path.basename(str(path)), 0, phases)
        path.write_text("\n".join(json.dumps(r) for r in recs) + "\n")

    def test_identical_runs_diff_clean(self, tmp_path, capsys):
        a, b = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
        self._write(a, {"aggregate": 0.1, "client.train": 0.2})
        self._write(b, {"aggregate": 0.1, "client.train": 0.2})
        assert trace_report.main(["--diff", str(a), str(b)]) == 0
        assert "REGRESSED" not in capsys.readouterr().out

    def test_regressed_phase_fails_and_is_named(self, tmp_path, capsys):
        a, b = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
        self._write(a, {"aggregate": 0.1, "client.train": 0.2})
        self._write(b, {"aggregate": 0.5, "client.train": 0.2})
        assert trace_report.main(["--diff", str(a), str(b)]) == 1
        out = capsys.readouterr().out
        agg_line = [l for l in out.splitlines()
                    if l.strip().startswith("aggregate")]
        assert agg_line and "REGRESSED" in agg_line[0]
        assert "client.train" in out
        assert not any("REGRESSED" in l for l in out.splitlines()
                       if "client.train" in l)

    def test_sub_millisecond_jitter_is_not_a_regression(self, tmp_path):
        a, b = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
        self._write(a, {"aggregate": 0.0004})
        self._write(b, {"aggregate": 0.0009})   # +125% but under the floor
        assert trace_report.main(["--diff", str(a), str(b)]) == 0


# ---------------------------------------------------------------------------
# Chaos: the acceptance layer
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def fault_free_final():
    """Telemetry-OFF, fault-free final model: the bit-exactness reference
    for every chaos leg below."""
    obs.shutdown()
    obs.registry().reset()
    LoopbackHub.reset()
    _, final, _ = _ft._run_chaos_topology("tel-baseline")
    return final


def _remote_spans(traces):
    return [sn for tr in traces.values() for sn in tr.spans.values()
            if (sn.start or {}).get("remote") is True]


def test_telemetry_chaos_bit_identical_and_grafted(fault_free_final):
    """Drop + reset + duplicate + delay with telemetry ON: the final model
    is bit-identical to the telemetry-off fault-free run, every round still
    closes, and the client-side sub-spans are grafted into the merged
    trees with the merge counters exported."""
    LoopbackHub.reset()
    run_id = "tel-chaos"
    with _to._traced(run_id, obs_telemetry=1) as mem:
        history, final, stats = _ft._run_chaos_topology(
            run_id, fault_plan=_ft._full_chaos_plan())
        assert len(history) == 2
    assert _ft._trees_bit_identical(final, fault_free_final), \
        "telemetry perturbed convergence under chaos"
    traces = _to._assert_rounds_closed(mem, run_id, 2)
    remote = _remote_spans(traces)
    assert remote, "no remote telemetry spans grafted into the round trees"
    assert {sn.name for sn in remote} >= {"client.train.step"}
    # remote sub-spans hang off the (deduped) local client.train spans
    for tr in traces.values():
        steps = [sn for sn in tr.spans.values()
                 if sn.name == "client.train.step"]
        assert steps
    metric_names = {r["metric"] for r in mem.by_topic("metrics")}
    assert "telemetry.blobs_merged" in metric_names
    assert "telemetry.records_merged" in metric_names
    # every round still exposes an attribution table with real numbers
    for tr in traces.values():
        rows = tr.clients()
        assert rows and all(row["compute_s"] > 0 for row in rows)


def test_telemetry_off_matches_on_without_faults(fault_free_final):
    """The other half of bit-exactness: a clean telemetry-ON run equals the
    telemetry-OFF reference too (the blob is pure observability)."""
    LoopbackHub.reset()
    with _to._traced("tel-clean", obs_telemetry=1) as mem:
        history, final, _ = _ft._run_chaos_topology("tel-clean")
        assert len(history) == 2
    assert _ft._trees_bit_identical(final, fault_free_final)
    traces = _to._assert_rounds_closed(mem, "tel-clean", 2)
    assert _remote_spans(traces)


def test_telemetry_server_kill_still_converges(fault_free_final, tmp_path):
    """A server killed mid-round and restarted: blobs in flight die with
    it, the fresh incarnation's merger re-seeds its sequence windows, and
    the run still converges bit-identically with closed merged trees."""
    LoopbackHub.reset()
    run_id = "tel-kill"
    with _to._traced(run_id, obs_telemetry=1) as mem:
        history, final, stats, restarts, killed, server = \
            _ft._run_server_kill_topology(run_id, tmp_path / "srv")
        assert restarts >= 1 and len(history) == 2
    assert _ft._trees_bit_identical(final, fault_free_final), \
        "telemetry perturbed the server-kill recovery path"
    traces = _to._assert_rounds_closed(mem, run_id, 2)
    assert _remote_spans(traces)
    metric_names = {r["metric"] for r in mem.by_topic("metrics")}
    assert "telemetry.blobs_merged" in metric_names
