"""Host-plane process-group collectives (core/distributed/collective.py) —
the multi-process transport the reference routes through torch.distributed
NCCL/GLOO process groups — and the intra-silo master/slave shard round
built on it (reference fedml_client_slave_manager.py)."""

import multiprocessing as mp
import pickle
import time

import numpy as np
import pytest
from netutil import force_child_cpu as _force_child_cpu, free_port as _free_port

from fedml_tpu.core.distributed.collective import ProcessGroup


def _collective_worker(rank, world, port, q):
    _force_child_cpu()
    pg = ProcessGroup(rank, world, addr=("127.0.0.1", port), timeout=30)
    try:
        # broadcast from 0
        tree = {"w": np.full((3,), float(rank)), "b": np.asarray(rank, np.float32)}
        got = pg.broadcast(tree if rank == 0 else None)
        # allreduce sum: ranks contribute rank value
        summed = pg.allreduce_sum({"v": np.full((2,), float(rank))})
        # weighted mean: weight = rank + 1
        mean = pg.allreduce_mean(np.full((2,), float(rank)), weight=rank + 1.0)
        # allgather
        gathered = pg.allgather(np.asarray([rank], np.int32))
        pg.barrier()
        q.put((rank, float(got["w"][0]), float(summed["v"][0]), float(mean[0]),
               [int(g[0]) for g in gathered]))
    finally:
        pg.close()


class TestProcessGroup:
    def test_collectives_across_processes(self):
        world, port = 3, _free_port()
        ctx = mp.get_context("spawn")
        q = ctx.Queue()
        procs = [ctx.Process(target=_collective_worker, args=(r, world, port, q))
                 for r in range(world)]
        for p in procs:
            p.start()
        results = {}
        for _ in range(world):
            rank, bcast, summed, mean, gathered = q.get(timeout=120)
            results[rank] = (bcast, summed, mean, gathered)
        for p in procs:
            p.join(timeout=30)
        assert set(results) == {0, 1, 2}
        for rank, (bcast, summed, mean, gathered) in results.items():
            assert bcast == 0.0  # everyone got rank 0's tree
            assert summed == 0.0 + 1.0 + 2.0
            # weighted mean: (0*1 + 1*2 + 2*3) / (1+2+3) = 8/6
            assert abs(mean - 8.0 / 6.0) < 1e-6
            assert gathered == [0, 1, 2]

    def test_single_process_group_is_identity(self):
        pg = ProcessGroup(0, 1)
        t = {"a": np.ones(2)}
        assert pg.broadcast(t) is t
        assert pg.allreduce_sum(t) is t
        assert pg.allgather(t) == [t]
        pg.barrier()
        pg.close()


def _silo_proc(rank, world, port, q):
    """One silo process training its shard of a shared linear regression;
    master (rank 0) broadcasts sync like TrainerDistAdapter.train does."""
    _force_child_cpu()
    pg = ProcessGroup(rank, world, addr=("127.0.0.1", port), timeout=30)
    try:
        rng = np.random.RandomState(0)  # same data everywhere (same mount)
        x = rng.randn(64, 4).astype(np.float32)
        w_true = np.asarray([1.0, -2.0, 0.5, 3.0], np.float32)
        y = x @ w_true
        w = pg.broadcast(np.zeros(4, np.float32) if rank == 0 else None)
        for _ in range(150):
            xs, ys = x[rank::world], y[rank::world]
            grad = xs.T @ (xs @ w - ys) / len(ys)
            w = w - 0.1 * grad
            w = pg.allreduce_mean(w, weight=float(len(ys)))
        q.put((rank, w))
    finally:
        pg.close()


class TestSiloShardRound:
    def test_sharded_training_converges_and_agrees(self):
        world, port = 2, _free_port()
        ctx = mp.get_context("spawn")
        q = ctx.Queue()
        procs = [ctx.Process(target=_silo_proc, args=(r, world, port, q))
                 for r in range(world)]
        for p in procs:
            p.start()
        out = {rank: w for rank, w in [q.get(timeout=120) for _ in range(world)]}
        for p in procs:
            p.join(timeout=30)
        np.testing.assert_allclose(out[0], out[1], rtol=1e-6)  # consensus
        np.testing.assert_allclose(out[0], [1.0, -2.0, 0.5, 3.0], atol=0.05)


def _adapter_proc(rank, world, port, q):
    """Real TrainerDistAdapter master/slave round over the host pg."""
    _force_child_cpu()
    from types import SimpleNamespace as NS

    import fedml_tpu
    from fedml_tpu.cross_silo.client.trainer_dist_adapter import TrainerDistAdapter

    args = NS(n_proc_in_silo=world, proc_rank_in_silo=rank,
              pg_master_address="127.0.0.1", pg_master_port=port,
              scenario="horizontal", epochs=2, batch_size=16,
              client_optimizer="sgd", learning_rate=0.1, random_seed=0,
              dataset="synthetic", rank=1)
    rng = np.random.RandomState(0)
    x = rng.randn(64, 8).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.int32)
    import jax

    from fedml_tpu.ml.engine.train import init_variables
    from fedml_tpu.models.linear import LogisticRegression

    model = LogisticRegression(output_dim=2)
    adapter = TrainerDistAdapter(
        args, None, 1, model, 64, {0: 64}, {0: (x, y)}, {0: (x, y)}
    )
    adapter.update_dataset(0)
    variables = init_variables(model, x[:1], seed=0)
    adapter.set_model_params(variables)
    if rank == 0:
        params0, n0 = adapter.train(0)
        params1, _ = adapter.train(1)
        adapter.finish_silo()
        leaves = jax.tree_util.tree_leaves(params1)
        q.put((rank, n0, float(np.sum([np.sum(np.abs(l)) for l in leaves]))))
    else:
        from fedml_tpu.cross_silo.client.fedml_client_slave_manager import (
            ClientSlaveManager,
        )

        ClientSlaveManager(args, adapter).run()
        leaves = jax.tree_util.tree_leaves(adapter.get_model_params())
        q.put((rank, 64, float(np.sum([np.sum(np.abs(l)) for l in leaves]))))


@pytest.mark.heavy
class TestSiloMasterSlaveAdapter:
    def test_master_slave_round_agrees(self):
        world, port = 2, _free_port()
        ctx = mp.get_context("spawn")
        q = ctx.Queue()
        procs = [ctx.Process(target=_adapter_proc, args=(r, world, port, q))
                 for r in range(world)]
        for p in procs:
            p.start()
        out = {}
        for _ in range(world):
            rank, n, norm = q.get(timeout=240)
            out[rank] = (n, norm)
        for p in procs:
            p.join(timeout=60)
        assert out[0][0] == 64  # master reports the FULL client sample count
        # both processes ended the rounds with the same merged model
        assert abs(out[0][1] - out[1][1]) < 1e-4, out
