"""Multi-process MPI-parity simulator (simulation/mpi_proc): OS-process
ranks over the ProcessGroup host plane, reference ``simulation/mpi``
semantics (workers train their strided share, one weighted reduce per
round).  Spawned children force the CPU backend (axon sitecustomize)."""

import numpy as np
import pytest

pytestmark = pytest.mark.heavy  # spawns full jax processes


CFG = {
    "common_args": {"training_type": "simulation", "random_seed": 0,
                    "run_id": "mpiproc"},
    "data_args": {"dataset": "mnist", "data_cache_dir": "",
                  "partition_method": "hetero", "partition_alpha": 0.5,
                  "synthetic_train_size": 640},
    "model_args": {"model": "lr"},
    "train_args": {"federated_optimizer": "FedAvg", "client_num_in_total": 6,
                   "client_num_per_round": 4, "comm_round": 3, "epochs": 1,
                   "batch_size": 32, "client_optimizer": "sgd",
                   "learning_rate": 0.1, "backend": "MPI_PROC"},
    "validation_args": {"frequency_of_the_test": 1},
    "comm_args": {"backend": "MPI_PROC"},
    "tracking_args": {"enable_wandb": False, "log_file_dir": "./log"},
}


def _run_world(world_size):
    import os

    import fedml_tpu

    os.environ["FEDML_FORCE_CPU"] = "1"
    try:
        return fedml_tpu.run_mpi_simulation(CFG, world_size)
    finally:
        os.environ.pop("FEDML_FORCE_CPU", None)


def test_two_rank_round_learns():
    metrics = _run_world(2)
    assert metrics and metrics["test_acc"] > 0.5, metrics


def test_matches_single_process():
    """The strided-share + weighted-allreduce aggregate must equal the
    1-rank run exactly (same sampling, same trainers, float tolerance)."""
    m1 = _run_world(1)
    m3 = _run_world(3)
    assert m1 and m3
    # metrics are rounded to 4 decimals and float32 summation order differs
    # between 1 and 3 ranks: allow one rounding step of slack
    assert abs(m1["test_loss"] - m3["test_loss"]) <= 2e-4, (m1, m3)
    assert abs(m1["test_acc"] - m3["test_acc"]) <= 1e-3, (m1, m3)


def test_unsupported_configs_fail_loud():
    """Algorithm zoo / security matrix don't run here — fail, don't silently
    degrade to plain FedAvg (reference parity lives on sp / XLA)."""
    import copy

    import fedml_tpu
    from fedml_tpu.arguments import Arguments
    from fedml_tpu.core.security.fedml_defender import FedMLDefender
    from fedml_tpu.simulation.mpi_proc import MPIProcessSimulator

    cfg = copy.deepcopy(CFG)
    cfg["train_args"]["federated_optimizer"] = "SCAFFOLD"
    args = fedml_tpu.init(Arguments.from_dict(cfg).validate(),
                          should_init_logs=False)
    args.mpi_rank, args.mpi_world_size = 0, 1
    dataset, out_dim = fedml_tpu.data.load(args)
    model = fedml_tpu.models.create(args, out_dim)
    with pytest.raises(NotImplementedError, match="FedAvg/FedProx"):
        MPIProcessSimulator(args, dataset, model)

    cfg2 = copy.deepcopy(CFG)
    args2 = fedml_tpu.init(Arguments.from_dict(cfg2).validate(),
                           should_init_logs=False)
    args2.mpi_rank, args2.mpi_world_size = 0, 1
    args2.enable_defense = True
    args2.defense_type = "krum"
    FedMLDefender._defender_instance = None
    FedMLDefender.get_instance().init(args2)
    try:
        with pytest.raises(NotImplementedError, match="attack/defense"):
            MPIProcessSimulator(args2, dataset, model)
    finally:
        FedMLDefender._defender_instance = None
