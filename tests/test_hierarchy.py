"""The hierarchical fan-in tier (``fedml_tpu.core.hierarchy``).

Four strata, mirroring the tier's contract:

* **Plan** — the blocked canonical fold: a degenerate single-block plan
  anchors bitwise to the classic host aggregators, the blocked fold is
  deterministic, and the compiled agg plane's ``partial_reduce`` leg
  evaluates the SAME plan bit-identically to the host leg.
* **Deployment** — live loopback trees: a 2-level and a 3-level tree
  (mean AND sum, shuffled arrival order, host and compiled legs) close
  rounds BIT-IDENTICAL to the flat evaluation of the same plan, because
  topology decides WHERE each block folds, never WHAT is computed.
* **Chaos** — the acceptance claim, wired into ``tools/chaos_check.py``'s
  ``hierarchy`` leg: the full drop + duplicate + delay + reset plan over
  the hierarchy vocabulary still converges bit-identically with
  exactly-once accounting, and a killed edge's replacement incarnation
  replays its journal and re-forwards under the SAME forward id — the
  root's dedup makes the replay invisible (2-level and 3-level).
* **Observability** — leaf telemetry blobs ride the edge hop
  (collect -> journal -> graft), so ``trace_report --clients`` still
  attributes per-leaf time and ``--assert-closed`` stays green.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import types

import jax
import numpy as np
import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))

import trace_report

from fedml_tpu.core import obs
from fedml_tpu.core.aggregate import unweighted_sum, weighted_mean
from fedml_tpu.core.compression import compress_update, wire_bytes
from fedml_tpu.core.distributed.comm_manager import FedMLCommManager
from fedml_tpu.core.distributed.communication.loopback import LoopbackHub
from fedml_tpu.core.hierarchy import (
    HierarchyPlan,
    HierarchyRouter,
    PartialDelta,
    estimate_scheme_bytes,
    negotiate_codec,
)
from fedml_tpu.core.hierarchy.edge import EdgeAggregator
from fedml_tpu.core.ingest import ReorderWindow
from fedml_tpu.core.obs.telemetry import ClientTelemetry, TelemetryMerger
from fedml_tpu.core.obs.trace import round_root_ctx


@pytest.fixture(autouse=True)
def _obs_hygiene():
    yield
    obs.shutdown()
    obs.registry().reset()


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def _bit_identical(a, b) -> bool:
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(la, lb))


def _updates(n, seed=0):
    rng = np.random.default_rng(seed)
    return [(float(rng.integers(1, 50)),
             {"w": rng.standard_normal((4, 3)).astype(np.float32),
              "b": rng.standard_normal((3,)).astype(np.float32)})
            for _ in range(n)]


def _mkargs(run_id, optimizer="FedAvg", **kw):
    return types.SimpleNamespace(run_id=run_id, federated_optimizer=optimizer,
                                 comm_max_retries=3, **kw)


class _Mgr(FedMLCommManager):
    """A bare manager: the root host and the leaf senders."""

    def register_message_receive_handlers(self) -> None:
        pass


class _Tree:
    """One deployed loopback tree + its teardown."""

    def __init__(self, args, plan, plane=None, merger=None):
        self.router = HierarchyRouter(args, plan=plan)
        self.root_mgr = _Mgr(args, rank=0, size=self.router.size)
        self.done = threading.Event()
        self.out = {}

        def on_round(r, tree, w, k):
            self.out["res"] = (tree, w, k)
            self.done.set()

        self.root = self.router.attach_root(self.root_mgr, merger=merger,
                                            on_round=on_round, plane=plane)
        self.edges = self.router.build_edges(plane=plane)
        self.leaves = [_Mgr(args, rank=self.router.leaf_rank(i),
                            size=self.router.size)
                       for i in range(plan.n_leaves)]
        self.extra = []
        for m in [self.root_mgr] + self.edges + self.leaves:
            m.run_async()
        time.sleep(0.2)

    def send(self, ups, round_idx=0, order=None, telemetry=None):
        idxs = list(order) if order is not None else range(len(self.leaves))
        for i in idxs:
            m = self.leaves[i]
            cap = telemetry[i] if telemetry is not None else None
            m.send_message(self.router.leaf_upload_message(
                m.rank, i, round_idx, ups[i][0], ups[i][1], telemetry=cap))

    def close(self):
        for m in [self.root_mgr] + self.edges + self.extra + self.leaves:
            try:
                m.finish()
            except Exception:
                pass

    def result(self, timeout=60):
        assert self.done.wait(timeout), "hierarchy round never closed"
        return self.out["res"]


# ---------------------------------------------------------------------------
# Plan: the blocked canonical fold
# ---------------------------------------------------------------------------

class TestPlan:
    def test_knob_validation(self):
        with pytest.raises(ValueError):
            HierarchyPlan(n_leaves=4, levels=4)
        with pytest.raises(ValueError):
            HierarchyPlan(n_leaves=0, levels=2)

    def test_block_shapes(self):
        plan = HierarchyPlan(n_leaves=10, levels=2, edge_fanout=4)
        assert plan.blocks == [[0, 1, 2, 3], [4, 5, 6, 7], [8, 9]]
        assert plan.n_edges == 3 and plan.n_mids == 0
        plan3 = HierarchyPlan(n_leaves=12, levels=3, edge_fanout=3)
        assert plan3.n_edges == 4 and plan3.mid_groups == [[0, 1, 2], [3]]
        assert plan3.edge_of(7) == 2 and plan3.mid_of(3) == 1

    def test_flush_timeout_parsing(self):
        assert HierarchyPlan(n_leaves=2, levels=2,
                             edge_flush="all").flush_timeout() is None
        assert HierarchyPlan(n_leaves=2, levels=2,
                             edge_flush=0.5).flush_timeout() == 0.5

    def test_degenerate_plan_anchors_to_classic_aggregators(self):
        """A single-block plan IS the classic fold — bit for bit.  This is
        the anchor that makes 'tree == flat' mean 'tree == what the flat
        server always computed'."""
        ups = _updates(10, seed=0)
        plan = HierarchyPlan(n_leaves=10, levels=1)
        assert _bit_identical(plan.aggregate(ups, mode="mean"),
                              weighted_mean(ups))
        assert _bit_identical(plan.aggregate(ups, mode="sum"),
                              unweighted_sum(ups))

    def test_blocked_fold_is_deterministic(self):
        ups = _updates(10, seed=1)
        for levels, fanout in ((2, 3), (3, 3)):
            plan = HierarchyPlan(n_leaves=10, levels=levels,
                                 edge_fanout=fanout)
            for mode in ("mean", "sum"):
                assert _bit_identical(plan.aggregate(ups, mode=mode),
                                      plan.aggregate(ups, mode=mode))

    def test_host_vs_compiled_partial_parity(self):
        """The compiled leg evaluates the SAME plan bit-identically: block
        folds via ``partial_reduce``, combines via the plane's sum fold."""
        from fedml_tpu.parallel.agg_plane import CompiledAggPlane

        ups = _updates(8, seed=2)
        plane = CompiledAggPlane()
        for levels, fanout in ((2, 3), (3, 2)):
            plan = HierarchyPlan(n_leaves=8, levels=levels,
                                 edge_fanout=fanout)
            for mode in ("mean", "sum"):
                host = plan.aggregate(ups, mode=mode)
                compiled = plan.aggregate(ups, mode=mode, plane=plane)
                assert _bit_identical(host, compiled), \
                    f"compiled leg diverged (levels={levels}, mode={mode})"


# ---------------------------------------------------------------------------
# ReorderWindow: the streaming fold's ordering seam
# ---------------------------------------------------------------------------

class TestReorderWindow:
    def test_in_order_releases_immediately(self):
        win = ReorderWindow([5, 7, 9])
        assert win.expected == 5
        assert win.stage(5, "a") == [(5, "a")]
        assert win.stage(7, "b") == [(7, "b")]
        assert not win.done()
        assert win.stage(9, "c") == [(9, "c")]
        assert win.done() and win.pending() == 0

    def test_out_of_order_holds_then_flushes_contiguous_run(self):
        win = ReorderWindow([0, 1, 2, 3])
        assert win.stage(2, "c") == []
        assert win.stage(1, "b") == []
        assert win.pending() == 2
        # 0 lands: the whole contiguous run releases in plan order
        assert win.stage(0, "a") == [(0, "a"), (1, "b"), (2, "c")]
        assert win.stage(3, "d") == [(3, "d")]

    def test_double_stage_and_unknown_key_raise(self):
        win = ReorderWindow([0, 1])
        win.stage(0, "a")
        with pytest.raises(ValueError):
            win.stage(0, "again")
        with pytest.raises(KeyError):
            win.stage(42, "who")


# ---------------------------------------------------------------------------
# Router: rank layout + codec negotiation
# ---------------------------------------------------------------------------

class TestRouter:
    def test_rank_layout_two_level(self):
        args = _mkargs("hier-layout2")
        plan = HierarchyPlan(n_leaves=10, levels=2, edge_fanout=4)
        router = HierarchyRouter(args, plan=plan)
        assert router.size == 1 + 3 + 10
        assert [router.edge_rank(e) for e in range(3)] == [1, 2, 3]
        assert router.leaf_rank(0) == 4
        assert router.leaf_target_rank(5) == router.edge_rank(1)
        assert router.root_child_ranks() == {0: 1, 1: 2, 2: 3}

    def test_rank_layout_three_level(self):
        args = _mkargs("hier-layout3")
        plan = HierarchyPlan(n_leaves=12, levels=3, edge_fanout=3)
        router = HierarchyRouter(args, plan=plan)
        # root, 4 edges, 2 mids, 12 leaves
        assert router.size == 19
        assert router.mid_rank(0) == 5 and router.mid_rank(1) == 6
        # mid ids live in the shared edge-id namespace
        assert router.mid_id(0) == 4 and router.mid_id(1) == 5
        assert router.root_child_ranks() == {4: 5, 5: 6}

    def test_router_rejects_flat_plan(self):
        with pytest.raises(ValueError):
            HierarchyRouter(_mkargs("hier-flat"),
                            plan=HierarchyPlan(n_leaves=4, levels=1))

    def test_negotiate_picks_cheapest_estimated(self):
        offers = {"schemes": ["none", "topk"],
                  "bytes": {"none": 1000, "topk": 120}}
        assert negotiate_codec(offers, ["none", "topk"]) == "topk"
        # the parent's accept list is a hard filter
        assert negotiate_codec(offers, ["none"]) == "none"
        assert negotiate_codec(offers, []) == "none"

    def test_negotiate_estimate_less_schemes_lose(self):
        offers = {"schemes": ["qsgd", "topk"], "bytes": {"topk": 500}}
        assert negotiate_codec(offers, ["qsgd", "topk"]) == "topk"

    def test_negotiate_ties_resolve_by_parent_order(self):
        offers = {"schemes": ["quantize", "qsgd"], "bytes": {}}
        assert negotiate_codec(offers, ["qsgd", "quantize"]) == "qsgd"

    def test_negotiate_malformed_degrades_to_none(self):
        assert negotiate_codec(None, ["topk"]) == "none"
        assert negotiate_codec("junk", ["topk"]) == "none"
        assert negotiate_codec({"schemes": ["evil"]}, ["topk"]) == "none"

    def test_estimates_are_honest(self):
        """The dense estimate IS the wire size; the top-k estimate agrees
        with ``wire_bytes`` of a real encoded payload."""
        rng = np.random.default_rng(3)
        tree = {"w": rng.standard_normal((64, 32)).astype(np.float32),
                "b": rng.standard_normal((32,)).astype(np.float32)}
        dense = estimate_scheme_bytes(tree, "none")
        assert dense == wire_bytes(tree)
        est = estimate_scheme_bytes(tree, "topk", ratio=0.1)
        assert 0 < est < dense
        payload, _ = compress_update(tree, method="topk", ratio=0.1)
        assert est == wire_bytes(payload)


class TestArgumentKnobs:
    def _args(self, **extra):
        import test_fault_tolerance as _ft

        return _ft._args("hier-knobs", 2, **extra)

    def test_valid_knobs_pass(self):
        args = self._args(fan_in_tree=3, edge_fanout=8, edge_flush="all")
        assert args.fan_in_tree == 3

    def test_bad_fan_in_tree_rejected(self):
        with pytest.raises(ValueError, match="fan_in_tree"):
            self._args(fan_in_tree=5)

    def test_bad_edge_fanout_rejected(self):
        with pytest.raises(ValueError, match="edge_fanout"):
            self._args(edge_fanout=-1)

    def test_bad_edge_flush_rejected(self):
        with pytest.raises(ValueError, match="edge_flush"):
            self._args(edge_flush="sometimes")
        with pytest.raises(ValueError, match="edge_flush"):
            self._args(edge_flush=0)


# ---------------------------------------------------------------------------
# Deployment: live trees vs the flat evaluation of the same plan
# ---------------------------------------------------------------------------

_MODES = (("mean", "FedAvg"), ("sum", "FedAvg_seq"))


class TestTreeVsFlat:
    @pytest.mark.parametrize("levels", (2, 3))
    @pytest.mark.parametrize("mode,opt", _MODES)
    def test_tree_round_bit_identical_to_flat(self, levels, mode, opt):
        n = 12
        ups = _updates(n, seed=10 + levels)
        plan = HierarchyPlan(n_leaves=n, levels=levels, edge_fanout=3)
        flat = plan.aggregate(ups, mode=mode)
        args = _mkargs(f"hier-tvf-{levels}-{mode}", optimizer=opt)
        tree = _Tree(args, plan)
        try:
            rng = np.random.default_rng(levels)
            order = list(range(n))
            rng.shuffle(order)  # the reorder window restores plan order
            tree.send(ups, order=order)
            got, weight, k = tree.result()
            assert _bit_identical(got, flat), \
                f"{levels}-level {mode} tree diverged from the flat fold"
            assert weight == sum(u[0] for u in ups)
            assert k == n
            assert tree.root.dup_forwards == 0
            assert tree.root.rounds_closed == 1
        finally:
            tree.close()

    def test_compiled_leg_tree_matches_flat_and_host(self):
        """The acceptance matrix's compiled column: edges and root fold
        through the agg plane, and the closed round still matches BOTH the
        compiled flat evaluation and the host one."""
        from fedml_tpu.parallel.agg_plane import CompiledAggPlane

        n = 10
        ups = _updates(n, seed=20)
        plan = HierarchyPlan(n_leaves=n, levels=2, edge_fanout=4)
        plane = CompiledAggPlane()
        args = _mkargs("hier-compiled")
        tree = _Tree(args, plan, plane=plane)
        try:
            tree.send(ups)
            got, _, _ = tree.result()
            assert _bit_identical(got, plan.aggregate(ups, "mean", plane))
            assert _bit_identical(got, plan.aggregate(ups, "mean"))
        finally:
            tree.close()

    def test_streaming_sum_fold_drops_payloads(self):
        """The O(model) claim: in sum mode the edge stream-folds each
        release and stages only ``(weight, None, epoch)`` — no per-leaf
        payload survives in memory, the journal keeps the durable copy."""
        n = 8
        ups = _updates(n, seed=21)
        plan = HierarchyPlan(n_leaves=n, levels=2, edge_fanout=4)
        args = _mkargs("hier-stream", optimizer="FedAvg_seq")
        tree = _Tree(args, plan)
        try:
            order = [3, 0, 2, 1, 7, 5, 4, 6]  # out-of-order arrival
            tree.send(ups, order=order)
            got, _, _ = tree.result()
            assert _bit_identical(got, plan.aggregate(ups, mode="sum"))
            for edge in tree.edges:
                staged = edge._staged.get(0, {})
                assert staged and all(t is None for _, t, _ in
                                      staged.values())
        finally:
            tree.close()


# ---------------------------------------------------------------------------
# Chaos: faults on the hierarchy vocabulary + edge kill replay
# ---------------------------------------------------------------------------

def _hier_chaos_plan():
    """Every fault kind aimed at the tier's own vocabulary.  Rules are
    per-endpoint occurrence counters, so EVERY leaf loses its first
    upload send, EVERY edge's counts send is RST and its forward
    duplicated — much denser than one fault per round."""
    return {"seed": 11, "rules": [
        {"kind": "drop", "direction": "send", "msg_type": "hier_upload",
         "times": 1},
        {"kind": "reset", "direction": "send", "msg_type": "hier_counts",
         "times": 1},
        {"kind": "duplicate", "direction": "send",
         "msg_type": "hier_partial", "times": 1},
        {"kind": "delay", "direction": "send", "msg_type": "hier_total",
         "times": 1, "delay_s": 0.05},
    ]}


class TestHierarchyChaos:
    @pytest.mark.parametrize("levels", (2, 3))
    @pytest.mark.parametrize("mode,opt", _MODES)
    def test_full_chaos_plan_converges_bit_identical(self, levels, mode,
                                                     opt):
        n = 12
        ups = _updates(n, seed=30 + levels)
        plan = HierarchyPlan(n_leaves=n, levels=levels, edge_fanout=3)
        flat = plan.aggregate(ups, mode=mode)
        args = _mkargs(f"hier-chaos-{levels}-{mode}", optimizer=opt,
                       fault_plan=_hier_chaos_plan())
        tree = _Tree(args, plan)
        try:
            tree.send(ups)
            got, weight, k = tree.result(timeout=90)
            assert _bit_identical(got, flat), \
                "chaos run diverged from the flat fold"
            assert weight == sum(u[0] for u in ups) and k == n
            # exactly-once: faults cost retries, never double counting
            assert tree.root.dup_forwards == 0
            assert tree.root.rounds_closed == 1
        finally:
            tree.close()

    def test_edge_kill_mid_round_replays_exactly_once(self, tmp_path):
        """Kill an edge after it journaled its block but before the global
        total exists; the replacement incarnation replays the journal,
        re-sends counts, and the round closes bit-identical.  A THIRD
        incarnation after the close re-forwards under the same forward id
        — the root counts the dup and the result never changes."""
        n = 8
        ups = _updates(n, seed=40)
        plan = HierarchyPlan(n_leaves=n, levels=2, edge_fanout=4)
        flat = plan.aggregate(ups, mode="mean")
        run = "hier-kill2"
        args = _mkargs(run, edge_checkpoint_dir=str(tmp_path))
        tree = _Tree(args, plan)
        try:
            # phase 1: only edge 0's block lands, then the edge dies
            tree.send(ups, order=plan.blocks[0])
            deadline = time.time() + 30
            while (len(tree.edges[0]._seen.get(0, ())) < len(plan.blocks[0])
                   and time.time() < deadline):
                time.sleep(0.02)
            assert len(tree.edges[0]._seen.get(0, ())) == len(plan.blocks[0])
            LoopbackHub.sever(run, tree.edges[0].rank)
            tree.edges[0].com_manager.stop_receive_message()

            # phase 2: the replacement replays the journal; edge 1's block
            # arrives; the round closes bit-identical with no dup at root
            edge0b = EdgeAggregator(args, plan, edge_id=0, parent_rank=0,
                                    children=plan.blocks[0],
                                    rank=tree.router.edge_rank(0),
                                    size=tree.router.size)
            tree.extra.append(edge0b)
            edge0b.run_async()
            tree.send(ups, order=plan.blocks[1])
            got, weight, k = tree.result()
            assert _bit_identical(got, flat)
            assert weight == sum(u[0] for u in ups) and k == n
            assert tree.root.dup_forwards == 0

            # phase 3: a post-close incarnation re-forwards the SAME id
            edge0c = EdgeAggregator(args, plan, edge_id=0, parent_rank=0,
                                    children=plan.blocks[0],
                                    rank=tree.router.edge_rank(0),
                                    size=tree.router.size)
            tree.extra.append(edge0c)
            edge0c.run_async()
            deadline = time.time() + 30
            while tree.root.dup_forwards < 1 and time.time() < deadline:
                time.sleep(0.05)
            assert tree.root.dup_forwards >= 1
            assert tree.root.rounds_closed == 1
            assert _bit_identical(tree.root.result(0)[0], flat), \
                "a replayed forward changed the closed round"
        finally:
            tree.close()

    def test_three_level_edge_kill_replays_through_mid(self, tmp_path):
        """Same replay contract one level down: the killed LEAF edge's
        replacement re-sends counts to its MID, which relays the total
        down idempotently, and the root still closes exactly-once."""
        n = 12
        ups = _updates(n, seed=41)
        plan = HierarchyPlan(n_leaves=n, levels=3, edge_fanout=3)
        flat = plan.aggregate(ups, mode="mean")
        run = "hier-kill3"
        args = _mkargs(run, edge_checkpoint_dir=str(tmp_path))
        tree = _Tree(args, plan)
        try:
            tree.send(ups, order=plan.blocks[0])
            deadline = time.time() + 30
            while (len(tree.edges[0]._seen.get(0, ())) < len(plan.blocks[0])
                   and time.time() < deadline):
                time.sleep(0.02)
            LoopbackHub.sever(run, tree.edges[0].rank)
            tree.edges[0].com_manager.stop_receive_message()

            mid0 = tree.router.mid_rank(plan.mid_of(0))
            edge0b = EdgeAggregator(args, plan, edge_id=0, parent_rank=mid0,
                                    children=plan.blocks[0],
                                    rank=tree.router.edge_rank(0),
                                    size=tree.router.size)
            tree.extra.append(edge0b)
            edge0b.run_async()
            rest = [i for i in range(n) if i not in plan.blocks[0]]
            tree.send(ups, order=rest)
            got, weight, k = tree.result()
            assert _bit_identical(got, flat)
            assert weight == sum(u[0] for u in ups) and k == n
            assert tree.root.dup_forwards == 0
            assert tree.root.rounds_closed == 1
        finally:
            tree.close()


# ---------------------------------------------------------------------------
# Knob-driven behavior: timeout flush, live codec negotiation
# ---------------------------------------------------------------------------

class TestKnobs:
    def test_timeout_flush_closes_without_the_missing_leaf(self):
        """``edge_flush`` trades the full-cohort bit-identity contract for
        liveness: a silent leaf must not wedge the round."""
        n = 4
        ups = _updates(n, seed=50)
        plan = HierarchyPlan(n_leaves=n, levels=2, edge_fanout=2,
                             edge_flush=0.3)
        args = _mkargs("hier-flush")
        tree = _Tree(args, plan)
        try:
            tree.send(ups, order=[0, 1, 2])  # leaf 3 never reports
            got, weight, k = tree.result()
            assert k == 3
            assert weight == sum(ups[i][0] for i in (0, 1, 2))
            # the arithmetic contract: same blocked fold over the cohort
            # that made the counts, with the root's global total
            total = weight
            expected = plan.combine([
                plan.block_partial([ups[0], ups[1]], total, "mean"),
                plan.block_partial([ups[2]], total, "mean"),
            ], "mean")
            assert _bit_identical(got, expected)
            # the straggler past the flush is counted and dropped
            tree.send(ups, order=[3])
            time.sleep(0.4)
            assert 3 not in tree.edges[1]._seen.get(0, set())
            assert _bit_identical(tree.root.result(0)[0], expected)
        finally:
            tree.close()

    def test_live_codec_negotiation_compresses_the_forward(self):
        """Edges offer top-k, the root accepts it: every link negotiates
        ``topk`` and the fused forwards ship compressed (lossy — the
        bit-identity contract is explicitly traded away here).  Trees are
        big enough that the honest estimate makes top-k actually win."""
        n = 6
        rng = np.random.default_rng(51)
        ups = [(float(rng.integers(1, 50)),
                {"w": rng.standard_normal((64, 32)).astype(np.float32)})
               for _ in range(n)]
        plan = HierarchyPlan(n_leaves=n, levels=2, edge_fanout=3)
        args = _mkargs("hier-codec", optimizer="FedAvg_seq",
                       edge_codec_offers="topk,none",
                       edge_codec_accept="topk,none",
                       edge_codec_ratio=0.1)
        tree = _Tree(args, plan)
        try:
            tree.send(ups)
            got, weight, k = tree.result()
            assert k == n and weight == sum(u[0] for u in ups)
            assert tree.root._codecs[0] == {0: "topk", 1: "topk"}
            # lossy, but structurally intact and in the right ballpark
            ref = plan.aggregate(ups, mode="sum")
            got_l = jax.tree_util.tree_leaves(got)
            ref_l = jax.tree_util.tree_leaves(ref)
            assert [np.asarray(x).shape for x in got_l] == \
                   [np.asarray(x).shape for x in ref_l]
        finally:
            tree.close()


# ---------------------------------------------------------------------------
# Observability: telemetry rides the edge hop
# ---------------------------------------------------------------------------

class TestTelemetryThroughTheTree:
    def test_leaf_spans_graft_and_trace_report_attributes(self, tmp_path):
        """Leaf telemetry blobs collected at the edge and grafted onto the
        fused forward reach the root merger intact: ``trace_report
        --clients`` attributes every leaf's train time through the edge
        hop and ``--assert-closed`` stays green."""
        n = 6
        run = "hier-tel"
        ups = _updates(n, seed=60)
        plan = HierarchyPlan(n_leaves=n, levels=2, edge_fanout=3)
        collected = []
        merger = TelemetryMerger(
            emit=lambda t, r: collected.append(dict(r, topic=t)))
        args = _mkargs(run)
        tree = _Tree(args, plan, merger=merger)
        try:
            caps = []
            for i in range(n):
                cap = ClientTelemetry(i, run)
                cap.record_span("client.train", 0.1 * (i + 1), round_idx=0,
                                seq=0, client=i)
                caps.append(cap)
            tree.send(ups, telemetry=caps)
            tree.result()
            # every leaf's span made it through the hop, attributed
            trains = [r for r in collected if r.get("topic") == "span_start"
                      and r.get("name") == "client.train"]
            assert {r["client"] for r in trains} == set(range(n))
            assert all(r["remote"] is True for r in trains)
            for i in range(n):
                assert merger.train_seconds(i) == pytest.approx(
                    0.1 * (i + 1))
            # the merged tree closes: local round root + grafted leaf spans
            root_ctx = round_root_ctx(run, 0)
            local = [
                {"topic": "span_start", "trace_id": root_ctx.trace_id,
                 "span_id": root_ctx.span_id, "name": "round", "node": 0,
                 "round_idx": 0, "ts": 10.0},
                {"topic": "span_end", "trace_id": root_ctx.trace_id,
                 "span_id": root_ctx.span_id, "name": "round",
                 "duration_s": 2.0, "ts": 12.0},
            ]
            recs = local + collected
            tr = trace_report.build_traces(recs)[root_ctx.trace_id]
            assert tr.problems() == []
            rows = {row["client"]: row for row in tr.clients()}
            assert set(rows) == set(range(n))
            assert rows[n - 1]["compute_s"] == pytest.approx(0.1 * n)
            # and the CLI contract the runbook points operators at
            p = tmp_path / "hier.jsonl"
            p.write_text("\n".join(json.dumps(r) for r in recs) + "\n")
            assert trace_report.main(
                [str(p), "--clients", "--assert-closed"]) == 0
        finally:
            tree.close()

    def test_replayed_edge_recarries_journaled_telemetry(self, tmp_path):
        """A killed edge's replacement re-grafts the journaled blobs, and
        the merger's per-node seq dedup keeps the accounting exact."""
        n = 4
        run = "hier-tel-replay"
        ups = _updates(n, seed=61)
        plan = HierarchyPlan(n_leaves=n, levels=2, edge_fanout=2)
        collected = []
        merger = TelemetryMerger(
            emit=lambda t, r: collected.append(dict(r, topic=t)))
        args = _mkargs(run, edge_checkpoint_dir=str(tmp_path))
        tree = _Tree(args, plan, merger=merger)
        try:
            caps = []
            for i in range(n):
                cap = ClientTelemetry(i, run)
                cap.record_span("client.train", 0.2, round_idx=0, seq=0,
                                client=i)
                caps.append(cap)
            tree.send(ups, order=plan.blocks[0], telemetry=caps)
            deadline = time.time() + 30
            while (len(tree.edges[0]._seen.get(0, ())) < 2
                   and time.time() < deadline):
                time.sleep(0.02)
            LoopbackHub.sever(run, tree.edges[0].rank)
            tree.edges[0].com_manager.stop_receive_message()
            edge0b = EdgeAggregator(args, plan, edge_id=0, parent_rank=0,
                                    children=plan.blocks[0],
                                    rank=tree.router.edge_rank(0),
                                    size=tree.router.size)
            tree.extra.append(edge0b)
            edge0b.run_async()
            tree.send(ups, order=plan.blocks[1], telemetry=caps)
            tree.result()
            trains = [r for r in collected if r.get("topic") == "span_start"
                      and r.get("name") == "client.train"]
            # every leaf attributed exactly once, replay notwithstanding
            assert sorted(r["client"] for r in trains) == list(range(n))
        finally:
            tree.close()
