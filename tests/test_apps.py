"""Apps layer (SURVEY.md §2.9 apps row): each app config parses and its
simulation learns on the synthetic data layer — the in-process twin of the
reference's example-as-test smoke matrix."""

import os

import pytest
import yaml

import fedml_tpu
from fedml_tpu.arguments import Arguments

pytestmark = pytest.mark.heavy  # long XLA compiles; see pytest.ini

APP_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "app")


def _run_config(path, **over):
    with open(path) as f:
        cfg = yaml.safe_load(f)
    args = Arguments.from_dict(cfg)
    args.data_cache_dir = ""  # force synthetic
    for k, v in over.items():
        setattr(args, k, v)
    args = fedml_tpu.init(args.validate(), should_init_logs=False)
    from fedml_tpu import FedMLRunner, data, models

    dataset, out_dim = data.load(args)
    model = models.create(args, out_dim)
    return FedMLRunner(args, None, dataset, model).run()


class TestApps:
    def test_fednlp_text_classification(self):
        m = _run_config(os.path.join(APP_DIR, "fednlp", "fedml_config.yaml"),
                        synthetic_train_size=512, comm_round=3)
        assert m["test_acc"] > 0.5  # 4 classes, band-separable tokens

    def test_fedcv_image_classification(self):
        m = _run_config(os.path.join(APP_DIR, "fedcv", "fedml_config.yaml"),
                        synthetic_train_size=512, comm_round=3, epochs=2,
                        partition_method="homo")
        assert m["test_acc"] > 0.2  # resnet20 needs many more rounds to saturate

    def test_fedcv_segmentation(self):
        m = _run_config(os.path.join(APP_DIR, "fedcv", "fedml_config_seg.yaml"),
                        synthetic_train_size=160, comm_round=2)
        assert m["test_acc"] > 0.5 and "test_miou" in m

    def test_fedgraphnn_molecule_classification(self):
        m = _run_config(os.path.join(APP_DIR, "fedgraphnn", "fedml_config.yaml"),
                        synthetic_train_size=512, comm_round=3)
        assert m["test_acc"] > 0.5

    def test_healthcare_tabular_fedprox(self):
        m = _run_config(os.path.join(APP_DIR, "healthcare", "fedml_config.yaml"),
                        synthetic_train_size=512, comm_round=3)
        assert m["test_acc"] > 0.7  # binary

    def test_app_entry_files_exist(self):
        for app in ("fednlp", "fedcv", "fedgraphnn", "healthcare"):
            assert os.path.exists(os.path.join(APP_DIR, app, "main.py"))
            assert os.path.exists(os.path.join(APP_DIR, app, "fedml_config.yaml"))
