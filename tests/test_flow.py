"""Flow DSL: the reference's canonical 1-server/2-client flow
(core/distributed/flow/test_fedml_flow.py shape) over the loopback backend,
all nodes in one process."""

import threading

import numpy as np
import pytest

from fedml_tpu.core import FedMLAlgorithmFlow, FedMLExecutor, Params
from fedml_tpu.core.distributed.communication.loopback import LoopbackHub


class _Args:
    def __init__(self, **kw):
        self.backend = "LOOPBACK"
        self.run_id = "flow-test"
        self.__dict__.update(kw)


class FlowClient(FedMLExecutor):
    def __init__(self, args):
        super().__init__(id=args.rank, neighbor_id_list=[0])
        self.trained = 0

    def handle_init_global_model(self):
        received = self.get_params()
        params = Params()
        params.add(Params.KEY_MODEL_PARAMS, received.get(Params.KEY_MODEL_PARAMS))
        return params

    def local_training(self):
        self.trained += 1
        w = np.asarray(self.get_params().get(Params.KEY_MODEL_PARAMS))
        params = Params()
        params.add(Params.KEY_MODEL_PARAMS, w + 1.0)
        return params


class FlowServer(FedMLExecutor):
    def __init__(self, args, client_num=2):
        super().__init__(id=args.rank, neighbor_id_list=list(range(1, client_num + 1)))
        self.client_num = client_num
        self.client_count = 0
        self.acc = None
        self.rounds_done = 0
        self.final_called = threading.Event()

    def init_global_model(self):
        params = Params()
        params.add(Params.KEY_MODEL_PARAMS, np.zeros(3))
        return params

    def server_aggregate(self):
        w = np.asarray(self.get_params().get(Params.KEY_MODEL_PARAMS))
        self.acc = w if self.acc is None else self.acc + w
        self.client_count += 1
        if self.client_count < self.client_num:
            return None  # hold until all clients reported
        mean = self.acc / self.client_num
        self.client_count = 0
        self.acc = None
        self.rounds_done += 1
        params = Params()
        params.add(Params.KEY_MODEL_PARAMS, mean)
        return params

    def final_eval(self):
        self.final_called.set()

    def server_aggregate_then_finish(self):
        result = self.server_aggregate()
        if result is None:
            return None  # hold: stragglers pending
        self.final_called.set()
        return result


@pytest.mark.parametrize("comm_round", [1, 3])
def test_flow_fedavg_roundtrip(comm_round):
    LoopbackHub.reset()
    server = FlowServer(_Args(rank=0))
    clients = [FlowClient(_Args(rank=r)) for r in (1, 2)]

    flows = []
    for executor in [server] + clients:
        flow = FedMLAlgorithmFlow(_Args(rank=executor.get_id()), executor)
        flow.add_flow("init_global_model", FlowServer.init_global_model)
        flow.add_flow("handle_init", FlowClient.handle_init_global_model)
        for _ in range(comm_round):
            flow.add_flow("local_training", FlowClient.local_training)
            flow.add_flow("server_aggregate", FlowServer.server_aggregate)
        flow.add_flow("final_eval", FlowServer.final_eval, flow_tag=FedMLAlgorithmFlow.FINISH)
        flow.build()
        flows.append(flow)

    threads = [f.run_async() for f in flows]
    for f in flows:
        assert f.wait_finished(timeout=30), "flow did not finish"
    for t in threads:
        t.join(timeout=10)

    assert server.final_called.is_set()
    assert server.rounds_done == comm_round
    for c in clients:
        assert c.trained == comm_round


def test_flow_aggregate_as_last_entry_holds_until_all_clients():
    """A None-returning (holding) aggregator as the final untagged entry must
    NOT finish the flow after the first client report (code-review finding)."""
    LoopbackHub.reset()
    server = FlowServer(_Args(rank=0))
    clients = [FlowClient(_Args(rank=r)) for r in (1, 2)]
    flows = []
    for executor in [server] + clients:
        flow = FedMLAlgorithmFlow(_Args(rank=executor.get_id()), executor)
        flow.add_flow("init_global_model", FlowServer.init_global_model)
        flow.add_flow("handle_init", FlowClient.handle_init_global_model)
        flow.add_flow("local_training", FlowClient.local_training)
        # server_aggregate returns Params once all clients reported; tag it
        # FINISH so completion (not premature first-report) ends the flow
        flow.add_flow("server_aggregate", FlowServer.server_aggregate_then_finish)
        flow.build()
        flows.append(flow)
    threads = [f.run_async() for f in flows]
    for f in flows:
        assert f.wait_finished(timeout=30)
    for t in threads:
        t.join(timeout=10)
    assert server.rounds_done == 1  # both clients were aggregated, not one


def test_flow_task_must_be_method():
    LoopbackHub.reset()
    server = FlowServer(_Args(rank=0))
    flow = FedMLAlgorithmFlow(_Args(rank=0), server)
    with pytest.raises(ValueError):
        flow.add_flow("bad", lambda: None)
