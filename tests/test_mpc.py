"""MPC primitive tests (reference has no unit suite for core/mpc — these
verify the exact algebraic contracts secagg/lightsecagg rely on)."""

from __future__ import annotations

import numpy as np
import pytest

from fedml_tpu.core.mpc import (
    FIELD_PRIME,
    BGW_decoding,
    BGW_encoding,
    LCC_decoding_with_points,
    LCC_encoding_with_points,
    aggregate_mask_reconstruction,
    compute_aggregate_encoded_mask,
    generate_additive_shares,
    mask_encoding,
    mod_inverse,
    my_key_agreement,
    my_pk_gen,
    transform_finite_to_tensor,
    transform_tensor_to_finite,
)
from fedml_tpu.core.mpc.secagg import mask_model_update, pairwise_mask


def test_mod_inverse():
    rng = np.random.default_rng(0)
    a = rng.integers(1, int(FIELD_PRIME), size=100, dtype=np.int64)
    inv = mod_inverse(a)
    assert np.all((a * inv) % FIELD_PRIME == 1)


def test_quantization_roundtrip():
    rng = np.random.default_rng(1)
    x = rng.standard_normal(1000).astype(np.float32)
    z = transform_tensor_to_finite(x, q_bits=16)
    x2 = transform_finite_to_tensor(z, q_bits=16)
    assert np.max(np.abs(x - x2)) < 2 ** -15


def test_quantized_sum_matches_float_sum():
    """The property SecAgg depends on: field-sum of quantized updates
    dequantizes to the float sum."""
    rng = np.random.default_rng(2)
    xs = [rng.standard_normal(257).astype(np.float32) for _ in range(10)]
    zs = [transform_tensor_to_finite(x) for x in xs]
    ztot = np.mod(np.sum(np.stack(zs), axis=0), FIELD_PRIME)
    back = transform_finite_to_tensor(ztot)
    assert np.max(np.abs(back - np.sum(xs, axis=0))) < 10 * 2 ** -16


def test_additive_shares():
    rng = np.random.default_rng(3)
    secret = transform_tensor_to_finite(rng.standard_normal(64))
    shares = generate_additive_shares(secret, 5, rng)
    assert shares.shape == (5, 64)
    assert np.all(np.mod(shares.sum(axis=0), FIELD_PRIME) == secret)
    # any 4 shares are uniform-ish: reconstruction must fail without all
    assert not np.all(np.mod(shares[:4].sum(axis=0), FIELD_PRIME) == secret)


def test_bgw_roundtrip():
    rng = np.random.default_rng(4)
    secret = transform_tensor_to_finite(rng.standard_normal(32))
    n, t = 7, 3
    shares = BGW_encoding(secret, n, t, rng)
    # any t+1 = 4 shares reconstruct
    idx = [1, 3, 4, 6]
    rec = BGW_decoding(shares[idx], np.array(idx, dtype=np.int64) + 1)
    assert np.all(rec == secret)


def test_lcc_roundtrip():
    rng = np.random.default_rng(5)
    K, N = 4, 9
    X = rng.integers(0, int(FIELD_PRIME), size=(K, 16), dtype=np.int64)
    alphas = np.arange(1, K + 1, dtype=np.int64)
    betas = np.arange(K + 1, K + N + 1, dtype=np.int64)
    enc = LCC_encoding_with_points(X, alphas, betas)
    # decode from any K of the N shares back to the alphas
    pick = [0, 2, 5, 8]
    dec = LCC_decoding_with_points(enc[pick], betas[pick], alphas)
    assert np.all(dec == X)


def test_key_agreement_symmetric():
    pk_a = my_pk_gen(12345)
    pk_b = my_pk_gen(67890)
    assert my_key_agreement(12345, pk_b) == my_key_agreement(67890, pk_a)


def test_pairwise_masks_cancel():
    rng = np.random.default_rng(6)
    n_clients = 4
    # symmetric pairwise keys
    keys = {}
    for i in range(n_clients):
        for j in range(i + 1, n_clients):
            keys[(i, j)] = int(rng.integers(1, 2**31))
    xs = [rng.standard_normal(50).astype(np.float32) for _ in range(n_clients)]
    masked = []
    for i in range(n_clients):
        peer_keys = {j: keys[(min(i, j), max(i, j))] for j in range(n_clients) if j != i}
        z = transform_tensor_to_finite(xs[i])
        masked.append(mask_model_update(z, i, peer_keys))
    total = np.mod(np.sum(np.stack(masked), axis=0), FIELD_PRIME)
    back = transform_finite_to_tensor(total)
    assert np.max(np.abs(back - np.sum(xs, axis=0))) < 10 * 2 ** -16


def test_lightsecagg_dropout_recovery():
    """3 of 5 clients survive; server recovers the SUM of surviving masks from
    u encoded shares (t=1 privacy, d=40 mask length)."""
    rng = np.random.default_rng(7)
    n, t, u, d = 5, 1, 3, 40
    masks = [rng.integers(0, int(FIELD_PRIME), size=d, dtype=np.int64) for _ in range(n)]
    encoded = [mask_encoding(d, n, t, u, m, np.random.default_rng(100 + i)) for i, m in enumerate(masks)]
    # encoded[i][j] is the sub-mask client i sends to client j
    surviving = [0, 2, 4]  # clients 1,3 dropped
    agg_encoded = {}
    for j in surviving:
        rows = {i: encoded[i][j] for i in surviving}
        agg_encoded[j + 1] = compute_aggregate_encoded_mask(rows, surviving)
    rec = aggregate_mask_reconstruction(agg_encoded, t, u, d)
    expect = np.mod(np.sum(np.stack([masks[i] for i in surviving]), axis=0), FIELD_PRIME)
    assert np.all(rec == expect)
