"""Over-commit + deadline-quorum pacing (core/population/pacing.py) layered
on the round-timeout machinery (core/distributed/straggler.py).

Two levels:

* mixin-level, with a stub aggregator — the quorum close condition, the
  reject-late accounting on stale uploads, the re-arm-below-floor path and
  generation safety, all deterministic (no wall clock);
* end-to-end over LOOPBACK with a scripted ``faults.py`` delay plan — one
  silo's upload is held in flight, the round must close at quorum, and the
  straggler's late upload must be rejected AND counted in ``cohort_stats``.
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.arguments import Arguments
from fedml_tpu.core import mlops
from fedml_tpu.core.distributed.communication.loopback import LoopbackHub
from fedml_tpu.core.distributed.straggler import RoundTimeoutMixin
from fedml_tpu.core.mlops import FanoutSink, InMemorySink
from fedml_tpu.core.population import PopulationPacingMixin


# ---------------------------------------------------------------------------
# Mixin level (stub aggregator, no transport, no wall clock)
# ---------------------------------------------------------------------------

class _StubAggregator:
    """The three calls the close path makes, over a plain set of ids."""

    def __init__(self, expected):
        self.expected = int(expected)
        self.flags = []
        self.consumed = None

    def note(self, cid):
        self.flags.append(int(cid))

    def received_indices(self):
        return list(self.flags)

    def check_whether_all_receive(self):
        return len(self.flags) >= self.expected

    def consume_received(self, got):
        self.consumed = list(got)
        return list(got)


def _manager(n=3, per_round=2, overcommit=1.0, quorum=0, timeout_s=30.0,
             min_clients=1):
    class _M(PopulationPacingMixin, RoundTimeoutMixin):
        pass

    class _A:
        pass

    a = _A()
    a.round_timeout_s = timeout_s
    a.round_timeout_min_clients = min_clients
    a.round_idx = 0
    a.pacing_overcommit = overcommit
    a.pacing_quorum = quorum
    a.selection_policy = "uniform"

    m = _M()
    m.args = a
    m.init_straggler_tolerance(a)
    m.init_population(a, list(range(1, n + 1)), rng_style="pcg64")
    m.client_id_list_in_this_round = m._population_round_list(0, per_round)
    m.aggregator = _StubAggregator(expected=len(m.client_id_list_in_this_round))
    m.finalized = []
    m._finalize_round = m.finalized.append
    return m


class TestPacingMixin:
    def test_overcommit_invite_list(self):
        m = _manager(n=6, per_round=4, overcommit=1.5)
        try:
            assert len(m.client_id_list_in_this_round) == 6  # ceil(4 * 1.5)
            assert m.population.quorum == 4  # quorum defaults to target K
        finally:
            m._cancel_round_timer()

    def test_pacing_off_is_wait_for_all(self):
        m = _manager(n=3, per_round=3)  # overcommit 1.0, quorum 0: inert
        assert not m.population.pacer.enabled
        for cid in m.client_id_list_in_this_round[:-1]:
            m.aggregator.note(cid)
            m._note_population_report(cid)
            assert m._close_round_if_complete() is False
        last = m.client_id_list_in_this_round[-1]
        m.aggregator.note(last)
        m._note_population_report(last)
        assert m._close_round_if_complete() is True
        assert m.finalized == [None]  # reference full-cohort close path
        assert m._had_timeout_close is False
        assert m.population.history[-1]["close_reason"] == "complete"

    def test_quorum_close_then_late_upload_rejected_and_counted(self):
        """The pacing contract end to end at the mixin seam: close at quorum
        with the straggler outstanding, then its late upload (old round tag)
        is dropped by the stale-upload policy and lands in the registry's
        rejected_late accounting."""
        m = _manager(n=3, per_round=2, overcommit=1.5)  # invite all 3, K=2
        invited = m.client_id_list_in_this_round
        assert len(invited) == 3 and m.population.quorum == 2

        m.aggregator.note(invited[0])
        m._note_population_report(invited[0])
        assert m._close_round_if_complete() is False  # 1 < quorum

        m.aggregator.note(invited[1])
        m._note_population_report(invited[1])
        assert m._close_round_if_complete() is True
        assert m.aggregator.consumed == [invited[0], invited[1]]
        assert m.finalized == [[invited[0], invited[1]]]
        # a straggler is outstanding: untagged late arrivals are now
        # droppable, exactly as after a deadline close
        assert m._had_timeout_close is True
        stats = m.population.history[-1]
        assert stats["close_reason"] == "quorum"
        assert stats["invited"] == 3 and stats["reported"] == 2
        assert stats["failed"] == 1

        # the server moves on; the straggler's round-0 upload arrives late
        m.args.round_idx = 1
        assert m._is_stale_upload(0, sender=invited[2]) is True
        assert m.population.registry.record(invited[2])["rejected_late"] == 1
        assert m.population.registry.snapshot()["rejected_late_total"] == 1

    def test_full_house_close_is_complete_even_with_pacing_on(self):
        m = _manager(n=3, per_round=3, overcommit=1.0, quorum=2)
        assert m.population.pacer.enabled  # quorum knob alone enables pacing
        for cid in m.client_id_list_in_this_round[:2]:
            m.aggregator.note(cid)
            m._note_population_report(cid)
        # feed the third BEFORE the close check runs (burst arrival): the
        # close must report 'complete', not 'quorum'
        third = m.client_id_list_in_this_round[2]
        m.aggregator.note(third)
        m._note_population_report(third)
        assert m._close_round_if_complete() is True
        assert m._had_timeout_close is False
        assert m.population.history[-1]["close_reason"] == "complete"

    def test_deadline_close_emits_cohort_stats(self):
        m = _manager(n=3, per_round=2, overcommit=1.5, min_clients=1)
        try:
            invited = m.client_id_list_in_this_round
            m.aggregator.note(invited[0])
            m._note_population_report(invited[0])
            m._on_round_timeout(m._gen)  # the deadline fires below quorum
            assert m.finalized == [[invited[0]]]
            assert m._had_timeout_close is True
            stats = m.population.history[-1]
            assert stats["close_reason"] == "deadline"
            assert stats["reported"] == 1 and stats["failed"] == 2
        finally:
            m._cancel_round_timer()

    def test_timeout_below_floor_rearms_instead_of_closing(self):
        m = _manager(n=3, per_round=2, overcommit=1.5, min_clients=2)
        try:
            invited = m.client_id_list_in_this_round
            m.aggregator.note(invited[0])
            m._note_population_report(invited[0])
            m._on_round_timeout(m._gen)  # 1 < min_clients floor
            assert m.finalized == []  # no close
            assert m.population.history == []  # no cohort_stats emitted
            assert m._round_timer is not None  # timer re-armed
        finally:
            m._cancel_round_timer()

    def test_stale_generation_timeout_is_a_noop(self):
        m = _manager(n=3, per_round=2, overcommit=1.5)
        try:
            for cid in m.client_id_list_in_this_round:
                m.aggregator.note(cid)
                m._note_population_report(cid)
            stale_gen = m._gen
            m._gen += 1  # the phase closed; the in-flight callback lost
            m._on_round_timeout(stale_gen)
            assert m.finalized == [] and m.population.history == []
        finally:
            m._cancel_round_timer()

    def test_rejoin_hook_reaches_registry(self):
        m = _manager(n=3, per_round=2)
        m.client_online_status = {}
        m.is_initialized = True
        m._note_client_online(2, epoch="aaa")  # first sight after init
        assert m.population.registry.record(2)["rejoins"] == 1


# ---------------------------------------------------------------------------
# End to end: chaos-style delay plan over LOOPBACK
# ---------------------------------------------------------------------------

def _e2e_args(run_id: str, n: int, **extra):
    cfg = {
        "common_args": {"training_type": "cross_silo", "random_seed": 0,
                        "run_id": run_id},
        "data_args": {"dataset": "synthetic", "data_cache_dir": "",
                      "partition_method": "homo", "synthetic_train_size": 240},
        "model_args": {"model": "lr"},
        "train_args": {
            "federated_optimizer": "FedAvg",
            "client_num_in_total": n,
            "client_num_per_round": n,
            "comm_round": 2,
            "epochs": 1,
            "batch_size": 16,
            "client_optimizer": "sgd",
            "learning_rate": 0.1,
            **extra,
        },
        "validation_args": {"frequency_of_the_test": 1},
        "comm_args": {"backend": "LOOPBACK"},
    }
    return Arguments.from_dict(cfg).validate()


def _run_server_bounded(server, timeout_s=150):
    import faulthandler

    out = {}

    def _target():
        try:
            out["history"] = server.run()
        except BaseException as e:
            out["exc"] = e

    t = threading.Thread(target=_target, daemon=True)
    t.start()
    t.join(timeout=timeout_s)
    if t.is_alive():
        faulthandler.dump_traceback()
        raise AssertionError(f"server.run() wedged for {timeout_s}s")
    if "exc" in out:
        raise out["exc"]
    return out["history"]


def test_quorum_close_with_late_upload_rejected_e2e():
    """3 silos, target K=2, overcommit 1.5 (invite all 3), with a faults.py
    delay holding silo 3's round-0 upload in flight: round 0 must close at
    quorum with 2 uploads, and the held upload must arrive during round 1,
    be dropped by its stale round tag, and show up in the cohort_stats
    stream (per-round ``rejected_late`` and fleet ``rejected_late_total``)."""
    LoopbackHub.reset()
    n = 3
    extra = dict(
        client_num_per_round=2,
        pacing_overcommit=1.5,
        round_timeout_s=30.0,
        fault_plan={
            "seed": 7,
            "rules": [
                # hold silo 3's round-0 upload (msg_type 3) in flight long
                # enough that the quorum close beats it...
                {"kind": "delay", "direction": "send", "sender": 3,
                 "msg_type": 3, "round": 0, "times": 1, "delay_s": 1.0},
                # ...and hold silos 1+2's round-1 sync (msg_type 2) even
                # longer, so the late upload lands while round 1 is open
                {"kind": "delay", "direction": "send", "sender": 0,
                 "receiver": [1, 2], "msg_type": 2, "round": 1, "times": 2,
                 "delay_s": 3.0},
            ],
        },
    )

    def mk_args(rank, role):
        a = _e2e_args("pop-pace-1", n, **extra)
        a.role, a.rank = role, rank
        return fedml_tpu.init(a, should_init_logs=False)

    from fedml_tpu.cross_silo.client.client import Client
    from fedml_tpu.cross_silo.server.server import Server

    args_s = mk_args(0, "server")
    mem = InMemorySink()
    mlops.init(args_s, FanoutSink([mem]))
    try:
        ds, out_dim = fedml_tpu.data.load(args_s)
        server = Server(args_s, None, ds, fedml_tpu.models.create(args_s, out_dim))

        clients = []
        for r in range(1, n + 1):
            a = mk_args(r, "client")
            ds_c, od = fedml_tpu.data.load(a)
            clients.append(Client(a, None, ds_c, fedml_tpu.models.create(a, od)))

        threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
        for t in threads:
            t.start()
        history = _run_server_bounded(server)
        assert len(history) == 2  # both rounds completed despite the holds

        deadline = time.time() + 120
        for t in threads:
            t.join(timeout=max(1.0, deadline - time.time()))
        assert not any(t.is_alive() for t in threads)

        records = mem.by_topic("cohort_stats")
        assert len(records) == 2  # one per round close
        r0 = next(rec for rec in records if rec["round_idx"] == 0)
        assert r0["close_reason"] == "quorum"
        assert r0["invited"] == 3 and r0["reported"] == 2 and r0["failed"] == 1
        assert r0["target_k"] == 2 and r0["overcommit"] == 1.5
        # the held round-0 upload was rejected while a later round was open
        assert records[-1]["rejected_late_total"] >= 1
        assert any(rec["rejected_late"] >= 1 for rec in records)
        # the registry agrees with the sink stream
        pop = server.server_manager.population
        assert pop.registry.snapshot()["rejected_late_total"] >= 1
    finally:
        mlops.finish()


def test_pacing_off_cross_silo_round_flow_unchanged():
    """Parity guard at the E2E seam: with the pacing knobs at their defaults
    the cross-silo run closes every round 'complete' with the full cohort —
    the pre-population round flow, now with cohort_stats observability."""
    LoopbackHub.reset()
    n = 2

    def mk_args(rank, role):
        a = _e2e_args("pop-pace-2", n)
        a.role, a.rank = role, rank
        return fedml_tpu.init(a, should_init_logs=False)

    from fedml_tpu.cross_silo.client.client import Client
    from fedml_tpu.cross_silo.server.server import Server

    args_s = mk_args(0, "server")
    mem = InMemorySink()
    mlops.init(args_s, FanoutSink([mem]))
    try:
        ds, out_dim = fedml_tpu.data.load(args_s)
        server = Server(args_s, None, ds, fedml_tpu.models.create(args_s, out_dim))
        clients = []
        for r in range(1, n + 1):
            a = mk_args(r, "client")
            ds_c, od = fedml_tpu.data.load(a)
            clients.append(Client(a, None, ds_c, fedml_tpu.models.create(a, od)))
        threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
        for t in threads:
            t.start()
        history = _run_server_bounded(server)
        assert len(history) == 2
        for t in threads:
            t.join(timeout=120)
        assert not any(t.is_alive() for t in threads)

        records = mem.by_topic("cohort_stats")
        assert len(records) == 2
        for rec in records:
            assert rec["close_reason"] == "complete"
            assert rec["invited"] == rec["reported"] == n
            assert rec["failed"] == 0 and rec["rejected_late"] == 0
    finally:
        mlops.finish()
