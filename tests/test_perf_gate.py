"""tools/perf_gate.py — the perf-regression gate over BENCH trajectories.

The gate exists because BENCH_r03-r05 went dark (probe timeouts, empty
tails) and shipped unnoticed.  These tests pin the acceptance contract:
the real r01-r02 records pass, the real r03 artifact FAILS the gate, a
synthetic regressed record fails the tolerance band, and the schema
constants in bench.py and perf_gate.py cannot drift apart.
"""

from __future__ import annotations

import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import perf_gate


def _round_file(tmp_path, n, rec, rc=0):
    """One driver-format BENCH_rNN.json with ``rec`` as the metric line."""
    tail = "noise line\n" + (json.dumps(rec) + "\n" if rec is not None else "")
    path = tmp_path / f"BENCH_r{n:02d}.json"
    path.write_text(json.dumps(
        {"n": n, "cmd": "python bench.py", "rc": rc, "tail": tail}))
    return str(path)


def _full(n, value, **extra):
    rec = {"metric": "m", "unit": "u", "value": value, "vs_baseline": value,
           "bench_schema": perf_gate.BENCH_SCHEMA_CURRENT, "mode": "full",
           "git_rev": "abc1234"}
    rec.update(extra)
    return rec


class TestRealTrajectory:
    """Against the repo's actual checked-in BENCH artifacts."""

    def test_r01_r02_pass(self, capsys):
        rc = perf_gate.main([os.path.join(REPO, "BENCH_r01.json"),
                             os.path.join(REPO, "BENCH_r02.json")])
        assert rc == 0
        assert "OK" in capsys.readouterr().out

    def test_real_r03_dark_round_fails(self, capsys):
        rc = perf_gate.main([os.path.join(REPO, "BENCH_r01.json"),
                             os.path.join(REPO, "BENCH_r02.json"),
                             os.path.join(REPO, "BENCH_r03.json")])
        assert rc == 1
        assert "DARK ROUND" in capsys.readouterr().out

    def test_known_dark_grandfathers_the_historical_window(self):
        rc = perf_gate.main(["--known-dark", "3,4,5"])
        assert rc == 0

    def test_advisory_reports_but_exits_zero(self, capsys):
        rc = perf_gate.main(["--advisory"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "ADVISORY" in out and "DARK ROUND" in out


class TestTolerance:
    def test_regressed_latest_fails(self, tmp_path, capsys):
        paths = [_round_file(tmp_path, 1, _full(1, 10.0)),
                 _round_file(tmp_path, 2, _full(2, 11.0)),
                 _round_file(tmp_path, 3, _full(3, 2.0))]  # < 50% of median
        rc = perf_gate.main(paths + ["--baseline", str(tmp_path / "nope")])
        assert rc == 1
        assert "REGRESSION" in capsys.readouterr().out

    def test_within_band_passes(self, tmp_path):
        paths = [_round_file(tmp_path, 1, _full(1, 10.0)),
                 _round_file(tmp_path, 2, _full(2, 11.0)),
                 _round_file(tmp_path, 3, _full(3, 6.0))]  # >= 50% of median
        assert perf_gate.main(
            paths + ["--baseline", str(tmp_path / "nope")]) == 0

    def test_new_dark_round_fails_despite_known_dark(self, tmp_path):
        paths = [_round_file(tmp_path, 1, _full(1, 10.0)),
                 _round_file(tmp_path, 2, None, rc=1),   # grandfathered
                 _round_file(tmp_path, 3, None, rc=1)]   # NEW dark round
        rc = perf_gate.main(paths + ["--known-dark", "2",
                                     "--baseline", str(tmp_path / "nope")])
        assert rc == 1

    def test_obs_overhead_cap(self, tmp_path, capsys):
        paths = [_round_file(tmp_path, 1,
                             _full(1, 10.0, obs_overhead_frac=0.4))]
        rc = perf_gate.main(paths + ["--baseline", str(tmp_path / "nope")])
        assert rc == 1
        assert "OBS OVERHEAD" in capsys.readouterr().out

    def test_published_baseline_bands_latest(self, tmp_path, capsys):
        base = tmp_path / "BASELINE.json"
        base.write_text(json.dumps({"published": {"vs_baseline": 10.0}}))
        paths = [_round_file(tmp_path, 1, _full(1, 3.0))]
        rc = perf_gate.main(paths + ["--baseline", str(base)])
        assert rc == 1
        assert "published" in capsys.readouterr().out


class TestSchemaValidation:
    def _gate(self, tmp_path, rec):
        path = _round_file(tmp_path, 1, rec)
        return perf_gate.main([path, "--baseline", str(tmp_path / "nope")])

    def test_degraded_without_reason_fails(self, tmp_path):
        rec = _full(1, 1.0)
        rec["mode"] = "degraded"
        assert self._gate(tmp_path, rec) == 1

    def test_full_with_reason_fails(self, tmp_path):
        assert self._gate(tmp_path, _full(1, 1.0, degraded_reason="x")) == 1

    def test_missing_git_rev_fails(self, tmp_path):
        rec = _full(1, 1.0)
        del rec["git_rev"]
        assert self._gate(tmp_path, rec) == 1

    def test_unknown_schema_fails(self, tmp_path):
        assert self._gate(
            tmp_path, _full(1, 1.0, bench_schema=99)) == 1

    def test_failed_mode_allows_null_value_but_needs_reason(self, tmp_path):
        rec = _full(1, None, degraded_reason="unhandled RuntimeError")
        rec["mode"] = "failed"
        assert self._gate(tmp_path, rec) == 0

    def test_legacy_record_numeric_value_passes(self, tmp_path):
        # pre-schema records (r01/r02 vintage) stay valid
        assert self._gate(
            tmp_path, {"metric": "m", "unit": "u", "value": 3.0}) == 0

    def test_legacy_record_non_numeric_value_fails(self, tmp_path):
        assert self._gate(
            tmp_path, {"metric": "m", "unit": "u", "value": "fast"}) == 1


class TestOutputAndParsing:
    def test_json_format_payload(self, tmp_path, capsys):
        paths = [_round_file(tmp_path, 1, _full(1, 10.0)),
                 _round_file(tmp_path, 2, None, rc=1)]
        rc = perf_gate.main(paths + ["--advisory", "--format", "json",
                                     "--baseline", str(tmp_path / "nope")])
        assert rc == 0
        payload = json.loads(capsys.readouterr().out)
        assert payload["ok"] is False and payload["advisory"] is True
        assert payload["n_rounds"] == 2
        assert [r["dark"] for r in payload["rounds"]] == [False, True]

    def test_extract_metric_line_takes_the_last(self):
        tail = ('{"metric": "old", "value": 1}\n'
                "junk {not json}\n"
                '{"metric": "new", "value": 2}\n')
        assert perf_gate.extract_metric_line(tail)["metric"] == "new"

    def test_unreadable_path_exits_2(self, tmp_path):
        assert perf_gate.main([str(tmp_path / "missing.json")]) == 2

    def test_bare_metric_record_accepted(self, tmp_path):
        path = tmp_path / "BENCH_r01.json"
        path.write_text(json.dumps(_full(1, 5.0)))
        assert perf_gate.main([str(path),
                               "--baseline", str(tmp_path / "nope")]) == 0


def test_schema_constant_pinned_to_bench():
    """bench.py stamps what perf_gate.py validates — one source of truth,
    two files, this assertion is the weld."""
    sys.path.insert(0, REPO)
    import bench

    assert bench.BENCH_SCHEMA == perf_gate.BENCH_SCHEMA_CURRENT
