"""The staged server ingest path (PR 10): deferred acks + zero-copy decode.

Three strata, mirroring the fault-tolerance and obs suites:

* **Unit** — the knob parse, the thread-local ticket sink, and the
  ``ZeroCopyDecoder``'s two planes (pytree intern, flax-msgpack bytes):
  arena reuse, signature-drift fallback, and the ``forget`` lifecycle.
* **Pipeline** — ``_IngestPipeline`` against fake manager/link seams: a
  message is NEVER acked before every journal ticket its dispatch produced
  is durable; a failed dispatch or failed batch forgets the msg-id (so the
  sender retransmits) and withholds the ack; FIFO dispatch order survives
  the staging.
* **Topology** — the acceptance layer, reusing the chaos harness from
  ``test_fault_tolerance``: the full chaos plan and the server-kill plan
  run with ``ingest_pipeline=True`` must converge BIT-IDENTICAL to the
  fault-free host-path model with exactly-once upload accounting, and a
  traced pipelined run must keep every round a single CLOSED span tree
  (``trace_report --assert-closed``) with the per-message ``ingest.accept``
  span present — on LOOPBACK in tier 1 and on every socketed backend in
  the slow sweep.
"""

from __future__ import annotations

import contextlib
import json
import os
import sys
import threading
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))

import trace_report

import test_fault_tolerance as _ft
from fedml_tpu.core import mlops, obs
from fedml_tpu.core import ingest
from fedml_tpu.core.checkpoint import JournalTicket
from fedml_tpu.core.distributed.comm_manager import _IngestPipeline
from fedml_tpu.core.distributed.communication.loopback import LoopbackHub
from fedml_tpu.core.distributed.communication.message import Message
from fedml_tpu.core.ingest import ZeroCopyDecoder, deferred_ack_scope
from fedml_tpu.core.mlops import FanoutSink, InMemorySink
from fedml_tpu.core.obs.trace import trace_id_for

# the pipeline knobs every pipelined topology in this file runs under: a
# visible coalescing window with a small batch cap, so group commit is
# exercised (not degenerate single-record batches) inside a test budget
_PIPELINE_KNOBS = dict(
    ingest_pipeline=True,
    journal_group_commit_ms=20.0,
    journal_group_commit_max=8,
)


@pytest.fixture(autouse=True)
def _obs_hygiene():
    """obs state is process-global: every test leaves it disabled and the
    registry empty so no other module inherits a live tracer."""
    yield
    obs.shutdown()
    obs.registry().reset()


def _fallbacks() -> float:
    return obs.registry().get_counter("ingest.decode_fallbacks")


# ---------------------------------------------------------------------------
# Unit: knob parse + ticket sink
# ---------------------------------------------------------------------------

class TestPipelineKnob:
    class _A:
        def __init__(self, v):
            self.ingest_pipeline = v

    def test_absent_is_off(self):
        class _Bare:
            pass

        assert ingest.pipeline_enabled(_Bare()) is False

    @pytest.mark.parametrize("v", [True, 1, "1", "true", "True", " on ", "yes"])
    def test_truthy_forms(self, v):
        assert ingest.pipeline_enabled(self._A(v)) is True

    @pytest.mark.parametrize("v", [False, 0, "", "0", "false", "off", "no"])
    def test_falsy_forms(self, v):
        assert ingest.pipeline_enabled(self._A(v)) is False


class TestTicketSink:
    def test_no_ambient_sink_outside_scope(self):
        assert ingest.current_sink() is None

    def test_scope_collects_and_restores(self):
        with deferred_ack_scope() as sink:
            assert ingest.current_sink() is sink
            t = JournalTicket()
            ingest.current_sink().add(t)
            assert sink.tickets == [t]
        assert ingest.current_sink() is None

    def test_nested_scopes_restore_outer(self):
        with deferred_ack_scope() as outer:
            with deferred_ack_scope() as inner:
                assert ingest.current_sink() is inner
            assert ingest.current_sink() is outer
            assert inner is not outer

    def test_scope_is_thread_local(self):
        seen = []
        with deferred_ack_scope():
            t = threading.Thread(target=lambda: seen.append(ingest.current_sink()))
            t.start()
            t.join()
        assert seen == [None]


# ---------------------------------------------------------------------------
# Unit: zero-copy decoder, pytree plane
# ---------------------------------------------------------------------------

def _tree(scale=1.0, shape=(4, 3)):
    return {
        "w": np.arange(np.prod(shape), dtype=np.float32).reshape(shape) * scale,
        "b": np.ones(shape[0], dtype=np.float32) * scale,
    }


class TestInternPlane:
    def test_intern_reuses_arena_storage(self):
        dec = ZeroCopyDecoder()
        before = _fallbacks()
        out1 = dec.intern("slot", _tree(1.0))
        out2 = dec.intern("slot", _tree(2.0))
        # the second intern refills the SAME storage the first allocated
        assert out1["w"] is out2["w"] and out1["b"] is out2["b"]
        np.testing.assert_array_equal(out2["w"], _tree(2.0)["w"])
        np.testing.assert_array_equal(out2["b"], _tree(2.0)["b"])
        assert _fallbacks() == before

    def test_interned_tree_detached_from_source(self):
        dec = ZeroCopyDecoder()
        src = _tree(3.0)
        out = dec.intern("slot", src)
        src["w"][:] = -1.0
        np.testing.assert_array_equal(out["w"], _tree(3.0)["w"])

    def test_signature_drift_falls_back(self):
        dec = ZeroCopyDecoder()
        dec.intern("slot", _tree())
        before = _fallbacks()
        drifted = _tree(shape=(5, 3))
        out = dec.intern("slot", drifted)
        assert out is drifted  # fallback returns the original tree untouched
        assert _fallbacks() == before + 1
        # other slots are unaffected: each slot has its own arena
        other = dec.intern("other", _tree(shape=(5, 3)))
        np.testing.assert_array_equal(other["w"], drifted["w"])

    def test_forget_drops_the_arena(self):
        dec = ZeroCopyDecoder()
        out1 = dec.intern("slot", _tree())
        dec.forget("slot")
        out2 = dec.intern("slot", _tree())
        assert out1["w"] is not out2["w"]


# ---------------------------------------------------------------------------
# Unit: zero-copy decoder, bytes plane (flax msgpack blobs)
# ---------------------------------------------------------------------------

def _blob(scale=1.0, shape=(4, 3), extra_scalars=True):
    from flax import serialization

    tree = _tree(scale, shape)
    if extra_scalars:
        # the wire payload mixes ndarray leaves with plain scalars — the
        # shape that forced the decoder's separate blob-arena plane
        tree.update({"sender": 2, "n_samples": 80})
    return serialization.msgpack_serialize(tree)


def _restored(blob):
    from flax import serialization

    return serialization.msgpack_restore(blob)


class TestBytesPlane:
    def test_learning_then_steady_state_no_fallback(self):
        dec = ZeroCopyDecoder()
        before = _fallbacks()
        out1 = dec.decode("slot", _blob(1.0))
        out2 = dec.decode("slot", _blob(2.0))
        assert _fallbacks() == before
        np.testing.assert_array_equal(out2["w"], _restored(_blob(2.0))["w"])
        assert out2["sender"] == 2 and out2["n_samples"] == 80
        # the learning pass's decoded leaves BECAME the arena storage, and
        # the steady state refills them in place
        assert out1["w"] is out2["w"] and out1["b"] is out2["b"]

    def test_steady_state_matches_plain_restore_bitwise(self):
        dec = ZeroCopyDecoder()
        dec.decode("slot", _blob(1.0))
        for scale in (2.0, -0.5, 7.25):
            got = dec.decode("slot", _blob(scale))
            ref = _restored(_blob(scale))
            for k in ("w", "b"):
                np.testing.assert_array_equal(got[k], ref[k])
                assert got[k].dtype == ref[k].dtype

    def test_shape_drift_falls_back_correctly(self):
        dec = ZeroCopyDecoder()
        dec.decode("slot", _blob())
        before = _fallbacks()
        drifted = _blob(shape=(5, 3))
        out = dec.decode("slot", drifted)
        assert _fallbacks() == before + 1
        np.testing.assert_array_equal(out["w"], _restored(drifted)["w"])

    def test_leaf_count_drift_falls_back(self):
        from flax import serialization

        dec = ZeroCopyDecoder()
        dec.decode("slot", _blob())
        before = _fallbacks()
        extra = _tree()
        extra["extra"] = np.zeros(2, dtype=np.float32)
        out = dec.decode("slot", serialization.msgpack_serialize(extra))
        assert _fallbacks() == before + 1
        np.testing.assert_array_equal(out["extra"], np.zeros(2, np.float32))

    def test_scalar_only_payload_never_learns(self):
        from flax import serialization

        dec = ZeroCopyDecoder()
        blob = serialization.msgpack_serialize({"sender": 1, "n": 40})
        assert dec.decode("s", blob) == {"sender": 1, "n": 40}
        assert dec.decode("s", blob) == {"sender": 1, "n": 40}
        assert dec._blob_arenas == {}  # nothing to arena: no ndarray frames

    def test_decoded_leaves_are_writable(self):
        dec = ZeroCopyDecoder()
        out = dec.decode("slot", _blob())
        out["w"] += 1.0  # the learning pass must detach from the wire buffer
        out = dec.decode("slot", _blob(2.0))
        np.testing.assert_array_equal(out["w"], _restored(_blob(2.0))["w"])

    def test_forget_drops_blob_arena(self):
        dec = ZeroCopyDecoder()
        out1 = dec.decode("slot", _blob())
        dec.forget("slot")
        out2 = dec.decode("slot", _blob())
        assert out1["w"] is not out2["w"]


# ---------------------------------------------------------------------------
# Pipeline: ack-after-durability against fake seams
# ---------------------------------------------------------------------------

class _FakeLink:
    def __init__(self):
        self.acked, self.forgotten = [], []

    def _send_ack(self, msg):
        self.acked.append(msg)

    def forget(self, msg):
        self.forgotten.append(msg)


class _FakeManager:
    rank = 0

    def __init__(self, handler=None):
        self.dispatched = []
        self._handler = handler

    def _dispatch(self, msg):
        self.dispatched.append(msg)
        if self._handler is not None:
            self._handler(msg)


def _msg(mtype=3, msg_id="2:abc:1"):
    m = Message(mtype, 2, 0)
    if msg_id is not None:
        m.add_params(Message.MSG_ARG_KEY_MSG_ID, msg_id)
    return m


@contextlib.contextmanager
def _pipeline(handler=None, depth=8):
    link = _FakeLink()
    manager = _FakeManager(handler)
    pipe = _IngestPipeline(manager, link, depth=depth)
    try:
        yield pipe, manager, link
    finally:
        pipe.stop()


class TestIngestPipeline:
    def test_no_tickets_acks_after_dispatch(self):
        with _pipeline() as (pipe, manager, link):
            m = _msg()
            pipe._process(m, needs_ack=True)
            assert manager.dispatched == [m]
            assert link.acked == [m] and link.forgotten == []

    def test_needs_ack_false_never_acks(self):
        with _pipeline() as (pipe, manager, link):
            pipe._process(_msg(mtype="connection_ready", msg_id=None),
                          needs_ack=False)
            assert len(manager.dispatched) == 1
            assert link.acked == []

    def test_ack_released_only_after_ticket_durable(self):
        """The tentpole contract: no transport ack before the journal batch
        holding the upload is fsynced."""
        ticket = JournalTicket()

        def handler(msg):
            ingest.current_sink().add(ticket)

        with _pipeline(handler) as (pipe, manager, link):
            m = _msg()
            pipe._process(m, needs_ack=True)
            assert manager.dispatched == [m]
            assert link.acked == []  # dispatched, journaled... NOT acked yet
            ticket._mark()  # the group-commit thread fsyncs the batch
            assert link.acked == [m] and link.forgotten == []

    def test_ack_waits_for_every_ticket(self):
        t1, t2 = JournalTicket(), JournalTicket()

        def handler(msg):
            ingest.current_sink().add(t1)
            ingest.current_sink().add(t2)

        with _pipeline(handler) as (pipe, _, link):
            pipe._process(_msg(), needs_ack=True)
            t1._mark()
            assert link.acked == []  # one durable ticket is not the batch
            t2._mark()
            assert len(link.acked) == 1

    def test_failed_batch_forgets_and_withholds_ack(self):
        ticket = JournalTicket()

        def handler(msg):
            ingest.current_sink().add(ticket)

        with _pipeline(handler) as (pipe, _, link):
            m = _msg()
            pipe._process(m, needs_ack=True)
            ticket._mark(error=OSError("disk gone"))
            assert link.acked == []
            assert link.forgotten == [m]  # sender's retransmit re-journals

    def test_failed_dispatch_forgets_and_withholds_ack(self):
        def handler(msg):
            raise RuntimeError("handler blew up")

        with _pipeline(handler) as (pipe, _, link):
            m = _msg()
            pipe._process(m, needs_ack=True)  # must not raise: worker parity
            assert link.acked == []
            assert link.forgotten == [m]

    def test_already_durable_ticket_acks_inline(self):
        ticket = JournalTicket()
        ticket._mark()

        def handler(msg):
            ingest.current_sink().add(ticket)

        with _pipeline(handler) as (pipe, _, link):
            pipe._process(_msg(), needs_ack=True)
            assert len(link.acked) == 1

    def test_submit_preserves_fifo_dispatch_order(self):
        """The io stage enqueues in arrival order and ONE worker dispatches:
        the single-threaded-handler invariant every round state machine
        assumes survives the staging."""
        done = threading.Event()
        order = []

        def handler(msg):
            order.append(msg.get(Message.MSG_ARG_KEY_MSG_ID))
            if len(order) == 16:
                done.set()

        with _pipeline(handler) as (pipe, _, link):
            msgs = [_msg(msg_id=f"2:abc:{i}") for i in range(16)]
            for m in msgs:
                pipe.submit(m, needs_ack=True)
            assert done.wait(10.0), "pipeline worker did not drain the queue"
        assert order == [f"2:abc:{i}" for i in range(16)]
        assert len(link.acked) == 16

    def test_worker_survives_poison_message(self):
        calls = []

        def handler(msg):
            calls.append(msg)
            if len(calls) == 1:
                raise RuntimeError("poison")

        with _pipeline(handler) as (pipe, _, link):
            pipe.submit(_msg(msg_id="2:abc:1"), needs_ack=True)
            pipe.submit(_msg(msg_id="2:abc:2"), needs_ack=True)
            deadline = time.time() + 10.0
            while len(link.acked) < 1 and time.time() < deadline:
                time.sleep(0.01)
        assert len(calls) == 2  # the poison did not kill the worker
        assert len(link.acked) == 1 and len(link.forgotten) == 1


# ---------------------------------------------------------------------------
# Topology: the acceptance layer (chaos harness with ingest_pipeline=True)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def reference_model():
    """The fault-free HOST-PATH reference every pipelined run must bit-match:
    the staged receive path is a transport optimization, so the final model
    must be a pure function of config — not of which ingest path ran."""
    LoopbackHub.reset()
    history, final, _ = _ft._run_chaos_topology("ingest-base", knobs={})
    assert len(history) == 2
    return final


def test_pipeline_chaos_converges_bit_identical(reference_model):
    """Full chaos plan (drop + reset + duplicate + delay) + crash-and-rejoin
    with the staged pipeline on: all rounds complete, the final model is
    bit-identical to the host path's, and dedup still runs on the io stage
    (the duplicate never reaches a handler)."""
    LoopbackHub.reset()
    history, final, stats = _ft._run_chaos_topology(
        "ingest-chaos", fault_plan=_ft._full_chaos_plan(), crash_rank=1,
        knobs=dict(_ft._CHAOS_KNOBS, **_PIPELINE_KNOBS))
    assert len(history) == 2
    assert _ft._trees_bit_identical(final, reference_model), \
        "pipelined chaos run diverged from the host-path model"
    srv = stats[0]
    assert srv["dup_dropped"] >= 1  # io-stage dedup, off the worker thread
    assert srv["rejoins"] >= 1
    assert srv["acks_sent"] > 0


def test_pipeline_server_kill_exactly_once(reference_model, tmp_path):
    """The durability acceptance: a server killed between two round-0
    uploads while running the staged pipeline + group-commit journal
    restarts from snapshot + journal and converges bit-identical with
    exactly-once upload accounting — an ack was never sent for anything the
    journal had not fsynced, so replay + retransmit cannot double-count."""
    LoopbackHub.reset()
    out = _ft._run_server_kill_topology(
        "ingest-kill", tmp_path / "srv", knobs=dict(_PIPELINE_KNOBS))
    _ft._assert_recovered(*out, reference_model)


def test_pipeline_traced_rounds_closed(reference_model, tmp_path):
    """Tracing acceptance on LOOPBACK: a pipelined run (journal on, so acks
    ride the group-commit thread) keeps every round ONE closed span tree
    with the per-message ``ingest.accept`` span present, and the exported
    JSONL passes ``trace_report --assert-closed`` — the off-thread ack
    release closes its span on every path."""
    LoopbackHub.reset()
    run_id = "ingest-traced"
    mem = InMemorySink()

    class _A:
        rank = 0

        def __init__(self):
            self.run_id = run_id
            self.obs_trace = True

    mlops.init(_A(), FanoutSink([mem]))
    try:
        history, final, _ = _ft._run_chaos_topology(
            run_id, knobs=dict(_PIPELINE_KNOBS,
                               server_checkpoint_dir=str(tmp_path / "srv")))
        assert len(history) == 2
    finally:
        mlops.finish()
    assert _ft._trees_bit_identical(final, reference_model)

    records = [dict(rec, topic=t) for t, rec in list(mem.records)
               if t in trace_report.SPAN_TOPICS]
    traces = trace_report.build_traces(records)
    names = set()
    for r in range(2):
        tid = trace_id_for(run_id, r)
        assert tid in traces, f"round {r}: no trace emitted"
        assert traces[tid].problems() == [], (r, traces[tid].problems())
        names |= {sn.name for sn in traces[tid].spans.values()}
    assert "ingest.accept" in names, names
    assert "journal.append" in names, names

    path = tmp_path / "trace.jsonl"
    path.write_text("\n".join(json.dumps(r) for r in records) + "\n")
    assert trace_report.main([str(path), "--assert-closed"]) == 0

    # the pipeline's stage accounting reached the registry on every stage
    reg = obs.registry()
    for stage in ("io", "queue", "dispatch"):
        h = reg.get_histogram("ingest.stage_seconds", {"stage": stage})
        assert h is not None and h["count"] > 0, stage
    assert reg.get_histogram("ingest.batch_fsync_seconds") is not None


@pytest.mark.slow
@pytest.mark.parametrize("backend", ["TRPC", "GRPC", "MQTT_S3"])
def test_pipeline_traced_all_backends(backend, reference_model, tmp_path):
    """The cross-backend acceptance sweep: the staged pipeline over every
    socketed transport converges bit-identical AND every round still
    reconstructs as one closed span tree with ``ingest.accept`` present —
    LOOPBACK in tier 1 plus these three makes all four backends."""
    comm_extra = {}
    broker = None
    if backend == "TRPC":
        comm_extra = {"trpc_base_port": 29710, "trpc_connect_retries": 3,
                      "trpc_retry_interval_s": 0.1}
    elif backend == "GRPC":
        comm_extra = {"grpc_base_port": 29810, "grpc_send_retries": 3,
                      "grpc_send_backoff_base_s": 0.05}
    else:
        from fedml_tpu.core.distributed.communication.mqtt_s3.broker import LocalBroker

        broker = LocalBroker().start()
        comm_extra = {"mqtt_host": "127.0.0.1", "mqtt_port": broker.port,
                      "s3_blob_root": str(tmp_path / "blobs"),
                      "mqtt_reconnect_retries": 10,
                      "mqtt_reconnect_base_s": 0.05}
    run_id = f"ingest-{backend.lower()}"
    mem = InMemorySink()

    class _A:
        rank = 0

        def __init__(self):
            self.run_id = run_id
            self.obs_trace = True

    mlops.init(_A(), FanoutSink([mem]))
    try:
        history, final, _ = _ft._run_chaos_topology(
            run_id, backend=backend, comm_extra=comm_extra,
            knobs=dict(_ft._CHAOS_KNOBS, **_PIPELINE_KNOBS,
                       server_checkpoint_dir=str(tmp_path / "srv")))
        assert len(history) == 2
    finally:
        mlops.finish()
        if broker is not None:
            broker.stop()
    assert _ft._trees_bit_identical(final, reference_model)

    records = [dict(rec, topic=t) for t, rec in list(mem.records)
               if t in trace_report.SPAN_TOPICS]
    traces = trace_report.build_traces(records)
    names = set()
    for r in range(2):
        tid = trace_id_for(run_id, r)
        assert tid in traces, f"round {r}: no trace emitted"
        assert traces[tid].problems() == [], (r, traces[tid].problems())
        names |= {sn.name for sn in traces[tid].spans.values()}
    assert "ingest.accept" in names, names
    path = tmp_path / "trace.jsonl"
    path.write_text("\n".join(json.dumps(r) for r in records) + "\n")
    assert trace_report.main([str(path), "--assert-closed"]) == 0
