"""Transport micro-benchmark harness (tools/transport_bench.py — the
counterpart of the reference's python/tests/grpc_benchmark): smoke the
measurement loop per backend at tiny scale."""

import sys

import pytest

from netutil import free_port

pytestmark = pytest.mark.heavy


@pytest.mark.parametrize("backend", ["loopback", "tcp", "grpc"])
def test_backend_measures(backend):
    sys.path.insert(0, ".")
    from tools.transport_bench import bench_backend

    rows = bench_backend(backend, sizes=[1024, 65536], iters=5,
                         base_port=free_port())
    assert len(rows) == 2
    for r in rows:
        assert r["backend"] == backend
        assert r["round_trips_per_s"] > 0
        assert r["mb_per_s"] > 0
