"""Native conv (LeNet-grade) edge trainer — reference MobileNN conv parity
(android/fedmlsdk/MobileNN/src/MNN/{mnist,cifar10}.cpp): conv training in
C++, CIFAR-10 binary reader, and a cross-device e2e round with a conv model."""

import struct

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from fedml_tpu.cross_device.edge_model import load_edge_model, save_edge_model

native = pytest.importorskip("fedml_tpu.native")


@pytest.fixture(scope="module")
def lib():
    return native.load()


H = W = 12
CLASSES = 4


class LeNetTiny(nn.Module):
    """Mirrors the native conv convention: VALID conv + ReLU + 2x2 max-pool,
    flatten (row-major HWC), dense softmax head."""

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.relu(nn.Conv(6, (5, 5), padding="VALID", name="conv0")(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        return nn.Dense(CLASSES, name="head")(x)


def _conv_data(n, seed=0):
    """Images with a class-dependent bright quadrant — conv-learnable."""
    rng = np.random.RandomState(seed)
    y = rng.randint(0, CLASSES, n).astype(np.int32)
    x = rng.rand(n, H, W, 1).astype(np.float32) * 0.1
    qy, qx = y // 2, y % 2
    for i in range(n):
        x[i, qy[i] * 6:qy[i] * 6 + 6, qx[i] * 6:qx[i] * 6 + 6, 0] += 0.9
    return x, y


def _save_flax_model(path, variables):
    from fedml_tpu.cross_device.edge_model import flatten_params

    save_edge_model(path, flatten_params(variables))
    return path


def _init_model(seed=0):
    model = LeNetTiny()
    variables = model.init(jax.random.PRNGKey(seed), jnp.zeros((1, H, W, 1)))
    return model, dict(variables)


class TestConvTrainer:
    def test_learns(self, lib, tmp_path):
        x, y = _conv_data(256)
        data = str(tmp_path / "d.ftem")
        save_edge_model(data, {"x": x, "y": y})
        model, variables = _init_model()
        mpath = _save_flax_model(str(tmp_path / "m.ftem"), variables)
        t = native.EdgeTrainer(mpath, data, batch_size=32, lr=0.1, epochs=8, seed=1)
        t.train()
        acc, loss = t.evaluate()
        assert acc > 0.8, (acc, loss)
        t.close()

    def test_one_step_matches_flax(self, lib, tmp_path):
        """One full-batch SGD step in C++ == the same step in flax/optax —
        verifies the hand-written conv/pool backward against autodiff."""
        x, y = _conv_data(32, seed=3)
        data = str(tmp_path / "d.ftem")
        save_edge_model(data, {"x": x, "y": y})
        model, variables = _init_model(seed=2)
        mpath = _save_flax_model(str(tmp_path / "m.ftem"), variables)

        lr = 0.05
        t = native.EdgeTrainer(mpath, data, batch_size=64, lr=lr, epochs=1, seed=1)
        t.train()
        out = str(tmp_path / "trained.ftem")
        t.save(out)
        t.close()
        got = load_edge_model(out)

        def loss_fn(params):
            logits = model.apply(dict(variables, params=params), jnp.asarray(x))
            return jnp.mean(
                optax.softmax_cross_entropy_with_integer_labels(logits, jnp.asarray(y))
            )

        grads = jax.grad(loss_fn)(variables["params"])
        expect = jax.tree_util.tree_map(
            lambda p, g: p - lr * g, variables["params"], grads
        )
        from fedml_tpu.cross_device.edge_model import flatten_params

        flat_expect = flatten_params({"params": expect})
        for k, v in flat_expect.items():
            np.testing.assert_allclose(got[k], v, rtol=2e-4, atol=2e-5, err_msg=k)

    def test_bad_conv_model_fails_loud(self, lib, tmp_path):
        # dense head input dim mismatched with the conv chain
        x, y = _conv_data(8)
        data = str(tmp_path / "d.ftem")
        save_edge_model(data, {"x": x, "y": y})
        rng = np.random.RandomState(0)
        save_edge_model(str(tmp_path / "bad.ftem"), {
            "params/conv0/kernel": rng.randn(5, 5, 1, 6).astype(np.float32) * 0.1,
            "params/conv0/bias": np.zeros(6, np.float32),
            "params/head/kernel": rng.randn(37, CLASSES).astype(np.float32),
            "params/head/bias": np.zeros(CLASSES, np.float32),
        })
        with pytest.raises(RuntimeError, match="dense head input dim"):
            native.EdgeTrainer(str(tmp_path / "bad.ftem"), data, 8, 0.1, 1, 0)


class TestCifarReader:
    def test_bin_to_ftem(self, lib, tmp_path):
        n = 7
        rng = np.random.RandomState(5)
        labels = rng.randint(0, 10, n).astype(np.uint8)
        planes = rng.randint(0, 256, (n, 3, 32, 32)).astype(np.uint8)
        bin_path = str(tmp_path / "data_batch_1.bin")
        with open(bin_path, "wb") as f:
            for i in range(n):
                f.write(struct.pack("B", labels[i]))
                f.write(planes[i].tobytes())
        out = native.cifar10_bin_to_ftem(bin_path, str(tmp_path / "c.ftem"))
        got = load_edge_model(out)
        assert got["x"].shape == (n, 32, 32, 3)
        assert got["y"].tolist() == labels.tolist()
        # NHWC interleave of the RGB planes, scaled to [0,1]
        np.testing.assert_allclose(
            got["x"][0, 1, 2, 0], planes[0, 0, 1, 2] / 255.0, rtol=1e-6
        )
        np.testing.assert_allclose(
            got["x"][0, 1, 2, 2], planes[0, 2, 1, 2] / 255.0, rtol=1e-6
        )

    def test_truncated_bin_rejected(self, lib, tmp_path):
        bad = str(tmp_path / "bad.bin")
        open(bad, "wb").write(b"\x00" * 100)
        with pytest.raises(RuntimeError, match="CIFAR-10"):
            native.cifar10_bin_to_ftem(bad, str(tmp_path / "c.ftem"))


class TestConvCrossDevice:
    def test_round_with_native_conv_devices(self, lib, tmp_path):
        """Beehive round where the devices train a CONV model in C++
        (VERDICT item: fake-device e2e round-tripping a conv model)."""
        from fedml_tpu.arguments import Arguments
        from fedml_tpu.core.distributed.communication.loopback import LoopbackHub
        from fedml_tpu.cross_device.fake_device import FakeDeviceManager
        from fedml_tpu.cross_device.fedml_aggregator import FedMLAggregator
        from fedml_tpu.cross_device.fedml_server_manager import FedMLServerManager

        LoopbackHub.reset()
        args = Arguments.from_dict(
            {
                "common_args": {"training_type": "cross_device", "random_seed": 0,
                                "run_id": "native-conv"},
                "data_args": {"dataset": "synthetic"},
                "model_args": {"model": "lenet_tiny"},
                "train_args": {
                    "federated_optimizer": "FedAvg",
                    "client_num_in_total": 2,
                    "client_num_per_round": 2,
                    "comm_round": 2,
                    "epochs": 4,
                    "batch_size": 32,
                    "learning_rate": 0.1,
                },
                "validation_args": {"frequency_of_the_test": 1},
                "comm_args": {"backend": "LOOPBACK"},
            }
        ).validate()
        x_test, y_test = _conv_data(128, seed=9)
        aggregator = FedMLAggregator(args, LeNetTiny(), (x_test, y_test),
                                     worker_num=2, model_dir=str(tmp_path / "models"))
        server = FedMLServerManager(args, aggregator, client_rank=0, client_num=2)
        devices = [
            FakeDeviceManager(args, r, _conv_data(192, seed=r), client_num=2,
                              upload_dir=str(tmp_path / f"dev{r}"), use_native=True)
            for r in (1, 2)
        ]
        threads = [server.run_async()] + [d.run_async() for d in devices]
        for t in threads:
            t.join(timeout=120)
        assert all(not t.is_alive() for t in threads)
        assert aggregator.eval_history[-1]["test_acc"] > 0.6
