"""Real-transport adapter seam (mqtt_s3/adapters.py): paho-mqtt and boto3
drop in behind the in-repo BrokerClient/BlobStore surface.  Neither library
is in the image, so these tests inject mock modules and assert the adapter
maps the surface onto the real client APIs correctly (reference
``mqtt_s3_multi_clients_comm_manager.py:214-284``, ``s3/remote_storage.py``)."""

import pickle
import types

import numpy as np
import pytest

from fedml_tpu.core.distributed.communication.mqtt_s3 import adapters
from fedml_tpu.core.distributed.communication.mqtt_s3.blob_store import BlobStore
from fedml_tpu.core.distributed.communication.mqtt_s3.broker import BrokerClient


class _MockPahoClient:
    """Records the paho Client calls the adapter makes."""

    def __init__(self, *a, **kw):
        self.calls = []
        self.on_message = None
        self.will = None
        self.connected = False

    def connect(self, host, port, keepalive=60):
        self.calls.append(("connect", host, port))
        self.connected = True

    def loop_start(self):
        self.calls.append(("loop_start",))

    def loop_stop(self):
        self.calls.append(("loop_stop",))

    def subscribe(self, topic):
        self.calls.append(("subscribe", topic))

    def unsubscribe(self, topic):
        self.calls.append(("unsubscribe", topic))

    def publish(self, topic, payload):
        self.calls.append(("publish", topic, payload))

    def will_set(self, topic, payload):
        assert not self.connected, "paho requires will_set before connect"
        self.will = (topic, payload)

    def disconnect(self):
        self.calls.append(("disconnect",))
        self.connected = False


def _mock_paho_module():
    mod = types.SimpleNamespace()
    mod.Client = _MockPahoClient
    return mod


class TestPahoAdapter:
    def _client(self, received):
        return adapters.PahoBrokerClient(
            "broker.example", 1883,
            on_message=lambda t, p: received.append((t, p)),
            mqtt_module=_mock_paho_module(),
        )

    def test_lazy_connect_and_surface_mapping(self):
        received = []
        c = self._client(received)
        raw = c._client
        assert not raw.connected  # lazy: no connect at construction
        c.set_last_will("fedml_run_status", {"rank": 1, "status": "OFFLINE"})
        c.subscribe("fedml_run_#")
        assert raw.connected
        # the will was installed BEFORE connect (paho's hard requirement)
        assert raw.will[0] == "fedml_run_status"
        assert pickle.loads(raw.will[1])["status"] == "OFFLINE"
        c.publish("fedml_run_1_0", {"msg_type": 3})
        kinds = [x[0] for x in raw.calls]
        assert kinds[:3] == ["connect", "loop_start", "subscribe"]
        assert ("unsubscribe", "t") not in raw.calls
        c.unsubscribe("t")
        c.disconnect()
        assert raw.calls[-1] == ("disconnect",)

    def test_payload_pickled_on_wire_and_unpickled_on_receive(self):
        received = []
        c = self._client(received)
        payload = {"model_params_url": "file:///x", "arr": np.arange(3)}
        c.publish("topic_a", payload)
        wire = [x for x in c._client.calls if x[0] == "publish"][0][2]
        assert isinstance(wire, (bytes, bytearray))  # bytes on the MQTT wire
        # simulate the broker delivering it back
        msg = types.SimpleNamespace(topic="topic_a", payload=wire)
        c._client.on_message(c._client, None, msg)
        t, p = received[0]
        assert t == "topic_a" and p["model_params_url"] == "file:///x"
        np.testing.assert_array_equal(p["arr"], np.arange(3))

    def test_factory_dispatch(self, monkeypatch):
        from fedml_tpu.core.distributed.communication.mqtt_s3.broker import LocalBroker

        broker = LocalBroker().start()
        try:
            c = adapters.create_broker_client(
                "127.0.0.1", broker.port, lambda t, p: None, transport="local")
            assert isinstance(c, BrokerClient)
            c.disconnect()
            # selection is explicit config, never import availability: even
            # with paho importable, the default stays the in-repo client (a
            # config's host:port points at a specific kind of broker)
            monkeypatch.setattr(adapters, "_paho", _mock_paho_module)
            c2 = adapters.create_broker_client(
                "127.0.0.1", broker.port, lambda t, p: None)
            assert isinstance(c2, BrokerClient)
            c2.disconnect()
        finally:
            broker.stop()
        monkeypatch.setattr(adapters, "_paho", lambda: None)
        with pytest.raises(ImportError):
            adapters.create_broker_client("h", 1, lambda t, p: None,
                                          transport="paho")
        monkeypatch.setattr(adapters, "_paho", _mock_paho_module)
        c3 = adapters.create_broker_client("h", 1, lambda t, p: None,
                                           transport="paho")
        assert isinstance(c3, adapters.PahoBrokerClient)

    def test_resubscribes_after_will_rearm_reconnect(self):
        received = []
        c = self._client(received)
        c.subscribe("fedml/run/#")
        assert ("subscribe", "fedml/run/#") in c._client.calls
        # will after subscribe: tears down, re-arms, and the next op must
        # restore the subscription on the fresh session
        c.set_last_will("fedml/run/status", {"s": "OFFLINE"})
        assert not c._client.connected
        c.publish("fedml/run/1_0", {"x": 1})
        tail = c._client.calls[-4:]
        kinds = [x[0] for x in tail]
        assert kinds == ["connect", "loop_start", "subscribe", "publish"], tail
        assert tail[2] == ("subscribe", "fedml/run/#")


class _MockS3:
    def __init__(self):
        self.objects = {}

    def put_object(self, Bucket, Key, Body):
        self.objects[(Bucket, Key)] = bytes(Body)

    def get_object(self, Bucket, Key):
        body = self.objects[(Bucket, Key)]
        return {"Body": types.SimpleNamespace(read=lambda: body)}


def _mock_boto3(s3):
    return types.SimpleNamespace(client=lambda kind: s3)


class TestS3Adapter:
    def test_roundtrip_via_mock_boto3(self):
        s3 = _MockS3()
        store = adapters.S3BlobStore("s3://mybucket/runs/42",
                                     boto3_module=_mock_boto3(s3))
        tree = {"w": np.ones((4,), np.float32), "b": 2.0}
        url = store.write_model("srv-m0", tree)
        assert url.startswith("s3://mybucket/runs/42/srv-m0-")
        back = store.read_model(url)
        np.testing.assert_array_equal(back["w"], tree["w"])
        assert back["b"] == 2.0

    def test_factory_dispatch(self):
        assert isinstance(adapters.create_blob_store(None), BlobStore)
        with pytest.raises(ImportError):
            adapters.create_blob_store("s3://bucket/prefix")  # no boto3 here


# ---------------------------------------------------------------------------
# LocalBroker robustness: the Java-wire (JSON) interop path and the client
# thread's cleanup guarantees
# ---------------------------------------------------------------------------

import json
import socket
import struct
import threading
import time

from fedml_tpu.core.distributed.communication.mqtt_s3.broker import LocalBroker

_LEN = struct.Struct(">I")


class _JavaWireSubscriber:
    """A strict JSON peer speaking the broker frame protocol over a raw
    socket — the shape of the Android SDK's wire, with no pickle fallback:
    any frame that is not valid JSON is a test failure, not a warning."""

    def __init__(self, port: int):
        self.sock = socket.create_connection(("127.0.0.1", port), timeout=10)

    def send(self, obj: dict) -> None:
        body = json.dumps(obj).encode("utf-8")
        self.sock.sendall(_LEN.pack(len(body)) + body)

    def recv(self, timeout: float = 5.0):
        """One decoded frame, or None on timeout (socket stays usable)."""
        self.sock.settimeout(timeout)
        try:
            hdr = self._exact(_LEN.size)
            (n,) = _LEN.unpack(hdr)
            return json.loads(self._exact(n).decode("utf-8"))
        except socket.timeout:
            return None
        finally:
            self.sock.settimeout(None)

    def _exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("broker closed the connection")
            buf += chunk
        return buf

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


class TestLocalBrokerJsonInterop:
    def test_numpy_scalar_payload_reaches_json_subscriber(self):
        """Regression: a Python silo publishing np.int64/np.float32/np.bool_
        status fields silently lost the WHOLE frame for JSON peers (json.dumps
        TypeError -> drop path).  The encoder now coerces numpy scalars."""
        broker = LocalBroker().start()
        sub = _JavaWireSubscriber(broker.port)
        pub = None
        try:
            sub.send({"op": "SUB", "topic": "fedml/status/#"})
            time.sleep(0.1)  # SUB must land before the publish fans out

            got = []
            pub = BrokerClient("127.0.0.1", broker.port,
                               lambda t, p: got.append((t, p)))
            pub.publish("fedml/status/1", {
                "round_idx": np.int64(3),
                "train_acc": np.float32(0.75),
                "uploaded": np.bool_(True),
            })
            frame = sub.recv()
            assert frame is not None, "numpy-scalar payload was dropped for the JSON peer"
            assert frame["op"] == "MSG" and frame["topic"] == "fedml/status/1"
            payload = frame["payload"]
            assert payload["round_idx"] == 3
            assert abs(payload["train_acc"] - 0.75) < 1e-6
            assert payload["uploaded"] is True
        finally:
            if pub is not None:
                pub.disconnect()
            sub.close()
            broker.stop()

    def test_non_finite_floats_still_dropped_for_json_peers_only(self):
        """Coercion must not smuggle NaN past allow_nan=False: a non-finite
        numpy float is still dropped for JSON subscribers while pickle
        subscribers receive the frame untouched."""
        broker = LocalBroker().start()
        sub = _JavaWireSubscriber(broker.port)
        got = []
        pickle_sub = None
        pub = None
        try:
            sub.send({"op": "SUB", "topic": "t/#"})
            pickle_sub = BrokerClient("127.0.0.1", broker.port,
                                      lambda t, p: got.append(p))
            pickle_sub.subscribe("t/#")
            time.sleep(0.1)
            pub = BrokerClient("127.0.0.1", broker.port, lambda t, p: None)
            pub.publish("t/1", {"loss": np.float64("nan")})
            pub.publish("t/2", {"loss": np.float64(0.5)})
            frame = sub.recv()
            assert frame is not None and frame["topic"] == "t/2", \
                "JSON peer should see only the finite payload"
            deadline = time.time() + 5
            while time.time() < deadline and len(got) < 2:
                time.sleep(0.02)
            assert len(got) == 2  # pickle peer got both, NaN included
        finally:
            for c in (pub, pickle_sub):
                if c is not None:
                    c.disconnect()
            sub.close()
            broker.stop()


class TestBrokerClientLoopCleanup:
    def test_malformed_frame_fires_last_will_and_unregisters(self):
        """Regression: an exception inside the broker's client loop (here a
        PUB frame with no topic) used to kill the thread BEFORE cleanup —
        a zombie registration held the dead socket in every future fan-out
        and the last will never fired.  The loop body is now try/finally."""
        broker = LocalBroker().start()
        watcher = _JavaWireSubscriber(broker.port)
        dying = _JavaWireSubscriber(broker.port)
        try:
            watcher.send({"op": "SUB", "topic": "liveness/#"})
            time.sleep(0.1)
            dying.send({"op": "WILL", "topic": "liveness/edge7",
                        "payload": {"status": "OFFLINE"}})
            time.sleep(0.1)
            assert len(broker._clients) == 2
            dying.send({"op": "PUB"})  # no topic: raises in the client loop

            will = watcher.recv()
            assert will is not None, "last will never fired for the dead client"
            assert will["topic"] == "liveness/edge7"
            assert will["payload"] == {"status": "OFFLINE"}
            deadline = time.time() + 5
            while time.time() < deadline and len(broker._clients) > 1:
                time.sleep(0.02)
            assert len(broker._clients) == 1, "dead client left a zombie registration"
            assert len(broker._send_locks) == 1 and len(broker._enc) == 1
        finally:
            watcher.close()
            dying.close()
            broker.stop()
