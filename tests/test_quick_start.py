"""quick_start/ parity (reference python/quick_start/{parrot,octopus,beehive}):
the beginner entry scripts must actually run."""

import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
QS = os.path.join(ROOT, "quick_start")


def _run_script(path, cfg):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # PYTHONPATH must NOT inherit the axon sitecustomize dir: it registers
    # the TPU backend in the child regardless of JAX_PLATFORMS
    env["PYTHONPATH"] = ROOT
    return subprocess.run(
        [sys.executable, path, "--cf", cfg],
        cwd=os.path.dirname(path), env=env, capture_output=True, text=True,
        timeout=300,
    )


@pytest.mark.heavy
@pytest.mark.parametrize("script", [
    "fedavg_mnist_lr_one_line_example.py",
    "fedavg_mnist_lr_step_by_step_example.py",
    "fedavg_mnist_lr_custom_data_and_model_example.py",
])
def test_parrot_quick_start(script):
    path = os.path.join(QS, "parrot", script)
    cfg = os.path.join(QS, "parrot", "fedml_config.yaml")
    proc = _run_script(path, cfg)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_quick_start_tree_complete():
    assert os.path.isfile(os.path.join(QS, "octopus", "server.py"))
    assert os.path.isfile(os.path.join(QS, "octopus", "client.py"))
    assert os.path.isfile(os.path.join(QS, "beehive", "server.py"))
