"""Standalone native device agent (native/agent.cpp + cross_device/
device_agent.py): the out-of-process edge client — directory protocol,
idempotent job handling, and a full cross-device FL round where every
client's training runs in a separate C++ process (the reference's
Java-service + MobileNN-C++ split)."""

import os

import numpy as np
import pytest

from fedml_tpu.arguments import Arguments
from fedml_tpu.core.distributed.communication.loopback import LoopbackHub
from fedml_tpu.cross_device.device_agent import AgentBridge
from fedml_tpu.cross_device.edge_model import load_edge_model, save_edge_model


def _separable(n, d=12, classes=4, seed=0):
    centers = np.random.RandomState(1234).randn(classes, d) * 3
    rng = np.random.RandomState(seed)
    y = rng.randint(0, classes, n)
    x = centers[y] + rng.randn(n, d) * 0.5
    return x.astype(np.float32), y.astype(np.int32)


def _dense_model(path, d=12, classes=4, seed=0):
    path = str(path)
    rng = np.random.RandomState(seed)
    save_edge_model(path, {
        "linear/kernel": (rng.randn(d, classes) * 0.01).astype(np.float32),
        "linear/bias": np.zeros(classes, np.float32),
    })
    return str(path)


class TestAgentBridge:
    def test_job_roundtrip_and_status(self, tmp_path):
        x, y = _separable(128)
        data = str(tmp_path / "data.ftem")
        save_edge_model(data, {"x": x, "y": y})
        model = _dense_model(tmp_path / "model.ftem")
        bridge = AgentBridge(str(tmp_path / "agent"))
        try:
            bridge.submit(0, model, data, batch_size=16, lr=0.2, epochs=8, seed=7)
            update, metrics = bridge.await_update(0, timeout=60)
            trained = load_edge_model(update)
            assert set(trained) == {"linear/kernel", "linear/bias"}
            assert metrics["num_samples"] == 128
            assert metrics["train_acc"] > 0.8  # separable: agent really trained
            # params actually moved
            init = load_edge_model(model)
            assert np.abs(trained["linear/kernel"] - init["linear/kernel"]).max() > 1e-4
            assert bridge.status()["state"] in ("idle", "training")
        finally:
            bridge.close()
        # clean shutdown: process gone, status says stopped
        assert bridge.status()["state"] == "stopped"

    def test_malformed_job_reports_err_and_agent_survives(self, tmp_path):
        bridge = AgentBridge(str(tmp_path / "agent"))
        try:
            bridge.submit(0, str(tmp_path / "missing.ftem"),
                          str(tmp_path / "missing_data.ftem"),
                          batch_size=16, lr=0.1, epochs=1, seed=0)
            with pytest.raises(RuntimeError, match="agent job r0"):
                bridge.await_update(0, timeout=30)
            # the agent did not die: a good follow-up job still runs
            x, y = _separable(64)
            data = str(tmp_path / "data.ftem")
            save_edge_model(data, {"x": x, "y": y})
            model = _dense_model(tmp_path / "model.ftem")
            bridge.submit(1, model, data, batch_size=16, lr=0.2, epochs=2, seed=0)
            _, metrics = bridge.await_update(1, timeout=60)
            assert metrics["num_samples"] == 64
        finally:
            bridge.close()


@pytest.mark.heavy
class TestAgentDeviceE2E:
    def test_cross_device_round_with_agent_processes(self, tmp_path):
        from fedml_tpu.cross_device.device_agent import AgentDeviceManager
        from fedml_tpu.cross_device.fedml_aggregator import FedMLAggregator
        from fedml_tpu.cross_device.fedml_server_manager import FedMLServerManager
        from fedml_tpu.models.linear import LogisticRegression

        LoopbackHub.reset()
        args = Arguments.from_dict(
            {
                "common_args": {"training_type": "cross_device", "random_seed": 0,
                                "run_id": "agent-e2e"},
                "data_args": {"dataset": "synthetic"},
                "model_args": {"model": "lr"},
                "train_args": {
                    "federated_optimizer": "FedAvg",
                    "client_num_in_total": 2,
                    "client_num_per_round": 2,
                    "comm_round": 3,
                    "epochs": 2,
                    "batch_size": 16,
                    "learning_rate": 0.2,
                },
                "validation_args": {"frequency_of_the_test": 1},
                "comm_args": {"backend": "LOOPBACK"},
            }
        ).validate()

        x_test, y_test = _separable(128, seed=9)
        model = LogisticRegression(output_dim=4)
        aggregator = FedMLAggregator(args, model, (x_test, y_test), worker_num=2,
                                     model_dir=str(tmp_path / "models"))
        server = FedMLServerManager(args, aggregator, client_rank=0, client_num=2)
        devices = [
            AgentDeviceManager(args, rank, _separable(96, seed=rank), client_num=2,
                               upload_dir=str(tmp_path / f"dev{rank}"))
            for rank in (1, 2)
        ]
        threads = [server.run_async()] + [d.run_async() for d in devices]
        for t in threads:
            t.join(timeout=120)
        for t in threads:
            assert not t.is_alive(), "protocol did not terminate"
        assert all(d.rounds_trained == 3 for d in devices)
        assert aggregator.eval_history[-1]["test_acc"] > 0.8
        # both agent processes are gone after FINISH
        for d in devices:
            assert d.bridge._proc is None or d.bridge._proc.poll() is not None
