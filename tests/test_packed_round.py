"""Packed ragged round (ml/engine/packed.py, args.xla_pack): must train to
the same quality as the padded round without per-client padding waste, and
support the in-mesh algorithm zoo."""

import jax
import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.arguments import Arguments
from fedml_tpu.parallel.mesh import create_fl_mesh
from fedml_tpu.simulation.xla.fed_sim import XLASimulator

pytestmark = pytest.mark.heavy  # long XLA compiles; see pytest.ini


def _args(**over):
    args = Arguments.from_dict(
        {
            "common_args": {"training_type": "simulation", "random_seed": 0, "run_id": "pk"},
            "data_args": {
                "dataset": "mnist",
                "data_cache_dir": "",
                "partition_method": "hetero",
                "partition_alpha": 0.5,
                "synthetic_train_size": 1600,
            },
            "model_args": {"model": "lr"},
            "train_args": {
                "federated_optimizer": "FedAvg",
                "client_num_in_total": 16,
                "client_num_per_round": 8,
                "comm_round": 4,
                "epochs": 2,
                "batch_size": 32,
                "client_optimizer": "sgd",
                "learning_rate": 0.1,
                "xla_pack": True,
            },
            "validation_args": {"frequency_of_the_test": 2},
            "comm_args": {"backend": "XLA"},
        }
    )
    for k, v in over.items():
        setattr(args, k, v)
    return args.validate()


def _build(args):
    args = fedml_tpu.init(args, should_init_logs=False)
    dataset, out_dim = fedml_tpu.data.load(args)
    model = fedml_tpu.models.create(args, out_dim)
    return args, dataset, model


class TestPackedRound:
    def test_learns_on_8dev_mesh(self):
        args, dataset, model = _build(_args())
        sim = XLASimulator(args, dataset, model)
        assert sim.packed
        metrics = sim.train()
        assert metrics["test_acc"] > 0.5

    def test_matches_padded_round_quality(self):
        """Packed and padded rounds use different shuffle streams so results
        differ bitwise, but trained quality must match closely."""
        args_p, dataset, model = _build(_args())
        sim_p = XLASimulator(args_p, dataset, model)
        m_packed = sim_p.train()

        args_d, dataset_d, model_d = _build(_args(xla_pack=False))
        sim_d = XLASimulator(args_d, dataset_d, model_d)
        m_padded = sim_d.train()
        assert abs(m_packed["test_acc"] - m_padded["test_acc"]) < 0.1, (
            m_packed, m_padded,
        )

    def test_packed_step_count_is_ragged(self):
        """The packed stream runs ceil(n_i/B) steps per client, not the
        padded global max."""
        from fedml_tpu.ml.engine.packed import pack_round

        args, dataset, model = _build(_args())
        sim = XLASimulator(args, dataset, model)
        sampled = sim._client_sampling(0)
        ids, real = sim._schedule(sampled)
        counts = np.where(real > 0, np.asarray(sim.client_counts)[ids], 0)
        sched = pack_round(
            np.asarray(ids).reshape(sim.n_dev, sim.slots),
            counts.reshape(sim.n_dev, sim.slots),
            lambda cid: sim._client_rows[cid],
            sim.batch_size, 2, 0, 0, sim.s_max,
        )
        expected = sum(2 * (-(-int(c) // sim.batch_size)) for c in counts if c > 0)
        assert int(sched.n_steps.sum()) == expected
        padded_steps = 2 * (-(-sim.padded_n // sim.batch_size)) * (counts > 0).sum()
        assert expected < padded_steps  # strictly less work than padding

    def test_async_fedavg_packed_trains(self):
        """Regression: algorithms that consume cex in client_contrib WITHOUT
        overriding engine_extra (async_fedavg's staleness counter) must get
        the real per-slot cex in the packed flush, not None."""
        args, dataset, model = _build(_args(
            federated_optimizer="async_fedavg", comm_round=2,
        ))
        sim = XLASimulator(args, dataset, model)
        assert sim.packed
        metrics = sim.train()
        assert np.isfinite(metrics["test_acc"])

    def test_scaffold_packed_matches_host_math(self):
        """Control-variate algorithm on the packed path: equivalence against
        an explicit host replay with the same host-side shuffles."""
        import jax.numpy as jnp

        from fedml_tpu.ml.engine.packed import pack_round

        N = 4
        args, dataset, model = _build(_args(
            federated_optimizer="SCAFFOLD", client_num_in_total=N,
            client_num_per_round=N, comm_round=2, epochs=1,
            partition_method="homo", synthetic_train_size=640,
        ))
        sim = XLASimulator(args, dataset, model, mesh=create_fl_mesh(4))
        w0 = sim.variables
        schedules = []
        orig = sim._schedule

        def capture(sampled):
            ids, real = orig(sampled)
            schedules.append((np.asarray(ids), np.asarray(real)))
            return ids, real

        sim._schedule = capture
        sim.train()
        got = sim.variables

        # host replay: same packed batch order, explicit SGD + SCAFFOLD math
        lr = float(args.learning_rate)
        x_all = np.asarray(sim.x_all)
        y_all = np.asarray(sim.y_all)
        zeros_p = jax.tree_util.tree_map(jnp.zeros_like, w0["params"])
        w = w0
        c_server = zeros_p
        c_clients = {i: zeros_p for i in range(N)}

        import optax

        from fedml_tpu.ml.engine.train import softmax_ce_loss

        def batch_step(params, bx, by, bm, c_i, c):
            def loss(p):
                logits = model.apply(dict(w, params=p), bx, train=True,
                                     rngs={"dropout": jax.random.PRNGKey(0)})
                return softmax_ce_loss(logits, by, bm)[0]

            g = jax.grad(loss)(params)
            g = jax.tree_util.tree_map(lambda gg, ci, cg: gg - ci + cg, g, c_i, c)
            return jax.tree_util.tree_map(lambda p, gg: p - lr * gg, params, g)

        for r in range(2):
            ids, real = schedules[r]
            counts = np.where(real > 0, np.asarray(sim.client_counts)[ids], 0)
            sched = pack_round(
                np.asarray(ids).reshape(sim.n_dev, sim.slots),
                counts.reshape(sim.n_dev, sim.slots),
                lambda cid: sim._client_rows[cid],
                sim.batch_size, 1, 0, r, sim.s_max,
            )
            acc = jax.tree_util.tree_map(jnp.zeros_like, w0)
            wsum = 0.0
            dc_sum = zeros_p
            for d in range(sim.n_dev):
                params = w["params"]
                step_in_client = 0
                for s in range(int(sched.n_steps[d])):
                    bx = jnp.asarray(x_all[sched.idx[d, s]])
                    by = jnp.asarray(y_all[sched.idx[d, s]])
                    bm = jnp.asarray(sched.mask[d, s])
                    ls = int(sched.slot[d, s])
                    cid = int(ids.reshape(sim.n_dev, sim.slots)[d, ls])
                    params = batch_step(params, bx, by, bm, c_clients[cid], c_server)
                    step_in_client += 1
                    if sched.boundary[d, s] > 0:
                        n_i = float(sched.weight[d, s])
                        K = float(step_in_client)
                        new_ci = jax.tree_util.tree_map(
                            lambda ci, cg, wg, wi: ci - cg + (wg - wi) / (K * lr),
                            c_clients[cid], c_server, w["params"], params,
                        )
                        dc_sum = jax.tree_util.tree_map(
                            lambda sacc, nn, oo: sacc + (nn - oo),
                            dc_sum, new_ci, c_clients[cid],
                        )
                        c_clients[cid] = new_ci
                        acc = jax.tree_util.tree_map(
                            lambda a, p: a + n_i * p, acc, dict(w, params=params)
                        )
                        wsum += n_i
                        params = w["params"]
                        step_in_client = 0
            w = jax.tree_util.tree_map(lambda a: a / wsum, acc)
            c_server = jax.tree_util.tree_map(
                lambda c, dcv: c + dcv / N, c_server, dc_sum
            )
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5
            ),
            got, w,
        )


class TestPregather:
    def test_pregather_matches_per_step_gather(self):
        """xla_pregather is a pure execution-strategy change: identical
        round outputs to the per-step-gather packed round."""
        outs = {}
        for pregather in (False, True):
            args, dataset, model = _build(_args(xla_pregather=pregather,
                                                comm_round=2))
            sim = XLASimulator(args, dataset, model)
            sim.train()
            leaves = jax.tree_util.tree_leaves(sim.variables)
            outs[pregather] = [np.asarray(l) for l in leaves]
        for a, b in zip(outs[False], outs[True]):
            np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)


class TestScanStream:
    def test_scan_matches_while_loop(self):
        """xla_stream='scan' is a pure execution-strategy change: the
        bucketed tail carries all-zero masks, so outputs are identical to
        the while_loop walk."""
        outs = {}
        for stream in ("while", "scan"):
            args, dataset, model = _build(_args(xla_stream=stream, comm_round=2))
            sim = XLASimulator(args, dataset, model)
            sim.train()
            outs[stream] = [np.asarray(l) for l in jax.tree_util.tree_leaves(sim.variables)]
        for a, b in zip(outs["while"], outs["scan"]):
            np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)

    def test_scan_matches_with_grad_hook(self):
        """FedProx's hook is nonzero on zero grads; the scan tail must be
        masked, not merely zero-grad."""
        outs = {}
        for stream in ("while", "scan"):
            args, dataset, model = _build(_args(xla_stream=stream, comm_round=2,
                                                proximal_mu=0.1))
            sim = XLASimulator(args, dataset, model)
            sim.train()
            outs[stream] = [np.asarray(l) for l in jax.tree_util.tree_leaves(sim.variables)]
        for a, b in zip(outs["while"], outs["scan"]):
            np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)


class TestStepScheduling:
    """The packed round schedules and models runtime in its native unit:
    compiled steps (ceil(n/B)*E), with a quantized stream bucket."""

    def test_scheduler_receives_step_costs(self):
        args, dataset, model = _build(_args(comm_round=1))
        sim = XLASimulator(args, dataset, model)
        captured = {}
        orig = sim.scheduler.schedule

        def spy(ids, sizes):
            captured["sizes"] = list(sizes)
            return orig(ids, sizes)

        sim.scheduler.schedule = spy
        sampled = sim._client_sampling(0)
        sim._schedule(sampled)
        b, e = int(args.batch_size), int(args.epochs)
        expect = [-(-int(sim.local_num_dict[int(c)]) // b) * e for c in sampled]
        assert captured["sizes"] == expect

    def test_runtime_model_records_steps(self):
        args, dataset, model = _build(_args(comm_round=4))
        sim = XLASimulator(args, dataset, model)
        sim.train()
        obs = sim.runtime_estimator._obs[0]
        # rounds 1..3, minus any round whose bucket shape first compiled
        assert 1 <= len(obs) <= 3
        max_steps_possible = sim.slots * (-(-sim.max_client_n // sim.batch_size)) \
            * int(args.epochs)
        for x, t in obs:
            assert 1 <= x <= max_steps_possible
            assert x == int(x)  # step counts, not raw sample sums
            assert t > 0

    def test_bucket_quantized_not_power_of_two(self):
        args, dataset, model = _build(_args(comm_round=2))
        sim = XLASimulator(args, dataset, model)
        sim.train()
        quantum = max(1, -(-sim.s_max // 8))
        assert sim._s_bucket % quantum == 0 or sim._s_bucket == sim.s_max
        assert sim._s_bucket <= sim.s_max

    def test_bucket_tracks_round_usage(self):
        """The bucket equals the quantized round usage — computed from the
        actual schedule, not assumed from the sampling draw."""
        args, dataset, model = _build(
            _args(comm_round=1, client_num_per_round=2, epochs=1)
        )
        sim = XLASimulator(args, dataset, model)
        sim.train()
        sampled = sim._client_sampling(0)
        ids, real = sim._schedule(sampled)
        steps = np.array([
            sim._client_steps(sim.local_num_dict[int(c)]) if r else 0
            for c, r in zip(ids, real)
        ])
        s_used = max(int(steps.reshape(sim.n_dev, -1).sum(axis=1).max()), 1)
        quantum = max(1, -(-sim.s_max // 8))
        expect = min(-(-s_used // quantum) * quantum, sim.s_max)
        assert sim._s_bucket == expect, (sim._s_bucket, expect, s_used, sim.s_max)


class TestDataStorageDtype:
    def test_bf16_storage_matches_fp32_storage(self):
        """Under bf16 compute the model's entry cast makes a stored-bf16
        gather bitwise-identical to gather-then-cast of fp32 storage, so
        halving the dataset's HBM footprint/gather traffic must not change
        the round outputs at all."""
        outs = {}
        for store in ("fp32", "bf16"):
            args, dataset, model = _build(_args(
                dataset="cifar10", model="resnet20", compute_dtype="bf16",
                xla_data_dtype=store, synthetic_train_size=256,
                client_num_in_total=4, client_num_per_round=4,
                comm_round=2, epochs=1, batch_size=16,
                frequency_of_the_test=0,
            ))
            sim = XLASimulator(args, dataset, model)
            assert str(sim.x_all.dtype) == ("bfloat16" if store == "bf16" else "float32")
            sim.train()
            outs[store] = [np.asarray(l) for l in jax.tree_util.tree_leaves(sim.variables)]
        for a, b in zip(outs["fp32"], outs["bf16"]):
            np.testing.assert_allclose(a, b, rtol=0, atol=0)

    def test_auto_keeps_fp32_for_unplumbed_models(self):
        """'auto' must not downcast the dataset for models that ignore
        compute_dtype (they'd consume degraded fp32 inputs)."""
        args, dataset, model = _build(_args(compute_dtype="bf16"))  # lr model
        sim = XLASimulator(args, dataset, model)
        assert str(sim.x_all.dtype) == "float32"

    def test_integer_token_data_never_downcast(self):
        """Token-id inputs (s2s/NWP) must keep their integer dtype even
        under an explicit bf16 storage request — nn.Embed requires ints
        (regression: the first bf16-storage cut cast them to float and the
        in-mesh s2s task crashed)."""
        args, dataset, model = _build(_args(
            dataset="synthetic_s2s", model="transformer_s2s",
            xla_data_dtype="bf16", synthetic_train_size=128,
            client_num_in_total=4, client_num_per_round=4, batch_size=16,
            comm_round=1, frequency_of_the_test=0,
        ))
        sim = XLASimulator(args, dataset, model)
        assert np.issubdtype(np.asarray(sim.x_all[:1]).dtype, np.integer)
