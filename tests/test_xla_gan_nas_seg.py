"""In-mesh FedGAN / FedNAS / FedSeg on the XLA backend (simulation/xla/
gan_nas.py + the fedseg->FedAvgInMesh registry row): the last zoo members
move off the host loop.  FedNAS is equivalence-gated against its sp twin
(identical round math, so the mesh program must reproduce it); FedGAN is
gated on determinism + adversarial-signal sanity; FedSeg on mIoU through the
full FedMLRunner XLA path."""

import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.arguments import Arguments
from fedml_tpu.parallel.mesh import create_fl_mesh

pytestmark = pytest.mark.heavy  # long XLA compiles; see pytest.ini


def _args(optimizer, dataset="cifar10", model="cnn", backend="XLA", **over):
    base = {
        "common_args": {"training_type": "simulation", "random_seed": 0, "run_id": "t"},
        "data_args": {
            "dataset": dataset,
            "data_cache_dir": "",
            "partition_method": "homo",
            "synthetic_train_size": 256,
        },
        "model_args": {"model": model},
        "train_args": {
            "federated_optimizer": optimizer,
            "client_num_in_total": 4,
            "client_num_per_round": 2,
            "comm_round": 2,
            "epochs": 1,
            "batch_size": 16,
            "client_optimizer": "sgd",
            "learning_rate": 0.05,
        },
        "validation_args": {"frequency_of_the_test": 1},
        "comm_args": {"backend": backend},
    }
    args = Arguments.from_dict(base)
    for k, v in over.items():
        setattr(args, k, v)
    return args.validate()


class TestDispatch:
    def test_simulator_xla_routes_gan_and_nas(self):
        """backend XLA + FedGAN/FedNAS must reach the dedicated in-mesh
        programs through the public SimulatorXLA dispatch (not fall through
        to XLASimulator's NotImplementedError)."""
        from fedml_tpu import data
        from fedml_tpu.simulation.simulator import SimulatorXLA
        from fedml_tpu.simulation.xla.gan_nas import GANInMeshAPI, NASInMeshAPI

        for opt, cls, ds, mdl in [("FedGAN", GANInMeshAPI, "mnist", "gan"),
                                  ("FedNAS", NASInMeshAPI, "cifar10", "darts")]:
            args = fedml_tpu.init(_args(opt, dataset=ds, model=mdl),
                                  should_init_logs=False)
            dataset, _ = data.load(args)
            sim = SimulatorXLA(args, None, dataset, None)
            assert isinstance(sim.sim, cls)


class TestGANInMesh:
    def _run(self, mesh_size):
        from fedml_tpu import data
        from fedml_tpu.simulation.xla.gan_nas import GANInMeshAPI

        args = fedml_tpu.init(
            _args("FedGAN", dataset="mnist", gan_local_steps=4, batch_size=8),
            should_init_logs=False,
        )
        dataset, _ = data.load(args)
        api = GANInMeshAPI(args, None, dataset, None, mesh=create_fl_mesh(mesh_size))
        out = api.train()
        return api, out

    def test_round_trains_both_nets(self):
        import jax

        api, out = self._run(2)
        # D winning early (score ~0) is legitimate GAN dynamics; the gate is
        # "a probability came out and both nets stayed finite + moved"
        assert 0.0 <= out["d_fake_score"] <= 1.0
        # both nets moved from init and stayed finite
        z0 = np.zeros((1, api.latent), np.float32)
        g0 = api.G.init(jax.random.PRNGKey(0), z0)
        moved = any(
            not np.allclose(np.asarray(a), np.asarray(b))
            for a, b in zip(
                jax.tree_util.tree_leaves(api.g_params), jax.tree_util.tree_leaves(g0)
            )
        )
        assert moved
        for leaf in jax.tree_util.tree_leaves((api.g_params, api.d_params)):
            assert np.all(np.isfinite(np.asarray(leaf)))

    def test_deterministic_across_runs(self):
        import jax

        api1, _ = self._run(2)
        api2, _ = self._run(2)
        for a, b in zip(
            jax.tree_util.tree_leaves(api1.g_params),
            jax.tree_util.tree_leaves(api2.g_params),
        ):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=0, rtol=0)


class TestNASInMesh:
    def test_matches_sp_twin(self):
        """Same sampling, same per-client search loop, order-invariant
        weighted mean: the mesh program must reproduce the sp FedNAS round
        math up to float reassociation."""
        from fedml_tpu import data
        from fedml_tpu.simulation.sp.fednas.fednas_api import FedNASAPI
        from fedml_tpu.simulation.xla.gan_nas import NASInMeshAPI

        args = fedml_tpu.init(_args("FedNAS"), should_init_logs=False)
        dataset, _ = data.load(args)
        sp = FedNASAPI(args, None, dataset, None)
        # drive sp WITHOUT its eval loop: train() logs eval; fine either way
        sp.train()

        args2 = fedml_tpu.init(_args("FedNAS"), should_init_logs=False)
        dataset2, _ = data.load(args2)
        mesh_api = NASInMeshAPI(args2, None, dataset2, None, mesh=create_fl_mesh(2))
        mesh_api.train()

        np.testing.assert_allclose(
            np.asarray(mesh_api.alphas), np.asarray(sp.alphas), atol=2e-4
        )
        import jax

        for a, b in zip(
            jax.tree_util.tree_leaves(mesh_api.params),
            jax.tree_util.tree_leaves(sp.params),
        ):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-3)

    def test_derives_architecture(self):
        from fedml_tpu import data
        from fedml_tpu.models.darts import OPS, init_alphas, num_edges
        from fedml_tpu.simulation.xla.gan_nas import NASInMeshAPI

        args = fedml_tpu.init(
            _args("FedNAS", comm_round=3, epochs=2, learning_rate=0.1),
            should_init_logs=False,
        )
        dataset, _ = data.load(args)
        api = NASInMeshAPI(args, None, dataset, None, mesh=create_fl_mesh(2))
        out = api.train()
        assert len(out["genotype"]) == num_edges()
        assert all(g["op"] in OPS for g in out["genotype"])
        assert not np.allclose(np.asarray(api.alphas), np.asarray(init_alphas(0)), atol=1e-5)


class TestSegInMesh:
    def test_fedseg_on_xla_backend(self):
        """FedSeg rides the main compiled round (fedseg -> FedAvgInMesh) with
        the seg eval aggregator reporting pixel acc + dataset-level mIoU."""
        from fedml_tpu import FedMLRunner, data, models

        args = fedml_tpu.init(
            _args("FedSeg", dataset="synthetic_seg", model="unet",
                  synthetic_train_size=160, comm_round=3, learning_rate=0.05),
            should_init_logs=False,
        )
        dataset, out_dim = data.load(args)
        model = models.create(args, out_dim)
        metrics = FedMLRunner(args, None, dataset, model).run()
        assert metrics["test_acc"] > 0.6  # pixel accuracy; bg-majority ~0.55
        assert "test_miou" in metrics and metrics["test_miou"] > 0.2
