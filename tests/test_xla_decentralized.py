"""In-mesh decentralized (gossip) FL (simulation/xla/decentralized.py):
node training + the all_gather/matmul neighbor exchange compile into one XLA
program; gated by exact equivalence against the sp twin."""

import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.arguments import Arguments
from fedml_tpu.parallel.mesh import create_fl_mesh

pytestmark = pytest.mark.heavy


def _args(**over):
    base = {
        "common_args": {"training_type": "simulation", "random_seed": 0, "run_id": "dec"},
        "data_args": {
            "dataset": "mnist",
            "data_cache_dir": "",
            # homo => equal client sizes => identical padded shapes on both
            # backends (the exact-equality precondition; see cls_trainer
            # padded_size vs the global pad)
            "partition_method": "homo",
            "synthetic_train_size": 512,
        },
        "model_args": {"model": "lr"},
        "train_args": {
            "federated_optimizer": "decentralized_fl",
            "client_num_in_total": 8,
            "client_num_per_round": 8,
            "comm_round": 3,
            "epochs": 1,
            "batch_size": 16,
            "client_optimizer": "sgd",
            "learning_rate": 0.1,
            "topology_neighbor_num": 2,
        },
        "validation_args": {"frequency_of_the_test": 1},
        "comm_args": {"backend": "XLA"},
    }
    args = Arguments.from_dict(base)
    for k, v in over.items():
        setattr(args, k, v)
    return args.validate()


def _build(**over):
    args = fedml_tpu.init(_args(**over), should_init_logs=False)
    dataset, out_dim = fedml_tpu.data.load(args)
    model = fedml_tpu.models.create(args, out_dim)
    return args, dataset, model


class TestDecentralizedInMesh:
    def test_matches_sp_twin_exactly(self):
        """Same topology seed, same per-(round, node) keys, same engine:
        the compiled gossip round must reproduce the sp actor loop."""
        import jax

        from fedml_tpu.simulation.sp.decentralized.decentralized_api import (
            DecentralizedFLAPI,
        )
        from fedml_tpu.simulation.xla.decentralized import DecentralizedInMeshAPI

        args, dataset, model = _build()
        sp = DecentralizedFLAPI(args, None, dataset, model)
        sp.train()

        args2, dataset2, model2 = _build()
        mesh_api = DecentralizedInMeshAPI(args2, None, dataset2, model2,
                                          mesh=create_fl_mesh(4))
        mesh_api.train()

        # consensus model agrees
        for a, b in zip(
            jax.tree_util.tree_leaves(mesh_api.consensus),
            jax.tree_util.tree_leaves(sp.w_global),
        ):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)
        # and so does every individual node model (gossip kept them distinct)
        for nid in (0, 3, 7):
            for a, b in zip(
                jax.tree_util.tree_leaves(mesh_api.node_params(nid)),
                jax.tree_util.tree_leaves(sp.node_models[nid]),
            ):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           rtol=1e-5, atol=1e-6)

    def test_nodes_stay_distinct_and_learn(self):
        import jax

        from fedml_tpu.simulation.xla.decentralized import DecentralizedInMeshAPI

        args, dataset, model = _build(comm_round=4)
        api = DecentralizedInMeshAPI(args, None, dataset, model,
                                     mesh=create_fl_mesh(4))
        out = api.train()
        assert out["test_acc"] > 0.5
        a = jax.tree_util.tree_leaves(api.node_params(0))
        b = jax.tree_util.tree_leaves(api.node_params(5))
        assert any(not np.allclose(np.asarray(x), np.asarray(y)) for x, y in zip(a, b))

    def test_runner_dispatch(self):
        from fedml_tpu.simulation.simulator import SimulatorXLA
        from fedml_tpu.simulation.xla.decentralized import DecentralizedInMeshAPI

        args, dataset, model = _build()
        sim = SimulatorXLA(args, None, dataset, model)
        assert isinstance(sim.sim, DecentralizedInMeshAPI)
