"""In-mesh decentralized (gossip) FL (simulation/xla/decentralized.py):
node training + the all_gather/matmul neighbor exchange compile into one XLA
program; gated by exact equivalence against the sp twin."""

import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.arguments import Arguments
from fedml_tpu.parallel.mesh import create_fl_mesh

pytestmark = pytest.mark.heavy


def _args(**over):
    base = {
        "common_args": {"training_type": "simulation", "random_seed": 0, "run_id": "dec"},
        "data_args": {
            "dataset": "mnist",
            "data_cache_dir": "",
            # homo => equal client sizes => identical padded shapes on both
            # backends (the exact-equality precondition; see cls_trainer
            # padded_size vs the global pad)
            "partition_method": "homo",
            "synthetic_train_size": 512,
        },
        "model_args": {"model": "lr"},
        "train_args": {
            "federated_optimizer": "decentralized_fl",
            "client_num_in_total": 8,
            "client_num_per_round": 8,
            "comm_round": 3,
            "epochs": 1,
            "batch_size": 16,
            "client_optimizer": "sgd",
            "learning_rate": 0.1,
            "topology_neighbor_num": 2,
        },
        "validation_args": {"frequency_of_the_test": 1},
        "comm_args": {"backend": "XLA"},
    }
    args = Arguments.from_dict(base)
    for k, v in over.items():
        setattr(args, k, v)
    return args.validate()


def _build(**over):
    args = fedml_tpu.init(_args(**over), should_init_logs=False)
    dataset, out_dim = fedml_tpu.data.load(args)
    model = fedml_tpu.models.create(args, out_dim)
    return args, dataset, model


class TestDecentralizedInMesh:
    def test_matches_sp_twin_exactly(self):
        """Same topology seed, same per-(round, node) keys, same engine:
        the compiled gossip round must reproduce the sp actor loop."""
        import jax

        from fedml_tpu.simulation.sp.decentralized.decentralized_api import (
            DecentralizedFLAPI,
        )
        from fedml_tpu.simulation.xla.decentralized import DecentralizedInMeshAPI

        args, dataset, model = _build()
        sp = DecentralizedFLAPI(args, None, dataset, model)
        sp.train()

        args2, dataset2, model2 = _build()
        mesh_api = DecentralizedInMeshAPI(args2, None, dataset2, model2,
                                          mesh=create_fl_mesh(4))
        mesh_api.train()

        # consensus model agrees
        for a, b in zip(
            jax.tree_util.tree_leaves(mesh_api.consensus),
            jax.tree_util.tree_leaves(sp.w_global),
        ):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)
        # and so does every individual node model (gossip kept them distinct)
        for nid in (0, 3, 7):
            for a, b in zip(
                jax.tree_util.tree_leaves(mesh_api.node_params(nid)),
                jax.tree_util.tree_leaves(sp.node_models[nid]),
            ):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           rtol=1e-5, atol=1e-6)

    def test_nodes_stay_distinct_and_learn(self):
        import jax

        from fedml_tpu.simulation.xla.decentralized import DecentralizedInMeshAPI

        args, dataset, model = _build(comm_round=4)
        api = DecentralizedInMeshAPI(args, None, dataset, model,
                                     mesh=create_fl_mesh(4))
        out = api.train()
        assert out["test_acc"] > 0.5
        a = jax.tree_util.tree_leaves(api.node_params(0))
        b = jax.tree_util.tree_leaves(api.node_params(5))
        assert any(not np.allclose(np.asarray(x), np.asarray(y)) for x, y in zip(a, b))

    def test_runner_dispatch(self):
        from fedml_tpu.simulation.simulator import SimulatorXLA
        from fedml_tpu.simulation.xla.decentralized import DecentralizedInMeshAPI

        args, dataset, model = _build()
        sim = SimulatorXLA(args, None, dataset, model)
        assert isinstance(sim.sim, DecentralizedInMeshAPI)


class TestSpreadGNNInMesh:
    def _cfg(self, **over):
        return _args(dataset="moleculenet_mtl", model="gcn_mtl",
                     federated_optimizer="SpreadGNN",
                     client_num_in_total=4, client_num_per_round=4,
                     batch_size=32, client_optimizer="adam",
                     learning_rate=0.002, synthetic_train_size=256,
                     topology_neighbor_num=2, **over)

    def test_matches_sp_twin_exactly(self):
        """Same gossip round as decentralized plus the head-locality filter:
        the mesh program must reproduce the sp SpreadGNN actor loop — shared
        encoder mixed, every node's head its own."""
        import jax

        from fedml_tpu.simulation.sp.spreadgnn.spreadgnn_api import SpreadGNNAPI
        from fedml_tpu.simulation.xla.decentralized import SpreadGNNInMeshAPI

        args = fedml_tpu.init(self._cfg(comm_round=2), should_init_logs=False)
        dataset, out_dim = fedml_tpu.data.load(args)
        model = fedml_tpu.models.create(args, out_dim)
        sp = SpreadGNNAPI(args, None, dataset, model)
        sp.train()

        args2 = fedml_tpu.init(self._cfg(comm_round=2), should_init_logs=False)
        dataset2, out_dim2 = fedml_tpu.data.load(args2)
        model2 = fedml_tpu.models.create(args2, out_dim2)
        api = SpreadGNNInMeshAPI(args2, None, dataset2, model2,
                                 mesh=create_fl_mesh(4))
        api.train()

        for nid in (0, 3):
            got = jax.tree_util.tree_flatten_with_path(api.node_params(nid))[0]
            want = jax.tree_util.tree_flatten_with_path(sp.node_models[nid])[0]
            for (pa, a), (pb, b) in zip(got, want):
                assert pa == pb
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           rtol=1e-5, atol=1e-6,
                                           err_msg=str(pa))

    def test_heads_stay_local_in_program(self):
        """After training, head leaves differ across nodes — they were never
        averaged (the mixing MATH itself is covered by the sp-exactness gate
        above plus the sp twin's synthetic-stack gossip unit test)."""
        import jax

        from fedml_tpu.simulation.sp.spreadgnn.spreadgnn_api import _is_local_head
        from fedml_tpu.simulation.xla.decentralized import SpreadGNNInMeshAPI

        args = fedml_tpu.init(self._cfg(comm_round=2), should_init_logs=False)
        dataset, out_dim = fedml_tpu.data.load(args)
        model = fedml_tpu.models.create(args, out_dim)
        api = SpreadGNNInMeshAPI(args, None, dataset, model,
                                 mesh=create_fl_mesh(4))
        out = api.train()
        assert 0.0 <= out["test_acc"] <= 1.0
        flat0 = jax.tree_util.tree_flatten_with_path(api.node_params(0))[0]
        flat1 = jax.tree_util.tree_flatten_with_path(api.node_params(1))[0]
        saw_head = head_diff = False
        for (path, a), (_, b) in zip(flat0, flat1):
            if _is_local_head(path, api.head_names):
                saw_head = True
                if not np.allclose(np.asarray(a), np.asarray(b)):
                    head_diff = True
        assert saw_head, "no head leaf matched api.head_names — vacuous test"
        assert head_diff, "personalized heads converged — the filter is dead"

    def test_runner_dispatch(self):
        from fedml_tpu.simulation.simulator import SimulatorXLA
        from fedml_tpu.simulation.xla.decentralized import SpreadGNNInMeshAPI

        args = fedml_tpu.init(self._cfg(), should_init_logs=False)
        dataset, out_dim = fedml_tpu.data.load(args)
        model = fedml_tpu.models.create(args, out_dim)
        sim = SimulatorXLA(args, None, dataset, model)
        assert isinstance(sim.sim, SpreadGNNInMeshAPI)
