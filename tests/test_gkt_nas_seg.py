"""FedGKT / FedNAS / FedSeg (SURVEY.md §2.5 rows fedgkt, fednas, fedseg)."""

import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.arguments import Arguments

pytestmark = pytest.mark.heavy  # long XLA compiles; see pytest.ini


def _args(optimizer, dataset="cifar10", model="cnn", **over):
    base = {
        "common_args": {"training_type": "simulation", "random_seed": 0, "run_id": "t"},
        "data_args": {
            "dataset": dataset,
            "data_cache_dir": "",
            "partition_method": "homo",
            "synthetic_train_size": 320,
        },
        "model_args": {"model": model},
        "train_args": {
            "federated_optimizer": optimizer,
            "client_num_in_total": 4,
            "client_num_per_round": 2,
            "comm_round": 2,
            "epochs": 1,
            "batch_size": 16,
            "client_optimizer": "sgd",
            "learning_rate": 0.05,
        },
        "validation_args": {"frequency_of_the_test": 1},
        "comm_args": {"backend": "sp"},
    }
    args = Arguments.from_dict(base)
    for k, v in over.items():
        setattr(args, k, v)
    return args.validate()


def _run(args):
    from fedml_tpu import FedMLRunner, data, models

    args = fedml_tpu.init(args, should_init_logs=False)
    dataset, out_dim = data.load(args)
    try:
        model = models.create(args, out_dim)
    except ValueError:
        model = None
    return FedMLRunner(args, None, dataset, model).run()


class TestFedGKT:
    def test_round_runs_and_knowledge_flows(self):
        metrics = _run(_args("FedGKT", synthetic_train_size=256))
        assert "test_acc" in metrics and metrics["test_acc"] > 0.0

    def test_client_models_stay_local(self):
        from fedml_tpu import data
        from fedml_tpu.simulation.sp.fedgkt.gkt_api import FedGKTAPI

        args = fedml_tpu.init(_args("FedGKT", synthetic_train_size=256), should_init_logs=False)
        dataset, _ = data.load(args)
        api = FedGKTAPI(args, None, dataset, None)
        api.train()
        # every participating client kept its own edge params (2 per round,
        # per-round sampling may rotate through up to 4)
        assert 2 <= len(api.client_params) <= 4
        cids = sorted(api.client_params)
        import jax

        a = jax.tree_util.tree_leaves(api.client_params[cids[0]])
        b = jax.tree_util.tree_leaves(api.client_params[cids[1]])
        assert any(not np.allclose(x, y) for x, y in zip(a, b))
        # the server produced knowledge for the last round's participants
        assert len(api.server_logits) == 2
        assert set(api.server_logits) <= set(cids)


class TestFedNAS:
    def test_search_learns_and_derives_architecture(self):
        from fedml_tpu.models.darts import OPS, num_edges

        metrics = _run(_args("FedNAS", synthetic_train_size=256, comm_round=4,
                             epochs=3, learning_rate=0.1))
        genotype = metrics["genotype"]
        assert len(genotype) == num_edges()
        assert all(g["op"] in OPS and g["op"] != "zero" for g in genotype)
        assert metrics["test_acc"] > 0.15  # above 10-class chance

    def test_alphas_move_from_init(self):
        from fedml_tpu import data
        from fedml_tpu.models.darts import init_alphas
        from fedml_tpu.simulation.sp.fednas.fednas_api import FedNASAPI

        args = fedml_tpu.init(_args("FedNAS", synthetic_train_size=256), should_init_logs=False)
        dataset, _ = data.load(args)
        api = FedNASAPI(args, None, dataset, None)
        api.train()
        assert not np.allclose(np.asarray(api.alphas), np.asarray(init_alphas(0)), atol=1e-5)


class TestFedSeg:
    def test_segmentation_learns(self):
        args = _args("FedSeg", dataset="synthetic_seg", model="unet",
                     synthetic_train_size=160, learning_rate=0.05, comm_round=3)
        metrics = _run(args)
        assert metrics["test_acc"] > 0.6  # pixel accuracy; bg-majority ~0.55
        assert "test_miou" in metrics and metrics["test_miou"] > 0.2

    def test_seg_dataset_shapes(self):
        from fedml_tpu import data

        args = fedml_tpu.init(
            _args("FedSeg", dataset="synthetic_seg", model="unet", synthetic_train_size=64),
            should_init_logs=False,
        )
        dataset, class_num = data.load(args)
        assert class_num == 3
        x, masks = dataset[2]
        assert x.shape[1:] == (32, 32, 3)
        assert masks.shape[1:] == (32, 32)
        assert set(np.unique(masks)) <= {0, 1, 2}

    def test_seg_hetero_partition_works(self):
        from fedml_tpu import data

        args = fedml_tpu.init(
            _args("FedSeg", dataset="synthetic_seg", model="unet", synthetic_train_size=64,
                  partition_method="hetero"),
            should_init_logs=False,
        )
        dataset, _ = data.load(args)
        assert sum(dataset[4].values()) == 64
