"""The compiled sharded aggregation plane (``fedml_tpu.parallel.agg_plane``).

Four strata:

* **Partition rules** — regex rules over ``/``-joined param paths, the
  ``param_spec`` heuristic fallback, scalar replication, and the degrade-to-
  replicate contract for rules naming unknown/non-divisible mesh axes.
* **Bit-exactness (the tier-1 acceptance claim)** — on CPU in f32 mode the
  compiled plane agrees BITWISE with the host path for both ``mean``
  (FedAvg) and ``sum`` (FedAvg_seq), microbatched or not, including through
  the ``FedMLAggOperator.agg`` routing seam; bf16 wire mode is pinned to a
  tolerance instead.
* **Guards and validation** — the unified non-positive-total error across
  ``weighted_mean`` / ``stacked_weighted_mean`` / the plane, and
  ``flatten_checked``'s clear client/leaf mismatch errors.
* **Observability + chaos** — ``aggregate.compile`` / ``aggregate.reduce``
  spans close under the caller's round span (``trace_report
  --assert-closed``), metrics flow with tracing off, and a retransmit/dup
  chaos topology running ``agg_plane=compiled`` converges bit-identical to
  the fault-free host run (this module is part of the
  ``tools/chaos_check.py`` matrix via the ``agg_plane`` keyword).
* **Elastic remesh** — the mesh-portable snapshot codec and ``remesh()``:
  export on mesh A / resume on mesh B (grow, shrink, 1-D, 2-D) bitwise,
  program-cache re-keying, the device-visibility shim, degrade-to-
  replicate, and the retry/backoff resume handshake (docs/ELASTICITY.md).
"""

from __future__ import annotations

import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))

import trace_report

from fedml_tpu.core import obs
from fedml_tpu.core.aggregate import (
    FedMLAggOperator,
    ServerRoundUpdater,
    flatten_checked,
    host_server_round_update,
    leaf_paths,
    make_host_round_step,
    opt_leaf_indices,
    stacked_weighted_mean,
    tree_stack,
    unweighted_sum,
    weighted_mean,
)
from fedml_tpu.core.mlops import InMemorySink
from fedml_tpu.parallel.agg_plane import (
    CompiledAggPlane,
    ShardedRoundPlane,
    _policy_tx,
    assemble_shards,
    broadcast_shards,
    match_partition_rules,
    plane_for,
    reset_planes,
)
from fedml_tpu.parallel.mesh import (
    create_round_mesh,
    mesh_fingerprint,
    set_visible_devices,
)


@pytest.fixture(autouse=True)
def _plane_hygiene():
    """Planes (and their compiled programs) are process-cached; obs state
    and device visibility are process-global.  Every test leaves all clean."""
    yield
    reset_planes()
    set_visible_devices(None)
    obs.shutdown()
    obs.registry().reset()


def _tree(seed: int):
    """A small but structurally honest update: matrices, a vector, a scalar,
    and an integer leaf (the dtype-policy edge)."""
    rng = np.random.default_rng(seed)
    return {
        "dense": {"kernel": jnp.asarray(rng.standard_normal((8, 4)),
                                        jnp.float32),
                  "bias": jnp.asarray(rng.standard_normal((4,)), jnp.float32)},
        "scale": jnp.float32(rng.standard_normal()),
        "steps": jnp.asarray(rng.integers(0, 100, (3,)), jnp.int32),
    }


def _updates(n: int, seed: int = 0):
    rng = np.random.default_rng(seed + 1000)
    return [(float(rng.integers(3, 97)), _tree(seed + i)) for i in range(n)]


def _assert_bit_identical(a, b):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        x, y = np.asarray(x), np.asarray(y)
        assert x.dtype == y.dtype, (x.dtype, y.dtype)
        np.testing.assert_array_equal(x, y)


class _FakeMesh:
    """match_partition_rules only consults ``mesh.shape`` — a dict-shaped
    stand-in lets the rule tests exercise tp>1 on a 1-device CPU host."""

    def __init__(self, **axes):
        self.shape = dict(axes)


# ---------------------------------------------------------------------------
# Partition rules
# ---------------------------------------------------------------------------

class TestPartitionRules:
    def test_first_matching_regex_wins_heuristic_covers_the_rest(self):
        mesh = _FakeMesh(tp=4)
        specs = match_partition_rules(
            [("kernel", P(None, "tp")), (".*", P())],
            ["layer1/kernel", "layer1/bias"], [(8, 4), (8,)], mesh)
        # the kernel rule fires before the catch-all; bias hits the
        # catch-all and replicates
        assert specs == [P(None, "tp"), P()]

    def test_unmatched_leaf_falls_back_to_param_spec_heuristic(self):
        mesh = _FakeMesh(tp=4)
        specs = match_partition_rules(
            [("embedding", P("tp",))], ["dense/kernel"], [(8, 4)], mesh)
        # largest axis (0, size 8) sharded over tp — sharding.param_spec
        assert specs == [P("tp", None)]

    def test_scalars_and_size_one_leaves_always_replicate(self):
        mesh = _FakeMesh(tp=4)
        specs = match_partition_rules(
            [("scale", P("tp",))], ["scale", "mu"], [(), (1,)], mesh)
        assert specs == [P(), P()]

    @pytest.mark.parametrize("rule_spec,shape", [
        (P("model",), (8, 4)),        # axis not on this mesh
        (P("tp",), (6, 4)),           # 6 % 4 != 0: not divisible
        (P("tp", None, None), (8,)),  # spec longer than the leaf rank
    ])
    def test_unusable_rule_degrades_to_replication(self, rule_spec, shape):
        mesh = _FakeMesh(tp=4)
        specs = match_partition_rules(
            [("kernel", rule_spec)], ["dense/kernel"], [shape], mesh)
        assert specs == [P()]


# ---------------------------------------------------------------------------
# Bit-exactness: compiled plane vs host path (CPU, f32)
# ---------------------------------------------------------------------------

class TestBitExactness:
    def test_mean_bit_exact_vs_host(self):
        updates = _updates(5)
        host = weighted_mean(updates)
        comp = CompiledAggPlane().aggregate(updates, mode="mean")
        _assert_bit_identical(host, comp)

    def test_sum_bit_exact_vs_host_including_int_dtype(self):
        updates = _updates(4, seed=11)
        host = unweighted_sum(updates)
        comp = CompiledAggPlane().aggregate(updates, mode="sum")
        _assert_bit_identical(host, comp)
        assert np.asarray(comp["steps"]).dtype == np.int32

    @pytest.mark.parametrize("optimizer", ["FedAvg", "FedAvg_seq"])
    def test_operator_routing_is_bit_exact(self, optimizer):
        class _Args:
            federated_optimizer = optimizer
            agg_plane = "compiled"

        class _Host(_Args):
            agg_plane = "host"

        updates = _updates(4, seed=3)
        _assert_bit_identical(FedMLAggOperator.agg(_Host, updates),
                              FedMLAggOperator.agg(_Args, updates))

    @pytest.mark.parametrize("mode", ["mean", "sum"])
    def test_microbatched_equals_full_stack_bitwise(self, mode):
        updates = _updates(5, seed=7)  # 5 clients, K=2: a padded last chunk
        full = CompiledAggPlane().aggregate(updates, mode=mode)
        micro = CompiledAggPlane(microbatch_clients=2).aggregate(
            updates, mode=mode)
        _assert_bit_identical(full, micro)

    def test_bf16_wire_within_tolerance(self):
        updates = _updates(5, seed=5)
        host = weighted_mean(updates)
        comp = CompiledAggPlane(wire_dtype="bf16").aggregate(updates)
        for x, y in zip(jax.tree_util.tree_leaves(host),
                        jax.tree_util.tree_leaves(comp)):
            x, y = np.asarray(x, np.float32), np.asarray(y, np.float32)
            # bf16 keeps 8 mantissa bits: inputs are O(1), 5 clients
            assert float(np.max(np.abs(x - y))) < 0.05

    def test_thousand_deltas_microbatched_smoke(self):
        # 1k clients on a 1-device mesh: the accumulator never materializes
        # the full stack, and the result still bit-matches the host loop
        rng = np.random.default_rng(42)
        updates = [(float(rng.integers(1, 50)),
                    {"w": jnp.asarray(rng.standard_normal(4), jnp.float32)})
                   for _ in range(1000)]
        host = weighted_mean(updates)
        comp = CompiledAggPlane(microbatch_clients=64).aggregate(updates)
        _assert_bit_identical(host, comp)

    def test_plane_for_caches_per_config(self):
        class _A:
            agg_wire_dtype, agg_microbatch_clients = "f32", 0

        class _B:
            agg_wire_dtype, agg_microbatch_clients = "bf16", 8

        assert plane_for(_A) is plane_for(_A)
        assert plane_for(_A) is not plane_for(_B)
        assert plane_for(_B).microbatch_clients == 8


# ---------------------------------------------------------------------------
# Sharded round plane: one compiled reduce→optimize round tail
# ---------------------------------------------------------------------------

_POLICIES = [("fedavg",), ("sgd", 0.1, 0.9), ("adam", 0.1, 0.9),
             ("yogi", 0.01, 0.9), ("adagrad", 0.1, 0.9)]


def _opt_tree(seed: int):
    """Production-shaped globals: a ``params`` collection (the optimizer's
    domain — ``flatten_params`` emits this prefix) plus an int leaf OUTSIDE
    it.  The collection keeps the opt-leaf mask stable across rounds even
    after a mean round promotes the int leaf to float; a flat all-float
    mask would widen mid-run and desync the optimizer state."""
    rng = np.random.default_rng(seed)
    return {
        "params": {
            "dense": {"kernel": jnp.asarray(rng.standard_normal((8, 4)),
                                            jnp.float32),
                      "bias": jnp.asarray(rng.standard_normal((4,)),
                                          jnp.float32)},
            "scale": jnp.float32(rng.standard_normal()),
        },
        "steps": jnp.asarray(rng.integers(0, 100, (3,)), jnp.int32),
    }


def _opt_updates(n: int, seed: int = 0):
    rng = np.random.default_rng(seed + 1000)
    return [(float(rng.integers(3, 97)), _opt_tree(seed + i))
            for i in range(n)]


def _host_opt_init(policy, params_tree):
    """(tx, fresh opt state, jitted host step) — the replicated oracle's
    starting point, using the same opt-leaf mask as the plane."""
    tx = _policy_tx(policy)
    if tx is None:
        return None, (), None
    leaves, td = jax.tree_util.tree_flatten(params_tree)
    idx = opt_leaf_indices(leaf_paths(td),
                           [jnp.result_type(l) for l in leaves])
    return tx, tx.init([jnp.asarray(leaves[i]) for i in idx]), \
        make_host_round_step(tx)


class TestShardedRoundPlane:
    @pytest.mark.parametrize("policy", _POLICIES, ids=lambda p: p[0])
    @pytest.mark.parametrize("mode", ["mean", "sum"])
    def test_multi_round_bit_exact_vs_host_oracle(self, policy, mode):
        """The tier-1 acceptance claim: three full rounds of the compiled
        sharded tail agree BITWISE with host aggregation + the jitted
        sp/fedopt server step, for every server-optimizer policy, in both
        agg modes — optimizer state carried across rounds on both sides."""
        params = _opt_tree(100)
        tx, opt_state, step = _host_opt_init(policy, params)
        plane = ShardedRoundPlane(policy=policy)
        host = out = params
        for r in range(3):
            updates = _opt_updates(4, seed=20 + r)
            host, opt_state = host_server_round_update(
                host, updates, tx, opt_state, mode=mode, step=step)
            out = plane.round_update(out, updates, mode=mode)
            _assert_bit_identical(host, out)

    def test_microbatched_round_equals_full_bitwise(self):
        """K=2 over 5 clients (padded last chunk, separate fold + tail
        programs) matches the single fused program bit-for-bit, across
        rounds — the accumulator carry-over cannot drift."""
        policy = ("adam", 0.1, 0.9)
        full = ShardedRoundPlane(policy=policy)
        micro = ShardedRoundPlane(microbatch_clients=2, policy=policy)
        a = b = _tree(200)
        for r in range(2):
            updates = _updates(5, seed=30 + r)
            a = full.round_update(a, updates)
            b = micro.round_update(b, updates)
            _assert_bit_identical(a, b)

    def test_optimizer_state_survives_value_copy_reinstall(self):
        """The aggregate→manager→aggregate round trip can hand back a
        VALUE copy of the globals (identity broken).  The same-structure
        re-install must keep the adam moments — the host oracle never
        resets its state mid-run either — so round 2 still bit-matches."""
        policy = ("adam", 0.1, 0.9)
        params = _opt_tree(7)
        tx, opt_state, step = _host_opt_init(policy, params)
        plane = ShardedRoundPlane(policy=policy)
        host, opt_state = host_server_round_update(
            params, _opt_updates(3, seed=1), tx, opt_state, step=step)
        out = plane.round_update(params, _opt_updates(3, seed=1))
        copy = jax.tree_util.tree_map(np.asarray, out)
        host, opt_state = host_server_round_update(
            host, _opt_updates(3, seed=2), tx, opt_state, step=step)
        out2 = plane.round_update(copy, _opt_updates(3, seed=2))
        _assert_bit_identical(host, out2)

    def test_export_load_state_round_trip_bit_identical(self):
        """Snapshot after round 1, restore into a FRESH plane (the server
        restart path), run round 2 on both: identical bits — the optimizer
        moments survive the numpy/state-dict codec exactly."""
        policy = ("yogi", 0.01, 0.9)
        plane = ShardedRoundPlane(policy=policy)
        assert plane.export_state() is None  # nothing resident yet
        out1 = plane.round_update(_opt_tree(5), _opt_updates(4, seed=8))
        snap = plane.export_state()
        out2 = plane.round_update(out1, _opt_updates(4, seed=9))

        clone = ShardedRoundPlane(policy=policy)
        clone.install(out1)
        clone.load_state(snap)
        _assert_bit_identical(
            out2, clone.round_update(out1, _opt_updates(4, seed=9)))

    def test_round_program_cache_keyed_on_mesh(self):
        """Same (treedef, shapes, K, policy) signature on a DIFFERENT mesh
        compiles its own program; a third plane on the default mesh reuses
        the cached one — and the math is mesh-shape-independent."""
        from fedml_tpu.parallel import agg_plane as _ap

        policy = ("adam", 0.1, 0.9)
        updates = _updates(3, seed=40)
        p1 = ShardedRoundPlane(policy=policy)
        out1 = p1.round_update(_tree(1), updates)
        n1 = len(_ap._ROUND_PROGRAMS)
        sub = create_round_mesh(clients=1, model=1,
                                devices=jax.devices()[:1])
        p2 = ShardedRoundPlane(mesh=sub, policy=policy)
        out2 = p2.round_update(_tree(1), updates)
        assert len(_ap._ROUND_PROGRAMS) == n1 + 1
        p3 = ShardedRoundPlane(policy=policy)
        p3.round_update(_tree(1), updates)
        assert len(_ap._ROUND_PROGRAMS) == n1 + 1
        _assert_bit_identical(out1, out2)

    def test_plane_for_rekeys_on_topology_change(self, monkeypatch):
        """Satellite contract: the process plane cache keys on the CURRENT
        mesh fingerprint — after a topology change plane_for hands out a
        fresh plane instead of replaying programs built for the old one."""
        from fedml_tpu.parallel import agg_plane as _ap

        class _A:
            agg_wire_dtype, agg_microbatch_clients = "f32", 0

        a = plane_for(_A)
        assert plane_for(_A) is a
        sub = _ap.default_agg_mesh(jax.devices()[:1])
        monkeypatch.setattr(_ap, "default_agg_mesh",
                            lambda devices=None: sub)
        b = _ap.plane_for(_A)
        assert b is not a
        assert _ap.plane_for(_A) is b

    def test_server_round_updater_facade(self):
        """The routing facade: lazy plane (no snapshot before round 1),
        FedOpt policy from args, and restore_state → next round bitwise
        equal to the uninterrupted updater."""

        class _Args:
            federated_optimizer = "FedOpt"
            server_optimizer = "adam"
            server_lr = 0.1
            server_momentum = 0.9
            server_state = "sharded"

        upd = ServerRoundUpdater(_Args)
        assert upd.export_state() is None
        out = upd.round_update(_opt_tree(9), _opt_updates(3, seed=9))
        snap = upd.export_state()
        assert snap is not None and snap["policy"][0] == "adam"
        clone = ServerRoundUpdater(_Args)
        clone.restore_state(out, snap)
        _assert_bit_identical(
            upd.round_update(out, _opt_updates(3, seed=10)),
            clone.round_update(out, _opt_updates(3, seed=10)))


# ---------------------------------------------------------------------------
# Elastic remesh (the topology-change robustness claim)
# ---------------------------------------------------------------------------

def _mesh_variants():
    """Target meshes for the elastic legs, relative to a (1, 4) source:
    shrink (model 4→2), grow (model 4→8), 1-D (model collapses to a single
    device), and 2-D relayout (the client axis widens to 2x2)."""
    devs = jax.devices()
    return [
        ("shrink", lambda: create_round_mesh(clients=1, model=2,
                                             devices=devs[:2])),
        ("grow", lambda: create_round_mesh(clients=1, model=len(devs),
                                           devices=devs)),
        ("one_d", lambda: create_round_mesh(clients=1, model=1,
                                            devices=devs[:1])),
        ("two_d", lambda: create_round_mesh(clients=2, model=2,
                                            devices=devs[:4])),
    ]


class TestElasticRemesh:
    """Mesh topology is a recoverable dimension: a snapshot taken on mesh A
    resumes on ANY mesh B with bitwise-identical params and optimizer
    moments, live remesh() is equivalent to export/restart/load, and the
    program caches re-key so nothing compiled for the dead mesh can run."""

    def _mesh_a(self):
        return create_round_mesh(clients=1, model=4,
                                 devices=jax.devices()[:4])

    @pytest.mark.parametrize("variant", [v[0] for v in _mesh_variants()])
    @pytest.mark.parametrize("policy", _POLICIES, ids=lambda p: p[0])
    def test_export_mesh_a_load_mesh_b_bitwise(self, policy, variant):
        """The acceptance claim: round 1 on mesh A, snapshot, resume round 2
        on mesh B (grow / shrink / 1-D / 2-D) — params AND optimizer
        moments bitwise equal to the uninterrupted fixed-mesh run, for
        every server policy."""
        mesh_b = dict(_mesh_variants())[variant]()
        ref = ShardedRoundPlane(mesh=self._mesh_a(), policy=policy)
        r1 = ref.round_update(_opt_tree(50), _opt_updates(4, seed=60))
        r2 = ref.round_update(r1, _opt_updates(4, seed=61))

        src = ShardedRoundPlane(mesh=self._mesh_a(), policy=policy)
        e1 = src.round_update(_opt_tree(50), _opt_updates(4, seed=60))
        snap = src.export_state()
        assert snap["manifest"]["mesh"]  # source fingerprint travels along
        dst = ShardedRoundPlane(mesh=mesh_b, policy=policy)
        dst.install(e1)
        dst.load_state(snap)
        e2 = dst.round_update(e1, _opt_updates(4, seed=61))
        _assert_bit_identical(r2, e2)
        _assert_bit_identical(ref.export_state()["opt"],
                              dst.export_state()["opt"])

    @pytest.mark.parametrize("variant", [v[0] for v in _mesh_variants()])
    def test_remesh_in_place_bit_identical(self, variant):
        """Live remesh() between rounds — host-gather, re-shard, pre-warm —
        is bitwise invisible to the round math, and the plane's cache
        identity (mesh_key) re-keys so the old mesh's programs are dead."""
        mesh_b = dict(_mesh_variants())[variant]()
        policy = ("adam", 0.1, 0.9)
        ref = ShardedRoundPlane(mesh=self._mesh_a(), policy=policy)
        r1 = ref.round_update(_opt_tree(51), _opt_updates(4, seed=70))
        r2 = ref.round_update(r1, _opt_updates(4, seed=71))

        plane = ShardedRoundPlane(mesh=self._mesh_a(), policy=policy)
        e1 = plane.round_update(_opt_tree(51), _opt_updates(4, seed=70))
        old_key = plane.mesh_key
        info = plane.remesh(mesh_b)
        assert info["changed"] and info["reshard_bytes"] > 0
        assert plane.mesh_key == mesh_fingerprint(mesh_b) != old_key
        _assert_bit_identical(r2, plane.round_update(
            e1, _opt_updates(4, seed=71)))

    def test_remesh_prewarms_round_program(self):
        """remesh() recompiles the most recent round program for the new
        mesh eagerly — the first post-resize round adds NO cache entry —
        and a same-mesh remesh is a no-op."""
        from fedml_tpu.parallel import agg_plane as _ap

        plane = ShardedRoundPlane(mesh=self._mesh_a(),
                                  policy=("adam", 0.1, 0.9))
        out = plane.round_update(_opt_tree(52), _opt_updates(3, seed=80))
        assert not plane.remesh(self._mesh_a())["changed"]
        mesh_b = create_round_mesh(clients=1, model=2,
                                   devices=jax.devices()[:2])
        info = plane.remesh(mesh_b)
        assert info["changed"] and info["recompile_s"] > 0
        n = len(_ap._ROUND_PROGRAMS)
        plane.round_update(out, _opt_updates(3, seed=81))
        assert len(_ap._ROUND_PROGRAMS) == n

    def test_visibility_shim_drives_default_meshes(self):
        """set_visible_devices() changes what default_round_mesh /
        round_mesh_for build — the seam fault injection and elastic
        restarts use to simulate chip loss deterministically."""
        from fedml_tpu.parallel import agg_plane as _ap

        full = mesh_fingerprint(_ap.default_round_mesh())
        set_visible_devices([d.id for d in jax.devices()[:2]])
        shrunk = mesh_fingerprint(_ap.default_round_mesh())
        assert shrunk != full
        assert dict(_ap.default_round_mesh().shape)["model"] == 2
        set_visible_devices(None)
        assert mesh_fingerprint(_ap.default_round_mesh()) == full

    def test_degrade_to_replicate_when_devices_cannot_satisfy(self):
        """server_model_parallel beyond the surviving device count degrades
        to a replicated model=1 mesh (and counts the degradation) instead
        of refusing to serve."""
        from fedml_tpu.parallel import agg_plane as _ap

        class _A:
            server_model_parallel = 4

        set_visible_devices([d.id for d in jax.devices()[:2]])
        mesh = _ap.round_mesh_for(_A)
        assert dict(mesh.shape) == {"client": 1, "model": 1}
        assert obs.registry().get_counter("mesh.degraded_total") >= 1

    def test_manifest_rejects_structurally_foreign_snapshot(self):
        """The portable codec fails loud, before touching devices, when the
        snapshot's manifest does not describe the installed params."""
        plane = ShardedRoundPlane(policy=("adam", 0.1, 0.9))
        plane.round_update(_opt_tree(53), _opt_updates(3, seed=90))
        snap = plane.export_state()
        other = ShardedRoundPlane(policy=("adam", 0.1, 0.9))
        other.install(_tree(1))  # different leaf paths AND shapes
        with pytest.raises(ValueError, match="differs from installed"):
            other.load_state(snap)

    def test_updater_remesh_retries_then_succeeds(self, monkeypatch):
        """The elastic resume handshake retries with backoff: a transiently
        failing device enumeration settles on a later attempt instead of
        failing the round."""
        from fedml_tpu.parallel import agg_plane as _ap

        class _Args:
            federated_optimizer = "FedOpt"
            server_optimizer = "adam"
            server_lr = 0.1
            server_momentum = 0.9
            server_state = "sharded"
            remesh_max_retries = 3
            remesh_backoff_s = 0.0

        upd = ServerRoundUpdater(_Args)
        assert upd.remesh() is None  # nothing resident yet
        out = upd.round_update(_opt_tree(54), _opt_updates(3, seed=95))
        mesh_b = create_round_mesh(clients=1, model=2,
                                   devices=jax.devices()[:2])
        real, calls = _ap.round_mesh_for, []

        def flaky(args, devices=None):
            calls.append(1)
            if len(calls) < 3:
                raise RuntimeError("device enumeration raced the resize")
            return mesh_b

        monkeypatch.setattr(_ap, "round_mesh_for", flaky)
        info = upd.remesh()
        monkeypatch.setattr(_ap, "round_mesh_for", real)
        assert len(calls) == 3 and info["changed"]
        assert upd.mesh_key() == mesh_fingerprint(mesh_b)
        upd.round_update(out, _opt_updates(3, seed=96))  # still serves


# ---------------------------------------------------------------------------
# Shard-addressable broadcast
# ---------------------------------------------------------------------------

class TestBroadcastShards:
    @pytest.mark.parametrize("n", [1, 2, 4])
    def test_round_trip_bit_identical_any_order(self, n):
        tree = _tree(3)
        td = jax.tree_util.tree_structure(tree)
        shards = broadcast_shards(tree, n)
        assert [s["shard"] for s in shards] == list(range(n))
        _assert_bit_identical(tree, assemble_shards(list(reversed(shards)),
                                                    td))

    def test_shards_split_the_payload(self):
        """Divisible leading dims are sliced (no shard carries the whole
        model), and the slices cover the tree exactly — no bytes invented
        or dropped."""
        tree = _tree(4)
        full = sum(np.asarray(l).nbytes
                   for l in jax.tree_util.tree_leaves(tree))
        per = [sum(p.nbytes for _, _, p in s["parts"])
               for s in broadcast_shards(tree, 4)]
        assert sum(per) == full
        assert max(per) < full

    def test_missing_or_duplicate_shards_raise(self):
        tree = _tree(2)
        td = jax.tree_util.tree_structure(tree)
        shards = broadcast_shards(tree, 3)
        with pytest.raises(ValueError, match="need shards"):
            assemble_shards(shards[:2], td)
        with pytest.raises(ValueError, match="need shards"):
            assemble_shards(shards + [shards[0]], td)
        with pytest.raises(ValueError, match="num_shards"):
            broadcast_shards(tree, 0)


# ---------------------------------------------------------------------------
# Guards + validation
# ---------------------------------------------------------------------------

class TestGuards:
    @pytest.mark.parametrize("ns", [(0.0, 0.0), (2.0, -2.0), (-1.0, -3.0)])
    def test_nonpositive_total_raises_everywhere(self, ns):
        trees = [_tree(0), _tree(1)]
        updates = list(zip(ns, trees))
        with pytest.raises(ValueError, match="must be positive"):
            weighted_mean(updates)
        with pytest.raises(ValueError, match="must be positive"):
            stacked_weighted_mean(tree_stack(trees), jnp.asarray(ns))
        with pytest.raises(ValueError, match="must be positive"):
            CompiledAggPlane().aggregate(updates, mode="mean")

    def test_stacked_weighted_mean_under_jit_keeps_the_clamp(self):
        # tracing can't raise on data: the documented traced-path behavior
        stacked = tree_stack([_tree(0), _tree(1)])
        out = jax.jit(stacked_weighted_mean)(stacked, jnp.zeros(2))
        assert all(np.all(np.isfinite(l))
                   for l in jax.tree_util.tree_leaves(out))

    def test_structure_mismatch_names_the_client(self):
        with pytest.raises(ValueError, match="client 1 pytree structure"):
            tree_stack([{"a": jnp.zeros(3)}, {"b": jnp.zeros(3)}])

    def test_shape_mismatch_names_client_and_leaf(self):
        trees = [{"m": {"w": jnp.zeros((3, 2))}},
                 {"m": {"w": jnp.zeros((3, 2))}},
                 {"m": {"w": jnp.zeros((4, 2))}}]
        with pytest.raises(ValueError,
                           match=r"client 2 leaf 'm/w' has shape \(4, 2\)"):
            flatten_checked(trees)
        updates = [(1.0, t) for t in trees]
        with pytest.raises(ValueError, match="client 2 leaf 'm/w'"):
            CompiledAggPlane().aggregate(updates)

    def test_leaf_paths_cached_per_treedef(self):
        td = jax.tree_util.tree_structure(_tree(0))
        assert leaf_paths(td) is leaf_paths(td)  # lru_cache hit
        assert "dense/kernel" in leaf_paths(td)

    def test_empty_updates_and_bad_mode_raise(self):
        plane = CompiledAggPlane()
        with pytest.raises(ValueError, match="no updates"):
            plane.aggregate([])
        with pytest.raises(ValueError, match="mean|sum"):
            plane.aggregate(_updates(2), mode="median")
        with pytest.raises(ValueError, match="agg_wire_dtype"):
            CompiledAggPlane(wire_dtype="f8")
        with pytest.raises(ValueError, match="agg_microbatch_clients"):
            CompiledAggPlane(microbatch_clients=-1)


# ---------------------------------------------------------------------------
# Observability: closed spans under the round root, metrics always on
# ---------------------------------------------------------------------------

class _ObsArgs:
    rank = 0

    def __init__(self, run_id):
        self.run_id = run_id
        self.obs_trace = True


class TestObservability:
    def test_agg_plane_spans_close_under_round_root(self, tmp_path):
        mem = InMemorySink()
        obs.configure(_ObsArgs("agg-obs"), mem.emit)
        try:
            with obs.round_span(0, mode="test"):
                # ambient parenting: the plane finds the round span without
                # any signature plumbing at the call site
                CompiledAggPlane().aggregate(_updates(3))
        finally:
            obs.shutdown()
        recs = [dict(rec, topic=t) for t, rec in list(mem.records)
                if t in trace_report.SPAN_TOPICS]
        names = {r["name"] for r in recs if r["topic"] == "span_start"}
        assert {"round", "aggregate.compile", "aggregate.reduce"} <= names
        traces = trace_report.build_traces(recs)
        assert len(traces) == 1
        (tr,) = traces.values()
        assert tr.problems() == []
        path = tmp_path / "agg.jsonl"
        path.write_text("\n".join(json.dumps(r) for r in recs) + "\n")
        assert trace_report.main([str(path), "--assert-closed"]) == 0

    def test_no_parent_no_spans_but_metrics_flow(self):
        # tracing disabled: no span records can exist, yet the registry
        # still sees the step histogram and the bytes counter
        n = 3
        plane = CompiledAggPlane()
        plane.aggregate(_updates(n))
        hist = obs.registry().get_histogram(
            "agg.step_seconds", {"path": "compiled", "mode": "mean"})
        assert hist is not None and hist["count"] == 1
        per_client = sum(
            int(np.prod(s) or 1) * np.dtype(d).itemsize
            for s, d in ((np.shape(l), np.asarray(l).dtype)
                         for l in jax.tree_util.tree_leaves(_tree(0))))
        assert obs.registry().get_counter(
            "agg.bytes_reduced", {"path": "compiled"}) == n * per_client

    def test_round_update_span_closes_under_round_root(self, tmp_path):
        """The sharded round tail traces as ``round.server_update`` (with
        ``aggregate.compile`` under it on the first round) and the whole
        trace closes clean under the round root."""
        mem = InMemorySink()
        obs.configure(_ObsArgs("round-obs"), mem.emit)
        try:
            with obs.round_span(0, mode="test"):
                ShardedRoundPlane(policy=("adam", 0.1, 0.9)).round_update(
                    _tree(55), _updates(3, seed=55))
        finally:
            obs.shutdown()
        recs = [dict(rec, topic=t) for t, rec in list(mem.records)
                if t in trace_report.SPAN_TOPICS]
        names = {r["name"] for r in recs if r["topic"] == "span_start"}
        assert {"round", "round.server_update", "aggregate.compile"} <= names
        traces = trace_report.build_traces(recs)
        assert len(traces) == 1
        (tr,) = traces.values()
        assert tr.problems() == []
        path = tmp_path / "round.jsonl"
        path.write_text("\n".join(json.dumps(r) for r in recs) + "\n")
        assert trace_report.main([str(path), "--assert-closed"]) == 0

    def test_sharded_metrics_flow_without_tracing(self):
        plane = ShardedRoundPlane(policy=("adam", 0.1, 0.9))
        plane.round_update(_tree(66), _updates(3, seed=66))
        hist = obs.registry().get_histogram(
            "server_opt.step_seconds", {"policy": "adam", "mode": "mean"})
        assert hist is not None and hist["count"] == 1
        hist = obs.registry().get_histogram(
            "agg.step_seconds", {"path": "sharded", "mode": "mean"})
        assert hist is not None and hist["count"] == 1
        shard_bytes = obs.registry().get_gauge(
            "server_state.shard_bytes", {"axis": "model"})
        assert shard_bytes is not None and shard_bytes > 0

    def test_host_path_emits_step_histogram_too(self):
        class _Args:
            federated_optimizer, agg_plane = "FedAvg", "host"

        FedMLAggOperator.agg(_Args, _updates(2))
        hist = obs.registry().get_histogram(
            "agg.step_seconds", {"path": "host", "mode": "mean"})
        assert hist is not None and hist["count"] == 1


# ---------------------------------------------------------------------------
# Chaos: retransmit/dup weather with agg_plane=compiled (chaos_check matrix)
# ---------------------------------------------------------------------------

def _retransmit_dup_plan():
    """Drop + duplicate rules from the full chaos plan: the two fault kinds
    that re-deliver or re-send model payloads into the aggregation path."""
    return {
        "seed": 7,
        "rules": [
            {"kind": "drop", "direction": "send", "sender": 0, "receiver": 3,
             "msg_type": 2, "round": 1, "times": 1},
            {"kind": "duplicate", "direction": "send", "sender": 3,
             "msg_type": 3, "round": 0, "times": 1},
        ],
    }


def test_chaos_retransmit_dup_with_compiled_agg_plane():
    """A topology under drop/duplicate chaos with ``agg_plane=compiled``
    finishes all rounds bit-identical to the fault-free HOST-plane run:
    the compiled reduction composes with retransmit healing and dedup, and
    its f32 bit-exactness holds end-to-end, not just in isolation."""
    import test_fault_tolerance as _ft
    from fedml_tpu.core.distributed.communication.loopback import LoopbackHub

    LoopbackHub.reset()
    history, host_final, _ = _ft._run_chaos_topology("aggp-base", knobs={})
    assert len(history) == 2

    LoopbackHub.reset()
    knobs = dict(_ft._CHAOS_KNOBS, agg_plane="compiled")
    history, comp_final, stats = _ft._run_chaos_topology(
        "aggp-chaos", fault_plan=_retransmit_dup_plan(), knobs=knobs)
    assert len(history) == 2
    assert _ft._trees_bit_identical(comp_final, host_final), \
        "compiled agg plane under chaos diverged from the fault-free host run"
    srv = stats[0]
    assert srv["faults_dropped"] >= 1
    assert srv["retransmits"] >= 1
