"""Checkpoint/resume subsystem (core/checkpoint.py) — the aux capability
SURVEY.md §5 flags as missing in the reference and required in the rebuild."""

import jax.numpy as jnp
import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.arguments import Arguments
from fedml_tpu.core.checkpoint import CheckpointManager


class TestCheckpointManager:
    def test_roundtrip_pytree(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        state = {
            "w": {"dense": {"kernel": jnp.arange(6.0).reshape(2, 3)}},
            "rng": jnp.array([0, 7], jnp.uint32),
            "note": 3,
        }
        mgr.save(4, state, metadata={"run": "t"})
        step, restored = mgr.restore()
        assert step == 4
        np.testing.assert_allclose(restored["w"]["dense"]["kernel"], np.arange(6.0).reshape(2, 3))
        np.testing.assert_array_equal(restored["rng"], [0, 7])
        assert mgr.metadata(4)["run"] == "t"

    def test_retention_keeps_last_n(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), keep=2)
        for step in range(5):
            mgr.save(step, {"x": np.float32(step)})
        assert mgr.all_steps() == [3, 4]
        _, state = mgr.restore(3)
        assert float(state["x"]) == 3.0

    def test_restore_empty_raises(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        with pytest.raises(FileNotFoundError):
            mgr.restore()


def _args(tmp_path, comm_round):
    return Arguments.from_dict(
        {
            "common_args": {"training_type": "simulation", "random_seed": 0, "run_id": "ck"},
            "data_args": {
                "dataset": "mnist",
                "data_cache_dir": "",
                "partition_method": "homo",
                "synthetic_train_size": 320,
            },
            "model_args": {"model": "lr"},
            "train_args": {
                "federated_optimizer": "FedAvg",
                "client_num_in_total": 4,
                "client_num_per_round": 2,
                "comm_round": comm_round,
                "epochs": 1,
                "batch_size": 32,
                "client_optimizer": "sgd",
                "learning_rate": 0.1,
                "checkpoint_dir": str(tmp_path / "ckpts"),
            },
            "validation_args": {"frequency_of_the_test": 100},
            "comm_args": {"backend": "sp"},
        }
    ).validate()


class TestSimulatorResume:
    def test_sp_resume_matches_straight_run(self, tmp_path):
        """2 rounds + resume for 2 more == 4 straight rounds (bitwise params)."""
        from fedml_tpu.simulation.sp.fedavg.fedavg_api import FedAvgAPI

        def build(comm_round, subdir):
            args = _args(tmp_path / subdir, comm_round)
            args = fedml_tpu.init(args, should_init_logs=False)
            from fedml_tpu import data, models

            dataset, out_dim = data.load(args)
            model = models.create(args, out_dim)
            return args, FedAvgAPI(args, None, dataset, model)

        args_a, api_straight = build(4, "a")
        api_straight.train()

        args_b, api_part1 = build(2, "b")
        api_part1.train()
        _, api_part2 = build(4, "b")  # same dir -> auto-resume at round 2
        api_part2.train()

        import jax

        flat_a = jax.tree_util.tree_leaves(api_straight.w_global)
        flat_b = jax.tree_util.tree_leaves(api_part2.w_global)
        for xa, xb in zip(flat_a, flat_b):
            np.testing.assert_allclose(np.asarray(xa), np.asarray(xb), rtol=1e-6, atol=1e-6)

    def test_fedopt_resume_restores_server_optimizer_state(self, tmp_path):
        """Server Adam moments must survive resume (checkpoint_state hook)."""
        from fedml_tpu.simulation.sp.fedopt.fedopt_api import FedOptAPI

        def build(comm_round, subdir):
            args = _args(tmp_path / subdir, comm_round)
            args.federated_optimizer = "FedOpt"
            args.server_optimizer = "adam"
            args = fedml_tpu.init(args, should_init_logs=False)
            from fedml_tpu import data, models

            dataset, out_dim = data.load(args)
            model = models.create(args, out_dim)
            return FedOptAPI(args, None, dataset, model)

        api_straight = build(4, "a")
        api_straight.train()

        build(2, "b").train()
        api_resumed = build(4, "b")
        api_resumed.train()

        import jax

        for xa, xb in zip(
            jax.tree_util.tree_leaves(api_straight.w_global),
            jax.tree_util.tree_leaves(api_resumed.w_global),
        ):
            np.testing.assert_allclose(np.asarray(xa), np.asarray(xb), rtol=1e-5, atol=1e-6)
