"""Checkpoint/resume subsystem (core/checkpoint.py) — the aux capability
SURVEY.md §5 flags as missing in the reference and required in the rebuild."""

import jax.numpy as jnp
import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.arguments import Arguments
from fedml_tpu.core.checkpoint import CheckpointManager


class TestCheckpointManager:
    def test_roundtrip_pytree(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        state = {
            "w": {"dense": {"kernel": jnp.arange(6.0).reshape(2, 3)}},
            "rng": jnp.array([0, 7], jnp.uint32),
            "note": 3,
        }
        mgr.save(4, state, metadata={"run": "t"})
        step, restored = mgr.restore()
        assert step == 4
        np.testing.assert_allclose(restored["w"]["dense"]["kernel"], np.arange(6.0).reshape(2, 3))
        np.testing.assert_array_equal(restored["rng"], [0, 7])
        assert mgr.metadata(4)["run"] == "t"

    def test_retention_keeps_last_n(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), keep=2)
        for step in range(5):
            mgr.save(step, {"x": np.float32(step)})
        assert mgr.all_steps() == [3, 4]
        _, state = mgr.restore(3)
        assert float(state["x"]) == 3.0

    def test_restore_empty_raises(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        with pytest.raises(FileNotFoundError):
            mgr.restore()

    def test_retention_removes_sidecars_too(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), keep=2)
        for step in range(5):
            mgr.save(step, {"x": np.float32(step)}, metadata={"r": step})
        names = sorted(p.name for p in tmp_path.iterdir())
        assert names == ["ckpt_3.msgpack", "ckpt_3.msgpack.json",
                         "ckpt_4.msgpack", "ckpt_4.msgpack.json"]

    def test_metadata_roundtrip_and_default(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(7, {"x": np.float32(1)}, metadata={"run": "m", "acc": 0.5})
        meta = mgr.metadata(7)
        assert meta["run"] == "m" and meta["acc"] == 0.5
        assert meta["step"] == 7 and "time" in meta
        # a step with no sidecar degrades to the bare step, never raises
        assert mgr.metadata(99) == {"step": 99}

    def test_no_tmp_file_left_behind(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(1, {"x": np.float32(1)})
        assert not [p for p in tmp_path.iterdir() if p.name.endswith(".tmp")]

    def test_corrupt_latest_falls_back_to_previous(self, tmp_path, caplog):
        mgr = CheckpointManager(str(tmp_path), keep=3)
        mgr.save(1, {"x": np.float32(1.0)})
        mgr.save(2, {"x": np.float32(2.0)})
        # a crash mid-write can only corrupt the file via the power-loss
        # window; simulate the worst case (truncated + garbage)
        (tmp_path / "ckpt_2.msgpack").write_bytes(b"\x00garbage")
        step, state = mgr.restore()
        assert step == 1 and float(state["x"]) == 1.0
        # the bad step was pruned (file AND sidecar) so the next save/restore
        # cycle never trips on it again
        assert mgr.all_steps() == [1]
        assert not (tmp_path / "ckpt_2.msgpack.json").exists()

    def test_corrupt_empty_latest_falls_back(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(1, {"x": np.float32(1.0)})
        mgr.save(2, {"x": np.float32(2.0)})
        (tmp_path / "ckpt_2.msgpack").write_bytes(b"")
        step, _ = mgr.restore()
        assert step == 1

    def test_all_corrupt_raises_file_not_found(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(1, {"x": np.float32(1.0)})
        (tmp_path / "ckpt_1.msgpack").write_bytes(b"junk")
        with pytest.raises(FileNotFoundError):
            mgr.restore()

    def test_explicitly_requested_corrupt_step_raises(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(1, {"x": np.float32(1.0)})
        mgr.save(2, {"x": np.float32(2.0)})
        (tmp_path / "ckpt_2.msgpack").write_bytes(b"junk")
        with pytest.raises(Exception):
            mgr.restore(2)  # an explicit ask must not silently time-travel


class TestUpdateJournal:
    def _journal(self, tmp_path, **kw):
        from fedml_tpu.core.checkpoint import UpdateJournal

        return UpdateJournal(str(tmp_path / "j"), **kw)

    def test_append_replay_roundtrip(self, tmp_path):
        j = self._journal(tmp_path)
        j.append(0, {"sender": 1, "n_samples": 10,
                     "model_params": {"w": np.arange(3.0)}})
        j.append(0, {"sender": 2, "n_samples": 20,
                     "model_params": {"w": np.arange(3.0) * 2}})
        records, bad_tail = j.replay(0)
        assert bad_tail == 0
        assert [int(r["sender"]) for r in records] == [1, 2]
        np.testing.assert_array_equal(records[1]["model_params"]["w"],
                                      np.arange(3.0) * 2)

    def test_replay_missing_round_is_empty(self, tmp_path):
        assert self._journal(tmp_path).replay(5) == ([], 0)

    def test_truncated_tail_keeps_complete_records(self, tmp_path):
        j = self._journal(tmp_path)
        j.append(0, {"sender": 1})
        j.append(0, {"sender": 2})
        path = tmp_path / "j" / "journal_r0.bin"
        blob = path.read_bytes()
        path.write_bytes(blob[:-3])  # crash mid-append of record 2
        records, bad_tail = j.replay(0)
        assert bad_tail == 1
        assert [int(r["sender"]) for r in records] == [1]

    def test_corrupt_tail_crc_detected(self, tmp_path):
        j = self._journal(tmp_path)
        j.append(0, {"sender": 1})
        j.append(0, {"sender": 2})
        path = tmp_path / "j" / "journal_r0.bin"
        blob = bytearray(path.read_bytes())
        blob[-1] ^= 0xFF  # bit-rot inside the last record's payload
        path.write_bytes(bytes(blob))
        records, bad_tail = j.replay(0)
        assert bad_tail == 1
        assert [int(r["sender"]) for r in records] == [1]

    def test_reset_and_prune(self, tmp_path):
        j = self._journal(tmp_path)
        for r in (0, 1, 2):
            j.append(r, {"sender": 1})
        j.prune_before(2)
        assert j.rounds() == [2]
        j.reset_round(2)
        assert j.rounds() == []
        assert j.replay(2) == ([], 0)

    def test_bad_fsync_policy_rejected(self, tmp_path):
        with pytest.raises(ValueError, match="fsync policy"):
            self._journal(tmp_path, fsync="sometimes")


class TestGroupCommitJournal:
    """PR 10 group-commit semantics: one fsync per batch, ack (ticket)
    released only once the batch is durable, replay tolerant of a batch
    torn by a crash mid-write."""

    def _journal(self, tmp_path, **kw):
        from fedml_tpu.core.checkpoint import UpdateJournal

        kw.setdefault("group_commit_ms", 5.0)
        return UpdateJournal(str(tmp_path / "j"), **kw)

    def test_concurrent_appends_coalesce_and_all_go_durable(self, tmp_path):
        import threading

        from fedml_tpu.core import obs

        def batches_committed():
            h = obs.registry().get_histogram("journal.batch_records")
            return int(h["count"]) if h else 0

        j = self._journal(tmp_path, group_commit_max=16)
        b0 = batches_committed()
        tickets = []
        lock = threading.Lock()

        def producer(base):
            for i in range(10):
                t = j.append_async(0, {"sender": base + i})
                with lock:
                    tickets.append(t)

        threads = [threading.Thread(target=producer, args=(base,))
                   for base in (0, 100, 200, 300)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        j.flush(timeout=10.0)
        assert all(t.durable for t in tickets)
        records, bad_tail = j.replay(0)
        assert bad_tail == 0
        assert sorted(int(r["sender"]) for r in records) == sorted(
            base + i for base in (0, 100, 200, 300) for i in range(10))
        # the whole point: 40 records reached disk in far fewer commits
        assert 1 <= batches_committed() - b0 < 40
        j.close()

    def test_blocking_append_is_durable_on_return(self, tmp_path):
        # a blocking append routes through the committer as urgent — it
        # must not wait out a long coalesce window, and the record must be
        # on disk (replayable) the moment it returns
        import time as _time

        j = self._journal(tmp_path, group_commit_ms=30000.0)
        t0 = _time.monotonic()
        j.append(0, {"sender": 7})
        assert _time.monotonic() - t0 < 5.0
        records, bad_tail = j.replay(0)
        assert bad_tail == 0 and [int(r["sender"]) for r in records] == [7]
        j.close()

    def test_kill_mid_batch_drops_only_torn_tail(self, tmp_path):
        # first batch acked and durable; then a crash tears the trailing
        # batch mid-write — replay must keep every acked record and drop
        # only the torn frame(s)
        j = self._journal(tmp_path)
        acked = [j.append_async(0, {"sender": s}) for s in (1, 2, 3)]
        j.flush(timeout=10.0)
        assert all(t.durable for t in acked)
        path = tmp_path / "j" / "journal_r0.bin"
        durable_blob = path.read_bytes()
        second = [j.append_async(0, {"sender": s}) for s in (4, 5)]
        j.flush(timeout=10.0)
        assert all(t.durable for t in second)
        torn = path.read_bytes()
        # the "kill": the second batch's write only partially hit the disk
        path.write_bytes(torn[:len(durable_blob) + 7])
        records, bad_tail = j.replay(0)
        assert bad_tail == 1
        assert [int(r["sender"]) for r in records] == [1, 2, 3]
        j.close()

    def test_unacked_tickets_never_claim_durability_on_io_error(self, tmp_path):
        import shutil

        j = self._journal(tmp_path)
        probe = j.append_async(0, {"sender": 1})
        j.flush(timeout=10.0)
        assert probe.durable
        # yank the directory out from under the committer: the next batch
        # cannot commit, its tickets must carry the error and stay
        # non-durable (the pipeline withholds those acks; senders retry)
        shutil.rmtree(tmp_path / "j")
        t = j.append_async(0, {"sender": 2})
        assert t.wait(10.0)
        assert not t.durable
        assert t.error is not None
        j.close()

    def test_append_after_close_is_refused(self, tmp_path):
        j = self._journal(tmp_path)
        ok = j.append_async(0, {"sender": 1})
        assert ok.wait(10.0) and ok.durable
        j.close()
        late = j.append_async(0, {"sender": 2})
        assert late.wait(1.0)
        assert not late.durable and isinstance(late.error, RuntimeError)

    def test_done_callback_fires_after_durability(self, tmp_path):
        import threading

        j = self._journal(tmp_path)
        fired = threading.Event()
        seen = {}

        t = j.append_async(0, {"sender": 1})
        t.add_done_callback(lambda tk: (seen.setdefault("durable", tk.durable),
                                        fired.set()))
        assert fired.wait(10.0)
        assert seen["durable"] is True
        # late registration on a settled ticket fires inline
        late = threading.Event()
        t.add_done_callback(lambda tk: late.set())
        assert late.is_set()
        j.close()

    def test_append_blob_async_replays_like_append(self, tmp_path):
        from flax import serialization

        j = self._journal(tmp_path)
        tree = {"w": np.arange(4.0, dtype=np.float32)}
        blob = serialization.msgpack_serialize(
            {"sender": 1, "n_samples": 8, "model_params": tree})
        tb = j.append_blob_async(0, blob)
        tr = j.append_async(0, {"sender": 2, "n_samples": 8,
                                "model_params": tree})
        j.flush(timeout=10.0)
        assert tb.durable and tr.durable
        records, bad_tail = j.replay(0)
        assert bad_tail == 0
        assert [int(r["sender"]) for r in records] == [1, 2]
        np.testing.assert_array_equal(records[0]["model_params"]["w"],
                                      tree["w"])
        j.close()

    def test_group_commit_disabled_append_async_degrades_to_blocking(
            self, tmp_path):
        from fedml_tpu.core.checkpoint import UpdateJournal

        j = UpdateJournal(str(tmp_path / "j"))  # group commit off
        assert not j.group_commit_enabled
        t = j.append_async(0, {"sender": 1})
        assert t.durable  # settled before return: the blocking path
        records, bad_tail = j.replay(0)
        assert bad_tail == 0 and len(records) == 1
        j.close()


class TestServerStateStore:
    def test_roundtrip_and_journal_reset_on_round_open(self, tmp_path):
        from fedml_tpu.core.checkpoint import ServerStateStore

        store = ServerStateStore(str(tmp_path / "srv"), keep=2)
        assert store.load_latest() is None
        store.save_round_start(0, {"participants": np.array([1, 2, 3])})
        store.journal.append(0, {"sender": 1})
        # next round open: old journal pruned, new round's journal fresh
        store.save_round_start(1, {"participants": np.array([1, 2, 3])})
        assert store.journal.rounds() == []
        round_idx, state = store.load_latest()
        assert round_idx == 1
        np.testing.assert_array_equal(state["participants"], [1, 2, 3])

    def test_reopening_same_round_discards_stale_journal(self, tmp_path):
        """A crash between round open and snapshot write leaves the PREVIOUS
        snapshot authoritative; reopening that round must not replay uploads
        accepted by the dead incarnation for its never-persisted round."""
        from fedml_tpu.core.checkpoint import ServerStateStore

        store = ServerStateStore(str(tmp_path / "srv"))
        store.save_round_start(3, {"v": 1})
        store.journal.append(3, {"sender": 9})
        store.save_round_start(3, {"v": 2})  # restarted incarnation reopens
        assert store.journal.replay(3) == ([], 0)
        assert store.load_latest()[1]["v"] == 2


class TestShardedServerOptSnapshot:
    def test_round_plane_state_survives_msgpack_bit_identical(self, tmp_path):
        """server_state=sharded recovery contract: the round plane's
        ``export_state`` snapshot rides the msgpack checkpoint codec and
        restores bit-identically — a plane rebuilt from the checkpoint
        produces the SAME next-round bits as the uninterrupted one."""
        import jax
        from fedml_tpu.parallel.agg_plane import (ShardedRoundPlane,
                                                  reset_planes)

        def tree(seed):
            r = np.random.default_rng(seed)
            return {"params": {
                "w": jnp.asarray(r.standard_normal((8, 4)), jnp.float32),
                "b": jnp.asarray(r.standard_normal((4,)), jnp.float32)}}

        def updates(seed):
            r = np.random.default_rng(seed)
            return [(float(r.integers(3, 97)), tree(seed + i))
                    for i in range(3)]

        try:
            plane = ShardedRoundPlane(policy=("adam", 0.1, 0.9))
            out1 = plane.round_update(tree(0), updates(10))
            mgr = CheckpointManager(str(tmp_path))
            mgr.save(1, {"server_opt": plane.export_state()})
            out2 = plane.round_update(out1, updates(20))

            step, restored = mgr.restore()
            assert step == 1
            clone = ShardedRoundPlane(policy=("adam", 0.1, 0.9))
            clone.install(out1)
            clone.load_state(restored["server_opt"])
            out2b = clone.round_update(out1, updates(20))
            for a, b in zip(jax.tree_util.tree_leaves(out2),
                            jax.tree_util.tree_leaves(out2b)):
                a, b = np.asarray(a), np.asarray(b)
                assert a.dtype == b.dtype
                np.testing.assert_array_equal(a, b)
        finally:
            reset_planes()


class _RecoveryHost:
    """Minimal ServerRecoveryMixin host: just the hooks, no transport."""

    def __init__(self, ckpt_dir, round_idx=0):
        import types

        from fedml_tpu.core.checkpoint import ServerRecoveryMixin
        from fedml_tpu.core.distributed.faults import CommStats

        class _H(ServerRecoveryMixin):
            def _capture_global_params(self):
                return {"w": np.arange(3.0)}

            def _restore_global_params(self, tree):
                self.restored_params = tree

            def _round_start_extras(self):
                return {}

            def _restore_round_extras(self, state):
                pass

            def _replay_upload(self, record):
                self.replayed.append(record)
                return True

            def _close_round_if_complete(self):
                self.close_attempts += 1

        h = _H()
        h.args = types.SimpleNamespace(server_checkpoint_dir=str(ckpt_dir),
                                       round_idx=round_idx)
        h._comm_stats = CommStats()
        h.client_id_list_in_this_round = [1, 2]
        h.replayed = []
        h.close_attempts = 0
        h.init_server_recovery(h.args)
        self.h = h


class TestServerRecoveryMixin:
    def test_same_round_duplicate_upload_discarded(self, tmp_path):
        h = _RecoveryHost(tmp_path / "srv").h
        h._save_round_start()
        assert h._journal_upload(1, n_samples=10) is True
        assert h._journal_upload(1, n_samples=10) is False
        assert h._comm_stats.get("dup_uploads_discarded") == 1
        assert h._journal_upload(2, n_samples=20) is True

    def test_restore_replays_journal_exactly_once(self, tmp_path):
        a = _RecoveryHost(tmp_path / "srv").h
        a._save_round_start()
        a._journal_upload(1, n_samples=10)
        # crash here; a fresh incarnation restores and replays
        b = _RecoveryHost(tmp_path / "srv").h
        assert b.server_epoch == 1
        assert b.args.round_idx == 0
        assert [int(r["sender"]) for r in b.replayed] == [1]
        assert b._comm_stats.get("server_restores") == 1
        assert b._comm_stats.get("epoch_bumps") == 1
        assert b._comm_stats.get("journal_replays") == 1
        # a retransmit of the replayed upload into the new incarnation is a
        # duplicate, not a double count
        assert b._journal_upload(1, n_samples=10) is False
        assert b._comm_stats.get("dup_uploads_discarded") == 1
        assert b._journal_upload(2, n_samples=20) is True
        # the recovered-round close check fires exactly once
        b._maybe_close_recovered_round()
        b._maybe_close_recovered_round()
        assert b.close_attempts == 1

    def test_round_open_clears_dedup_even_without_store(self, tmp_path):
        h = _RecoveryHost(tmp_path / "srv").h
        h._store = None  # persistence off: dedup still enforced per round
        h._save_round_start()
        assert h._journal_upload(1) is True
        assert h._journal_upload(1) is False
        h._save_round_start()
        assert h._journal_upload(1) is True


class TestCheckpointKnobValidation:
    def _cfg(self, **train_extra):
        cfg = {
            "common_args": {"training_type": "cross_silo", "random_seed": 0,
                            "run_id": "kv"},
            "data_args": {"dataset": "synthetic", "data_cache_dir": "",
                          "partition_method": "homo"},
            "model_args": {"model": "lr"},
            "train_args": {
                "federated_optimizer": "FedAvg", "client_num_in_total": 2,
                "client_num_per_round": 2, "comm_round": 1, "epochs": 1,
                "batch_size": 16, "client_optimizer": "sgd",
                "learning_rate": 0.1, **train_extra,
            },
            "validation_args": {"frequency_of_the_test": 1},
            "comm_args": {"backend": "LOOPBACK"},
        }
        return Arguments.from_dict(cfg)

    def test_valid_knobs_pass(self, tmp_path):
        self._cfg(server_checkpoint_dir=str(tmp_path), checkpoint_keep=5,
                  checkpoint_frequency=2, server_journal_fsync="never").validate()

    def test_non_path_dir_rejected(self):
        with pytest.raises(ValueError, match="server_checkpoint_dir"):
            self._cfg(server_checkpoint_dir=123).validate()
        with pytest.raises(ValueError, match="checkpoint_dir"):
            self._cfg(checkpoint_dir=["a"]).validate()

    def test_bad_keep_and_frequency_rejected(self):
        with pytest.raises(ValueError, match="checkpoint_keep"):
            self._cfg(checkpoint_keep=0).validate()
        with pytest.raises(ValueError, match="checkpoint_frequency"):
            self._cfg(checkpoint_frequency="soon").validate()

    def test_bad_fsync_policy_rejected(self):
        with pytest.raises(ValueError, match="server_journal_fsync"):
            self._cfg(server_journal_fsync="sometimes").validate()


def _args(tmp_path, comm_round):
    return Arguments.from_dict(
        {
            "common_args": {"training_type": "simulation", "random_seed": 0, "run_id": "ck"},
            "data_args": {
                "dataset": "mnist",
                "data_cache_dir": "",
                "partition_method": "homo",
                "synthetic_train_size": 320,
            },
            "model_args": {"model": "lr"},
            "train_args": {
                "federated_optimizer": "FedAvg",
                "client_num_in_total": 4,
                "client_num_per_round": 2,
                "comm_round": comm_round,
                "epochs": 1,
                "batch_size": 32,
                "client_optimizer": "sgd",
                "learning_rate": 0.1,
                "checkpoint_dir": str(tmp_path / "ckpts"),
            },
            "validation_args": {"frequency_of_the_test": 100},
            "comm_args": {"backend": "sp"},
        }
    ).validate()


class TestSimulatorResume:
    def test_sp_resume_matches_straight_run(self, tmp_path):
        """2 rounds + resume for 2 more == 4 straight rounds (bitwise params)."""
        from fedml_tpu.simulation.sp.fedavg.fedavg_api import FedAvgAPI

        def build(comm_round, subdir):
            args = _args(tmp_path / subdir, comm_round)
            args = fedml_tpu.init(args, should_init_logs=False)
            from fedml_tpu import data, models

            dataset, out_dim = data.load(args)
            model = models.create(args, out_dim)
            return args, FedAvgAPI(args, None, dataset, model)

        args_a, api_straight = build(4, "a")
        api_straight.train()

        args_b, api_part1 = build(2, "b")
        api_part1.train()
        _, api_part2 = build(4, "b")  # same dir -> auto-resume at round 2
        api_part2.train()

        import jax

        flat_a = jax.tree_util.tree_leaves(api_straight.w_global)
        flat_b = jax.tree_util.tree_leaves(api_part2.w_global)
        for xa, xb in zip(flat_a, flat_b):
            np.testing.assert_allclose(np.asarray(xa), np.asarray(xb), rtol=1e-6, atol=1e-6)

    def test_fedopt_resume_restores_server_optimizer_state(self, tmp_path):
        """Server Adam moments must survive resume (checkpoint_state hook)."""
        from fedml_tpu.simulation.sp.fedopt.fedopt_api import FedOptAPI

        def build(comm_round, subdir):
            args = _args(tmp_path / subdir, comm_round)
            args.federated_optimizer = "FedOpt"
            args.server_optimizer = "adam"
            args = fedml_tpu.init(args, should_init_logs=False)
            from fedml_tpu import data, models

            dataset, out_dim = data.load(args)
            model = models.create(args, out_dim)
            return FedOptAPI(args, None, dataset, model)

        api_straight = build(4, "a")
        api_straight.train()

        build(2, "b").train()
        api_resumed = build(4, "b")
        api_resumed.train()

        import jax

        for xa, xb in zip(
            jax.tree_util.tree_leaves(api_straight.w_global),
            jax.tree_util.tree_leaves(api_resumed.w_global),
        ):
            np.testing.assert_allclose(np.asarray(xa), np.asarray(xb), rtol=1e-5, atol=1e-6)
