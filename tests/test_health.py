"""The live health & SLO plane (``fedml_tpu.core.obs.health``).

Four strata, mirroring the plane's contract:

* **Unit** — watchdog arm/beat/idle/expire-once/recover semantics under a
  ManualClock (heartbeat AND thread mode), z-score windows firing exactly
  once per incident and re-arming after clean samples, silence monitors,
  and the ok/degraded/critical status machine's recovery hysteresis.
* **Chaos** — the acceptance claim, wired into ``tools/chaos_check.py``'s
  ``health`` leg: an injected ingest-queue stall, a killed chunk-pump
  thread, and a silent edge aggregator each fire the RIGHT detector on an
  exact deterministic schedule (the injected clock decides, never the
  wall clock), each incident triggers EXACTLY ONE flight dump carrying
  the health snapshot in its meta, and enabling ``obs_health`` leaves a
  fault-free run's final model bit-identical with every round's span
  tree still closed.
* **Exposition** — ``/healthz`` returns 200/ok and 503/critical, the
  exporter's (idempotent) shutdown writes a final health snapshot next
  to the metrics snapshot, and ``fedml_health_status`` lands in the
  registry.
* **Report** — ``tools/health_report.py`` renders live snapshots and
  health-triggered flight dumps, and ``--assert-healthy`` gates on the
  status.
"""

from __future__ import annotations

import contextlib
import json
import os
import sys
import types

import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))

import health_report

import test_fault_tolerance as _ft
from fedml_tpu.core import mlops, obs
from fedml_tpu.core.async_fl.clock import ManualClock
from fedml_tpu.core.distributed.communication.loopback import LoopbackHub
from fedml_tpu.core.distributed.communication.message import Message
from fedml_tpu.core.hierarchy import protocol as hier_protocol
from fedml_tpu.core.hierarchy.root import HierarchyRoot
from fedml_tpu.core.mlops import FanoutSink, InMemorySink
from fedml_tpu.core.obs import MetricsRegistry
from fedml_tpu.core.obs.exposition import MetricsExporter
from fedml_tpu.core.obs.health import (
    EVENT_ANOMALY,
    EVENT_RECOVERED,
    EVENT_STATUS,
    EVENT_WATCHDOG_EXPIRED,
    EVENT_WATCHDOG_RECOVERED,
    HEALTH_STATUS_GAUGE,
    HealthPlane,
)


@pytest.fixture(autouse=True)
def _obs_hygiene():
    """obs state is process-global: every test leaves it disabled and the
    registry empty so no other module inherits a live health plane."""
    yield
    obs.shutdown()
    obs.registry().reset()


def _plane(clock=None, registry=None, **kw):
    """A standalone plane with a collecting emitter: (plane, clock, events)."""
    clock = clock or ManualClock()
    kw.setdefault("watchdog_deadline_s", 5.0)
    kw.setdefault("warmup", 4)
    p = HealthPlane(registry=registry, clock=clock, **kw)
    events = []
    p.emitter = lambda name, attrs: events.append((name, dict(attrs)))
    return p, clock, events


def _names(events):
    return [name for name, _ in events]


# ---------------------------------------------------------------------------
# Unit: watchdogs
# ---------------------------------------------------------------------------

class TestHealthWatchdog:
    def test_health_watchdog_expires_once_at_the_deadline_and_recovers(self):
        p, clk, events = _plane()
        wd = p.register("worker", deadline_s=3.0)
        wd.beat()
        clk.advance(2.9)
        assert p.tick() == "ok"          # inside the deadline: quiet
        clk.advance(0.2)                 # now 3.1s since the beat
        assert p.tick() == "critical"
        clk.advance(50.0)
        p.tick()
        p.tick()                         # stays expired, fires NO second event
        assert _names(events).count(EVENT_WATCHDOG_EXPIRED) == 1
        assert wd.expirations == 1
        wd.beat()                        # the worker comes back
        assert EVENT_WATCHDOG_RECOVERED in _names(events)
        assert not wd.expired

    def test_health_watchdog_idle_disarms_the_contract(self):
        # the journal committer parks on an empty queue for unbounded time:
        # idle() means "not wedged, just nothing to do"
        p, clk, events = _plane()
        wd = p.register("journal.committer", deadline_s=2.0)
        wd.beat()
        wd.idle()
        clk.advance(1000.0)
        assert p.tick() == "ok"
        assert events == []
        wd.beat()                        # work arrived: re-armed
        clk.advance(3.0)
        assert p.tick() == "critical"

    def test_health_thread_mode_watchdog_fires_when_thread_dies(self):
        import threading

        p, clk, events = _plane()
        t = threading.Thread(target=lambda: None)
        t.start()
        t.join()
        wd = p.register("obs.exporter", thread=t)
        assert wd.mode == "thread"
        assert p.tick() == "critical"
        p.tick()
        assert _names(events).count(EVENT_WATCHDOG_EXPIRED) == 1
        expired = dict(events[_names(events).index(EVENT_WATCHDOG_EXPIRED)][1])
        assert expired["watchdog"] == "obs.exporter"
        assert expired["mode"] == "thread"

    def test_health_reregistration_resets_the_contract(self):
        p, clk, events = _plane()
        wd = p.register("pump", deadline_s=1.0)
        wd.beat()
        clk.advance(2.0)
        assert p.tick() == "critical"
        p.register("pump", deadline_s=1.0)  # a restarted worker re-registers
        assert p.snapshot()["watchdogs"]["pump"]["expired"] is False


# ---------------------------------------------------------------------------
# Unit: rolling windows + silences
# ---------------------------------------------------------------------------

class TestHealthWindows:
    def test_health_zscore_fires_once_per_incident_and_rearms(self):
        p, clk, events = _plane(z_threshold=4.0, ewma_alpha=0.3,
                                warmup=4, recover_ticks=2)
        for _ in range(6):
            p.observe("round.seconds", 1.0)
        assert events == []              # a flat series never fires
        p.observe("round.seconds", 100.0)
        anomalies = [a for n, a in events if n == EVENT_ANOMALY]
        assert len(anomalies) == 1
        a = anomalies[0]
        assert a["series"] == "round.seconds" and a["kind"] == "zscore"
        assert abs(a["z"]) > 4.0
        p.observe("round.seconds", 100.0)  # still out of band: no re-fire
        assert _names(events).count(EVENT_ANOMALY) == 1
        # recover_ticks in-band samples re-arm the window...
        # (the EWMA folded the spike in, so "in band" is near the new mean)
        snap = p.snapshot()["windows"]["round.seconds"]
        for _ in range(4):
            p.observe("round.seconds", snap["mean"])
            snap = p.snapshot()["windows"]["round.seconds"]
        assert [n for n, _ in events].count(EVENT_RECOVERED) == 1
        assert not p.snapshot()["windows"]["round.seconds"]["firing"]

    def test_health_level_shift_becomes_the_new_normal(self):
        # anomalous samples still fold into the EWMA: a sustained shift
        # fires once, then converges instead of alarming forever
        p, clk, events = _plane(warmup=4, recover_ticks=3)
        for _ in range(6):
            p.observe("s", 1.0)
        for _ in range(40):
            p.observe("s", 10.0)
        assert _names(events).count(EVENT_ANOMALY) == 1
        assert _names(events).count(EVENT_RECOVERED) == 1

    def test_health_silence_monitor_fires_on_stall_and_recovers(self):
        p, clk, events = _plane()
        mon = p.silence("chunk.stream_stall", max_age_s=4.0)
        clk.advance(100.0)
        assert p.tick() == "ok"          # never noted: not armed, no alarm
        mon.note()
        clk.advance(3.9)
        assert p.tick() == "ok"
        clk.advance(0.2)
        assert p.tick() == "degraded"
        p.tick()
        anomalies = [a for n, a in events if n == EVENT_ANOMALY]
        assert len(anomalies) == 1 and anomalies[0]["kind"] == "silence"
        mon.note()                       # activity resumes
        assert EVENT_RECOVERED in _names(events)
        mon.idle()                       # stream closed: disarm entirely
        clk.advance(100.0)
        p.tick()
        assert _names(events).count(EVENT_ANOMALY) == 1


# ---------------------------------------------------------------------------
# Unit: the status machine
# ---------------------------------------------------------------------------

class TestHealthStatus:
    def test_health_status_hysteresis_and_transition_events(self):
        p, clk, events = _plane(recover_ticks=3)
        wd = p.register("w", deadline_s=1.0)
        wd.beat()
        clk.advance(2.0)
        assert p.tick() == "critical"
        wd.beat()                        # recovered, but the status holds
        assert p.tick() == "critical"
        assert p.tick() == "critical"
        assert p.tick() == "ok"          # third clean tick releases it
        statuses = [a for n, a in events if n == EVENT_STATUS]
        assert [(s["from"], s["to"]) for s in statuses] == [
            ("ok", "critical"), ("critical", "ok")]

    def test_health_status_gauge_mirrors_the_code(self):
        reg = MetricsRegistry()
        p, clk, _ = _plane(registry=reg)
        wd = p.register("w", deadline_s=1.0)
        wd.beat()
        p.tick()
        assert reg.get_gauge(HEALTH_STATUS_GAUGE) == 0.0
        clk.advance(2.0)
        p.tick()
        assert reg.get_gauge(HEALTH_STATUS_GAUGE) == 2.0

    def test_health_snapshot_shapes(self):
        p, clk, _ = _plane()
        p.register("w").beat()
        p.silence("s", max_age_s=2.0).note()
        p.observe("x", 1.0)
        p.tick()
        snap = p.snapshot()
        assert snap["schema"] == "fedml-health-1"
        assert snap["status"] in ("ok", "degraded", "critical")
        assert snap["watchdogs"]["w"]["mode"] == "heartbeat"
        assert snap["silences"]["s"]["armed"] is True
        assert snap["windows"]["x"]["n"] == 1
        compact = p.snapshot_compact()
        assert set(compact) == {"status", "status_code", "ticks",
                                "expired_watchdogs", "firing_series"}


# ---------------------------------------------------------------------------
# Facade: off = null handles, bit-identical; knobs validated
# ---------------------------------------------------------------------------

class TestHealthFacade:
    def test_health_off_hands_out_null_handles(self):
        assert obs.health_enabled() is False
        assert obs.health_status() == "ok"
        assert obs.health_tick() is None
        wd = obs.health_watchdog("anything")
        mon = obs.health_silence("anything")
        assert wd is obs.NULL_WATCHDOG and mon is obs.NULL_SILENCE
        wd.beat(); wd.idle(); wd.close()     # all free no-ops
        mon.note(); mon.idle(); mon.close()
        obs.health_observe("x", 1.0)

    def test_health_configured_from_args_with_injected_clock(self):
        clk = ManualClock()
        args = types.SimpleNamespace(
            run_id="h", obs_health=1, obs_health_clock=clk,
            obs_health_watchdog_s=2.0, obs_health_warmup=3)
        obs.configure(args, lambda t, rec: None)
        try:
            plane = obs.health_plane()
            assert plane is not None and plane.clock is clk
            assert plane.watchdog_deadline_s == 2.0
            wd = obs.health_watchdog("w")
            wd.beat()
            clk.advance(3.0)
            assert obs.health_tick() == "critical"
            assert obs.health_status() == "critical"
        finally:
            obs.shutdown()
        assert obs.health_enabled() is False

    def test_health_knobs_validated(self):
        from test_obs import _knob_args

        _knob_args(obs_health=True, obs_health_watchdog_s=10.0,
                   obs_health_z=3.0, obs_health_ewma_alpha=0.2,
                   obs_health_warmup=4).validate()
        for bad in (dict(obs_health_watchdog_s=0),
                    dict(obs_health_watchdog_s="soon"),
                    dict(obs_health_z=-1),
                    dict(obs_health_ewma_alpha=0.0),
                    dict(obs_health_ewma_alpha=1.5),
                    dict(obs_health_warmup=1)):
            with pytest.raises(ValueError):
                _knob_args(**bad).validate()


# ---------------------------------------------------------------------------
# Chaos: each injected failure fires the right detector, exactly one dump
# ---------------------------------------------------------------------------

def _health_obs(tmp_path, clk, **over):
    """Configure the full facade: health plane on the injected clock, flight
    recorder dumping into ``tmp_path``, records collected in-memory."""
    recs = []
    kw = dict(run_id="h-chaos", obs_health=1, obs_health_clock=clk,
              obs_health_warmup=4, obs_flight_dir=str(tmp_path))
    kw.update(over)
    obs.configure(types.SimpleNamespace(**kw),
                  lambda t, rec: recs.append((t, dict(rec))))
    return recs


def _dumps(tmp_path):
    return sorted(p for p in os.listdir(tmp_path) if p.startswith("flight-"))


def test_health_chaos_ingest_queue_stall_fires_anomaly_and_one_dump(tmp_path):
    """An ingest dispatch stall: the io→dispatch queue depth (normally ~0,
    drained as fast as it fills) climbs without bound.  The rolling window
    over the ``ingest.queue_depth`` gauge fires ONE ``health.anomaly``,
    which triggers ONE flight dump carrying the health snapshot."""
    clk = ManualClock()
    _health_obs(tmp_path, clk)
    try:
        for _ in range(6):               # steady state: queue near-empty
            obs.gauge_set("ingest.queue_depth", 1.0)
            clk.advance(1.0)
            assert obs.health_tick() == "ok"
        obs.gauge_set("ingest.queue_depth", 500.0)   # the stall
        clk.advance(1.0)
        assert obs.health_tick() == "degraded"
        assert len(_dumps(tmp_path)) == 1
        for _ in range(3):               # still stalled: no dump storm
            clk.advance(1.0)
            obs.health_tick()
        assert len(_dumps(tmp_path)) == 1
        dump = os.path.join(tmp_path, _dumps(tmp_path)[0])
        assert "health.anomaly" in dump
        view = health_report.load_input(dump)
        assert view["snapshot"]["status"] == "degraded"
        assert "ingest.queue_depth" in view["snapshot"]["firing_series"]
        assert any(e.get("event") == EVENT_ANOMALY for e in view["events"])
    finally:
        obs.shutdown()


def test_health_chaos_killed_pump_thread_expires_watchdog_one_dump(
        tmp_path, monkeypatch):
    """A chunk pump thread killed before its first pass: ``send()`` armed
    the watchdog from the calling thread, so the dead pump expires at its
    exact deadline on the injected clock — one ``health.watchdog_expired``,
    one flight dump — while the ack-stall monitor (a different detector on
    a longer fuse) stays quiet."""
    from fedml_tpu.core.distributed.chunking import ChunkedSender
    from test_chunking import _FakeTxManager, _inner_msg

    clk = ManualClock()
    _health_obs(tmp_path, clk, obs_health_watchdog_s=3.0)
    try:
        monkeypatch.setattr(ChunkedSender, "_pump",
                            lambda self, st, chunks: None)  # killed at birth
        tx = ChunkedSender(_FakeTxManager(), chunk_bytes=64, window=2)
        assert tx.send(_inner_msg(payload=b"x" * 400)) is True
        clk.advance(2.9)
        assert obs.health_tick() == "ok"     # inside the deadline
        clk.advance(1.0)                     # 3.9s: past 3.0, before the
        assert obs.health_tick() == "critical"   # 5.0s stall fuse
        for _ in range(3):
            obs.health_tick()
        dumps = _dumps(tmp_path)
        assert len(dumps) == 1 and "health.watchdog_expired" in dumps[0]
        view = health_report.load_input(os.path.join(tmp_path, dumps[0]))
        assert view["snapshot"]["expired_watchdogs"] == ["chunk.pump.rank7"]
        assert view["snapshot"]["firing_series"] == []   # stall stayed quiet
    finally:
        obs.shutdown()


def test_health_chaos_silent_edge_fires_silence_anomaly_one_dump(tmp_path):
    """An edge that counted into the round but never forwards (killed,
    wedged, partitioned): the root's ``hierarchy.edge_silence`` monitor
    fires ONE silence anomaly at the deterministic max-age instead of
    ``wait_round`` parking forever — and ONE flight dump records it."""
    clk = ManualClock()
    _health_obs(tmp_path, clk, obs_health_watchdog_s=6.0)
    try:
        mgr = types.SimpleNamespace(
            args=types.SimpleNamespace(federated_optimizer="FedAvg"),
            register_message_receive_handler=lambda t, fn: None,
            get_sender_id=lambda: 0,
            send_message=lambda m: None)
        root = HierarchyRoot(mgr, plan=None, child_ranks={0: 1, 1: 2})
        counts = Message(hier_protocol.HIER_COUNTS, 1, 0)
        counts.add_params(hier_protocol.KEY_ROUND, 0)
        counts.add_params(hier_protocol.KEY_EDGE, 0)
        counts.add_params(hier_protocol.KEY_TOTAL_WEIGHT, 10.0)
        counts.add_params(hier_protocol.KEY_N_CLIENTS, 2)
        counts.add_params(hier_protocol.KEY_OFFERS, "none")
        root._handle_counts(counts)      # edge 0 checks in... then silence
        clk.advance(5.9)
        assert obs.health_tick() == "ok"
        clk.advance(0.2)
        assert obs.health_tick() == "degraded"
        for _ in range(3):
            clk.advance(1.0)
            obs.health_tick()
        dumps = _dumps(tmp_path)
        assert len(dumps) == 1 and "health.anomaly" in dumps[0]
        view = health_report.load_input(os.path.join(tmp_path, dumps[0]))
        assert "hierarchy.edge_silence" in view["snapshot"]["firing_series"]
    finally:
        obs.shutdown()


@contextlib.contextmanager
def _traced_health(run_id):
    mem = InMemorySink()
    args = types.SimpleNamespace(run_id=run_id, obs_trace=True, obs_health=1,
                                 rank=0)
    mlops.init(args, FanoutSink([mem]))
    try:
        yield mem
    finally:
        mlops.finish()


def test_health_convergence_bit_identical_on_off_and_traces_closed():
    """Correctness half of the overhead budget: a fault-free topology run
    with the health plane ON converges to the BIT-IDENTICAL final model of
    a plane-off run, and every round still closes as one span tree
    (``trace_report --assert-closed`` semantics stay green)."""
    from test_obs import _assert_rounds_closed

    LoopbackHub.reset()
    _, final_off, _ = _ft._run_chaos_topology("health-off", knobs={})
    assert obs.enabled() is False
    with _traced_health("health-on") as mem:
        history, final_on, _ = _ft._run_chaos_topology("health-on", knobs={})
        assert len(history) == 2
        assert obs.health_enabled() is True
        assert obs.health_status() == "ok"
    assert _ft._trees_bit_identical(final_off, final_on)
    _assert_rounds_closed(mem, "health-on", 2)


# ---------------------------------------------------------------------------
# Exposition: /healthz + the final health snapshot
# ---------------------------------------------------------------------------

class TestHealthz:
    def test_healthz_200_ok_then_503_critical(self):
        import urllib.error
        import urllib.request

        p, clk, _ = _plane()
        exp = MetricsExporter(MetricsRegistry(), port=0,
                              health_provider=p.snapshot).start()
        try:
            url = exp.url.replace("/metrics", "/healthz")
            with urllib.request.urlopen(url, timeout=5) as resp:
                assert resp.status == 200
                body = json.loads(resp.read().decode("utf-8"))
            assert body["status"] == "ok"
            wd = p.register("w", deadline_s=1.0)
            wd.beat()
            clk.advance(2.0)
            p.tick()
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(url, timeout=5)
            assert exc.value.code == 503
            assert json.loads(exc.value.read().decode("utf-8"))[
                "status"] == "critical"
        finally:
            exp.shutdown()

    def test_healthz_404_without_a_plane(self):
        import urllib.error
        import urllib.request

        exp = MetricsExporter(MetricsRegistry(), port=0).start()
        try:
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(
                    exp.url.replace("/metrics", "/healthz"), timeout=5)
            assert exc.value.code == 404
        finally:
            exp.shutdown()

    def test_health_final_snapshot_on_idempotent_shutdown(self, tmp_path):
        snap = tmp_path / "metrics.prom"
        p, clk, _ = _plane()
        p.register("w").beat()
        p.tick()
        exp = MetricsExporter(MetricsRegistry(), snapshot_path=str(snap),
                              health_provider=p.snapshot).start()
        assert exp.health_snapshot_path == str(snap) + ".health.json"
        exp.shutdown()
        exp.shutdown()                    # second shutdown: no-op, no raise
        health = json.loads((tmp_path / "metrics.prom.health.json")
                            .read_text())
        assert health["schema"] == "fedml-health-1"
        assert "w" in health["watchdogs"]


# ---------------------------------------------------------------------------
# Report: tools/health_report.py
# ---------------------------------------------------------------------------

class TestHealthReport:
    def _snap_file(self, tmp_path, plane):
        path = tmp_path / "snap.health.json"
        path.write_text(json.dumps(plane.snapshot()))
        return str(path)

    def test_health_report_renders_snapshot(self, tmp_path, capsys):
        p, clk, _ = _plane()
        wd = p.register("ingest.worker.rank0", deadline_s=2.0)
        wd.beat()
        clk.advance(3.0)
        p.tick()
        rc = health_report.main([self._snap_file(tmp_path, p)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "health status: CRITICAL" in out
        assert "ingest.worker.rank0" in out and "EXPIRED" in out

    def test_health_report_assert_healthy_gates(self, tmp_path, capsys):
        p, clk, _ = _plane()
        assert health_report.main(
            [self._snap_file(tmp_path, p), "--assert-healthy"]) == 0
        wd = p.register("w", deadline_s=1.0)
        wd.beat()
        clk.advance(2.0)
        p.tick()
        assert health_report.main(
            [self._snap_file(tmp_path, p), "--assert-healthy"]) == 1
        capsys.readouterr()

    def test_health_report_json_mode(self, tmp_path, capsys):
        p, clk, _ = _plane()
        p.tick()
        rc = health_report.main([self._snap_file(tmp_path, p), "--json"])
        assert rc == 0
        view = json.loads(capsys.readouterr().out)
        assert view["status"] == "ok" and view["source"] == "snapshot"

    def test_health_report_reads_health_triggered_dump(self, tmp_path,
                                                       capsys):
        clk = ManualClock()
        _health_obs(tmp_path, clk, obs_health_watchdog_s=2.0)
        try:
            wd = obs.health_watchdog("edge.flush.3")
            wd.beat()
            clk.advance(3.0)
            obs.health_tick()
        finally:
            obs.shutdown()
        dump = os.path.join(tmp_path, _dumps(tmp_path)[0])
        rc = health_report.main([dump])
        out = capsys.readouterr().out
        assert rc == 0
        assert "health status: CRITICAL" in out
        assert "health.watchdog_expired" in out
        assert health_report.main([dump, "--assert-healthy"]) == 1
        capsys.readouterr()
