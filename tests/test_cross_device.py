"""Cross-device (Beehive) stack: FTEM edge-model files, file-plane aggregator,
server round state machine + fake-device protocol harness — the in-process
twin of the reference's android_protocol_test (SURVEY.md §2.7, §4)."""

import os

import numpy as np
import pytest

from fedml_tpu.arguments import Arguments
from fedml_tpu.core.distributed.comm_manager import FedMLCommManager
from fedml_tpu.core.distributed.communication.loopback import LoopbackHub
from fedml_tpu.core.distributed.communication.message import Message
from fedml_tpu.cross_device.edge_model import (
    flatten_params,
    load_edge_model,
    save_edge_model,
    unflatten_params,
)


class TestEdgeModelFormat:
    def test_roundtrip(self, tmp_path):
        params = {
            "params": {
                "Dense_0": {"kernel": np.random.randn(4, 3).astype(np.float32),
                            "bias": np.zeros(3, np.float32)},
                "step": np.array([7], np.int32),
            }
        }
        path = str(tmp_path / "m.ftem")
        save_edge_model(path, params)
        flat = load_edge_model(path)
        assert set(flat) == {"params/Dense_0/kernel", "params/Dense_0/bias", "params/step"}
        np.testing.assert_array_equal(flat["params/Dense_0/kernel"],
                                      params["params"]["Dense_0"]["kernel"])
        assert flat["params/step"].dtype == np.int32
        nested = unflatten_params(flat)
        np.testing.assert_array_equal(nested["params"]["Dense_0"]["bias"], np.zeros(3))

    def test_flatten_jax_pytree(self):
        import jax.numpy as jnp

        flat = flatten_params({"a": {"b": jnp.ones((2, 2))}})
        assert list(flat) == ["a/b"]
        assert flat["a/b"].dtype == np.float32

    def test_zero_size_and_scalar_tensors(self, tmp_path):
        path = str(tmp_path / "z.ftem")
        save_edge_model(path, {"empty": np.zeros((0, 4), np.float32),
                               "scalar": np.float32(2.5),
                               "after": np.ones(3, np.float32)})
        flat = load_edge_model(path)
        assert flat["empty"].shape == (0, 4)
        assert float(flat["scalar"]) == 2.5
        np.testing.assert_array_equal(flat["after"], np.ones(3))

    def test_bad_magic_rejected(self, tmp_path):
        path = tmp_path / "junk.ftem"
        path.write_bytes(b"NOPE" + b"\x00" * 16)
        with pytest.raises(ValueError):
            load_edge_model(str(path))


def _separable(n, d=12, classes=4, seed=0):
    # class centers are FIXED (seed 1234) so every device and the test set
    # share one underlying problem; `seed` only varies the samples
    centers = np.random.RandomState(1234).randn(classes, d) * 3
    rng = np.random.RandomState(seed)
    y = rng.randint(0, classes, n)
    x = centers[y] + rng.randn(n, d) * 0.5
    return x.astype(np.float32), y.astype(np.int32)


class TestCrossDeviceE2E:
    def test_server_with_two_fake_devices(self, tmp_path):
        from fedml_tpu.cross_device.fake_device import FakeDeviceManager
        from fedml_tpu.cross_device.fedml_aggregator import FedMLAggregator
        from fedml_tpu.cross_device.fedml_server_manager import FedMLServerManager
        from fedml_tpu.models.linear import LogisticRegression

        LoopbackHub.reset()
        args = Arguments.from_dict(
            {
                "common_args": {"training_type": "cross_device", "random_seed": 0,
                                "run_id": "beehive-t"},
                "data_args": {"dataset": "synthetic"},
                "model_args": {"model": "lr"},
                "train_args": {
                    "federated_optimizer": "FedAvg",
                    "client_num_in_total": 2,
                    "client_num_per_round": 2,
                    "comm_round": 3,
                    "epochs": 2,
                    "batch_size": 16,
                    "learning_rate": 0.2,
                },
                "validation_args": {"frequency_of_the_test": 1},
                "comm_args": {"backend": "LOOPBACK"},
            }
        ).validate()

        x_test, y_test = _separable(128, seed=9)
        model = LogisticRegression(output_dim=4)
        aggregator = FedMLAggregator(args, model, (x_test, y_test), worker_num=2,
                                     model_dir=str(tmp_path / "models"))
        server = FedMLServerManager(args, aggregator, client_rank=0, client_num=2)

        devices = [
            FakeDeviceManager(args, rank, _separable(96, seed=rank), client_num=2,
                              upload_dir=str(tmp_path / f"dev{rank}"))
            for rank in (1, 2)
        ]

        threads = [server.run_async()] + [d.run_async() for d in devices]
        for t in threads:
            t.join(timeout=60)
        for t in threads:
            assert not t.is_alive(), "protocol did not terminate"

        assert all(d.rounds_trained == 3 for d in devices)
        assert aggregator.eval_history, "server never evaluated"
        assert aggregator.eval_history[-1]["test_acc"] > 0.8
        # global model file for every round was published
        files = os.listdir(tmp_path / "models")
        assert any(f.startswith("global_model_r2") for f in files)

    def test_numpy_trainer_learns(self):
        from fedml_tpu.cross_device.fake_device import train_numpy

        x, y = _separable(256, seed=3)
        flat = {
            "params/Dense_0/kernel": np.zeros((12, 4), np.float32),
            "params/Dense_0/bias": np.zeros(4, np.float32),
        }
        trained = train_numpy(flat, x, y, lr=0.3, epochs=4)
        logits = x.reshape(len(y), -1) @ trained["params/Dense_0/kernel"] + trained["params/Dense_0/bias"]
        acc = (logits.argmax(1) == y).mean()
        assert acc > 0.9


class _SilentDevice(FedMLCommManager):
    """A device that comes ONLINE then never uploads — the normal phone
    failure mode round_timeout_s exists for (backgrounded app, dead radio)."""

    def __init__(self, args, rank, client_num):
        super().__init__(args, None, rank, client_num + 1, backend="LOOPBACK")

    def register_message_receive_handlers(self) -> None:
        from fedml_tpu.cross_device.message_define import MNNMessage

        self.register_message_receive_handler(
            MNNMessage.MSG_TYPE_S2C_CHECK_CLIENT_STATUS, self._on_check
        )
        self.register_message_receive_handler(
            MNNMessage.MSG_TYPE_S2C_FINISH, lambda m: self.finish()
        )

    def _on_check(self, msg) -> None:
        from fedml_tpu.cross_device.message_define import MNNMessage

        m = Message(MNNMessage.MSG_TYPE_C2S_CLIENT_STATUS, self.rank, 0)
        m.add_params(MNNMessage.MSG_ARG_KEY_CLIENT_STATUS, MNNMessage.CLIENT_STATUS_ONLINE)
        self.send_message(m)


class TestCrossDeviceFaultTolerance:
    def test_round_survives_silent_device(self, tmp_path):
        """2 live fake devices + 1 silent: with round_timeout_s the fleet
        round closes on the uploads that arrived (beehive straggler path)."""
        import time

        from fedml_tpu.cross_device.fake_device import FakeDeviceManager
        from fedml_tpu.cross_device.fedml_aggregator import FedMLAggregator
        from fedml_tpu.cross_device.fedml_server_manager import FedMLServerManager
        from fedml_tpu.models.linear import LogisticRegression

        LoopbackHub.reset()
        args = Arguments.from_dict(
            {
                "common_args": {"training_type": "cross_device", "random_seed": 0,
                                "run_id": "beehive-ft"},
                "data_args": {"dataset": "synthetic"},
                "model_args": {"model": "lr"},
                "train_args": {
                    "federated_optimizer": "FedAvg",
                    "client_num_in_total": 3,
                    "client_num_per_round": 3,
                    "comm_round": 2,
                    "epochs": 2,
                    "batch_size": 16,
                    "learning_rate": 0.2,
                    "round_timeout_s": 3.0,
                    "round_timeout_min_clients": 2,
                },
                "validation_args": {"frequency_of_the_test": 1},
                "comm_args": {"backend": "LOOPBACK"},
            }
        ).validate()

        x_test, y_test = _separable(128, seed=9)
        model = LogisticRegression(output_dim=4)
        aggregator = FedMLAggregator(args, model, (x_test, y_test), worker_num=3,
                                     model_dir=str(tmp_path / "models"))
        server = FedMLServerManager(args, aggregator, client_rank=0, client_num=3)
        devices = [
            FakeDeviceManager(args, rank, _separable(96, seed=rank), client_num=3,
                              upload_dir=str(tmp_path / f"dev{rank}"))
            for rank in (1, 2)
        ]
        silent = _SilentDevice(args, rank=3, client_num=3)

        t0 = time.time()
        threads = ([server.run_async()] + [d.run_async() for d in devices]
                   + [silent.run_async()])
        for t in threads:
            t.join(timeout=60)
        for t in threads:
            assert not t.is_alive(), "protocol did not terminate"
        assert time.time() - t0 < 45  # bounded by ~2 timeouts, not forever
        assert all(d.rounds_trained == 2 for d in devices)
        assert aggregator.eval_history and 0.0 <= aggregator.eval_history[-1]["test_acc"] <= 1.0

    def test_slow_device_upload_dropped_by_round_tag(self, tmp_path, caplog):
        """A SLOW (not dead) device whose upload lands after its round was
        closed: the round tag must drop it instead of folding a round-N
        model into round N+1."""
        import logging as _logging
        import time

        from fedml_tpu.cross_device.fake_device import FakeDeviceManager
        from fedml_tpu.cross_device.fedml_aggregator import FedMLAggregator
        from fedml_tpu.cross_device.fedml_server_manager import FedMLServerManager
        from fedml_tpu.models.linear import LogisticRegression

        class SlowDevice(FakeDeviceManager):
            def _on_model(self, msg):
                time.sleep(4.5)  # > round_timeout_s: round closes without us
                super()._on_model(msg)

        LoopbackHub.reset()
        args = Arguments.from_dict(
            {
                "common_args": {"training_type": "cross_device", "random_seed": 0,
                                "run_id": "beehive-slow"},
                "data_args": {"dataset": "synthetic"},
                "model_args": {"model": "lr"},
                "train_args": {
                    "federated_optimizer": "FedAvg",
                    "client_num_in_total": 3,
                    "client_num_per_round": 3,
                    "comm_round": 2,
                    "epochs": 1,
                    "batch_size": 16,
                    "learning_rate": 0.2,
                    "round_timeout_s": 3.0,
                    "round_timeout_min_clients": 2,
                },
                "validation_args": {"frequency_of_the_test": 1},
                "comm_args": {"backend": "LOOPBACK"},
            }
        ).validate()
        x_test, y_test = _separable(128, seed=9)
        aggregator = FedMLAggregator(args, LogisticRegression(output_dim=4),
                                     (x_test, y_test), worker_num=3,
                                     model_dir=str(tmp_path / "models"))
        server = FedMLServerManager(args, aggregator, client_rank=0, client_num=3)
        devices = [
            FakeDeviceManager(args, rank, _separable(96, seed=rank), client_num=3,
                              upload_dir=str(tmp_path / f"dev{rank}"))
            for rank in (1, 2)
        ]
        slow = SlowDevice(args, 3, _separable(96, seed=3), client_num=3,
                          upload_dir=str(tmp_path / "dev3"))
        with caplog.at_level(_logging.WARNING,
                             logger="fedml_tpu.core.distributed.straggler"):
            with caplog.at_level(_logging.WARNING,
                                 logger="fedml_tpu.cross_device.fedml_server_manager"):
                threads = ([server.run_async()] + [d.run_async() for d in devices]
                           + [slow.run_async()])
                for t in threads:
                    t.join(timeout=90)
        for t in threads:
            assert not t.is_alive(), "protocol did not terminate"
        assert aggregator.eval_history
        # the slow device's late round-0 upload was dropped by its tag
        assert any("dropping stale round-0 upload" in r.getMessage()
                   for r in caplog.records), [r.getMessage() for r in caplog.records]

    def test_straggler_rejoins_next_round(self, tmp_path, caplog):
        """Elastic re-membership: a device that misses round 0 (slow once)
        picks up the round-1 sync and participates normally — later rounds
        close on the all-received fast path, not by timeout."""
        import logging as _logging
        import time

        from fedml_tpu.cross_device.fake_device import FakeDeviceManager
        from fedml_tpu.cross_device.fedml_aggregator import FedMLAggregator
        from fedml_tpu.cross_device.fedml_server_manager import FedMLServerManager
        from fedml_tpu.models.linear import LogisticRegression

        class SlowOnce(FakeDeviceManager):
            _slept = False

            def _on_model(self, msg):
                if not self._slept:
                    self._slept = True
                    time.sleep(4.5)  # only round 0's upload misses the window
                super()._on_model(msg)

        LoopbackHub.reset()
        args = Arguments.from_dict(
            {
                "common_args": {"training_type": "cross_device", "random_seed": 0,
                                "run_id": "beehive-rejoin"},
                "data_args": {"dataset": "synthetic"},
                "model_args": {"model": "lr"},
                "train_args": {
                    "federated_optimizer": "FedAvg",
                    "client_num_in_total": 3,
                    "client_num_per_round": 3,
                    "comm_round": 3,
                    "epochs": 1,
                    "batch_size": 16,
                    "learning_rate": 0.2,
                    "round_timeout_s": 3.0,
                    "round_timeout_min_clients": 2,
                },
                "validation_args": {"frequency_of_the_test": 1},
                "comm_args": {"backend": "LOOPBACK"},
            }
        ).validate()
        x_test, y_test = _separable(128, seed=9)
        aggregator = FedMLAggregator(args, LogisticRegression(output_dim=4),
                                     (x_test, y_test), worker_num=3,
                                     model_dir=str(tmp_path / "models"))
        server = FedMLServerManager(args, aggregator, client_rank=0, client_num=3)
        devices = [
            FakeDeviceManager(args, rank, _separable(96, seed=rank), client_num=3,
                              upload_dir=str(tmp_path / f"dev{rank}"))
            for rank in (1, 2)
        ]
        slow = SlowOnce(args, 3, _separable(96, seed=3), client_num=3,
                        upload_dir=str(tmp_path / "dev3"))
        with caplog.at_level(_logging.WARNING,
                             logger="fedml_tpu.core.distributed.straggler"):
            threads = ([server.run_async()] + [d.run_async() for d in devices]
                       + [slow.run_async()])
            for t in threads:
                t.join(timeout=90)
        for t in threads:
            assert not t.is_alive(), "protocol did not terminate"
        assert len(aggregator.eval_history) == 3
        # the slow device handled every sync (late round-0 + rounds 1, 2)
        assert slow.rounds_trained == 3
        # only round 0 closed by timeout; rounds 1-2 were all-received
        closes = [r.getMessage() for r in caplog.records
                  if "timeout: closing" in r.getMessage()]
        assert len(closes) == 1 and "round 0 timeout" in closes[0], closes
