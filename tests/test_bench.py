"""bench.py helpers (the driver runs bench.py itself on the real chip; these
cover the opt-in metric paths at smoke scale on CPU)."""

import json
import sys

import pytest


@pytest.mark.heavy
def test_autotune_picks_a_valid_strategy():
    """bench autotune must return a subset of the two lever flags and leave
    the simulator runnable with the winner (CPU smoke at lr scale)."""
    import jax

    sys.path.insert(0, ".")
    import bench
    import fedml_tpu
    from fedml_tpu import data
    from fedml_tpu.simulation.xla.fed_sim import XLASimulator

    n = len(jax.devices())
    args = bench._bench_args(n)
    args.model = "lr"
    args.dataset = "mnist"
    args.synthetic_train_size = 800
    args.client_num_per_round = 8
    args.comm_round = 2
    args = fedml_tpu.init(args, should_init_logs=False)
    dataset, out_dim = data.load(args)
    model = fedml_tpu.models.create(args, out_dim)
    tuned, sim = bench._autotune(args, dataset, model)
    assert tuned is not None and set(tuned) <= {"xla_pregather", "xla_stream"}
    if sim is not None:
        # winner == last variant: main() keeps training the compiled sim —
        # more rounds append without a rebuild
        n_before = len(sim.round_times)
        sim.args.comm_round = 2
        sim.train()
        assert len(sim.round_times) == n_before + 2
    else:
        # winner was an earlier variant (only one candidate is kept alive):
        # main() rebuilds it from the returned flags
        for k, v in tuned.items():
            setattr(args, k, v)
        sim = XLASimulator(args, dataset, model)
        sim.train()
    assert sim.throughput()["samples_per_sec"] > 0


@pytest.mark.heavy
def test_transformer_bench_metric_line(monkeypatch):
    sys.path.insert(0, ".")
    import bench

    for k, v in {"BENCH_TF_DMODEL": "64", "BENCH_TF_LAYERS": "2",
                 "BENCH_TF_HEADS": "4", "BENCH_TF_DFF": "256",
                 "BENCH_TF_SEQ": "128", "BENCH_TF_BATCH": "2",
                 "BENCH_TF_STEPS": "3"}.items():
        monkeypatch.setenv(k, v)
    out = bench._measure_transformer()
    json.dumps(out)  # one JSON-serializable line
    assert out["unit"] == "tokens/s/chip"
    assert out["value"] > 0
    assert 0 <= out["mfu"] <= 1
    assert out["n_params"] > 0


class TestBackendWait:
    """The outage-riding probe (round 5): BENCH_r03/r04 were lost because
    the first jax.devices() throw killed the bench — the probe must ride a
    bounded window in a SUBPROCESS (a failed in-process init is cached by
    jax) and give up cleanly when it closes."""

    def test_probe_passes_when_backend_answers(self, monkeypatch):
        sys.path.insert(0, ".")
        import bench

        # fast fake probe: the loop logic is under test, not the (minutes-
        # per-attempt) real jax import
        monkeypatch.setattr(bench, "_PROBE_CODE", "print(1)")
        monkeypatch.setenv("BENCH_WAIT_MIN", "0.2")
        assert bench._wait_for_backend() is True

    def test_probe_rides_window_then_fails(self, monkeypatch):
        sys.path.insert(0, ".")
        import time

        import bench

        monkeypatch.setattr(
            bench, "_PROBE_CODE",
            "import sys; print('UNAVAILABLE', file=sys.stderr); sys.exit(1)")
        monkeypatch.setenv("BENCH_WAIT_MIN", "0.03")  # ~2s window
        monkeypatch.setenv("BENCH_WAIT_POLL_S", "1")
        t0 = time.time()
        assert bench._wait_for_backend() is False
        # it actually polled (>= one retry sleep) and respected the bound
        assert 1.0 <= time.time() - t0 < 60

    def test_probe_recovers_mid_window(self, monkeypatch, tmp_path):
        sys.path.insert(0, ".")
        import bench

        # fails until the marker file exists, then succeeds: the tunnel-
        # recovery scenario the loop exists for
        marker = tmp_path / "up"
        code = ("import os, sys\n"
                f"if os.path.exists({str(marker)!r}):\n"
                "    print(1)\n"
                "else:\n"
                "    sys.exit(1)\n")
        monkeypatch.setattr(bench, "_PROBE_CODE", code)
        monkeypatch.setenv("BENCH_WAIT_MIN", "1")
        monkeypatch.setenv("BENCH_WAIT_POLL_S", "1")
        import threading

        threading.Timer(2.0, marker.touch).start()
        assert bench._wait_for_backend() is True

    def test_probe_timeout_knob_bounds_a_hung_probe(self, monkeypatch):
        """BENCH_PROBE_TIMEOUT_S: a hung tunnel (probe that never answers)
        is killed per-attempt instead of eating the whole wait window."""
        sys.path.insert(0, ".")
        import time

        import bench

        monkeypatch.setattr(bench, "_PROBE_CODE", "import time; time.sleep(60)")
        monkeypatch.setenv("BENCH_PROBE_TIMEOUT_S", "0.5")
        monkeypatch.setenv("BENCH_WAIT_MIN", "0")
        t0 = time.time()
        assert bench._wait_for_backend() is False
        assert time.time() - t0 < 10


class TestMetricLineContract:
    """Schema-2 stamping + the exactly-one-JSON-line guarantee on every
    exit path (r03-r05 shipped EMPTY tails; tools/perf_gate.py now rejects
    a round that does that again)."""

    def test_emit_stamps_schema_provenance(self, capsys, monkeypatch):
        sys.path.insert(0, ".")
        import bench

        monkeypatch.setattr(bench, "_emitted", False)
        bench._emit({"metric": "m", "value": 1.0, "unit": "u"}, "full")
        lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
        assert len(lines) == 1
        rec = json.loads(lines[0])
        assert rec["bench_schema"] == bench.BENCH_SCHEMA
        assert rec["mode"] == "full"
        assert rec["git_rev"]  # short rev or "unknown", never absent
        assert rec["metric"] == "m" and rec["value"] == 1.0
        assert bench._emitted is True

    def test_required_tpu_missing_emits_one_failed_line(self, capsys,
                                                        monkeypatch):
        sys.path.insert(0, ".")
        import bench

        monkeypatch.setattr(bench, "_PROBE_CODE", "import sys; sys.exit(3)")
        monkeypatch.setattr(bench, "_emitted", False)
        monkeypatch.setenv("BENCH_REQUIRE_TPU", "1")
        monkeypatch.setenv("BENCH_WAIT_MIN", "0")
        assert bench.main() == 1
        lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
        assert len(lines) == 1  # the dark round still leaves a record
        rec = json.loads(lines[0])
        assert rec["mode"] == "failed" and rec["value"] is None
        assert rec["bench_schema"] == bench.BENCH_SCHEMA
        assert "without a metric line" in rec["degraded_reason"]

    def test_unhandled_exception_emits_failed_record_then_reraises(
            self, capsys, monkeypatch):
        sys.path.insert(0, ".")
        import bench

        def _boom():
            raise RuntimeError("boom")

        monkeypatch.setattr(bench, "_emitted", False)
        monkeypatch.setattr(bench, "_main", _boom)
        with pytest.raises(RuntimeError):
            bench.main()
        lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
        assert len(lines) == 1
        rec = json.loads(lines[0])
        assert rec["mode"] == "failed"
        assert "RuntimeError" in rec["degraded_reason"]
        assert "boom" in rec["degraded_reason"]
