"""bench.py helpers (the driver runs bench.py itself on the real chip; these
cover the opt-in metric paths at smoke scale on CPU)."""

import json
import sys

import pytest


@pytest.mark.heavy
def test_transformer_bench_metric_line(monkeypatch):
    sys.path.insert(0, ".")
    import bench

    for k, v in {"BENCH_TF_DMODEL": "64", "BENCH_TF_LAYERS": "2",
                 "BENCH_TF_HEADS": "4", "BENCH_TF_DFF": "256",
                 "BENCH_TF_SEQ": "128", "BENCH_TF_BATCH": "2",
                 "BENCH_TF_STEPS": "3"}.items():
        monkeypatch.setenv(k, v)
    out = bench._measure_transformer()
    json.dumps(out)  # one JSON-serializable line
    assert out["unit"] == "tokens/s/chip"
    assert out["value"] > 0
    assert 0 <= out["mfu"] <= 1
    assert out["n_params"] > 0
