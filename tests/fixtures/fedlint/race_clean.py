"""Golden fixture: the same shape as race_seeded, made clean (expected: 0
findings) — the latch carries an ownership annotation and the counter is
written under a lock from both contexts."""

import threading


class Pump:
    def __init__(self):
        self.active = False
        self.last_seen = 0
        self._lock = threading.Lock()
        self._thread = None

    def start(self):
        # owned-by: main — start/stop latch; the worker only reads
        self.active = True  # owned-by: main
        self._thread = threading.Thread(target=self._worker)
        self._thread.start()

    def stop(self):
        self.active = False

    def reset(self):
        with self._lock:
            self.last_seen = 0

    def _worker(self):
        while self.active:
            with self._lock:
                self.last_seen = self.last_seen + 1
