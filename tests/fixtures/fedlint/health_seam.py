"""health-seam fixture: hand-rolled liveness bookkeeping outside the seam.

Expected findings (pinned exactly by tests/test_fedlint.py):

* line 17 — health-seam: ``heartbeat timestamp stored into 'last_beat'``
* line 22 — health-seam: ``heartbeat timestamp stored into 'last_heartbeat'``
* line 27 — health-seam: ``'_worker.is_alive()' polled on a threading.Thread``
* line 30 — health-seam (subscript store through a clock call)

and the NON-findings that pin the scoping: a non-Thread ``is_alive()``
(a *process* health check), a round-number ``last_seen_round`` store,
and a justified pragma.
"""
import threading
import time

last_beat = time.monotonic()  # plain-name clock store: hand-rolled liveness


class _Pump:
    def __init__(self):
        self.last_heartbeat = time.time()  # attribute clock store
        self._worker = threading.Thread(target=lambda: None)
        self._proc = FakeProcess()

    def wedged(self, table):
        alive = self._worker.is_alive()  # thread liveness poll
        # subscript clock store into a liveness-named table
        heartbeat = table
        heartbeat["pump"] = time.perf_counter()
        return alive

    def fine(self, registry, round_idx):
        ok = self._proc.is_alive()  # Process, not Thread: NOT a finding
        # round-number bookkeeping, no clock on the RHS: NOT a finding
        registry.last_seen_round = int(round_idx)
        # justified escape hatch: NOT a finding
        self.last_heartbeat = time.time()  # fedlint: allow[health-seam] — fixture demonstrates the pragma
        return ok


class FakeProcess:
    def is_alive(self):
        return True
