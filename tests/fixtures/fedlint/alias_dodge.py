"""Regression fixture: import-alias dodges the OLD grep linters missed.

Every call below was invisible to the raw-pattern legacy scripts (the
text ``os.fsync(`` / ``msgpack.unpackb(`` / ``np.random.shuffle(`` never
appears), but resolves to the banned target through the import map:

Line 18 — ``f(fd)`` IS ``os.fsync`` (perf-stray-fsync).
Line 19 — ``mp.unpackb`` IS ``msgpack.unpackb`` (perf-hot-codec).
Line 20 — ``nr.shuffle`` IS ``numpy.random.shuffle`` (rng-global-rng).
"""

from os import fsync as f
import msgpack as mp
import numpy.random as nr


def sneaky(fd, blob, xs):
    f(fd)
    data = mp.unpackb(blob)
    nr.shuffle(xs)
    return data
