"""Golden fixture: chunk-header parsing outside the chunking seam
(expected: 3).  The ``chunk_`` basename opts this file into the
``chunk-reassembly-seam`` scope (real seam files —
``core/distributed/chunking.py``, ``core/ingest.py`` — are exempt by
path).

Line 21 — chunk-reassembly-seam: a wire key pulled out of a message by
literal is a second header-parsing site.
Line 25 — chunk-reassembly-seam: subscripting a journal record with the
wire key forks the record shape the replay path depends on.
Line 31 — chunk-reassembly-seam: hand-rolled framing via ``build_chunks``
outside the seam picks its own stream identity.

The clean counterparts: ``via_constant`` imports the seam's constant
instead of spelling the literal, and ``justified`` carries the pragma a
deliberate out-of-seam probe needs.
"""


def rogue_parse(msg):
    return msg.get("chunk_idx")


def rogue_record(rec):
    return rec["chunk_stream"]


def rogue_frame(stream_id, inner, payload):
    from fedml_tpu.core.distributed.chunking import build_chunks

    return build_chunks(stream_id, inner, payload, 4096)


def via_constant(msg):
    from fedml_tpu.core.distributed import chunking

    return msg.get_type() == chunking.CHUNK_TYPE


def justified(msg):
    return msg.get("chunk_n")  # fedlint: allow[chunk-reassembly-seam] — wire-compat probe for the seam test itself
