"""Golden fixture: partial reductions outside the hierarchy seam
(expected: 3).  The ``hier_`` basename opts this file into the
``hierarchy-reduce-seam`` scope (real seam files — ``core/hierarchy/``,
``core/aggregate.py``, ``parallel/agg_plane.py`` — are exempt by path).

Line 22 — hierarchy-reduce-seam: a direct ``partial_fold`` call in
application code picks its own total.
Line 26 — hierarchy-reduce-seam: ``plane.partial_reduce`` invoked
outside the plan's routing.
Line 32 — hierarchy-reduce-seam: ``combine_partials`` outside the seam
re-folds child partials in an order the plan never blessed.

The clean counterparts: ``via_plan`` delegates to the plan (topology
decides WHERE, the plan decides WHAT), and ``justified`` carries the
pragma a deliberate out-of-seam oracle needs.
"""


def rogue_block(updates):
    from fedml_tpu.core.aggregate import partial_fold

    return partial_fold(updates, 10.0, mode="mean")


def rogue_compiled(plane, updates):
    return plane.partial_reduce(updates, total_weight=10.0)


def rogue_combine(partials):
    from fedml_tpu.core.aggregate import combine_partials

    return combine_partials(partials)


def via_plan(plan, updates, mode):
    return plan.aggregate(updates, mode=mode)


def justified(partials):
    from fedml_tpu.core.aggregate import combine_partials

    return combine_partials(partials)  # fedlint: allow[hierarchy-reduce-seam] — parity oracle for the seam test itself
