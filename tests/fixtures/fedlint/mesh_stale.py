"""Golden fixture: stale compiled-program cache reads (expected: 3).

Line 20 — mesh-stale-program: ``_PROGRAMS.get`` in a function with no
mesh identity anywhere in its key.
Line 27 — mesh-stale-program: subscript load from ``self._planes`` in a
method that never references mesh_key.
Line 47 — mesh-stale-program: closure fetches from the cache and neither
it nor its enclosing function touches the mesh fingerprint.

The ``keyed_*`` and ``enclosing_keyed`` functions are the clean
counterparts — the fetch is fine as long as the lexical function chain
builds its key from ``mesh_key`` / ``mesh_fingerprint``.
"""

_PROGRAMS = {}


def stale_lookup(shapes, dtypes):
    sig = (shapes, dtypes)
    return _PROGRAMS.get(sig)


class Plane:
    _planes = {}

    def stale_subscript(self, key):
        return self._planes[key]

    def keyed_method(self, key):
        sig = (self.mesh_key, key)
        prog = self._planes.get(sig)
        if prog is None:
            prog = object()
            self._planes[sig] = prog
        return prog


def keyed_lookup(mesh, shapes):
    from fedml_tpu.parallel.mesh import mesh_fingerprint

    sig = (mesh_fingerprint(mesh), shapes)
    return _PROGRAMS.get(sig)


def stale_closure(shapes):
    def fetch():
        return _PROGRAMS.get(shapes)

    return fetch()


def enclosing_keyed(mesh, shapes):
    sig = (mesh_fingerprint(mesh), shapes)

    def fetch():
        return _PROGRAMS.get(sig)

    return fetch()
