"""Golden fixture: correctly ordered acks (expected: 0 findings) — a
journal append, a deferred_ack_scope ticket, and a dispatch hand-off each
count as the durability marker preceding the ack."""


class Handler:
    def journal_first(self, msg):
        self._journal.append(msg.payload)
        self._link._send_ack(msg)

    def ticketed(self, msg, ingest):
        with ingest.deferred_ack_scope() as sink:
            self.handle(msg)
        if not sink.tickets:
            self._link._send_ack(msg)

    def handed_off(self, msg):
        self.dispatch(msg)
        self._link._send_ack(msg)
