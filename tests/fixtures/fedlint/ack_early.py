"""Golden fixture: ack before durability (expected: 1 finding).

Line 10 — ack-before-journal: the handler acks the upload before the
journal append, so a crash between the two loses an acked update.
"""


class Handler:
    def on_receive(self, msg):
        self._link._send_ack(msg)
        self._journal.append(msg.payload)
