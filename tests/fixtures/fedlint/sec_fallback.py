"""Golden fixture: host aggregation folds in the security plane
(expected: 3).  The ``sec_`` basename opts this file into the
``sec-host-fallback`` scope (the rule otherwise keys on the
``core/security`` / ``core/dp`` / ``core/mpc`` path fragments).

Line 25 — sec-host-fallback: a Python loop folding client ``updates``
into a running accumulator (the host-fallback aggregation pattern).
Line 32 — sec-host-fallback: a modular fold over masked payloads
through ``.values()`` — the SecAgg host field sum.
Line 40 — sec-host-fallback: ``tree_map`` over a client payload
collection in a function with no JAX-compute marker — a host pytree
fold.

The clean counterparts: ``inspect_updates`` iterates payloads without
accumulating (no fold), ``compiled_fold`` uses ``tree_map`` next to
``jnp`` compute (a compiled stage, not a host fallback), and
``oracle_fold`` carries a justified pragma (the retained-oracle seam).
"""

import numpy as np


def host_fold(updates):
    total = np.zeros(4)
    for _, p in updates:
        total = total + p
    return total


def masked_field_sum(masked, prime):
    total = np.zeros(4, np.int64)
    for v in masked.values():
        total = np.mod(total + v, prime)
    return total


def host_tree_fold(raw_grad_list, tree_map):
    acc = raw_grad_list[0]
    for g in raw_grad_list[1:]:
        acc = tree_map(lambda a, b: a + b, acc, g)
    return acc


def inspect_updates(updates):
    names = []
    for n, _ in updates:
        names.append(n)
    return names


def compiled_fold(updates, jnp, tree_map):
    return tree_map(lambda s: jnp.sum(s, axis=0), updates)


def oracle_fold(updates):
    total = np.zeros(4)
    for _, p in updates:  # fedlint: allow[sec-host-fallback] — retained host oracle for the fixture
        total = total + p
    return total
