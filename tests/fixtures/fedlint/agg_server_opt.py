"""Golden fixture: host server-optimizer round tail (expected: 2 findings).

Line 17 — agg-server-opt-host: pseudo-gradient tree_map in the same
function as the optax apply.
Line 24 — agg-server-opt-host: same pattern built inline with optax.adam.
A pseudo-gradient fold WITHOUT an optax apply (client_delta) is clean —
plain delta computation is everywhere and not a server-optimizer tail.
"""

import jax
import optax


def fedopt_round(params, avg, tx, opt_state):
    # the whole FedOpt tail on the host: exactly what the sharded round
    # plane (and core/aggregate.host_server_round_update) own now
    pseudo_grad = jax.tree_util.tree_map(lambda p, a: p - a, params, avg)
    updates, opt_state = tx.update(pseudo_grad, opt_state, params)
    return optax.apply_updates(params, updates), opt_state


def fedadam_tail(params, avg, opt_state):
    tx = optax.adam(0.1)
    grad = jax.tree_util.tree_map(lambda p, a: p - a, params, avg)
    upd, opt_state = tx.update(grad, opt_state, params)
    new = optax.apply_updates(params, upd)
    return new, opt_state


def client_delta(new_params, old_params):
    # clean: a plain delta, no optimizer step in this function
    return jax.tree_util.tree_map(lambda a, b: a - b, new_params, old_params)
