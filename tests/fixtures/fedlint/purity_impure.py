"""Golden fixture: impure jit body (expected: 5 findings).

Line 19 — purity-wall-clock: time.perf_counter() in a traced body.
Line 20 — purity-host-rng: stdlib random draw in a traced body.
Line 21 — purity-host-numpy: host numpy on the traced ``params``.
Line 22 — purity-unsorted-dict: unsorted .items() on the traced ``batch``.
Line 29 — purity-donated-reuse: ``params`` read after being donated.
"""

import time
import random

import jax
import numpy as np


@jax.jit
def impure_step(params, batch):
    t = time.perf_counter()
    noise = random.random()
    host = np.sum(params)
    out = {k: v for k, v in batch.items()}
    return host + noise + t, out


def reuse_after_donation(params, grads):
    step = jax.jit(lambda p, g: p, donate_argnums=(0,))
    new = step(params, grads)
    return params + new
