"""Golden fixture: seeded thread-ownership races (expected: 2 findings).

Line 19 — race-unannotated-shared: ``active`` is written from main and
read by the worker thread, with no lock and no annotation.
Line 28 — race-cross-thread-write: ``last_seen`` is owned by main but
written from the worker context without a lock.
"""

import threading


class Pump:
    def __init__(self):
        self.active = False
        self.last_seen = 0  # owned-by: main
        self._thread = None

    def start(self):
        self.active = True
        self._thread = threading.Thread(target=self._worker)
        self._thread.start()

    def stop(self):
        self.active = False

    def _worker(self):
        while self.active:
            self.last_seen = self.last_seen + 1
