"""New task families (reference app/fednlp/{seq_tagging,span_extraction},
app/fedcv/object_detection) and mounted-file parsers (CINIC-10 image folder,
tabular CSV)."""

import os

import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.arguments import Arguments

pytestmark = pytest.mark.heavy  # transformer/conv XLA compiles


def _cfg(dataset, model, **over):
    d = {
        "common_args": {"training_type": "simulation", "random_seed": 0,
                        "run_id": f"task-{dataset}"},
        "data_args": {"dataset": dataset, "data_cache_dir": "",
                      "partition_method": "homo", "synthetic_train_size": 512},
        "model_args": {"model": model},
        "train_args": {"federated_optimizer": "FedAvg", "client_num_in_total": 4,
                       "client_num_per_round": 4, "comm_round": 3, "epochs": 1,
                       "batch_size": 32, "client_optimizer": "adam",
                       "learning_rate": 0.002},
        "validation_args": {"frequency_of_the_test": 2},
        "comm_args": {"backend": "sp"},
    }
    args = Arguments.from_dict(d)
    for k, v in over.items():
        setattr(args, k, v)
    return args.validate()


def _run(args):
    args = fedml_tpu.init(args, should_init_logs=False)
    device = fedml_tpu.device.get_device(args)
    dataset, out_dim = fedml_tpu.data.load(args)
    model = fedml_tpu.models.create(args, out_dim)
    from fedml_tpu.simulation.simulator import create_simulator

    return create_simulator(args, device, dataset, model).run()


class TestSeqTagging:
    def test_learns_per_token_tags(self):
        metrics = _run(_cfg("onto_tagging", "transformer_tagger", comm_round=4,
                            epochs=3, learning_rate=0.01))
        # per-token accuracy well above 1/8 chance (band-tag signal)
        assert metrics["test_acc"] > 0.4, metrics


class TestSpanExtraction:
    def test_learns_spans(self):
        metrics = _run(_cfg("squad_span", "transformer_span", comm_round=5,
                            epochs=2, learning_rate=0.002,
                            synthetic_train_size=2048))
        # held-out exact-match: rule learning, not memorization
        assert metrics["test_acc"] > 0.3, metrics


class TestDetection:
    def test_learns_class_and_box(self):
        metrics = _run(_cfg("synthetic_det", "tiny_detector", comm_round=4,
                            epochs=2, learning_rate=0.005))
        assert metrics["test_acc"] > 0.5, metrics  # 6-class chance = 0.17
        assert metrics.get("test_mean_iou", 0) > 0.2, metrics

    def test_det_loss_shape(self):
        import jax.numpy as jnp

        from fedml_tpu.ml.engine.train import detection_loss

        logits = jnp.zeros((4, 10))  # 6 classes + 4 box
        labels = jnp.zeros((4, 5))
        loss, _ = detection_loss(logits, labels, jnp.ones(4))
        assert float(loss) > 0


class TestParsers:
    def test_image_folder_cinic(self, tmp_path):
        from PIL import Image

        from fedml_tpu.data.loaders import load_image_folder

        rng = np.random.RandomState(0)
        for split, n in (("train", 3), ("test", 2)):
            for cls in ("airplane", "dog"):
                d = tmp_path / split / cls
                d.mkdir(parents=True)
                for i in range(n):
                    arr = rng.randint(0, 255, (32, 32, 3), dtype=np.uint8)
                    Image.fromarray(arr).save(d / f"img{i}.png")
        out = load_image_folder(str(tmp_path))
        assert out is not None
        xt, yt, xe, ye = out
        assert xt.shape == (6, 32, 32, 3) and xe.shape == (4, 32, 32, 3)
        assert set(yt.tolist()) == {0, 1}
        assert xt.max() <= 1.0

    def test_csv_labeled_with_header(self, tmp_path):
        from fedml_tpu.data.loaders import load_csv_labeled

        with open(tmp_path / "train.csv", "w") as f:
            f.write("f1,f2,label\n")
            for i in range(10):
                f.write(f"{i * 0.1},{i * 0.2},{i % 2}\n")
        with open(tmp_path / "test.csv", "w") as f:
            f.write("f1,f2,label\n0.5,0.9,1\n")
        xt, yt, xe, ye = load_csv_labeled(str(tmp_path))
        assert xt.shape == (10, 2) and yt.tolist() == [i % 2 for i in range(10)]
        assert xe.shape == (1, 2) and ye.tolist() == [1]

    def test_csv_no_header_last_column_label(self, tmp_path):
        from fedml_tpu.data.loaders import load_csv_labeled

        with open(tmp_path / "train.csv", "w") as f:
            for i in range(20):
                f.write(f"{i * 0.1},{i * 0.2},{i % 2}\n")
        xt, yt, xe, ye = load_csv_labeled(str(tmp_path))
        assert xt.shape[1] == 2 and len(yt) + len(ye) == 20

    def test_tabular_dataset_via_mounted_csv(self, tmp_path):
        # end-to-end: 'uci' with a mounted CSV uses the real file parser
        root = tmp_path / "uci"
        root.mkdir()
        rng = np.random.RandomState(1)
        with open(root / "train.csv", "w") as f:
            f.write(",".join(f"f{i}" for i in range(32)) + ",label\n")
            for _ in range(200):
                y = rng.randint(0, 2)
                row = rng.randn(32) + y * 2.0
                f.write(",".join(f"{v:.4f}" for v in row) + f",{y}\n")
        args = _cfg("uci", "lr", data_cache_dir=str(tmp_path))
        args = fedml_tpu.init(args, should_init_logs=False)
        dataset, out_dim = fedml_tpu.data.load(args)
        assert out_dim == 2
        assert not getattr(args, "dataset_is_synthetic", True)


class TestAppConfigsExist:
    @pytest.mark.parametrize("cfg", [
        "app/fednlp/fedml_config_tagging.yaml",
        "app/fednlp/fedml_config_span.yaml",
        "app/fedcv/fedml_config_det.yaml",
    ])
    def test_config_loads(self, cfg):
        import yaml

        path = os.path.join(os.path.dirname(__file__), os.pardir, cfg)
        with open(path) as f:
            d = yaml.safe_load(f)
        Arguments.from_dict(d).validate()
