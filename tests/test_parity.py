"""Accuracy-parity gates (BASELINE.md / reference BENCHMARK_MPI.md).

Two tiers:

* Seeded synthetic convergence-to-threshold gates — always run.  They prove
  the training stack optimizes to a target under the benchmark's
  hyperparameter SHAPE (clients, sampling, lr schedule), on shape-faithful
  synthetic data.
* Real-data gates — run only when a dataset is mounted at ``./fedml_data``
  (or ``$FEDML_DATA_DIR``); zero-egress environments skip them.  Thresholds
  and hyperparameters follow the reference benchmark tables
  (BENCHMARK_MPI.md:9 MNIST+LR target >75; BENCHMARK_simulation.md:5).
  Measured results are recorded in PARITY.md.
"""

import os

import pytest

import fedml_tpu
from fedml_tpu.arguments import Arguments

pytestmark = pytest.mark.heavy

DATA_DIR = os.environ.get("FEDML_DATA_DIR", "./fedml_data")
HAS_REAL_DATA = os.path.isdir(DATA_DIR) and any(
    os.scandir(DATA_DIR)
) if os.path.isdir(DATA_DIR) else False


def _run(cfg):
    args = Arguments.from_dict(cfg).validate()
    args = fedml_tpu.init(args, should_init_logs=False)
    device = fedml_tpu.device.get_device(args)
    dataset, out_dim = fedml_tpu.data.load(args)
    model = fedml_tpu.models.create(args, out_dim)
    from fedml_tpu.simulation.simulator import create_simulator

    return create_simulator(args, device, dataset, model).run()


def _cfg(backend, *, dataset="mnist", model="lr", clients=(50, 10), rounds=20,
         batch=10, lr=0.03, data_dir="", train_size=2500, **train_extra):
    return {
        "common_args": {"training_type": "simulation", "random_seed": 0,
                        "run_id": f"parity-{backend}-{dataset}-{model}"},
        "data_args": {"dataset": dataset, "data_cache_dir": data_dir,
                      "partition_method": "hetero", "partition_alpha": 0.5,
                      "synthetic_train_size": train_size},
        "model_args": {"model": model},
        "train_args": {"federated_optimizer": "FedAvg",
                       "client_num_in_total": clients[0],
                       "client_num_per_round": clients[1],
                       "comm_round": rounds, "epochs": 1, "batch_size": batch,
                       "client_optimizer": "sgd", "learning_rate": lr,
                       **train_extra},
        "validation_args": {"frequency_of_the_test": max(rounds // 2, 1)},
        "comm_args": {"backend": backend},
    }


class TestSyntheticConvergenceGates:
    """Benchmark-shaped runs on synthetic data: the gate is convergence to a
    seeded threshold, proving the optimization stack (sampling, engine,
    aggregation) works at the benchmark's configuration shape."""

    def test_mnist_lr_sp_gate(self):
        # BENCHMARK_MPI.md:9 shape (1000 clients, 10/round, b=10, lr=0.03),
        # scaled to 50 clients / 20 rounds for CI
        metrics = _run(_cfg("sp"))
        assert metrics["test_acc"] >= 0.90, metrics

    def test_mnist_lr_xla_gate(self):
        metrics = _run(_cfg("XLA"))
        assert metrics["test_acc"] >= 0.90, metrics

    def test_cifar_resnet20_trajectory(self):
        # shortened CIFAR ResNet trajectory (BENCHMARK_MPI.md:101 shape):
        # above-chance accuracy within a few rounds.  sp backend: one jitted
        # local-train compile instead of an 8-device shard_map compile (this
        # gate runs on the CPU mesh where resnet compiles are minutes).
        metrics = _run(_cfg("sp", dataset="cifar10", model="resnet20",
                            clients=(4, 4), rounds=4, batch=32, lr=0.2,
                            train_size=512, epochs=3))
        assert metrics["test_acc"] > 0.15, metrics  # 10-class chance = 0.1

    def test_fed_shakespeare_rnn_shape(self):
        # BENCHMARK_simulation.md:9 shape (RNN next-char); synthetic tokens
        metrics = _run(_cfg("sp", dataset="shakespeare", model="rnn",
                            clients=(10, 5), rounds=4, batch=8, lr=0.3,
                            train_size=400))
        assert metrics["test_acc"] > 0.0, metrics


@pytest.mark.skipif(not HAS_REAL_DATA, reason="no dataset mounted at ./fedml_data")
class TestRealDataGates:
    """Published-accuracy gates; run when real data is mounted."""

    def test_mnist_lr_200_rounds(self):
        # BENCHMARK_MPI.md:9: MNIST + LR, FedAvg, >100 rounds, target >75.
        metrics = _run(_cfg("XLA", clients=(1000, 10), rounds=200,
                            data_dir=DATA_DIR))
        assert metrics["test_acc"] >= 0.75, metrics

    def test_cifar10_resnet56_short(self):
        # headline-model trajectory check (full 4000-round run is offline):
        # 50 rounds must clear 35% (well above chance, on the published
        # trajectory toward 93.19 IID — BENCHMARK_MPI.md:101)
        metrics = _run(_cfg("XLA", dataset="cifar10", model="resnet56",
                            clients=(10, 10), rounds=50, batch=64, lr=0.1,
                            data_dir=DATA_DIR))
        assert metrics["test_acc"] >= 0.35, metrics
