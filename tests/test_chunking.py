"""Chunked resumable upload streaming (core/distributed/chunking.py).

Unit layer: framing/reassembly round trips, crc integrity, dedup, journal
restore, buffer-pressure shedding, windowed sender accounting, and the
pinned deterministic retransmit-backoff schedule.

Topology layer: chunked rounds must converge BIT-IDENTICALLY to
whole-message rounds (chunking is transport plumbing, never semantics) —
fault-free, under a full chunk-vocabulary chaos plan with crash-and-rejoin,
after a ``mid_message_disconnect`` at 90% of an upload (re-sending < 20%
of the message bytes: the resumability claim), across a mixed
chunked/whole-message fleet (negotiate-down interop), and across a server
kill mid-upload with journal replay + exactly-once accounting."""

from __future__ import annotations

import pickle
import random
import threading
import time
import types

import pytest

import fedml_tpu
import test_fault_tolerance as _ft
from fedml_tpu.core.distributed import chunking
from fedml_tpu.core.distributed.chunking import (
    CHUNK_OK_KEY,
    CHUNK_RESET_TYPE,
    CHUNK_TYPE,
    ChunkedSender,
    ChunkError,
    ChunkingState,
    ChunkReassembler,
    build_chunks,
    split_payload,
    truncate_for_fault,
)
from fedml_tpu.core.distributed.comm_manager import FedMLCommManager
from fedml_tpu.core.distributed.communication.loopback import LoopbackHub
from fedml_tpu.core.distributed.communication.message import Message
from fedml_tpu.core.distributed.faults import FAULT_KINDS, CommStats

# chunk sizing for the topology runs: the synthetic-lr upload pickles to a
# few KB, so 64-byte chunks give ~40+ chunks per stream — enough
# granularity for the "resume re-sends < 20%" claim to be measurable.
# Backoff base 0.25s keeps retransmits OUT of a 0.2s disconnect window
# (the first retransmit lands after carrier returns: one resend per
# affected chunk, not three).
_CHUNK_KNOBS = dict(
    comm_max_retries=5,
    comm_backoff_base_s=0.25,
    comm_backoff_max_s=0.5,
    upload_chunk_bytes=64,
    chunk_window=2,
)


def _inner_msg(sender=1, receiver=0, payload=b"x" * 500, round_idx=0):
    m = Message(3, sender, receiver)
    m.add_params(Message.MSG_ARG_KEY_MODEL_PARAMS, payload)
    if round_idx is not None:
        m.add_params("round_idx", round_idx)
    return m


# ---------------------------------------------------------------------------
# Unit layer: framing + reassembly (no transport)
# ---------------------------------------------------------------------------

class _FakeRxManager:
    """The slice of FedMLCommManager a ChunkReassembler touches."""

    def __init__(self):
        self._comm_stats = CommStats()
        self.rank = 0
        self.sent = []

    def _send_one(self, msg, msg_id=None):
        self.sent.append(msg)


def _frames(payload=b"q" * 300, chunk_bytes=64, stream="c1:aa:1", sender=1):
    inner = _inner_msg(sender=sender, payload=payload)
    return build_chunks(stream, inner, pickle.dumps(
        inner.get_params(), protocol=pickle.HIGHEST_PROTOCOL), chunk_bytes)


class TestFraming:
    def test_split_payload_round_trip(self):
        for size in (0, 1, 63, 64, 65, 300, 1024):
            payload = bytes(range(256)) * 5
            payload = payload[:size]
            slices = split_payload(payload, 64)
            assert b"".join(slices) == payload
            assert all(len(s) <= 64 for s in slices)
            # empty payloads still produce one (empty) frame
            assert len(slices) == max(1, -(-size // 64))

    def test_build_chunks_headers(self):
        inner = _inner_msg(payload=b"z" * 200, round_idx=7)
        payload = pickle.dumps(inner.get_params(),
                               protocol=pickle.HIGHEST_PROTOCOL)
        frames = _frames(payload=b"z" * 200)
        # rebuild with the tagged inner to check round propagation
        frames = build_chunks("s1", inner, payload, 64)
        n = len(frames)
        assert n == -(-len(payload) // 64)
        for idx, f in enumerate(frames):
            assert f.get_type() == CHUNK_TYPE
            assert f.get(chunking._KEY_STREAM) == "s1"
            assert int(f.get(chunking._KEY_IDX)) == idx
            assert int(f.get(chunking._KEY_N)) == n
            assert int(f.get(chunking._KEY_TOTAL)) == len(payload)
            assert f.get(chunking._KEY_INNER_TYPE) == "3"
            assert f.get("round_idx") == 7
            data = f.get(chunking._KEY_DATA)
            assert int(f.get(chunking._KEY_CRC)) == chunking._crc(data)
        assert b"".join(f.get(chunking._KEY_DATA) for f in frames) == payload

    def test_truncate_for_fault_copies_and_keeps_original_intact(self):
        frame = _frames()[0]
        before = frame.get(chunking._KEY_DATA)
        torn = truncate_for_fault(frame)
        assert torn is not frame
        assert frame.get(chunking._KEY_DATA) == before  # retransmit source
        assert torn.get(chunking._KEY_DATA) == before[: len(before) // 2]
        # stale crc kept: the receiver's integrity check must reject it
        assert int(torn.get(chunking._KEY_CRC)) == chunking._crc(before)
        assert truncate_for_fault(_inner_msg()) is None  # nothing to tear


class TestReassembler:
    def _rx(self, buffer_bytes=1 << 20):
        mgr = _FakeRxManager()
        rx = ChunkReassembler(mgr, buffer_bytes=buffer_bytes)
        got = []
        return mgr, rx, got, got.append

    def test_out_of_order_dispatches_exactly_once(self):
        mgr, rx, got, sink = self._rx()
        frames = _frames()
        assert len(frames) > 2
        for f in reversed(frames):
            rx.accept(f, sink)
        assert len(got) == 1
        assert got[0].get(Message.MSG_ARG_KEY_MODEL_PARAMS) == b"q" * 300
        assert mgr._comm_stats.get("streams_completed") == 1

    def test_crc_mismatch_raises_and_withholds(self):
        mgr, rx, got, sink = self._rx()
        frames = _frames()
        torn = truncate_for_fault(frames[0])
        with pytest.raises(ChunkError):
            rx.accept(torn, sink)
        assert mgr._comm_stats.get("chunks_crc_bad") == 1
        # the intact retransmit completes the stream normally
        for f in frames:
            rx.accept(f, sink)
        assert len(got) == 1

    def test_duplicate_chunk_is_counted_and_ignored(self):
        mgr, rx, got, sink = self._rx()
        frames = _frames()
        rx.accept(frames[0], sink)
        rx.accept(frames[0], sink)  # same stream+idx again
        assert mgr._comm_stats.get("chunks_dup") == 1
        for f in frames[1:]:
            rx.accept(f, sink)
        assert len(got) == 1

    def test_late_duplicate_after_completion_is_reacked_not_redispatched(self):
        mgr, rx, got, sink = self._rx()
        frames = _frames()
        for f in frames:
            rx.accept(f, sink)
        rx.accept(frames[-1], sink)  # the final ack was lost; re-delivery
        assert len(got) == 1
        assert mgr._comm_stats.get("chunks_dup") == 1

    def test_total_mismatch_drops_stream(self):
        mgr, rx, got, sink = self._rx()
        frames = _frames()
        for f in frames:  # lie about the stream total, keep slice crcs valid
            f.add_params(chunking._KEY_TOTAL, 10_000)
        with pytest.raises(ChunkError):
            for f in frames:
                rx.accept(f, sink)
        assert got == []
        assert rx.stats_snapshot()["open_streams"] == 0

    def test_dispatch_failure_withholds_final_chunk(self):
        mgr, rx, got, sink = self._rx()
        frames = _frames()
        calls = {"n": 0}

        def flaky(inner):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("handler died")
            got.append(inner)

        with pytest.raises(RuntimeError):
            for f in frames:
                rx.accept(f, flaky)
        # the transport forgets + withholds the ack; the retransmit of the
        # final chunk re-completes the stream
        rx.accept(frames[-1], flaky)
        assert len(got) == 1

    def test_shed_oldest_sends_reset_and_survivor_completes(self):
        mgr, rx, got, sink = self._rx(buffer_bytes=200)
        a = _frames(payload=b"a" * 220, stream="cA", sender=1)
        b = _frames(payload=b"b" * 220, stream="cB", sender=2)
        rx.accept(a[0], sink)
        rx.accept(a[1], sink)
        rx.accept(b[0], sink)
        rx.accept(b[1], sink)  # over budget: stream A (oldest) is shed
        assert mgr._comm_stats.get("streams_shed") == 1
        assert len(mgr.sent) == 1
        reset = mgr.sent[0]
        assert reset.get_type() == CHUNK_RESET_TYPE
        assert reset.get(chunking._KEY_STREAM) == "cA"
        assert int(reset.get_receiver_id()) == 1
        for f in b[2:]:
            rx.accept(f, sink)
        assert len(got) == 1  # survivor B finished
        # A's restart (fresh stream id) then completes alone
        a2 = _frames(payload=b"a" * 220, stream="cA2", sender=1)
        for f in a2:
            rx.accept(f, sink)
        assert len(got) == 2

    def test_journal_then_restore_resumes_partial_stream(self):
        mgr, rx, got, sink = self._rx()
        records = []
        rx.bind_journal(lambda rnd, rec: records.append((rnd, dict(rec))))
        frames = _frames()
        for f in frames[:3]:
            rx.accept(f, sink)
        assert [r[1][chunking._KEY_IDX] for r in records] == [0, 1, 2]
        assert all(r[1]["kind"] == "chunk" for r in records)

        # "restart": a fresh reassembler replays the journal, then the
        # sender's retransmits deliver only the unacked tail
        mgr2 = _FakeRxManager()
        rx2 = ChunkReassembler(mgr2)
        got2 = []
        assert rx2.restore([r[1] for r in records]) == 3
        rx2.accept(frames[1], got2.append)  # a retransmit of an acked chunk
        assert mgr2._comm_stats.get("chunks_dup") == 1
        for f in frames[3:]:
            rx2.accept(f, got2.append)
        assert len(got2) == 1
        assert got2[0].get(Message.MSG_ARG_KEY_MODEL_PARAMS) == b"q" * 300

    def test_restore_completed_stream_dispatches_on_live_retransmit_only(self):
        mgr, rx, got, sink = self._rx()
        records = []
        rx.bind_journal(lambda rnd, rec: records.append(dict(rec)))
        frames = _frames()
        for f in frames:
            rx.accept(f, sink)
        assert len(got) == 1 and len(records) == len(frames)

        mgr2 = _FakeRxManager()
        rx2 = ChunkReassembler(mgr2)
        got2 = []
        rx2.restore(records)
        assert got2 == []  # held, never replay-dispatched on its own
        # the lost final ack guarantees a live retransmit: dispatch NOW
        rx2.accept(frames[0], got2.append)
        assert len(got2) == 1
        rx2.accept(frames[1], got2.append)  # later duplicates only re-ack
        assert len(got2) == 1
        assert mgr2._comm_stats.get("chunks_dup") == 1


# ---------------------------------------------------------------------------
# Unit layer: the windowed sender
# ---------------------------------------------------------------------------

class _FakeLink:
    max_retries = 2
    backoff_max_s = 0.05

    def __init__(self):
        self._n = 0
        self.listeners = []

    def add_ack_listener(self, fn):
        self.listeners.append(fn)

    def stamp(self, msg):
        self._n += 1
        mid = f"7:fake:{self._n}"
        msg.add_params(Message.MSG_ARG_KEY_MSG_ID, mid)
        return mid


class _FakeTxManager:
    """Transport double: every chunk handed over is acked synchronously
    (optionally reporting retransmit attempts, optionally not at all)."""

    def __init__(self, ack=True, first_chunk_attempts=0):
        self._comm_stats = CommStats()
        self.rank = 7
        self._link = _FakeLink()
        self.sent = []
        self.ack = ack
        self._first_attempts = first_chunk_attempts
        self._acked = 0

    def _send_one(self, msg, msg_id=None):
        self.sent.append(msg)
        if not self.ack or msg_id is None:
            return msg_id
        attempts = self._first_attempts if self._acked == 0 else 0
        self._acked += 1
        for fn in self._link.listeners:
            fn(msg_id, attempts, True)
        return msg_id


def _wait_for(cond, timeout_s=10.0):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(0.01)
    return cond()


class TestChunkedSender:
    def test_small_payload_falls_back_to_whole_message(self):
        mgr = _FakeTxManager()
        tx = ChunkedSender(mgr, chunk_bytes=1 << 20, window=2)
        assert tx.send(_inner_msg(payload=b"tiny")) is False
        assert mgr.sent == []

    def test_stream_completes_with_resume_accounting(self):
        mgr = _FakeTxManager(first_chunk_attempts=1)
        tx = ChunkedSender(mgr, chunk_bytes=64, window=2)
        msg = _inner_msg(payload=b"y" * 400)
        total = len(tx.serialize(msg))
        assert tx.send(msg) is True  # consumed; a pump thread streams it
        stats = mgr._comm_stats
        assert _wait_for(lambda: stats.get("streams_completed") == 1)
        n = -(-total // 64)
        assert len(mgr.sent) == n
        assert stats.get("chunks_sent") == n
        assert stats.get("streams_completed") == 1
        # one chunk needed one retransmit: only ITS bytes count as resent,
        # and the rest of the stream is the resumability savings
        first_size = len(mgr.sent[0].get(chunking._KEY_DATA))
        assert stats.get("chunk_bytes_resent") == first_size
        assert stats.get("resume_bytes_saved") == total - first_size

    def test_reset_restarts_stream_under_fresh_ids(self):
        mgr = _FakeTxManager(ack=False)
        tx = ChunkedSender(mgr, chunk_bytes=512, window=2)
        msg = _inner_msg(payload=b"r" * 700)  # 2 chunks: fits the window
        assert tx.send(msg) is True
        assert _wait_for(lambda: len(mgr.sent) == 2)  # handed over, unacked
        first = {m.get(chunking._KEY_STREAM) for m in mgr.sent}
        assert len(first) == 1
        mgr.ack = True  # the restarted stream gets a healthy link
        reset = Message(CHUNK_RESET_TYPE, 0, 7)
        reset.add_params(chunking._KEY_STREAM, next(iter(first)))
        tx.on_reset(reset)
        stats = mgr._comm_stats
        assert _wait_for(lambda: stats.get("streams_completed") == 1)
        assert stats.get("streams_restarted") == 1
        assert stats.get("streams_completed") == 1
        second = {m.get(chunking._KEY_STREAM) for m in mgr.sent} - first
        assert len(second) == 1  # full replay under a fresh stream identity


class TestChunkingState:
    def _state(self, **kw):
        mgr = _FakeTxManager()
        defaults = dict(upload_chunk_bytes=64, chunk_window=2)
        defaults.update(kw)
        mgr.args = types.SimpleNamespace(**defaults)
        return mgr, ChunkingState(mgr)

    def test_negotiation_gates_chunking(self):
        mgr, state = self._state()
        msg = _inner_msg(sender=7, receiver=0)
        # peer has not advertised: whole-message fallback
        assert state.maybe_send_chunked(msg) is False
        hello = Message("hello", 0, 7)
        hello.add_params(CHUNK_OK_KEY, 1)
        state.observe(hello)
        assert state.peer_supports(0)
        assert state.maybe_send_chunked(_inner_msg(sender=7, receiver=0))
        assert _wait_for(lambda: mgr._comm_stats.get("streams_completed") == 1)

    def test_control_traffic_never_chunked(self):
        mgr, state = self._state()
        hello = Message("hello", 0, 7)
        hello.add_params(CHUNK_OK_KEY, 1)
        state.observe(hello)
        ctl = Message(2, 7, 0)
        ctl.add_params("some_flag", "x" * 500)  # big but not payload-keyed
        assert state.maybe_send_chunked(ctl) is False
        assert state.maybe_send_chunked(_frames()[0]) is False  # never re-chunk

    def test_hier_payload_is_chunkable(self):
        mgr, state = self._state()
        hello = Message("hello", 0, 7)
        hello.add_params(CHUNK_OK_KEY, 1)
        state.observe(hello)
        m = Message("hier_partial", 7, 0)
        m.add_params("hier_payload", b"e" * 500)
        assert state.maybe_send_chunked(m) is True

    def test_advertise_follows_receive_knob(self):
        _, state = self._state(chunk_receive=False)
        m = _inner_msg()
        state.advertise(m)
        assert m.get(CHUNK_OK_KEY) is None
        _, state2 = self._state()
        state2.advertise(m)
        assert m.get(CHUNK_OK_KEY) == 1


# ---------------------------------------------------------------------------
# Unit layer: deterministic retransmit backoff + the new fault kinds
# ---------------------------------------------------------------------------

class TestBackoffDeterminism:
    def _link(self, rank=1, seed=123, **kw):
        from fedml_tpu.core.distributed.comm_manager import _ReliableLink

        defaults = dict(max_retries=5, backoff_base_s=0.05,
                        backoff_max_s=0.3, jitter=0.25, backoff_seed=seed)
        defaults.update(kw)
        return _ReliableLink(rank, CommStats(), **defaults)

    def test_seeded_schedule_is_pinned_to_the_formula(self):
        link = self._link()
        rng = random.Random("123:1")
        expect = [min(0.05 * (2 ** a), 0.3) * (1.0 + 0.25 * rng.random())
                  for a in range(6)]
        assert [link._backoff(a) for a in range(6)] == expect

    def test_same_seed_reproduces_across_incarnations(self):
        a = [self._link()._backoff(i) for i in range(4)]
        b = [self._link()._backoff(i) for i in range(4)]
        assert a == b

    def test_ranks_decorrelate(self):
        a = [self._link(rank=1)._backoff(i) for i in range(4)]
        b = [self._link(rank=2)._backoff(i) for i in range(4)]
        assert a != b

    def test_unseeded_keeps_legacy_per_nonce_stream(self):
        a = [self._link(seed=None)._backoff(i) for i in range(4)]
        b = [self._link(seed=None)._backoff(i) for i in range(4)]
        assert a != b  # fresh nonce per link: not reproducible by design

    def test_manager_plumbs_backoff_seed_knob(self):
        class _Null(FedMLCommManager):
            def register_message_receive_handlers(self):
                pass

        LoopbackHub.reset()
        a = _ft._args("chunk-seed", 1, comm_backoff_seed=123)
        a.role, a.rank = "client", 1
        mgr = _Null(a, None, rank=1, size=1, backend="LOOPBACK")
        try:
            rng = random.Random("123:1")
            assert [mgr._link._rng.random() for _ in range(3)] == \
                [rng.random() for _ in range(3)]
            # ack frames must advertise the chunk capability: on pure
            # fan-in links acks are the only reverse traffic
            assert mgr._link.ack_decorator.__self__ is mgr._chunking
        finally:
            mgr.finish()
        # default: falls back to random_seed (0 in the harness config)
        LoopbackHub.reset()
        a2 = _ft._args("chunk-seed2", 1)
        a2.role, a2.rank = "client", 1
        mgr2 = _Null(a2, None, rank=1, size=1, backend="LOOPBACK")
        try:
            rng0 = random.Random("0:1")
            assert [mgr2._link._rng.random() for _ in range(3)] == \
                [rng0.random() for _ in range(3)]
        finally:
            mgr2.finish()


class TestChunkFaultKinds:
    """New chaos vocabulary: rides the TestFaultSeam stub harness."""

    def _seam(self, rules, seed=0):
        return _ft.TestFaultSeam._seam(_ft.TestFaultSeam(), rules, seed=seed)

    def test_kinds_registered_and_flight_triggered(self):
        from fedml_tpu.core.obs.flight import DUMP_EVENTS

        assert "mid_message_disconnect" in FAULT_KINDS
        assert "truncated_frame" in FAULT_KINDS
        assert "mid_message_disconnect" in DUMP_EVENTS
        assert "truncated_frame" in DUMP_EVENTS

    def test_disconnect_darkens_link_both_ways_then_heals(self):
        seam, inner, cap, stats = self._seam(
            [{"kind": "mid_message_disconnect", "msg_type": 3, "times": 1,
              "delay_s": 0.15}])
        seam.send_message(Message(3, 1, 0))  # trigger: the frame dies
        assert inner.sent == []
        assert stats.get("faults_disconnects") == 1
        seam.send_message(Message(2, 1, 0))          # dark: outbound dropped
        seam.receive_message("2", Message(2, 0, 1))  # dark: inbound dropped
        assert inner.sent == [] and cap.got == []
        assert stats.get("faults_dropped") == 3
        ready = Message("connection_ready", 1, 1)
        seam.receive_message("connection_ready", ready)  # exempt, even dark
        assert cap.got == [ready]
        time.sleep(0.2)
        m = Message(2, 1, 0)
        seam.send_message(m)  # carrier back
        assert inner.sent == [m]

    def test_truncated_frame_tears_chunks_and_passes_the_rest(self):
        seam, inner, _, stats = self._seam(
            [{"kind": "truncated_frame", "direction": "send", "times": 2}])
        frame = _frames()[0]
        before = frame.get(chunking._KEY_DATA)
        seam.send_message(frame)
        assert stats.get("faults_truncated") == 1
        torn = inner.sent[0]
        assert torn is not frame  # the retransmitter keeps the intact copy
        assert torn.get(chunking._KEY_DATA) == before[: len(before) // 2]
        assert frame.get(chunking._KEY_DATA) == before
        plain = Message(3, 1, 0)
        seam.send_message(plain)  # nothing to tear: forwarded unchanged
        assert inner.sent[1] is plain


# ---------------------------------------------------------------------------
# Topology layer: chunked rounds over the loopback transport
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def plain_reference():
    """Whole-message fault-free run: the model every chunked run must
    bit-match (chunking must never change what is computed)."""
    LoopbackHub.reset()
    history, final, stats = _ft._run_chaos_topology("chunk-plain", knobs={})
    assert len(history) == 2
    assert stats[1].get("chunks_sent", 0) == 0  # default-off knob
    return final


@pytest.fixture(scope="module")
def chunked_baseline(plain_reference):
    """Fault-free CHUNKED run: yields ``(final, stats, n_chunks)`` where
    n_chunks is the per-upload stream length (used to aim mid-stream
    faults at exact chunk offsets)."""
    LoopbackHub.reset()
    history, final, stats = _ft._run_chaos_topology(
        "chunk-base", knobs=_CHUNK_KNOBS)
    assert len(history) == 2
    assert _ft._trees_bit_identical(final, plain_reference), \
        "chunked round diverged from the whole-message round"
    # 2 rounds -> 2 upload streams per client
    n_chunks = stats[1]["chunks_sent"] // 2
    assert n_chunks >= 10, stats[1]
    return final, stats, n_chunks


def test_chunked_fault_free_negotiates_and_streams(chunked_baseline):
    _, stats, n_chunks = chunked_baseline
    for rank in (0, 1, 2, 3):
        assert stats[rank]["chunks_sent"] > 0, (rank, stats[rank])
        assert stats[rank]["streams_completed"] >= 2
    # fault-free: nothing was shed, restarted, or torn
    assert stats[0]["streams_shed"] == 0
    assert stats[0]["chunks_crc_bad"] == 0
    for rank in (1, 2, 3):
        assert stats[rank]["streams_restarted"] == 0


def test_resume_after_mid_message_disconnect(plain_reference,
                                             chunked_baseline):
    """The acceptance run: the link dies at 90% of client 1's round-0
    upload; after the dark window the stream RESUMES from its last acked
    chunk — under 20% of the message bytes re-sent, no stream restart,
    and a bit-identical final model."""
    _, _, n_chunks = chunked_baseline
    after = max(1, int(0.9 * n_chunks))
    plan = {"seed": 5, "rules": [
        {"kind": "mid_message_disconnect", "direction": "send", "sender": 1,
         "msg_type": CHUNK_TYPE, "round": 0, "after": after, "times": 1,
         "delay_s": 0.2}]}
    LoopbackHub.reset()
    history, final, stats = _ft._run_chaos_topology(
        "chunk-resume", fault_plan=plan, knobs=_CHUNK_KNOBS)
    assert len(history) == 2
    assert _ft._trees_bit_identical(final, plain_reference), \
        "resumed run diverged from the fault-free model"
    s1 = stats[1]
    assert s1["faults_disconnects"] == 1
    assert s1["faults_dropped"] >= 1
    assert s1["retransmits"] >= 1         # the unacked tail was re-sent...
    assert s1["streams_restarted"] == 0   # ...but the stream never restarted
    resent = s1["chunk_bytes_resent"]
    saved = s1["resume_bytes_saved"]
    assert resent > 0 and saved > 0
    total = resent + saved  # per-stream: total == resent + saved
    assert resent < 0.2 * total, \
        f"resume re-sent {resent}/{total} bytes ({100 * resent / total:.0f}%)"


def test_chunked_full_chaos_plan_bit_identical(plain_reference,
                                               chunked_baseline):
    """drop / duplicate / delay / reset / torn-frame / disconnect over the
    CHUNK vocabulary plus a client crash-and-rejoin, in one run: every
    fault heals at sub-message granularity and the final model still
    bit-matches the whole-message fault-free run."""
    _, _, n_chunks = chunked_baseline
    k = max(1, int(0.9 * n_chunks))
    plan = {"seed": 11, "rules": [
        {"kind": "drop", "direction": "send", "sender": 0, "receiver": 3,
         "msg_type": CHUNK_TYPE, "round": 1, "after": 2, "times": 1},
        {"kind": "reset", "direction": "send", "sender": 2,
         "msg_type": CHUNK_TYPE, "round": 0, "times": 1},
        {"kind": "duplicate", "direction": "send", "sender": 3,
         "msg_type": CHUNK_TYPE, "round": 0, "after": 1, "times": 1},
        {"kind": "delay", "direction": "send", "sender": 0, "receiver": 2,
         "msg_type": CHUNK_TYPE, "round": 1, "times": 1, "delay_s": 0.05},
        {"kind": "truncated_frame", "direction": "send", "sender": 1,
         "msg_type": CHUNK_TYPE, "round": 0, "after": 5, "times": 1},
        {"kind": "mid_message_disconnect", "direction": "send", "sender": 2,
         "msg_type": CHUNK_TYPE, "round": 1, "after": k, "times": 1,
         "delay_s": 0.2},
    ]}
    LoopbackHub.reset()
    history, final, stats = _ft._run_chaos_topology(
        "chunk-chaos", fault_plan=plan, crash_rank=1, knobs=_CHUNK_KNOBS)
    assert len(history) == 2
    assert _ft._trees_bit_identical(final, plain_reference), \
        "chunked chaos run diverged from the whole-message fault-free model"
    srv = stats[0]
    assert srv["rejoins"] >= 1            # crash-and-rejoin composes
    assert srv["faults_dropped"] >= 1     # dropped sync chunk...
    assert srv["retransmits"] >= 1        # ...healed per-chunk
    assert srv["faults_delayed"] >= 1
    assert srv["dup_dropped"] >= 1        # duplicated chunk deduped by msg-id
    assert srv["chunks_crc_bad"] >= 1     # torn frame rejected by crc...
    assert stats[1]["faults_truncated"] >= 1
    assert stats[2]["faults_reset"] >= 1  # chunk send retried synchronously
    assert stats[2]["retries"] >= 1
    assert stats[2]["faults_disconnects"] >= 1
    assert stats[3]["faults_duplicated"] >= 1


def test_negotiate_down_when_no_peer_advertises(plain_reference):
    """chunk_receive=False fleet-wide: senders keep upload_chunk_bytes set
    but no peer ever advertises, so every message goes whole — wire
    compatibility is the default, not an error path."""
    LoopbackHub.reset()
    knobs = {**_CHUNK_KNOBS, "chunk_receive": False}
    history, final, stats = _ft._run_chaos_topology("chunk-legacy",
                                                    knobs=knobs)
    assert len(history) == 2
    assert _ft._trees_bit_identical(final, plain_reference)
    for rank in (0, 1, 2, 3):
        assert stats[rank]["chunks_sent"] == 0, (rank, stats[rank])


def _run_mixed_topology(run_id, rank_knobs, n=3):
    """1 server + ``n`` silos where each rank can override the chunking
    knobs: the mixed-fleet interop leg (_run_chaos_topology applies one
    knob set to every rank)."""
    import threading as _threading

    def mk_args(rank, role):
        extra = dict(_CHUNK_KNOBS)
        extra.update(rank_knobs.get(rank, {}))
        a = _ft._args(run_id, n, **extra)
        a.role, a.rank = role, rank
        return fedml_tpu.init(a, should_init_logs=False)

    from fedml_tpu.cross_silo.client.client import Client
    from fedml_tpu.cross_silo.server.server import Server

    args_s = mk_args(0, "server")
    ds, out_dim = fedml_tpu.data.load(args_s)
    server = Server(args_s, None, ds, fedml_tpu.models.create(args_s, out_dim))
    clients = {}
    for rank in range(1, n + 1):
        a = mk_args(rank, "client")
        ds_c, od = fedml_tpu.data.load(a)
        clients[rank] = Client(a, None, ds_c,
                               fedml_tpu.models.create(a, od))
    threads = [_threading.Thread(target=c.run, daemon=True)
               for c in clients.values()]
    for t in threads:
        t.start()
    history = _ft._run_server_bounded(server)
    _ft._join_all(threads)
    final = server.server_manager.aggregator.get_global_model_params()
    stats = {0: server.server_manager.comm_stats_snapshot()}
    for r, c in clients.items():
        stats[r] = c.manager.comm_stats_snapshot()
    return history, final, stats


def test_mixed_fleet_interop_bit_identical(plain_reference):
    """Negotiate-down is PER LINK: client 2 keeps whole-message uploads
    (chunked sending off) while the rest of the fleet streams chunks —
    both coexist in one round and the result is unchanged."""
    LoopbackHub.reset()
    history, final, stats = _run_mixed_topology(
        "chunk-mixed", {2: {"upload_chunk_bytes": 0}})
    assert len(history) == 2
    assert _ft._trees_bit_identical(final, plain_reference)
    assert stats[1]["chunks_sent"] > 0
    assert stats[3]["chunks_sent"] > 0
    assert stats[2]["chunks_sent"] == 0        # whole-message uploads...
    assert stats[2]["chunks_received"] > 0     # ...but chunked syncs land
    assert stats[0]["streams_completed"] >= 4  # server still fans chunks in


def test_hierarchy_edge_folds_chunked_uploads():
    """Edge-tier chunking end to end: round 0 runs whole-message (a leaf
    has never heard from its edge), but the edge's ACKS carry the
    chunk_ok advert back down the fan-in link, so round-1 uploads stream
    as chunks — and survive a mid-stream disconnect — while the edge
    folds completed uploads with other leaves' chunks still in flight.
    Both rounds close bit-identical to the flat fold."""
    import test_hierarchy as _th

    n = 8
    ups = _th._updates(n, seed=77)
    plan = _th.HierarchyPlan(n_leaves=n, levels=2, edge_fanout=4)
    flat = plan.aggregate(ups, mode="mean")
    chaos = {"seed": 3, "rules": [
        {"kind": "mid_message_disconnect", "direction": "send",
         "msg_type": CHUNK_TYPE, "after": 4, "times": 1, "delay_s": 0.2}]}
    args = _th._mkargs("hier-chunk", fault_plan=chaos,
                       upload_chunk_bytes=64, chunk_window=2,
                       comm_backoff_base_s=0.25, comm_backoff_max_s=0.5)
    tree = _th._Tree(args, plan)
    try:
        tree.send(ups, round_idx=0)
        got0, weight0, k0 = tree.result(timeout=90)
        assert _th._bit_identical(got0, flat)
        assert sum(m.comm_stats_snapshot()["chunks_sent"]
                   for m in tree.leaves) == 0  # capability not yet seen

        tree.done.clear()
        tree.send(ups, round_idx=1)
        got1, weight1, k1 = tree.result(timeout=90)
        assert _th._bit_identical(got1, flat), \
            "chunked hierarchy round diverged from the flat fold"
        assert weight1 == sum(u[0] for u in ups) and k1 == n
        leaf_stats = [m.comm_stats_snapshot() for m in tree.leaves]
        assert sum(s["chunks_sent"] for s in leaf_stats) > 0
        assert sum(s["streams_completed"] for s in leaf_stats) == n
        assert sum(e.comm_stats_snapshot()["chunks_received"]
                   for e in tree.edges) > 0
        # every leaf's stream crossed its own disconnect seam and resumed
        assert sum(s["faults_disconnects"] for s in leaf_stats) >= 1
        assert tree.root.dup_forwards == 0
        assert tree.root.rounds_closed == 2
    finally:
        tree.close()


def test_server_kill_mid_upload_replays_exactly_once(plain_reference,
                                                     chunked_baseline,
                                                     tmp_path):
    """The server is killed mid-round-0 uploads, BETWEEN chunks of live
    streams: the journal (chunk records written before each ack) restores
    the partial reassembly state, the clients' retransmitters deliver the
    unacked tails, and the fleet registry still counts every report
    exactly once with a bit-identical final model."""
    _, _, n_chunks = chunked_baseline
    after = n_chunks + max(1, n_chunks // 2)  # mid-stream, mid-cohort
    plan = {"seed": 7, "rules": [
        {"kind": "server_kill", "direction": "recv", "receiver": 0,
         "msg_type": CHUNK_TYPE, "round": 0, "after": after, "times": 1}]}
    # a longer retry budget so chunk retransmits outlive the restart gap
    knobs = {**_CHUNK_KNOBS, "comm_max_retries": 20}
    LoopbackHub.reset()
    out = _ft._run_server_kill_topology("chunk-kill", tmp_path / "srv",
                                        fault_plan=plan, knobs=knobs)
    _ft._assert_recovered(*out, plain_reference)
    history, final, stats, restarts, killed_stats, server = out
    # the dead incarnation really was mid-upload...
    assert sum(s.get("chunks_received", 0) for s in killed_stats) >= 1
    # ...and journaled its partial streams chunk-by-chunk before dying
    from fedml_tpu.core.checkpoint import UpdateJournal

    journal = UpdateJournal(str(tmp_path / "srv" / "journal"))
    records, _ = journal.replay(0)
    assert any(r.get("kind") == "chunk" for r in records), \
        "no chunk records journaled before the kill"
    # the surviving incarnation finished the fan-in over chunks
    assert stats[0]["chunks_received"] >= 1
    assert stats[0]["streams_completed"] >= 3


# ---------------------------------------------------------------------------
# Topology layer: buffer-pressure shedding end to end
# ---------------------------------------------------------------------------

class _BlobReceiver(FedMLCommManager):
    """Raw fan-in endpoint with a tiny reassembly budget."""

    def __init__(self, args, size, got):
        self._got = got
        self._n_peers = size
        super().__init__(args, None, rank=0, size=size, backend="LOOPBACK")

    def register_message_receive_handlers(self):
        self.register_message_receive_handler("connection_ready",
                                              self._on_ready)
        self.register_message_receive_handler("blob", self._got.append)

    def _on_ready(self, msg):
        for r in range(1, self._n_peers + 1):  # advertise chunk_ok to peers
            self.send_message(Message("hello", 0, r))


class _BlobSender(FedMLCommManager):
    def __init__(self, args, rank, size):
        super().__init__(args, None, rank=rank, size=size, backend="LOOPBACK")

    def register_message_receive_handlers(self):
        self.register_message_receive_handler("connection_ready",
                                              lambda m: None)
        self.register_message_receive_handler("hello", lambda m: None)


def test_buffer_pressure_sheds_and_restarts_end_to_end():
    """Two concurrent streams against a 300-byte reassembly budget: the
    receiver sheds the oldest incomplete stream (withholding nothing it
    acked — the victim's sender gets a reset and REPLAYS the stream from
    scratch), and both blobs still land exactly once.

    A scripted drop stalls sender 1 mid-stream so sender 2's burst is
    guaranteed to catch it incomplete — the shed is deterministic, not a
    scheduling accident."""
    LoopbackHub.reset()
    run_id = "chunk-shed"
    base = dict(_CHUNK_KNOBS)

    def args_for(rank, **extra):
        a = _ft._args(run_id, 2, **{**base, **extra})
        a.role = "server" if rank == 0 else "client"
        a.rank = rank
        return a

    got = []
    rx = _BlobReceiver(args_for(0, upload_chunk_bytes=0,
                                chunk_buffer_bytes=300), size=2, got=got)
    drop_plan = {"seed": 1, "rules": [
        {"kind": "drop", "direction": "send", "sender": 1,
         "msg_type": CHUNK_TYPE, "after": 5, "times": 1}]}
    tx1 = _BlobSender(args_for(1, fault_plan=drop_plan), rank=1, size=2)
    tx2 = _BlobSender(args_for(2), rank=2, size=2)
    threads = [threading.Thread(target=m.run, daemon=True)
               for m in (rx, tx1, tx2)]
    for t in threads:
        t.start()
    try:
        for tx in (tx1, tx2):
            deadline = time.time() + 20
            while time.time() < deadline and not \
                    tx._chunking.peer_supports(0):
                time.sleep(0.01)
            assert tx._chunking.peer_supports(0), "capability never landed"

        big = Message("blob", 1, 0)
        big.add_params(Message.MSG_ARG_KEY_MODEL_PARAMS, b"a" * 1600)
        t_big = threading.Thread(target=lambda: tx1.send_message(big),
                                 daemon=True)
        t_big.start()
        # wait for the scripted drop: sender 1 is now stalled mid-stream
        deadline = time.time() + 20
        while time.time() < deadline and \
                tx1.comm_stats_snapshot()["faults_dropped"] == 0:
            time.sleep(0.01)
        assert tx1.comm_stats_snapshot()["faults_dropped"] >= 1

        small = Message("blob", 2, 0)
        small.add_params(Message.MSG_ARG_KEY_MODEL_PARAMS, b"b" * 150)
        tx2.send_message(small)  # pushes the receiver over its budget

        deadline = time.time() + 60
        while time.time() < deadline and len(got) < 2:
            time.sleep(0.05)
        assert len(got) == 2, f"blobs delivered: {len(got)}"
        time.sleep(0.5)  # settle: retransmits/dups must not re-dispatch
        assert len(got) == 2
        payloads = sorted(
            (int(m.get_sender_id()),
             m.get(Message.MSG_ARG_KEY_MODEL_PARAMS)) for m in got)
        assert payloads == [(1, b"a" * 1600), (2, b"b" * 150)]
        assert rx.comm_stats_snapshot()["streams_shed"] >= 1
        assert tx1.comm_stats_snapshot()["streams_restarted"] >= 1
        t_big.join(timeout=30)
        assert not t_big.is_alive()
    finally:
        for m in (tx1, tx2, rx):
            try:
                m.finish()
            except Exception:
                pass
        _ft._join_all(threads, timeout_s=30)
