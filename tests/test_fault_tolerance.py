"""Straggler/fault tolerance in the cross-silo round (beyond-reference:
the reference server blocks a round forever on a dead client — SURVEY.md §5
'failure detection').  With ``round_timeout_s`` set, a silo that goes
silent after its ONLINE handshake must not wedge training: the server
closes each round with the cohort that responded and drops stale uploads
by round tag."""

from __future__ import annotations

import threading
import time

import pytest

import fedml_tpu
from fedml_tpu.arguments import Arguments
from fedml_tpu.core.distributed.comm_manager import FedMLCommManager
from fedml_tpu.core.distributed.communication.loopback import LoopbackHub
from fedml_tpu.core.distributed.communication.message import Message
from fedml_tpu.cross_silo.message_define import MyMessage


def _args(run_id: str, n_clients: int, **extra):
    cfg = {
        "common_args": {"training_type": "cross_silo", "random_seed": 0, "run_id": run_id},
        "data_args": {"dataset": "synthetic", "data_cache_dir": "", "partition_method": "homo",
                      "synthetic_train_size": 240},
        "model_args": {"model": "lr"},
        "train_args": {
            "federated_optimizer": "FedAvg",
            "client_num_in_total": n_clients,
            "client_num_per_round": n_clients,
            "comm_round": 2,
            "epochs": 1,
            "batch_size": 16,
            "client_optimizer": "sgd",
            "learning_rate": 0.1,
            **extra,
        },
        "validation_args": {"frequency_of_the_test": 1},
        "comm_args": {"backend": "LOOPBACK"},
    }
    return Arguments.from_dict(cfg).validate()


class _SilentClient(FedMLCommManager):
    """A faulty silo: completes the ONLINE handshake, then never trains —
    the failure mode round_timeout_s exists for."""

    def __init__(self, args, rank, size):
        super().__init__(args, None, rank, size, backend="LOOPBACK")

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler("connection_ready", self._on_ready)
        self.register_message_receive_handler(MyMessage.MSG_TYPE_S2C_FINISH, self._on_finish)

    def _on_ready(self, msg: Message) -> None:
        m = Message(MyMessage.MSG_TYPE_C2S_CLIENT_STATUS, self.rank, 0)
        m.add_params(MyMessage.MSG_ARG_KEY_CLIENT_STATUS, MyMessage.CLIENT_STATUS_ONLINE)
        self.send_message(m)

    def _on_finish(self, msg: Message) -> None:
        self.finish()


def _build_client(run_id: str, rank: int, n_clients: int, **extra):
    args_c = _args(run_id, n_clients, **extra)
    args_c.role = "client"
    args_c.rank = rank
    args_c = fedml_tpu.init(args_c, should_init_logs=False)
    ds, out_dim = fedml_tpu.data.load(args_c)
    from fedml_tpu.cross_silo.client.client import Client

    return Client(args_c, None, ds, fedml_tpu.models.create(args_c, out_dim))




def _join_all(threads, timeout_s=120):
    """Join with a bound; on failure dump every thread so a wedge is
    diagnosable from CI output instead of an opaque hang/timeout."""
    import faulthandler

    deadline = time.time() + timeout_s
    for t in threads:
        t.join(timeout=max(1.0, deadline - time.time()))
    alive = [t for t in threads if t.is_alive()]
    if alive:
        faulthandler.dump_traceback()
        raise AssertionError(f"threads still alive after {timeout_s}s: {alive}")


def _run_server_bounded(server, timeout_s=150):
    """Run the server with a hard wall-clock bound: a wedged round must FAIL
    the test (with a thread dump), never hang CI forever."""
    import faulthandler

    out = {}

    def _target():
        try:
            out["history"] = server.run()
        except BaseException as e:  # surfaced below, not via excepthook
            out["exc"] = e

    t = threading.Thread(target=_target, daemon=True)
    t.start()
    t.join(timeout=timeout_s)
    if t.is_alive():
        faulthandler.dump_traceback()
        raise AssertionError(f"server.run() wedged for {timeout_s}s")
    if "exc" in out:
        raise out["exc"]
    return out["history"]


def test_round_survives_silent_silo():
    """1 server + 2 live silos + 1 silent silo: with round_timeout_s the
    run completes, aggregating the 2 live silos each round."""
    LoopbackHub.reset()
    n = 3
    extra = dict(round_timeout_s=3.0, round_timeout_min_clients=2)
    args_s = _args("ft-1", n, **extra)
    args_s.role = "server"
    args_s.rank = 0
    args_s = fedml_tpu.init(args_s, should_init_logs=False)
    ds, out_dim = fedml_tpu.data.load(args_s)
    from fedml_tpu.cross_silo.server.server import Server

    server = Server(args_s, None, ds, fedml_tpu.models.create(args_s, out_dim))

    live = [_build_client("ft-1", r, n, **extra) for r in (1, 2)]
    silent = _SilentClient(_args("ft-1", n, **extra), rank=3, size=n + 1)

    threads = [threading.Thread(target=c.run, daemon=True) for c in live]
    threads.append(threading.Thread(target=silent.run, daemon=True))
    for t in threads:
        t.start()
    t0 = time.time()
    history = _run_server_bounded(server)
    assert len(history) == 2
    assert 0.0 <= history[-1]["test_acc"] <= 1.0
    # bounded, not fast: under full-suite load the live silos' first XLA
    # compiles can outlast several 3s timer re-arms (below the min-client
    # floor the timer re-arms, so correctness never depends on timing);
    # the bound only proves no reference-style wait-forever wedge
    assert time.time() - t0 < 120
    _join_all(threads)


def test_all_silos_alive_is_unchanged():
    """With every silo healthy the timeout path must never fire — rounds
    close on the all-received fast path exactly as without the knob."""
    LoopbackHub.reset()
    n = 2
    extra = dict(round_timeout_s=60.0)
    args_s = _args("ft-2", n, **extra)
    args_s.role = "server"
    args_s.rank = 0
    args_s = fedml_tpu.init(args_s, should_init_logs=False)
    ds, out_dim = fedml_tpu.data.load(args_s)
    from fedml_tpu.cross_silo.server.server import Server

    server = Server(args_s, None, ds, fedml_tpu.models.create(args_s, out_dim))
    clients = [_build_client("ft-2", r, n, **extra) for r in (1, 2)]
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    t0 = time.time()
    history = _run_server_bounded(server)
    assert time.time() - t0 < 50  # no 60s timeout ever fired
    assert len(history) == 2
    _join_all(threads)


def test_round_survives_silent_silo_over_mqtt(tmp_path):
    """Straggler tolerance is transport-independent: the same silent-silo
    scenario over the MQTT broker backend (whose last-will liveness plane
    coexists with the round timer) completes with the live cohort."""
    from fedml_tpu.core.distributed.communication.mqtt_s3.broker import LocalBroker

    broker = LocalBroker().start()
    try:
        n = 3
        extra = dict(round_timeout_s=3.0, round_timeout_min_clients=2,
                     mqtt_host="127.0.0.1", mqtt_port=broker.port,
                     s3_blob_root=str(tmp_path / "blobs"))

        def mqtt_args(rank, role):
            # comm_args flattens LAST, so backend must be set after _args
            a = _args("ft-mqtt", n, **extra)
            a.backend = "MQTT_S3"
            a.role, a.rank = role, rank
            return fedml_tpu.init(a, should_init_logs=False)

        args_s = mqtt_args(0, "server")
        ds, out_dim = fedml_tpu.data.load(args_s)
        from fedml_tpu.cross_silo.client.client import Client
        from fedml_tpu.cross_silo.server.server import Server

        server = Server(args_s, None, ds, fedml_tpu.models.create(args_s, out_dim))
        live = []
        for r in (1, 2):
            a = mqtt_args(r, "client")
            ds_c, od_c = fedml_tpu.data.load(a)
            live.append(Client(a, None, ds_c, fedml_tpu.models.create(a, od_c)))

        class _SilentMqtt(_SilentClient):
            def __init__(self, args, rank, size):
                FedMLCommManager.__init__(self, args, None, rank, size,
                                          backend="MQTT_S3")

        silent = _SilentMqtt(mqtt_args(3, "client"), rank=3, size=n + 1)
        threads = [threading.Thread(target=c.run, daemon=True) for c in live]
        threads.append(threading.Thread(target=silent.run, daemon=True))
        for t in threads:
            t.start()
        t0 = time.time()
        history = _run_server_bounded(server)
        assert len(history) == 2
        # bounded, not fast (see test_round_survives_silent_silo)
        assert time.time() - t0 < 120
        _join_all(threads)
    finally:
        broker.stop()


class TestStaleUploadPolicy:
    """The round-tag matrix of RoundTimeoutMixin._is_stale_upload: tagged
    uploads match by round; untagged uploads are accepted only with
    straggler tolerance OFF (reference semantics — rounds cannot overlap
    when the server waits forever) and DROPPED with it on, where a
    round-less late upload is exactly the wrong-round corruption the tag
    prevents."""

    def _mixin(self, timeout_s):
        from fedml_tpu.core.distributed.straggler import RoundTimeoutMixin

        class _M(RoundTimeoutMixin):
            pass

        m = _M()

        class _A:
            round_timeout_s = timeout_s
            round_idx = 4

        m.init_straggler_tolerance(_A())
        m.args = _A()
        return m

    def test_matching_tag_accepted(self):
        assert self._mixin(3.0)._is_stale_upload(4, sender=1) is False

    def test_mismatched_tag_dropped(self):
        assert self._mixin(3.0)._is_stale_upload(3, sender=1) is True

    def test_untagged_accepted_when_tolerance_off(self):
        assert self._mixin(0)._is_stale_upload(None, sender=1) is False

    def test_untagged_accepted_before_any_timeout_close(self):
        # while every round still closes with its full cohort no upload can
        # be stale — a legacy untagged fleet must keep working (dropping
        # outright would livelock below the min-client floor)
        assert self._mixin(3.0)._is_stale_upload(None, sender=1) is False

    def test_untagged_dropped_after_first_timeout_close(self):
        m = self._mixin(3.0)
        m._had_timeout_close = True
        assert m._is_stale_upload(None, sender=1) is True

    def test_mismatched_tag_dropped_even_without_tolerance(self):
        # a tagged client never regresses: the tag check is independent of
        # the timer knob
        assert self._mixin(0)._is_stale_upload(2, sender=1) is True
