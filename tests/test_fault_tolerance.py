"""Straggler/fault tolerance in the cross-silo round (beyond-reference:
the reference server blocks a round forever on a dead client — SURVEY.md §5
'failure detection').  With ``round_timeout_s`` set, a silo that goes
silent after its ONLINE handshake must not wedge training: the server
closes each round with the cohort that responded and drops stale uploads
by round tag.

Plus the chaos suite for the self-healing transport layer: scripted,
seeded fault plans (drop / delay / duplicate / reset / crash-and-rejoin)
injected at the transport seam, after which every backend must complete
all rounds and converge to the BIT-IDENTICAL final model of a fault-free
run — faults may cost retries, never correctness."""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.arguments import Arguments
from fedml_tpu.core.distributed.comm_manager import FedMLCommManager
from fedml_tpu.core.distributed.communication.loopback import LoopbackHub
from fedml_tpu.core.distributed.communication.message import Message
from fedml_tpu.cross_silo.message_define import MyMessage


def _args(run_id: str, n_clients: int, **extra):
    cfg = {
        "common_args": {"training_type": "cross_silo", "random_seed": 0, "run_id": run_id},
        "data_args": {"dataset": "synthetic", "data_cache_dir": "", "partition_method": "homo",
                      "synthetic_train_size": 240},
        "model_args": {"model": "lr"},
        "train_args": {
            "federated_optimizer": "FedAvg",
            "client_num_in_total": n_clients,
            "client_num_per_round": n_clients,
            "comm_round": 2,
            "epochs": 1,
            "batch_size": 16,
            "client_optimizer": "sgd",
            "learning_rate": 0.1,
            **extra,
        },
        "validation_args": {"frequency_of_the_test": 1},
        "comm_args": {"backend": "LOOPBACK"},
    }
    return Arguments.from_dict(cfg).validate()


class _SilentClient(FedMLCommManager):
    """A faulty silo: completes the ONLINE handshake, then never trains —
    the failure mode round_timeout_s exists for."""

    def __init__(self, args, rank, size):
        super().__init__(args, None, rank, size, backend="LOOPBACK")

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler("connection_ready", self._on_ready)
        self.register_message_receive_handler(MyMessage.MSG_TYPE_S2C_FINISH, self._on_finish)

    def _on_ready(self, msg: Message) -> None:
        m = Message(MyMessage.MSG_TYPE_C2S_CLIENT_STATUS, self.rank, 0)
        m.add_params(MyMessage.MSG_ARG_KEY_CLIENT_STATUS, MyMessage.CLIENT_STATUS_ONLINE)
        self.send_message(m)

    def _on_finish(self, msg: Message) -> None:
        self.finish()


def _build_client(run_id: str, rank: int, n_clients: int, **extra):
    args_c = _args(run_id, n_clients, **extra)
    args_c.role = "client"
    args_c.rank = rank
    args_c = fedml_tpu.init(args_c, should_init_logs=False)
    ds, out_dim = fedml_tpu.data.load(args_c)
    from fedml_tpu.cross_silo.client.client import Client

    return Client(args_c, None, ds, fedml_tpu.models.create(args_c, out_dim))




def _join_all(threads, timeout_s=120):
    """Join with a bound; on failure dump every thread so a wedge is
    diagnosable from CI output instead of an opaque hang/timeout."""
    import faulthandler

    deadline = time.time() + timeout_s
    for t in threads:
        t.join(timeout=max(1.0, deadline - time.time()))
    alive = [t for t in threads if t.is_alive()]
    if alive:
        faulthandler.dump_traceback()
        raise AssertionError(f"threads still alive after {timeout_s}s: {alive}")


def _run_server_bounded(server, timeout_s=150):
    """Run the server with a hard wall-clock bound: a wedged round must FAIL
    the test (with a thread dump), never hang CI forever."""
    import faulthandler

    out = {}

    def _target():
        try:
            out["history"] = server.run()
        except BaseException as e:  # surfaced below, not via excepthook
            out["exc"] = e

    t = threading.Thread(target=_target, daemon=True)
    t.start()
    t.join(timeout=timeout_s)
    if t.is_alive():
        faulthandler.dump_traceback()
        raise AssertionError(f"server.run() wedged for {timeout_s}s")
    if "exc" in out:
        raise out["exc"]
    return out["history"]


def test_round_survives_silent_silo():
    """1 server + 2 live silos + 1 silent silo: with round_timeout_s the
    run completes, aggregating the 2 live silos each round."""
    LoopbackHub.reset()
    n = 3
    extra = dict(round_timeout_s=3.0, round_timeout_min_clients=2)
    args_s = _args("ft-1", n, **extra)
    args_s.role = "server"
    args_s.rank = 0
    args_s = fedml_tpu.init(args_s, should_init_logs=False)
    ds, out_dim = fedml_tpu.data.load(args_s)
    from fedml_tpu.cross_silo.server.server import Server

    server = Server(args_s, None, ds, fedml_tpu.models.create(args_s, out_dim))

    live = [_build_client("ft-1", r, n, **extra) for r in (1, 2)]
    silent = _SilentClient(_args("ft-1", n, **extra), rank=3, size=n + 1)

    threads = [threading.Thread(target=c.run, daemon=True) for c in live]
    threads.append(threading.Thread(target=silent.run, daemon=True))
    for t in threads:
        t.start()
    t0 = time.time()
    history = _run_server_bounded(server)
    assert len(history) == 2
    assert 0.0 <= history[-1]["test_acc"] <= 1.0
    # bounded, not fast: under full-suite load the live silos' first XLA
    # compiles can outlast several 3s timer re-arms (below the min-client
    # floor the timer re-arms, so correctness never depends on timing);
    # the bound only proves no reference-style wait-forever wedge
    assert time.time() - t0 < 120
    _join_all(threads)


def test_all_silos_alive_is_unchanged():
    """With every silo healthy the timeout path must never fire — rounds
    close on the all-received fast path exactly as without the knob."""
    LoopbackHub.reset()
    n = 2
    extra = dict(round_timeout_s=60.0)
    args_s = _args("ft-2", n, **extra)
    args_s.role = "server"
    args_s.rank = 0
    args_s = fedml_tpu.init(args_s, should_init_logs=False)
    ds, out_dim = fedml_tpu.data.load(args_s)
    from fedml_tpu.cross_silo.server.server import Server

    server = Server(args_s, None, ds, fedml_tpu.models.create(args_s, out_dim))
    clients = [_build_client("ft-2", r, n, **extra) for r in (1, 2)]
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    t0 = time.time()
    history = _run_server_bounded(server)
    assert time.time() - t0 < 50  # no 60s timeout ever fired
    assert len(history) == 2
    _join_all(threads)


def test_round_survives_silent_silo_over_mqtt(tmp_path):
    """Straggler tolerance is transport-independent: the same silent-silo
    scenario over the MQTT broker backend (whose last-will liveness plane
    coexists with the round timer) completes with the live cohort."""
    from fedml_tpu.core.distributed.communication.mqtt_s3.broker import LocalBroker

    broker = LocalBroker().start()
    try:
        n = 3
        extra = dict(round_timeout_s=3.0, round_timeout_min_clients=2,
                     mqtt_host="127.0.0.1", mqtt_port=broker.port,
                     s3_blob_root=str(tmp_path / "blobs"))

        def mqtt_args(rank, role):
            # comm_args flattens LAST, so backend must be set after _args
            a = _args("ft-mqtt", n, **extra)
            a.backend = "MQTT_S3"
            a.role, a.rank = role, rank
            return fedml_tpu.init(a, should_init_logs=False)

        args_s = mqtt_args(0, "server")
        ds, out_dim = fedml_tpu.data.load(args_s)
        from fedml_tpu.cross_silo.client.client import Client
        from fedml_tpu.cross_silo.server.server import Server

        server = Server(args_s, None, ds, fedml_tpu.models.create(args_s, out_dim))
        live = []
        for r in (1, 2):
            a = mqtt_args(r, "client")
            ds_c, od_c = fedml_tpu.data.load(a)
            live.append(Client(a, None, ds_c, fedml_tpu.models.create(a, od_c)))

        class _SilentMqtt(_SilentClient):
            def __init__(self, args, rank, size):
                FedMLCommManager.__init__(self, args, None, rank, size,
                                          backend="MQTT_S3")

        silent = _SilentMqtt(mqtt_args(3, "client"), rank=3, size=n + 1)
        threads = [threading.Thread(target=c.run, daemon=True) for c in live]
        threads.append(threading.Thread(target=silent.run, daemon=True))
        for t in threads:
            t.start()
        t0 = time.time()
        history = _run_server_bounded(server)
        assert len(history) == 2
        # bounded, not fast (see test_round_survives_silent_silo)
        assert time.time() - t0 < 120
        _join_all(threads)
    finally:
        broker.stop()


class TestStaleUploadPolicy:
    """The round-tag matrix of RoundTimeoutMixin._is_stale_upload: tagged
    uploads match by round; untagged uploads are accepted only with
    straggler tolerance OFF (reference semantics — rounds cannot overlap
    when the server waits forever) and DROPPED with it on, where a
    round-less late upload is exactly the wrong-round corruption the tag
    prevents."""

    def _mixin(self, timeout_s):
        from fedml_tpu.core.distributed.straggler import RoundTimeoutMixin

        class _M(RoundTimeoutMixin):
            pass

        m = _M()

        class _A:
            round_timeout_s = timeout_s
            round_idx = 4

        m.init_straggler_tolerance(_A())
        m.args = _A()
        return m

    def test_matching_tag_accepted(self):
        assert self._mixin(3.0)._is_stale_upload(4, sender=1) is False

    def test_mismatched_tag_dropped(self):
        assert self._mixin(3.0)._is_stale_upload(3, sender=1) is True

    def test_untagged_accepted_when_tolerance_off(self):
        assert self._mixin(0)._is_stale_upload(None, sender=1) is False

    def test_untagged_accepted_before_any_timeout_close(self):
        # while every round still closes with its full cohort no upload can
        # be stale — a legacy untagged fleet must keep working (dropping
        # outright would livelock below the min-client floor)
        assert self._mixin(3.0)._is_stale_upload(None, sender=1) is False

    def test_untagged_dropped_after_first_timeout_close(self):
        m = self._mixin(3.0)
        m._had_timeout_close = True
        assert m._is_stale_upload(None, sender=1) is True

    def test_mismatched_tag_dropped_even_without_tolerance(self):
        # a tagged client never regresses: the tag check is independent of
        # the timer knob
        assert self._mixin(0)._is_stale_upload(2, sender=1) is True


# ---------------------------------------------------------------------------
# Chaos suite: the self-healing transport layer under scripted fault plans
# ---------------------------------------------------------------------------

# knobs every chaos run uses: retries ON (the layer under test), small
# backoffs so recovery fits a unit-test budget
_CHAOS_KNOBS = dict(
    comm_max_retries=5,
    comm_backoff_base_s=0.05,
    comm_backoff_max_s=0.3,
)


def _full_chaos_plan():
    """One plan exercising every fault kind (crash-and-rejoin is scripted by
    the harness, not the plan): msg_type 2 = SYNC_MODEL, 3 = model upload."""
    return {
        "seed": 7,
        "rules": [
            # in-flight loss of a model sync: healed by ack/retransmit
            {"kind": "drop", "direction": "send", "sender": 0, "receiver": 3,
             "msg_type": 2, "round": 1, "times": 1},
            # peer RST on an upload: healed by the synchronous send retry
            {"kind": "reset", "direction": "send", "sender": 2, "msg_type": 3,
             "round": 0, "times": 1},
            # duplicated upload: receive-side dedup must make it invisible
            {"kind": "duplicate", "direction": "send", "sender": 3,
             "msg_type": 3, "round": 0, "times": 1},
            # congested path: a late sync must not corrupt the round
            {"kind": "delay", "direction": "send", "sender": 0, "receiver": 2,
             "msg_type": 2, "round": 1, "times": 1, "delay_s": 0.05},
        ],
    }


def _trees_bit_identical(a, b) -> bool:
    import jax

    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y)) for x, y in zip(la, lb)
    )


def _run_chaos_topology(run_id, backend="LOOPBACK", n=3, fault_plan=None,
                        crash_rank=None, comm_extra=None, knobs=None):
    """1 server + ``n`` silos over ``backend``; optionally a scripted hard
    crash of silo ``crash_rank`` right after its round-0 upload, followed by
    a fresh incarnation that must rejoin mid-run.  Returns
    ``(history, final_model_params, {rank: comm_stats})``."""
    extra = dict(knobs if knobs is not None else _CHAOS_KNOBS)
    if fault_plan is not None:
        extra["fault_plan"] = fault_plan
    comm_extra = comm_extra or {}

    def mk_args(rank, role):
        a = _args(run_id, n, **extra)
        for k, v in comm_extra.items():
            setattr(a, k, v)
        a.backend = backend
        a.role, a.rank = role, rank
        return fedml_tpu.init(a, should_init_logs=False)

    from fedml_tpu.cross_silo.client.client import Client
    from fedml_tpu.cross_silo.server.server import Server

    args_s = mk_args(0, "server")
    ds, out_dim = fedml_tpu.data.load(args_s)
    server = Server(args_s, None, ds, fedml_tpu.models.create(args_s, out_dim))

    def build_client(rank):
        a = mk_args(rank, "client")
        ds_c, od = fedml_tpu.data.load(a)
        return Client(a, None, ds_c, fedml_tpu.models.create(a, od))

    clients = {r: build_client(r) for r in range(1, n + 1)}

    if crash_rank is not None:
        mgr = clients[crash_rank].manager
        orig_send = mgr.send_model_to_server

        def crash_send(receive_id, weights, n_samples, _mgr=mgr, _orig=orig_send):
            _orig(receive_id, weights, n_samples)
            if _mgr.round_idx == 0:
                _mgr.finish()  # hard death: transport torn down, no FINISH

        mgr.send_model_to_server = crash_send

    threads = {r: threading.Thread(target=c.run, daemon=True)
               for r, c in clients.items()}
    for t in threads.values():
        t.start()

    rejoin_err = []

    def rejoin():
        try:
            threads[crash_rank].join(timeout=90)
            assert not threads[crash_rank].is_alive(), \
                "crash incarnation did not exit"
            if backend == "LOOPBACK":
                # the crash analog for the queue transport: in-flight frames
                # die and the rejoined incarnation gets a fresh mailbox
                LoopbackHub.sever(run_id, crash_rank)
            c2 = None
            for _ in range(40):  # dead incarnation's port may still be freeing
                try:
                    c2 = build_client(crash_rank)
                    break
                except OSError:
                    time.sleep(0.25)
            assert c2 is not None, "rejoined incarnation could not rebind"
            clients[crash_rank] = c2
            threads[crash_rank] = threading.Thread(target=c2.run, daemon=True)
            threads[crash_rank].start()
        except BaseException as e:  # surfaced by the main thread below
            rejoin_err.append(e)

    rejoin_thread = None
    if crash_rank is not None:
        rejoin_thread = threading.Thread(target=rejoin, daemon=True)
        rejoin_thread.start()

    try:
        history = _run_server_bounded(server)
    finally:
        if rejoin_err:
            raise rejoin_err[0]
    if rejoin_thread is not None:
        rejoin_thread.join(timeout=120)
        if rejoin_err:
            raise rejoin_err[0]
    _join_all(list(threads.values()))

    final = server.server_manager.aggregator.get_global_model_params()
    stats = {0: server.server_manager.comm_stats_snapshot()}
    for r, c in clients.items():
        stats[r] = c.manager.comm_stats_snapshot()
    return history, final, stats


@pytest.fixture(scope="module")
def fault_free_final_model():
    """The fault-free reference run every chaos run must bit-match (shared
    across the matrix: the final model is a pure function of config, not of
    transport weather — that is the claim under test)."""
    LoopbackHub.reset()
    history, final, _ = _run_chaos_topology("chaos-base", knobs={})
    assert len(history) == 2
    return final


def test_chaos_full_plan_converges_bit_identical(fault_free_final_model):
    """The acceptance run: one LOOPBACK topology absorbing >=1 drop, >=1
    duplicate, >=1 reset, >=1 delay AND a crash-and-rejoin, finishing all
    rounds with the bit-identical final model of the fault-free run, with
    every recovery visible in the exported counters."""
    from fedml_tpu.core import mlops
    from fedml_tpu.core.mlops import FanoutSink, InMemorySink

    mem = InMemorySink()

    class _A:
        run_id, rank = "chaos-full", 0

    mlops.init(_A(), FanoutSink([mem]))
    try:
        history, final, stats = _run_chaos_topology(
            "chaos-full", fault_plan=_full_chaos_plan(), crash_rank=1)
        assert len(history) == 2
        assert _trees_bit_identical(final, fault_free_final_model), \
            "chaos run diverged from the fault-free model"
        srv = stats[0]
        assert srv["rejoins"] >= 1          # crash-and-rejoin detected
        assert srv["faults_dropped"] >= 1   # drop rule fired...
        assert srv["retransmits"] >= 1      # ...and was healed by retransmit
        assert srv["faults_delayed"] >= 1
        assert srv["dup_dropped"] >= 1      # duplicate was deduped
        assert srv["acks_sent"] > 0 and srv["acks_received"] > 0
        assert stats[2]["faults_reset"] >= 1
        assert stats[2]["retries"] >= 1     # reset healed by sync send retry
        assert stats[3]["faults_duplicated"] >= 1
        # the counters are exported through the mlops sink at finish()
        recs = mem.by_topic("comm_stats")
        assert any(r.get("rank") == 0 and r.get("rejoins", 0) >= 1 for r in recs)
        assert any(r.get("rank") == 2 and r.get("retries", 0) >= 1 for r in recs)
    finally:
        mlops.finish()


_MATRIX_PLANS = {
    "drop": {"seed": 3, "rules": [
        {"kind": "drop", "direction": "send", "sender": 0, "receiver": 2,
         "msg_type": 2, "round": 1, "times": 1}]},
    "duplicate": {"seed": 3, "rules": [
        {"kind": "duplicate", "direction": "send", "sender": 1,
         "msg_type": 3, "round": 0, "times": 1}]},
    "delay": {"seed": 3, "rules": [
        {"kind": "delay", "direction": "send", "sender": 0, "receiver": 1,
         "msg_type": 2, "round": 1, "times": 1, "delay_s": 0.05}]},
    "reset": {"seed": 3, "rules": [
        {"kind": "reset", "direction": "send", "sender": 2, "msg_type": 3,
         "round": 0, "times": 1}]},
}

_MATRIX_COUNTER = {  # (rank whose stats carry it, counter, injected-counter)
    "drop": (0, "retransmits", "faults_dropped"),
    "duplicate": (0, "dup_dropped", None),
    "delay": (0, "faults_delayed", None),
    "reset": (2, "retries", "faults_reset"),
}


@pytest.mark.parametrize("kind", sorted(_MATRIX_PLANS))
def test_chaos_matrix_loopback(kind, fault_free_final_model):
    """Single-fault matrix over the in-process transport (the fast tier-1
    slice of the cross-backend matrix below)."""
    history, final, stats = _run_chaos_topology(
        f"chaos-m-{kind}", fault_plan=_MATRIX_PLANS[kind])
    assert len(history) == 2
    assert _trees_bit_identical(final, fault_free_final_model)
    rank, counter, injected = _MATRIX_COUNTER[kind]
    assert stats[rank][counter] >= 1, (kind, stats[rank])
    if injected is not None:
        # dup/delay are observed on the injecting sender's own stats instead
        src = 0 if kind == "drop" else rank
        assert stats[src][injected] >= 1


@pytest.mark.slow
@pytest.mark.parametrize("backend", ["TRPC", "GRPC", "MQTT_S3"])
def test_chaos_full_plan_all_backends(backend, fault_free_final_model, tmp_path):
    """The same scripted plan + crash-and-rejoin over every socketed
    backend: recovery must be transport-independent AND the final model must
    bit-match the (loopback) fault-free run — transports may reorder and
    retry, never alter, the round."""
    comm_extra = {}
    broker = None
    if backend == "TRPC":
        comm_extra = {"trpc_base_port": 29310, "trpc_connect_retries": 3,
                      "trpc_retry_interval_s": 0.1}
    elif backend == "GRPC":
        comm_extra = {"grpc_base_port": 29410, "grpc_send_retries": 3,
                      "grpc_send_backoff_base_s": 0.05}
    else:
        from fedml_tpu.core.distributed.communication.mqtt_s3.broker import LocalBroker

        broker = LocalBroker().start()
        comm_extra = {"mqtt_host": "127.0.0.1", "mqtt_port": broker.port,
                      "s3_blob_root": str(tmp_path / "blobs"),
                      "mqtt_reconnect_retries": 10,
                      "mqtt_reconnect_base_s": 0.05}
    try:
        history, final, stats = _run_chaos_topology(
            f"chaos-{backend.lower()}", backend=backend,
            fault_plan=_full_chaos_plan(), crash_rank=1, comm_extra=comm_extra)
        assert len(history) == 2
        assert _trees_bit_identical(final, fault_free_final_model)
        assert stats[0]["rejoins"] >= 1
        assert stats[0]["dup_dropped"] >= 1
        assert stats[2]["faults_reset"] >= 1
    finally:
        if broker is not None:
            broker.stop()


@pytest.mark.slow
def test_chaos_check_gate():
    """The anti-flake gate: the fast chaos matrix must hold up over
    consecutive full-process runs (tools/chaos_check.py is the operator
    entry point for the same sweep)."""
    import subprocess
    import sys

    proc = subprocess.run(
        [sys.executable, "tools/chaos_check.py", "--runs", "2"],
        capture_output=True, text=True, timeout=1200,
        cwd=__import__("os").path.dirname(__import__("os").path.dirname(__file__)),
    )
    assert proc.returncode == 0, proc.stdout[-3000:] + proc.stderr[-2000:]


# ---------------------------------------------------------------------------
# Server-kill suite: durable server state + supervisor restart
# ---------------------------------------------------------------------------

def _server_kill_plan(extra_rules=None):
    """Kill the server on the SECOND round-0 upload it receives: the first
    upload is journaled+acked before death, the killed one is lost pre-ack
    (its sender must be re-synced by the restarted incarnation)."""
    rules = list(extra_rules or [])
    rules.append({"kind": "server_kill", "direction": "recv", "receiver": 0,
                  "msg_type": 3, "round": 0, "after": 1, "times": 1})
    return {"seed": 7, "rules": rules}


def _without_kill(plan):
    return {"seed": plan["seed"],
            "rules": [r for r in plan["rules"] if r["kind"] != "server_kill"]}


def _run_server_kill_topology(run_id, ckpt_dir, backend="LOOPBACK", n=3,
                              fault_plan=None, comm_extra=None,
                              max_restarts=3, knobs=None, on_restart=None):
    """1 server + ``n`` silos; the server is KILLED mid-round by the fault
    seam and a supervisor loop restarts it from its durable state
    (``server_checkpoint_dir``).  Only incarnation 0 carries the kill rule —
    a supervisor restarts the same binary, but a kill that re-fired every
    incarnation would never let the run end.  ``on_restart(restarts)`` runs
    between the death and the rebuild (the elastic suite shrinks device
    visibility there, restarting onto different hardware).  Returns
    ``(history, final, {rank: stats}, restarts, killed_stats, server)``."""
    plan = fault_plan if fault_plan is not None else _server_kill_plan()
    client_plan = _without_kill(plan)
    extra = dict(_CHAOS_KNOBS)
    extra.update(knobs or {})  # e.g. the async_fl suite's fl_mode knobs
    extra["server_checkpoint_dir"] = str(ckpt_dir)
    comm_extra = comm_extra or {}

    def mk_args(rank, role, plan_):
        kw = dict(extra)
        if plan_["rules"]:
            kw["fault_plan"] = plan_
        a = _args(run_id, n, **kw)
        for k, v in comm_extra.items():
            setattr(a, k, v)
        a.backend = backend
        a.role, a.rank = role, rank
        return fedml_tpu.init(a, should_init_logs=False)

    from fedml_tpu.cross_silo.client.client import Client
    from fedml_tpu.cross_silo.server.server import Server

    def build_server(plan_):
        a = mk_args(0, "server", plan_)
        ds, od = fedml_tpu.data.load(a)
        return Server(a, None, ds, fedml_tpu.models.create(a, od))

    def build_client(rank):
        a = mk_args(rank, "client", client_plan)
        ds_c, od = fedml_tpu.data.load(a)
        return Client(a, None, ds_c, fedml_tpu.models.create(a, od))

    clients = {r: build_client(r) for r in range(1, n + 1)}
    threads = {r: threading.Thread(target=c.run, daemon=True)
               for r, c in clients.items()}
    for t in threads.values():
        t.start()

    server = build_server(plan)
    restarts = 0
    killed_stats = []
    while True:
        history = _run_server_bounded(server)
        mgr = server.server_manager
        if mgr._finished:
            break
        # run() returned without finishing: the only legal cause here is the
        # scripted kill (anything else is a transport bug)
        seam = mgr.com_manager
        assert getattr(seam, "kill_event", None) is not None \
            and seam.kill_event.is_set(), \
            "server run() exited unfinished without a scripted kill"
        killed_stats.append(mgr.comm_stats_snapshot())
        mgr.finish()  # tear down the dead incarnation's link/transport
        if backend == "LOOPBACK":
            # the crash analog for the queue transport: the dead
            # incarnation's mailbox (and its _STOP sentinel) dies with it
            LoopbackHub.sever(run_id, 0)
        restarts += 1
        assert restarts <= max_restarts, "server restart loop did not converge"
        if on_restart is not None:
            on_restart(restarts)
        server = None
        for _ in range(40):  # dead incarnation's port may still be freeing
            try:
                server = build_server(client_plan)
                break
            except OSError:
                time.sleep(0.25)
        assert server is not None, "restarted server could not rebind"
        assert server.resumed, "restart did not restore the durable snapshot"

    _join_all(list(threads.values()))
    final = server.server_manager.aggregator.get_global_model_params()
    stats = {0: server.server_manager.comm_stats_snapshot()}
    for r, c in clients.items():
        stats[r] = c.manager.comm_stats_snapshot()
    return history, final, stats, restarts, killed_stats, server


def _assert_recovered(history, final, stats, restarts, killed_stats, server,
                      fault_free_final_model, n=3):
    assert restarts >= 1
    assert len(history) == 2
    assert _trees_bit_identical(final, fault_free_final_model), \
        "restarted run diverged from the fault-free model"
    # the kill is visible on the DEAD incarnation's counters...
    assert sum(s.get("faults_killed", 0) for s in killed_stats) >= 1
    # ...and the recovery on the surviving incarnation's
    srv = stats[0]
    assert srv["server_restores"] >= 1
    assert srv["epoch_bumps"] >= 1
    assert srv["journal_replays"] >= 1
    mgr = server.server_manager
    assert mgr.server_epoch == restarts
    # exactly-once accounting: journal replay + re-uploads must not
    # double-count any report in the fleet registry
    reg = mgr.population.registry.snapshot()
    assert reg["reported_total"] == n * 2, reg


def test_server_kill_restart_bit_identical(fault_free_final_model, tmp_path):
    """The acceptance run: a server killed between two round-0 uploads
    restarts from snapshot + journal, re-syncs the clients whose uploads
    died with it, and finishes with the bit-identical final model."""
    LoopbackHub.reset()
    out = _run_server_kill_topology("kill-loop", tmp_path / "srv")
    _assert_recovered(*out, fault_free_final_model)


def test_server_kill_under_client_chaos_bit_identical(fault_free_final_model,
                                                      tmp_path):
    """Combined plan: the server kill rides on top of the full client-side
    chaos plan (drop + reset + duplicate + delay) — recovery and the
    self-healing transport must compose, not merely coexist."""
    LoopbackHub.reset()
    plan = _server_kill_plan(extra_rules=_full_chaos_plan()["rules"])
    out = _run_server_kill_topology("kill-chaos", tmp_path / "srv",
                                    fault_plan=plan)
    _assert_recovered(*out, fault_free_final_model)
    _, _, stats, _, killed, _ = out
    # the client-side chaos actually fired somewhere in the run
    assert stats[2]["faults_reset"] >= 1
    assert stats[3]["faults_duplicated"] >= 1


def test_server_kill_sharded_state_bit_identical(tmp_path):
    """server_state=sharded crash leg: the server is killed in ROUND 1 —
    after round 0's FedOpt/adam step, so the round-1 snapshot carries the
    model-sharded server-optimizer state (first/second moments) — and the
    restarted incarnation must restore it bit-identically: the final model
    matches a fault-free sharded run exactly, with exactly-once report
    accounting.  A round-0 kill would never exercise the optimizer-state
    restore (the round plane is only built at the first aggregate)."""
    knobs = {"server_state": "sharded", "federated_optimizer": "FedOpt",
             "server_optimizer": "adam"}
    LoopbackHub.reset()
    history, ref_final, _ = _run_chaos_topology(
        "sharded-base", knobs={**_CHAOS_KNOBS, **knobs})
    assert len(history) == 2
    LoopbackHub.reset()
    plan = {"seed": 7, "rules": [
        {"kind": "server_kill", "direction": "recv", "receiver": 0,
         "msg_type": 3, "round": 1, "after": 1, "times": 1}]}
    history, final, stats, restarts, killed_stats, server = (
        _run_server_kill_topology("sharded-kill", tmp_path / "srv",
                                  fault_plan=plan, knobs=knobs))
    assert restarts >= 1
    assert len(history) == 2
    assert _trees_bit_identical(final, ref_final), \
        "sharded-state restart diverged from the fault-free sharded run"
    assert sum(s.get("faults_killed", 0) for s in killed_stats) >= 1
    assert stats[0]["server_restores"] >= 1
    # exactly-once accounting across the kill + journal replay
    reg = server.server_manager.population.registry.snapshot()
    assert reg["reported_total"] == 3 * 2, reg


# ---------------------------------------------------------------------------
# Elastic suite: topology change (mesh shrink / device loss) mid-run
# ---------------------------------------------------------------------------

_SHARDED_KNOBS = {"server_state": "sharded", "federated_optimizer": "FedOpt",
                  "server_optimizer": "adam"}


@pytest.fixture
def _elastic_hygiene():
    """Device visibility and the plane/program caches are process-global;
    an elastic test must never leak a shrunken topology into its
    neighbours."""
    from fedml_tpu.parallel.agg_plane import reset_planes
    from fedml_tpu.parallel.mesh import set_visible_devices

    set_visible_devices(None)
    reset_planes()
    yield set_visible_devices
    set_visible_devices(None)
    reset_planes()


def test_elastic_live_remesh_under_client_chaos_bit_identical(
        _elastic_hygiene):
    """Mid-run topology change WITHOUT a restart: a ``mesh_shrink`` fault
    (half the devices vanish during round 1's uploads) rides on top of
    drop/dup/delay client chaos.  Three rounds, so the plane installs on
    the full mesh in round 0, loses half its devices mid-round-1, and the
    round-2 boundary (``maybe_remesh``) re-shards the resident state,
    bumps the incarnation epoch, and still converges bit-identical to the
    fixed-mesh fault-free run with exactly-once report accounting."""
    from fedml_tpu.core import obs

    knobs = {**_CHAOS_KNOBS, **_SHARDED_KNOBS, "comm_round": 3}
    LoopbackHub.reset()
    _, ref_final, _ = _run_chaos_topology("elastic-base", knobs=knobs)

    LoopbackHub.reset()
    plan = {"seed": 7, "rules": _full_chaos_plan()["rules"] + [
        # half the fleet's devices die on the second round-1 upload the
        # server receives — after the plane is resident on the full mesh —
        # so round 2 must open through a live re-shard
        {"kind": "mesh_shrink", "direction": "recv", "receiver": 0,
         "msg_type": 3, "round": 1, "after": 1, "times": 1}]}
    history, final, stats = _run_chaos_topology(
        "elastic-shrink", fault_plan=plan, knobs=knobs)
    assert len(history) == 3
    assert _trees_bit_identical(final, ref_final), \
        "live remesh diverged from the fixed-mesh run"
    srv = stats[0]
    assert srv["faults_topology"] >= 1
    assert srv["epoch_bumps"] >= 1  # the resize bumped the incarnation epoch
    assert obs.registry().get_counter("mesh.resizes_total") >= 1


def test_elastic_server_kill_mesh_shrink_restart_bit_identical(
        _elastic_hygiene, tmp_path):
    """The chaos_check ``elastic`` acceptance leg: the server is killed in
    round 1 (sharded optimizer state resident) and the supervisor restarts
    it with the model axis shrunk 4→2 — the restored incarnation rebuilds
    its round mesh over the surviving devices, re-shards the snapshot
    through the portable codec, and finishes bit-identical to the
    uninterrupted 4-device run with exactly-once accounting."""
    import jax

    from fedml_tpu.parallel.mesh import set_visible_devices

    ids = [d.id for d in jax.devices()]
    assert len(ids) >= 4, "elastic leg needs >= 4 (virtual) devices"
    set_visible_devices(ids[:4])  # model axis = 4

    LoopbackHub.reset()
    history, ref_final, _ = _run_chaos_topology(
        "elastic-kill-base", knobs={**_CHAOS_KNOBS, **_SHARDED_KNOBS})
    assert len(history) == 2

    LoopbackHub.reset()
    plan = {"seed": 7, "rules": [
        {"kind": "server_kill", "direction": "recv", "receiver": 0,
         "msg_type": 3, "round": 1, "after": 1, "times": 1}]}
    history, final, stats, restarts, killed_stats, server = (
        _run_server_kill_topology(
            "elastic-kill", tmp_path / "srv", fault_plan=plan,
            knobs=_SHARDED_KNOBS,
            on_restart=lambda _n: set_visible_devices(ids[:2])))
    assert restarts >= 1
    assert len(history) == 2
    assert _trees_bit_identical(final, ref_final), \
        "shrunken-mesh restart diverged from the fixed-mesh run"
    assert sum(s.get("faults_killed", 0) for s in killed_stats) >= 1
    assert stats[0]["server_restores"] >= 1
    # exactly-once accounting across the kill + shrink + journal replay
    reg = server.server_manager.population.registry.snapshot()
    assert reg["reported_total"] == 3 * 2, reg


@pytest.mark.slow
@pytest.mark.parametrize("backend", ["TRPC", "GRPC", "MQTT_S3"])
def test_server_kill_restart_all_backends(backend, fault_free_final_model,
                                          tmp_path):
    """Server crash recovery is transport-independent: the same kill +
    supervisor restart over every socketed backend (the restarted
    incarnation must rebind the listener / reconnect the broker) converges
    to the bit-identical final model."""
    comm_extra = {}
    broker = None
    if backend == "TRPC":
        comm_extra = {"trpc_base_port": 29510, "trpc_connect_retries": 3,
                      "trpc_retry_interval_s": 0.1}
    elif backend == "GRPC":
        comm_extra = {"grpc_base_port": 29610, "grpc_send_retries": 3,
                      "grpc_send_backoff_base_s": 0.05}
    else:
        from fedml_tpu.core.distributed.communication.mqtt_s3.broker import LocalBroker

        broker = LocalBroker().start()
        comm_extra = {"mqtt_host": "127.0.0.1", "mqtt_port": broker.port,
                      "s3_blob_root": str(tmp_path / "blobs"),
                      "mqtt_reconnect_retries": 10,
                      "mqtt_reconnect_base_s": 0.05}
    try:
        out = _run_server_kill_topology(
            f"kill-{backend.lower()}", tmp_path / "srv", backend=backend,
            comm_extra=comm_extra)
        _assert_recovered(*out, fault_free_final_model)
    finally:
        if broker is not None:
            broker.stop()


# ---------------------------------------------------------------------------
# Unit layer: the reliability link and the fault seam, no topology needed
# ---------------------------------------------------------------------------

class TestReliableLink:
    def _link(self, **kw):
        from fedml_tpu.core.distributed.comm_manager import _ReliableLink
        from fedml_tpu.core.distributed.faults import CommStats

        stats = CommStats()
        link = _ReliableLink(1, stats, **kw)
        sent = []
        link.bind(sent.append)
        return link, stats, sent

    def _msg(self, mtype=3, sender=2, receiver=1, msg_id=None):
        m = Message(mtype, sender, receiver)
        if msg_id is not None:
            m.add_params(Message.MSG_ARG_KEY_MSG_ID, msg_id)
        return m

    def test_stamp_is_monotonic_and_unique(self):
        link, _, _ = self._link()
        ids = [link.stamp(self._msg()) for _ in range(5)]
        assert len(set(ids)) == 5
        assert [int(i.rsplit(":", 1)[1]) for i in ids] == [1, 2, 3, 4, 5]

    def test_duplicate_delivery_acked_but_dropped(self):
        link, stats, sent = self._link()
        m = self._msg(msg_id="2:abc:1")
        assert link.on_receive(m) is True
        assert link.on_receive(m) is False  # re-delivery suppressed
        assert stats.get("dup_dropped") == 1
        # BOTH deliveries were acked: the first ack may be the lost frame
        assert stats.get("acks_sent") == 2
        from fedml_tpu.core.distributed.comm_manager import COMM_ACK_TYPE
        assert all(a.get_type() == COMM_ACK_TYPE for a in sent)

    def test_ack_consumes_pending_retransmit(self):
        link, stats, _ = self._link(max_retries=5, backoff_base_s=5.0)
        m = self._msg()
        mid = link.stamp(m)
        link.track(mid, m)
        assert mid in link._pending
        ack = self._msg(mtype="comm_ack", msg_id=mid)
        assert link.on_receive(ack) is False  # acks never reach handlers
        assert mid not in link._pending
        assert stats.get("acks_received") == 1
        link.stop()

    def test_legacy_unstamped_messages_pass_without_ack(self):
        link, stats, sent = self._link()
        assert link.on_receive(self._msg()) is True
        assert link.on_receive(self._msg()) is True  # no dedup either
        assert stats.get("acks_sent") == 0 and sent == []

    def test_unacked_message_is_retransmitted_then_given_up(self):
        link, stats, sent = self._link(
            max_retries=2, backoff_base_s=0.01, backoff_max_s=0.02)
        m = self._msg()
        link.track(link.stamp(m), m)
        deadline = time.time() + 5
        while time.time() < deadline and stats.get("delivery_failures") == 0:
            time.sleep(0.01)
        assert stats.get("retransmits") == 2
        assert stats.get("delivery_failures") == 1
        assert len(sent) == 2 and not link._pending
        link.stop()


class _StubBackend:
    """Minimal BaseCommunicationManager double for the fault seam."""

    def __init__(self):
        self.sent = []
        self.observers = []

    def send_message(self, msg):
        self.sent.append(msg)

    def add_observer(self, o):
        self.observers.append(o)

    def remove_observer(self, o):
        self.observers.remove(o)

    def handle_receive_message(self):
        pass

    def stop_receive_message(self):
        pass


class _CaptureObserver:
    def __init__(self):
        self.got = []

    def receive_message(self, msg_type, msg):
        self.got.append(msg)


class TestFaultSeam:
    def _seam(self, rules, seed=0):
        from fedml_tpu.core.distributed.faults import (
            CommStats, FaultPlan, FaultyCommManager)

        inner = _StubBackend()
        stats = CommStats()
        plan = FaultPlan.from_dict({"seed": seed, "rules": rules})
        seam = FaultyCommManager(inner, plan.injector(1), stats)
        cap = _CaptureObserver()
        seam.add_observer(cap)
        return seam, inner, cap, stats

    def test_occurrence_window_after_and_times(self):
        seam, inner, _, stats = self._seam(
            [{"kind": "drop", "msg_type": 3, "after": 1, "times": 2}])
        for _ in range(5):
            seam.send_message(Message(3, 1, 0))
        # 1st passes (after=1), 2nd+3rd dropped (times=2), rest pass
        assert len(inner.sent) == 3
        assert stats.get("faults_dropped") == 2

    def test_round_scoped_rule_ignores_untagged(self):
        seam, inner, _, _ = self._seam(
            [{"kind": "drop", "round": 1, "times": None}])
        m = Message(3, 1, 0)
        seam.send_message(m)  # no round tag -> rule cannot match
        tagged = Message(3, 1, 0)
        tagged.add_params("round_idx", 1)
        seam.send_message(tagged)
        assert inner.sent == [m]

    def test_partition_defaults_to_forever(self):
        seam, inner, _, stats = self._seam(
            [{"kind": "partition", "receiver": 0}])
        for _ in range(4):
            seam.send_message(Message(3, 1, 0))
        seam.send_message(Message(3, 1, 2))  # other receiver unaffected
        assert len(inner.sent) == 1
        assert stats.get("faults_dropped") == 4

    def test_send_reset_raises_recv_reset_degrades_to_drop(self):
        seam, inner, cap, stats = self._seam(
            [{"kind": "reset", "direction": "send", "msg_type": 3},
             {"kind": "reset", "direction": "recv", "msg_type": 2}])
        with pytest.raises(ConnectionError):
            seam.send_message(Message(3, 1, 0))
        assert stats.get("faults_reset") == 1
        seam.receive_message("2", Message(2, 0, 1))  # dies with the socket
        assert cap.got == [] and stats.get("faults_dropped") == 1
        seam.receive_message("2", Message(2, 0, 1))  # rule spent
        assert len(cap.got) == 1

    def test_connection_ready_is_exempt(self):
        seam, _, cap, _ = self._seam([{"kind": "drop", "times": None}])
        ready = Message("connection_ready", 1, 1)
        seam.receive_message("connection_ready", ready)
        assert cap.got == [ready]

    def test_server_kill_silences_seam_and_signals_supervisor(self):
        seam, inner, cap, stats = self._seam(
            [{"kind": "server_kill", "direction": "recv", "msg_type": 3,
              "after": 1, "times": 1}])
        seam.receive_message("3", Message(3, 1, 0))  # after=1: passes
        assert len(cap.got) == 1
        seam.receive_message("3", Message(3, 2, 0))  # the kill: msg dies too
        assert len(cap.got) == 1
        assert seam.kill_event.is_set()
        assert stats.get("faults_killed") == 1
        # a killed process neither sends nor receives — the seam plays dead
        seam.send_message(Message(2, 0, 1))
        assert inner.sent == []
        seam.receive_message("3", Message(3, 3, 0))
        assert len(cap.got) == 1

    def test_seeded_probability_replays_exactly(self):
        from fedml_tpu.core.distributed.faults import FaultPlan

        plan = FaultPlan.from_dict({"seed": 11, "rules": [
            {"kind": "drop", "p": 0.5, "times": None}]})

        def trace():
            inj = plan.injector(3)
            return [inj.decide("send", Message(3, 1, 0)) is not None
                    for _ in range(32)]

        a, b = trace(), trace()
        assert a == b and any(a) and not all(a)
