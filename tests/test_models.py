"""Model zoo shape tests (every hub key initializes and produces logits)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.models import hub

pytestmark = pytest.mark.heavy  # long XLA compiles; see pytest.ini


class _Args:
    def __init__(self, model, dataset="cifar10"):
        self.model = model
        self.dataset = dataset


IMAGE_CASES = [
    ("lr", 10, (2, 28, 28, 1)),
    ("cnn", 62, (2, 28, 28, 1)),
    ("cnn_web", 10, (2, 28, 28, 1)),
    ("resnet20", 10, (2, 32, 32, 3)),
    ("resnet56", 10, (2, 32, 32, 3)),
    ("resnet18_gn", 100, (2, 32, 32, 3)),
    ("mobilenet", 10, (2, 32, 32, 3)),
    ("mobilenet_v3", 10, (2, 32, 32, 3)),
    ("vgg11", 10, (2, 32, 32, 3)),
]


@pytest.mark.parametrize("name,classes,shape", IMAGE_CASES)
def test_image_model_forward(name, classes, shape):
    m = hub.create(_Args(name), classes)
    x = jnp.zeros(shape, jnp.float32)
    variables = m.init(jax.random.PRNGKey(0), x, train=False)
    out = m.apply(variables, x, train=False)
    assert out.shape == (shape[0], classes)


SEQ_CASES = [
    ("rnn", 90, (2, 16)),
    ("rnn_stackoverflow", 1004, (2, 16)),
]


@pytest.mark.parametrize("name,vocab,shape", SEQ_CASES)
def test_seq_model_forward(name, vocab, shape):
    m = hub.create(_Args(name, dataset="shakespeare"), vocab)
    x = jnp.zeros(shape, jnp.int32)
    variables = m.init(jax.random.PRNGKey(0), x, train=False)
    out = m.apply(variables, x, train=False)
    assert out.shape[0] == shape[0] and out.shape[-1] >= vocab


def test_transformer_forward():
    from fedml_tpu.models.transformer import TransformerConfig, TransformerLM

    cfg = TransformerConfig(vocab_size=128, d_model=64, n_heads=4, n_layers=2, d_ff=128)
    m = TransformerLM(cfg)
    x = jnp.zeros((2, 16), jnp.int32)
    variables = m.init(jax.random.PRNGKey(0), x, train=False)
    out = m.apply(variables, x, train=False)
    assert out.shape == (2, 16, 128)


def test_transformer_causality():
    """Changing a future token must not change past logits."""
    from fedml_tpu.models.transformer import TransformerConfig, TransformerLM

    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64)
    m = TransformerLM(cfg)
    x1 = jnp.zeros((1, 8), jnp.int32)
    x2 = x1.at[0, 7].set(5)
    v = m.init(jax.random.PRNGKey(0), x1, train=False)
    o1 = m.apply(v, x1, train=False)
    o2 = m.apply(v, x2, train=False)
    np.testing.assert_allclose(o1[0, :7], o2[0, :7], atol=1e-5)
    assert not np.allclose(o1[0, 7], o2[0, 7])


def test_gan_pair():
    from fedml_tpu.models.gan import MNISTDiscriminator, MNISTGenerator

    g, d = MNISTGenerator(), MNISTDiscriminator()
    z = jnp.zeros((2, 100))
    gv = g.init(jax.random.PRNGKey(0), z, train=False)
    img = g.apply(gv, z, train=False)
    assert img.shape == (2, 28, 28, 1)
    dv = d.init(jax.random.PRNGKey(1), img, train=False)
    out = d.apply(dv, img, train=False)
    assert out.shape == (2, 1)


def test_unknown_model_raises():
    with pytest.raises(ValueError):
        hub.create(_Args("nope"), 10)
