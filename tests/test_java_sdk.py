"""Java edge SDK (android/sdk — ai.fedml.tpu): protocol drift gates that run
everywhere, plus javac/JVM legs that activate when a JDK is present.

The SDK's wire is the broker's JSON interop encoding (broker.py sniffs each
connection), so a Python client in encoding="json" mode exercises EXACTLY
the bytes the Java BrokerConnection produces — the 'Java-shaped client'
below walks the full cross-device round against a real server with it.
Reference role: android_protocol_test + the ~7k-LoC
android/fedmlsdk/src/main/java/ai/fedml service layer."""

from __future__ import annotations

import os
import re
import shutil
import subprocess
import threading
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SDK = os.path.join(REPO, "android", "sdk", "src", "main", "java", "ai", "fedml", "tpu")
JNI_CPP = os.path.join(REPO, "native", "android", "fedml_jni.cpp")


def _java(name: str) -> str:
    with open(os.path.join(SDK, name)) as f:
        return f.read()


class TestProtocolDriftGates:
    """Parse the Java sources and pin them to their Python twins — adding or
    renaming a constant on one side fails here."""

    def test_message_define_matches_python(self):
        from fedml_tpu.cross_device.message_define import MNNMessage

        src = _java("MessageDefine.java")
        ints = dict(re.findall(r"int (MSG_TYPE_\w+) = (\d+);", src))
        strs = dict(re.findall(r'String (\w+) = "([^"]*)";', src))
        assert ints, "no int constants parsed from MessageDefine.java"
        for name, val in ints.items():
            assert getattr(MNNMessage, name) == int(val), name
        for name, val in strs.items():
            if name == "MSG_TYPE_CONNECTION_READY":
                assert val == "connection_ready"
                continue
            assert getattr(MNNMessage, name) == val, name
        # completeness: every Python MSG_TYPE/MSG_ARG the device protocol
        # uses exists on the Java side
        for name in dir(MNNMessage):
            if name.startswith(("MSG_TYPE_", "MSG_ARG_KEY_", "CLIENT_STATUS_")):
                assert name in ints or name in strs, f"missing in Java: {name}"

    def test_native_binding_matches_jni_exports(self):
        src = _java("NativeFedMLTrainer.java")
        java_methods = set(re.findall(r"native [\w\[\]]+ (\w+)\(", src))
        with open(JNI_CPP) as f:
            cpp = f.read()
        cpp_exports = set(re.findall(
            r"Java_ai_fedml_tpu_NativeFedMLTrainer_(\w+)\(", cpp))
        assert java_methods == cpp_exports, (
            java_methods ^ cpp_exports)

    def test_topic_scheme_matches_python(self):
        src = _java("EdgeCommunicator.java")
        # fedml/{runId}/{sender}/{receiver} + fedml/{runId}/status + the
        # run-prefix subscription — the MqttS3CommManager scheme
        assert '"fedml/" + runId + "/" + sender + "/" + receiver' in src
        assert '"fedml/" + runId + "/status"' in src
        assert '"fedml/" + runId + "/#"' in src


class TestJsonWireInterop:
    """The broker side of the Java wire: a JSON-encoding client and a pickle
    client share topics, payloads, and last-will semantics."""

    def test_json_and_pickle_clients_interoperate(self):
        from fedml_tpu.core.distributed.communication.mqtt_s3.broker import (
            BrokerClient,
            LocalBroker,
        )

        broker = LocalBroker().start()
        try:
            got_py, got_js = [], []
            py = BrokerClient("127.0.0.1", broker.port,
                              lambda t, p: got_py.append((t, p)))
            js = BrokerClient("127.0.0.1", broker.port,
                              lambda t, p: got_js.append((t, p)),
                              encoding="json")
            py.subscribe("fedml/run/#")
            js.subscribe("fedml/run/#")
            time.sleep(0.2)
            js.publish("fedml/run/1/0", {"msg_type": "5", "sender": 1,
                                         "receiver": 0, "client_status": "ONLINE"})
            py.publish("fedml/run/0/1", {"msg_type": "2", "sender": 0,
                                         "receiver": 1, "round_idx": 3,
                                         "model_params_file": "/tmp/m.ftem"})
            deadline = time.time() + 5
            while (len(got_py) < 2 or len(got_js) < 2) and time.time() < deadline:
                time.sleep(0.05)
            py_by_topic = dict(got_py)
            js_by_topic = dict(got_js)
            assert py_by_topic["fedml/run/1/0"]["client_status"] == "ONLINE"
            assert js_by_topic["fedml/run/0/1"]["model_params_file"] == "/tmp/m.ftem"
            # ints survive the cross-encoding trip
            assert int(js_by_topic["fedml/run/0/1"]["round_idx"]) == 3
            py.disconnect()
            js.disconnect()
        finally:
            broker.stop()

    def test_json_client_last_will_reaches_pickle_subscriber(self):
        from fedml_tpu.core.distributed.communication.mqtt_s3.broker import (
            BrokerClient,
            LocalBroker,
        )

        broker = LocalBroker().start()
        try:
            got = []
            watcher = BrokerClient("127.0.0.1", broker.port,
                                   lambda t, p: got.append((t, p)))
            watcher.subscribe("fedml/run/status")
            js = BrokerClient("127.0.0.1", broker.port, lambda t, p: None,
                              encoding="json")
            js.set_last_will("fedml/run/status", '{"rank": 3, "status": "OFFLINE"}')
            time.sleep(0.2)
            # unclean death -> broker fires the will (shutdown, not close:
            # close() is deferred while the client's recv thread holds the fd)
            import socket as _socket

            js._sock.shutdown(_socket.SHUT_RDWR)
            js._sock.close()
            deadline = time.time() + 5
            while not got and time.time() < deadline:
                time.sleep(0.05)
            assert got and "OFFLINE" in str(got[0][1])
            watcher.disconnect()
        finally:
            broker.stop()


def _separable(n, d=12, classes=4, seed=0):
    centers = np.random.RandomState(1234).randn(classes, d) * 3
    rng = np.random.RandomState(seed)
    y = rng.randint(0, classes, n)
    x = centers[y] + rng.randn(n, d) * 0.5
    return x.astype(np.float32), y.astype(np.int32)


class JavaShapedDevice:
    """A device speaking byte-for-byte what ClientManager.java sends: JSON
    wire frames, the same topics, the same message fields in the same flow
    (handshake ONLINE -> train -> tagged upload -> FINISH).  Training runs
    through the numpy twin of the native trainer the Java SDK drives."""

    def __init__(self, broker_port, run_id, rank, data, upload_dir, lr=0.2, epochs=2):
        from fedml_tpu.core.distributed.communication.mqtt_s3.broker import BrokerClient

        self.run_id, self.rank = run_id, rank
        self.x, self.y = data
        self.upload_dir = upload_dir
        self.lr, self.epochs = lr, epochs
        self.rounds_trained = 0
        self.finished = threading.Event()
        self.client = BrokerClient("127.0.0.1", broker_port, self._on_message,
                                   encoding="json")
        self.client.set_last_will(
            f"fedml/{run_id}/status", '{"rank": %d, "status": "OFFLINE"}' % rank)
        self.client.subscribe(f"fedml/{run_id}/#")

    def _send(self, params):
        self.client.publish(
            f"fedml/{self.run_id}/{self.rank}/0", params)

    def _on_message(self, topic, payload):
        parts = topic.split("/")
        if len(parts) != 4 or parts[3] != str(self.rank):
            return
        msg_type = str(payload.get("msg_type"))
        if msg_type == "6":  # CHECK_CLIENT_STATUS -> announce ONLINE
            self._send({"msg_type": "5", "sender": self.rank, "receiver": 0,
                        "client_status": "ONLINE"})
        elif msg_type in ("1", "2"):  # INIT / SYNC -> train + tagged upload
            from fedml_tpu.cross_device.edge_model import (
                load_edge_model,
                save_edge_model,
            )
            from fedml_tpu.cross_device.fake_device import train_numpy

            round_idx = int(payload["round_idx"])
            flat = load_edge_model(payload["model_params_file"])
            trained = train_numpy(flat, self.x, self.y, lr=self.lr,
                                  epochs=self.epochs, batch_size=16,
                                  seed=round_idx * 1000 + self.rank)
            out = os.path.join(self.upload_dir,
                               f"model_r{round_idx}_c{self.rank}.ftem")
            save_edge_model(out, trained)
            self.rounds_trained += 1
            self._send({"msg_type": "3", "sender": self.rank, "receiver": 0,
                        "round_idx": round_idx, "model_params_file": out,
                        "num_samples": int(len(self.y))})
        elif msg_type == "7":  # FINISH
            self.finished.set()
            self.client.disconnect()


class TestJavaShapedDeviceE2E:
    def test_round_with_json_wire_devices(self, tmp_path):
        """Full cross-device run: Python server over MQTT_S3 (MNN file
        plane), two devices on the JSON interop wire doing exactly the Java
        ClientManager flow."""
        from fedml_tpu.arguments import Arguments
        from fedml_tpu.core.distributed.communication.mqtt_s3.broker import LocalBroker
        from fedml_tpu.cross_device.fedml_aggregator import FedMLAggregator
        from fedml_tpu.cross_device.fedml_server_manager import FedMLServerManager
        from fedml_tpu.models.linear import LogisticRegression

        broker = LocalBroker().start()
        try:
            args = Arguments.from_dict({
                "common_args": {"training_type": "cross_device", "random_seed": 0,
                                "run_id": "java-e2e"},
                "data_args": {"dataset": "synthetic"},
                "model_args": {"model": "lr"},
                "train_args": {
                    "federated_optimizer": "FedAvg",
                    "client_num_in_total": 2, "client_num_per_round": 2,
                    "comm_round": 3, "epochs": 2, "batch_size": 16,
                    "learning_rate": 0.2,
                },
                "validation_args": {"frequency_of_the_test": 1},
                "comm_args": {"backend": "MQTT_S3_MNN"},
            }).validate()
            args.mqtt_host, args.mqtt_port = "127.0.0.1", broker.port
            args.s3_blob_root = str(tmp_path / "blobs")

            x_test, y_test = _separable(128, seed=9)
            aggregator = FedMLAggregator(
                args, LogisticRegression(output_dim=4), (x_test, y_test),
                worker_num=2, model_dir=str(tmp_path / "models"))
            server = FedMLServerManager(args, aggregator, client_rank=0,
                                        client_num=2, backend="MQTT_S3_MNN")
            devices = [
                JavaShapedDevice(broker.port, "java-e2e", rank,
                                 _separable(96, seed=rank),
                                 str(tmp_path))
                for rank in (1, 2)
            ]
            t = server.run_async()
            for d in devices:
                assert d.finished.wait(timeout=60), "device never saw FINISH"
            t.join(timeout=30)
            assert not t.is_alive()
            assert all(d.rounds_trained == 3 for d in devices)
            assert aggregator.eval_history[-1]["test_acc"] > 0.8
        finally:
            broker.stop()


HAVE_JAVAC = shutil.which("javac") is not None


@pytest.mark.skipif(not HAVE_JAVAC, reason="no JDK in this image")
class TestJavacCompile:
    def test_sdk_compiles(self, tmp_path):
        srcs = [os.path.join(SDK, f) for f in sorted(os.listdir(SDK))
                if f.endswith(".java")]
        srcs.append(os.path.join(REPO, "android", "sdk", "harness",
                                 "EdgeHarness.java"))
        out = subprocess.run(
            ["javac", "-Werror", "-d", str(tmp_path)] + srcs,
            capture_output=True, text=True)
        assert out.returncode == 0, out.stderr
        assert (tmp_path / "ai" / "fedml" / "tpu"
                / "FedEdgeManager.class").exists()


class TestGracefulClose:
    """The publish-then-disconnect contract: every frame published before a
    clean DISCONNECT must reach subscribers.  An abrupt close() used to RST
    the connection (the closer always holds undrained wildcard deliveries),
    and the RST discarded the still-unqueued tail at the broker — observed
    losing the last FINISH of a fan-out, wedging a client forever."""

    def test_publish_burst_then_disconnect_loses_nothing(self):
        from fedml_tpu.core.distributed.communication.mqtt_s3.broker import (
            BrokerClient,
            LocalBroker,
        )

        broker = LocalBroker().start()
        try:
            got = []
            sub = BrokerClient("127.0.0.1", broker.port,
                               lambda t, p: got.append(p))
            sub.subscribe("run/#")
            # the publisher also subscribes (cross-silo peers all hold the
            # run wildcard), so it always has undrained inbound — the RST
            # precondition
            pub = BrokerClient("127.0.0.1", broker.port, lambda t, p: None)
            pub.subscribe("run/#")
            time.sleep(0.2)
            n = 200
            for i in range(n):
                pub.publish("run/x", {"i": i})
            pub.disconnect()  # immediately after the burst
            deadline = time.time() + 20
            while len(got) < n and time.time() < deadline:
                time.sleep(0.05)
            assert len(got) == n, f"lost {n - len(got)} frames to the close"
            assert [p["i"] for p in got] == list(range(n))
            sub.disconnect()
        finally:
            broker.stop()
