"""Android JNI bridge (native/android/fedml_jni.cpp): the shim must compile
against the ABI-faithful stub header and export the full
ai.fedml.tpu.NativeFedMLTrainer surface over the C runtime (reference
android/fedmlsdk/src/main/jni/OnLoad.cpp + JniFedMLClientManager.cpp)."""

import os
import subprocess

import pytest

NATIVE = os.path.join(os.path.dirname(__file__), os.pardir, "native")

EXPECTED = {
    "create", "train", "save", "evaluate", "epochLoss", "numSamples", "stop",
    "destroy", "lastError", "clientCreate", "clientTrain", "clientSaveMasked",
    "clientMaskDim", "clientEncodeMask", "clientDestroy",
}


@pytest.mark.heavy
def test_jni_shim_compiles_and_exports_surface(tmp_path):
    subprocess.run(["make", "-C", NATIVE, "jni_check"], check=True,
                   capture_output=True)
    so = os.path.join(NATIVE, "android", "libfedml_jni_check.so")
    out = subprocess.run(["nm", "-D", so], check=True, capture_output=True,
                         text=True).stdout
    exported = {
        line.rsplit("Java_ai_fedml_tpu_NativeFedMLTrainer_", 1)[1]
        for line in out.splitlines()
        if "Java_ai_fedml_tpu_NativeFedMLTrainer_" in line
    }
    assert exported == EXPECTED, exported.symmetric_difference(EXPECTED)
    assert "JNI_OnLoad" in out
