"""In-mesh hierarchical FL (simulation/xla/hierarchical.py): both reduce
levels (client -> group -> global) compile into one XLA program; gated by
exact equivalence against the sp twin."""

import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.arguments import Arguments
from fedml_tpu.parallel.mesh import create_fl_mesh

pytestmark = pytest.mark.heavy


def _args(**over):
    base = {
        "common_args": {"training_type": "simulation", "random_seed": 0, "run_id": "hier"},
        "data_args": {
            "dataset": "mnist",
            "data_cache_dir": "",
            # homo => equal client sizes => identical padded shapes on both
            # backends (the exact-equality precondition)
            "partition_method": "homo",
            "synthetic_train_size": 512,
        },
        "model_args": {"model": "lr"},
        "train_args": {
            "federated_optimizer": "HierarchicalFL",
            "client_num_in_total": 8,
            "client_num_per_round": 4,
            "comm_round": 4,
            "epochs": 1,
            "batch_size": 16,
            "client_optimizer": "sgd",
            "learning_rate": 0.1,
            "group_num": 2,
            "group_comm_round": 2,
        },
        "validation_args": {"frequency_of_the_test": 1},
        "comm_args": {"backend": "XLA"},
    }
    args = Arguments.from_dict(base)
    for k, v in over.items():
        setattr(args, k, v)
    return args.validate()


def _build(**over):
    args = fedml_tpu.init(_args(**over), should_init_logs=False)
    dataset, out_dim = fedml_tpu.data.load(args)
    model = fedml_tpu.models.create(args, out_dim)
    return args, dataset, model


class TestHierarchicalInMesh:
    def test_matches_sp_twin_exactly(self):
        """Same membership permutation, same per-group sampling streams,
        same per-(round, client) keys, same engine: the compiled two-level
        round must reproduce the sp group loop."""
        import jax

        from fedml_tpu.simulation.sp.hierarchical_fl.hier_api import HierarchicalFLAPI
        from fedml_tpu.simulation.xla.hierarchical import HierarchicalInMeshAPI

        args, dataset, model = _build()
        sp = HierarchicalFLAPI(args, None, dataset, model)
        sp.train()

        args2, dataset2, model2 = _build()
        api = HierarchicalInMeshAPI(args2, None, dataset2, model2,
                                    mesh=create_fl_mesh(4))
        api.train()

        for a, b in zip(
            jax.tree_util.tree_leaves(api.w_global),
            jax.tree_util.tree_leaves(sp.w_global),
        ):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)
        # group models agree too (round 4 synced: stack == global)
        for g in range(2):
            for a, b in zip(
                jax.tree_util.tree_leaves(api.group_model(g)),
                jax.tree_util.tree_leaves(sp.group_models[g]),
            ):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           rtol=1e-5, atol=1e-6)

    def test_groups_diverge_between_syncs(self):
        import jax

        from fedml_tpu.simulation.xla.hierarchical import HierarchicalInMeshAPI

        # 3 rounds with sync every 2: the last round leaves groups diverged
        args, dataset, model = _build(comm_round=3)
        api = HierarchicalInMeshAPI(args, None, dataset, model,
                                    mesh=create_fl_mesh(4))
        out = api.train()
        assert out["test_acc"] > 0.5
        a = jax.tree_util.tree_leaves(api.group_model(0))
        b = jax.tree_util.tree_leaves(api.group_model(1))
        assert any(not np.allclose(np.asarray(x), np.asarray(y)) for x, y in zip(a, b))

    def test_runner_dispatch(self):
        from fedml_tpu.simulation.simulator import SimulatorXLA
        from fedml_tpu.simulation.xla.hierarchical import HierarchicalInMeshAPI

        args, dataset, model = _build()
        sim = SimulatorXLA(args, None, dataset, model)
        assert isinstance(sim.sim, HierarchicalInMeshAPI)
