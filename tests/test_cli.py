"""CLI + edge deployment (SURVEY.md §2.9 cli/): build packaging, the run
supervisor's spawn/restart/status lifecycle, and the command surface."""

import json
import os
import sys
import textwrap

import pytest

from fedml_tpu.cli.build import build_package, read_package_meta, unpack_package
from fedml_tpu.cli.cli import main
from fedml_tpu.cli.edge_deployment.client_runner import FedMLRunnerSupervisor


@pytest.fixture
def user_project(tmp_path):
    """A minimal user training project: entry + config."""
    src = tmp_path / "src"
    src.mkdir()
    (src / "train.py").write_text(textwrap.dedent("""\
        import argparse, sys
        p = argparse.ArgumentParser()
        p.add_argument("--cf"); p.add_argument("--run_id"); p.add_argument("--role")
        p.add_argument("--fail", action="store_true")
        a, _ = p.parse_known_args()
        print("training with", a.cf, a.run_id, a.role)
        sys.exit(1 if a.fail else 0)
    """))
    cfg = tmp_path / "fedml_config.yaml"
    cfg.write_text("train_args:\n  epochs: 1\n")
    return src, cfg


class TestBuild:
    def test_build_and_unpack(self, user_project, tmp_path):
        src, cfg = user_project
        pkg = build_package(str(src), "train.py", str(cfg), str(tmp_path / "pkg.zip"))
        meta = read_package_meta(pkg)
        assert meta["entry"] == "train.py" and meta["type"] == "client"

        dest = tmp_path / "unpacked"
        meta2 = unpack_package(pkg, str(dest))
        assert (dest / "src" / "train.py").exists()
        assert (dest / "config" / "fedml_config.yaml").exists()
        assert meta2 == meta

    def test_missing_entry_rejected(self, user_project, tmp_path):
        src, cfg = user_project
        with pytest.raises(FileNotFoundError):
            build_package(str(src), "nope.py", str(cfg), str(tmp_path / "p.zip"))

    def test_zip_slip_rejected(self, tmp_path):
        import zipfile

        evil = tmp_path / "evil.zip"
        with zipfile.ZipFile(evil, "w") as z:
            z.writestr("fedml_package.json", json.dumps({"entry": "x", "config": "c"}))
            z.writestr("../escape.txt", "boom")
        with pytest.raises(ValueError, match="unsafe"):
            unpack_package(str(evil), str(tmp_path / "out"))


class TestSupervisor:
    def _pkg(self, user_project, tmp_path):
        src, cfg = user_project
        return build_package(str(src), "train.py", str(cfg), str(tmp_path / "pkg.zip"))

    def test_successful_run_reports_finished(self, user_project, tmp_path):
        pkg = self._pkg(user_project, tmp_path)
        sup = FedMLRunnerSupervisor(pkg, str(tmp_path / "run"), run_id="7")
        assert sup.run() == 0
        statuses = [r["status"] for r in FedMLRunnerSupervisor.read_status(str(tmp_path / "run"))]
        assert statuses == ["INITIALIZING", "TRAINING", "FINISHED"]
        log = (tmp_path / "run" / "run.log").read_text()
        assert "training with" in log

    def test_crash_restarts_then_fails(self, user_project, tmp_path):
        pkg = self._pkg(user_project, tmp_path)
        sup = FedMLRunnerSupervisor(pkg, str(tmp_path / "run"), run_id="8",
                                    max_restarts=1, extra_args=["--fail"])
        assert sup.run() != 0
        statuses = [r["status"] for r in FedMLRunnerSupervisor.read_status(str(tmp_path / "run"))]
        assert statuses.count("TRAINING") == 2  # initial + 1 restart
        assert statuses[-1] == "FAILED"

    def test_server_role_vocab(self, user_project, tmp_path):
        pkg = self._pkg(user_project, tmp_path)
        sup = FedMLRunnerSupervisor(pkg, str(tmp_path / "run"), role="server")
        assert sup.run() == 0
        statuses = [r["status"] for r in FedMLRunnerSupervisor.read_status(str(tmp_path / "run"))]
        assert statuses == ["STARTING", "RUNNING", "FINISHED"]


class TestCLICommands:
    def test_version(self, capsys):
        assert main(["version"]) == 0
        assert "fedml_tpu version" in capsys.readouterr().out

    def test_env(self, capsys):
        assert main(["env"]) == 0
        out = capsys.readouterr().out
        assert "python:" in out and "jax:" in out

    def test_build_run_status_logs(self, user_project, tmp_path, capsys):
        src, cfg = user_project
        pkg = str(tmp_path / "p.zip")
        assert main(["build", "-sf", str(src), "-ep", "train.py", "-cf", str(cfg),
                     "--dest_package", pkg]) == 0
        run_dir = str(tmp_path / "run")
        assert main(["run", "-p", pkg, "-d", run_dir, "--run_id", "42"]) == 0
        assert main(["status", "-d", run_dir]) == 0
        out = capsys.readouterr().out
        assert "FINISHED" in out
        assert main(["logs", "-d", run_dir]) == 0
        assert "training with" in capsys.readouterr().out

    def test_login_logout(self, tmp_path, monkeypatch, capsys):
        import fedml_tpu.cli.cli as cli_mod

        monkeypatch.setattr(cli_mod, "ACCOUNT_DIR", str(tmp_path / "acct"))
        monkeypatch.setattr(cli_mod, "ACCOUNT_FILE", str(tmp_path / "acct" / "account.json"))
        assert main(["login", "acct-123"]) == 0
        assert json.load(open(cli_mod.ACCOUNT_FILE))["account_id"] == "acct-123"
        assert main(["logout"]) == 0
        assert not os.path.exists(cli_mod.ACCOUNT_FILE)
