"""The examples/ tree is the integration-fixture matrix (reference CI runs
its examples/ dirs the same way — SURVEY.md §4): every config must load,
validate, and run its scenario end-to-end on tiny synthetic data."""

import glob
import os
import threading

import pytest
import yaml

import fedml_tpu
from fedml_tpu.arguments import Arguments

EXAMPLES = os.path.join(os.path.dirname(__file__), os.pardir, "examples")

pytestmark = pytest.mark.heavy  # e2e rounds + XLA compiles; see pytest.ini


def _load(cfg_path, **over):
    with open(cfg_path) as f:
        cfg = yaml.safe_load(f)
    args = Arguments.from_dict(cfg)
    for k, v in over.items():
        setattr(args, k, v)
    return args.validate()


def _all_configs(subdir):
    pat = os.path.join(EXAMPLES, subdir, "*", "fedml_config.yaml")
    return sorted(glob.glob(pat))


def test_examples_exist():
    assert len(_all_configs("simulation")) >= 10
    assert len(_all_configs("cross_silo")) >= 4


@pytest.mark.parametrize(
    "cfg", _all_configs("simulation"), ids=lambda p: p.split(os.sep)[-2]
)
def test_simulation_example(cfg):
    args = _load(cfg, run_id=f"ex-{os.path.basename(os.path.dirname(cfg))}")
    args = fedml_tpu.init(args, should_init_logs=False)
    device = fedml_tpu.device.get_device(args)
    dataset, out_dim = fedml_tpu.data.load(args)
    model = fedml_tpu.models.create(args, out_dim)
    from fedml_tpu.runner import FedMLRunner

    metrics = FedMLRunner(args, device, dataset, model).run()
    if str(getattr(args, "federated_optimizer", "")).lower() == "fedgan":
        # FedGAN reports adversarial health (d_fake_score), not accuracy
        assert metrics and "d_fake_score" in metrics
    else:
        assert metrics and "test_acc" in metrics


@pytest.mark.parametrize(
    "cfg",
    [c for c in _all_configs("cross_silo")
     # (light)secagg: own protocol harnesses below; hierarchical: needs
     # spawned silo slave processes (test_hierarchical_cross_silo_example)
     if "secagg" not in c and "hierarchical" not in c],
    ids=lambda p: p.split(os.sep)[-2],
)
def test_cross_silo_example(cfg, tmp_path):
    name = os.path.basename(os.path.dirname(cfg))
    broker = None
    over = {"run_id": f"ex-{name}"}
    if "mqtt" in name:
        from fedml_tpu.core.distributed.communication.mqtt_s3.broker import LocalBroker

        broker = LocalBroker().start()
        over.update(mqtt_port=broker.port, s3_blob_root=str(tmp_path / "blobs"))
    try:
        args_s = _load(cfg, role="server", rank=0, **over)
        args_s = fedml_tpu.init(args_s, should_init_logs=False)
        dataset, out_dim = fedml_tpu.data.load(args_s)
        model = fedml_tpu.models.create(args_s, out_dim)
        from fedml_tpu.cross_silo.server.server import Server

        server = Server(args_s, None, dataset, model)

        clients = []
        for rank in range(1, int(args_s.client_num_in_total) + 1):
            args_c = _load(cfg, role="client", rank=rank, **over)
            args_c = fedml_tpu.init(args_c, should_init_logs=False)
            ds_c, od_c = fedml_tpu.data.load(args_c)
            from fedml_tpu.cross_silo.client.client import Client

            clients.append(Client(args_c, None, ds_c, fedml_tpu.models.create(args_c, od_c)))

        threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
        for t in threads:
            t.start()
        history = server.run()
        for t in threads:
            t.join(timeout=30)
            assert not t.is_alive()
        assert history and 0.0 <= history[-1]["test_acc"] <= 1.0
    finally:
        if broker is not None:
            broker.stop()


def _hier_slave_proc(cfg_path, rank, pg_port, run_id):
    """One silo slave process: joins the silo's host pg, trains stride-shards
    until FINISH.  Spawned children skip conftest, so force CPU first."""
    from netutil import force_child_cpu

    force_child_cpu()
    import yaml as _yaml

    import fedml_tpu as _f
    from fedml_tpu.arguments import Arguments as _Args

    with open(cfg_path) as f:
        cfg = _yaml.safe_load(f)
    args = _Args.from_dict(cfg)
    args.role, args.rank, args.run_id = "client", rank, run_id
    args.proc_rank_in_silo = 1
    args.pg_master_port = pg_port
    args = _f.init(args.validate(), should_init_logs=False)
    ds, out_dim = _f.data.load(args)
    from fedml_tpu.cross_silo.client.client import Client as _Client

    _Client(args, None, ds, _f.models.create(args, out_dim)).run()


def test_hierarchical_cross_silo_example():
    """Hierarchical Octopus: 1 server + 2 client silos over GRPC, each silo
    = master thread + one spawned slave process synchronized over the host
    ProcessGroup plane (reference torchrun-spawned ClientSlaveManager)."""
    import multiprocessing as mp

    from netutil import free_port

    cfg = os.path.join(EXAMPLES, "cross_silo", "hierarchical_fedavg_mnist_lr",
                       "fedml_config.yaml")
    run_id = "ex-hier"
    args_s = _load(cfg, role="server", rank=0, run_id=run_id)
    args_s = fedml_tpu.init(args_s, should_init_logs=False)
    dataset, out_dim = fedml_tpu.data.load(args_s)
    model = fedml_tpu.models.create(args_s, out_dim)
    from fedml_tpu.cross_silo.server.server import Server

    server = Server(args_s, None, dataset, model)

    ctx = mp.get_context("spawn")
    pg_ports = {rank: free_port() for rank in (1, 2)}
    slaves = [ctx.Process(target=_hier_slave_proc,
                          args=(cfg, rank, pg_ports[rank], run_id), daemon=True)
              for rank in (1, 2)]
    for p in slaves:
        p.start()

    masters = []
    for rank in (1, 2):
        args_c = _load(cfg, role="client", rank=rank, run_id=run_id,
                       proc_rank_in_silo=0, pg_master_port=pg_ports[rank])
        args_c = fedml_tpu.init(args_c, should_init_logs=False)
        ds_c, od_c = fedml_tpu.data.load(args_c)
        from fedml_tpu.cross_silo.client.client import Client

        masters.append(Client(args_c, None, ds_c, fedml_tpu.models.create(args_c, od_c)))

    threads = [threading.Thread(target=c.run, daemon=True) for c in masters]
    for t in threads:
        t.start()
    history = server.run()
    for t in threads:
        t.join(timeout=60)
        assert not t.is_alive()
    for p in slaves:
        p.join(timeout=60)
        assert p.exitcode == 0
    assert history and 0.0 <= history[-1]["test_acc"] <= 1.0


def test_cross_device_example(tmp_path):
    """Beehive example: server + fake devices over the file model plane."""
    import importlib.util as ilu

    ex = os.path.join(EXAMPLES, "cross_device", "beehive_fedavg_synthetic_lr")
    spec = ilu.spec_from_file_location("beehive_example", os.path.join(ex, "main.py"))
    mod = ilu.module_from_spec(spec)
    spec.loader.exec_module(mod)
    history = mod.main(os.path.join(ex, "fedml_config.yaml"),
                       workdir=str(tmp_path))
    assert history and history[-1]["test_acc"] > 0.5


def test_lightsecagg_example():
    cfg = os.path.join(EXAMPLES, "cross_silo", "lightsecagg_mnist_lr", "fedml_config.yaml")
    args = _load(cfg, run_id="ex-lsa")
    args = fedml_tpu.init(args, should_init_logs=False)
    from fedml_tpu.cross_silo.lightsecagg import run_lightsecagg_topology_in_threads

    history = run_lightsecagg_topology_in_threads(
        args,
        lambda a: fedml_tpu.data.load(a),
        lambda a, out_dim: fedml_tpu.models.create(a, out_dim),
    )
    assert history


def test_secagg_example():
    from fedml_tpu.core.distributed.communication.loopback import LoopbackHub

    LoopbackHub.reset()
    cfg = os.path.join(EXAMPLES, "cross_silo", "secagg_mnist_lr", "fedml_config.yaml")
    args = _load(cfg, run_id="ex-sa")
    args = fedml_tpu.init(args, should_init_logs=False)
    from fedml_tpu.cross_silo.secagg import run_secagg_topology_in_threads

    history = run_secagg_topology_in_threads(
        args,
        lambda a: fedml_tpu.data.load(a),
        lambda a, out_dim: fedml_tpu.models.create(a, out_dim),
    )
    assert history and history[-1]["test_acc"] > 0.2
