"""Stacked (XLA-backend) security math == the host list-based hooks.

core/security/stacked.py restates every attack/defense over the compiled
round's ``[n, D]`` update stack; these tests pin each rule to the host
dispatcher path (attack_model / defend_before+aggregate / defend_on /
defend_after) on the same inputs.  Fast suite: tiny trees, CPU."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.core.aggregate import weighted_mean
from fedml_tpu.core.security import stacked as S
from fedml_tpu.core.security.fedml_attacker import FedMLAttacker
from fedml_tpu.core.security.fedml_defender import FedMLDefender


class _Args:
    def __init__(self, **kw):
        self.random_seed = 0
        for k, v in kw.items():
            setattr(self, k, v)


def _tree(vec):
    """Deterministic 10-dim test tree: params.w [2,3] + params.b [3] + extra [1]."""
    v = np.asarray(vec, np.float32)
    return {
        "params": {"w": jnp.asarray(v[:6].reshape(2, 3)), "b": jnp.asarray(v[6:9])},
        "stats": {"m": jnp.asarray(v[9:10])},
    }


def _make_updates(n=6, seed=0, outlier=None):
    rng = np.random.RandomState(seed)
    ups = []
    for i in range(n):
        vec = rng.normal(1.0, 0.05, 10)
        if outlier is not None and i in outlier:
            vec = rng.normal(8.0, 0.5, 10)
        ups.append((float(1 + i % 3), _tree(vec)))
    return ups


def _stack(updates):
    trees = [p for _, p in updates]
    stack = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs, 0), *trees)
    w = jnp.asarray([n for n, _ in updates], jnp.float32)
    return stack, w


def _flat(tree):
    from jax.flatten_util import ravel_pytree

    return np.asarray(ravel_pytree(tree)[0])


GLOBAL = _tree(np.ones(10))


def _host_defense_agg(defender, updates, global_params):
    """The ServerAggregator hook order on the host list path."""
    if defender.is_defense_before_aggregation():
        updates = defender.defend_before_aggregation(updates, global_params)
        return weighted_mean(updates)
    if defender.is_defense_on_aggregation():
        return defender.defend_on_aggregation(
            updates,
            base_aggregation_func=lambda a, u: weighted_mean(u),
            extra_auxiliary_info=global_params,
        )
    return defender.defend_after_aggregation(weighted_mean(updates))


DEFENSE_CASES = [
    ("krum", dict(byzantine_client_num=1)),
    ("multi_krum", dict(byzantine_client_num=1, krum_param_m=3)),
    ("norm_diff_clipping", dict(norm_bound=2.0)),
    ("3sigma", {}),
    ("geometric_median", dict(geo_median_max_iter=8)),
    ("rfa", dict(geo_median_max_iter=8)),
    ("cclip", dict(tau=1.5, bucket_iter=2)),
    ("slsgd", dict(trim_param_b=1, alpha=0.5)),
    ("foolsgold", {}),
    ("robust_learning_rate", dict(robust_threshold=4)),
    ("coordinate_wise_median", {}),
    ("coordinate_wise_trimmed_mean", dict(beta=0.2)),
    ("bulyan", dict(byzantine_client_num=1)),
    ("weak_dp", dict(stddev=0.0)),  # stddev 0: deterministic comparison
    ("wbc", dict(wbc_strength=0.0, client_num_in_total=6,
                 client_num_per_round=6)),  # strength 0: deterministic
    ("soteria", dict(soteria_layer=("w",), soteria_percentile=34.0)),
]


def test_defense_matrix_is_complete():
    """Drift gate: every defense the host dispatcher supports MUST have a
    stacked cross-check case here — adding a defense to one path without
    the other (or without extending this matrix) fails this test."""
    from fedml_tpu.core.security.fedml_defender import SUPPORTED_DEFENSES

    assert sorted({name for name, _ in DEFENSE_CASES}) == SUPPORTED_DEFENSES


@pytest.mark.parametrize("defense,extra", DEFENSE_CASES)
def test_stacked_defense_matches_host(defense, extra):
    updates = _make_updates(outlier={2})
    d = FedMLDefender.get_instance()
    d.init(_Args(enable_defense=True, defense_type=defense, **extra))
    host = _host_defense_agg(d, updates, GLOBAL)

    stack, w = _stack(updates)
    fn = S.build_stacked_defense(_Args(**extra), defense)
    state = S.init_defense_state(defense, int(w.shape[0]), S.flat_dim(GLOBAL))
    agg, _ = fn(stack, w, GLOBAL, jax.random.PRNGKey(0), state)

    np.testing.assert_allclose(_flat(agg), _flat(host), rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("defense,extra", DEFENSE_CASES)
def test_rows_mode_aggregate_matches_tree_mode(defense, extra):
    """The ext-aggregator path (rows=True) must stay consistent with the
    acc path: the weighted mean of the defended row space equals the
    tree-mode aggregate, for every rule."""
    updates = _make_updates(outlier={2})
    stack, w = _stack(updates)
    state = S.init_defense_state(defense, int(w.shape[0]), S.flat_dim(GLOBAL))
    agg, _ = S.build_stacked_defense(_Args(**extra), defense)(
        stack, w, GLOBAL, jax.random.PRNGKey(0), state
    )
    mat2, w2, _ = S.build_stacked_defense(_Args(**extra), defense, rows=True)(
        stack, w, GLOBAL, jax.random.PRNGKey(0), state
    )
    rows_agg = np.asarray((w2 @ mat2) / jnp.maximum(jnp.sum(w2), 1e-9))
    np.testing.assert_allclose(rows_agg, _flat(agg), rtol=2e-4, atol=2e-5)


def test_stacked_foolsgold_state_accumulates():
    updates = _make_updates()
    stack, w = _stack(updates)
    fn = S.build_stacked_defense(_Args(), "foolsgold")
    state = S.init_defense_state("foolsgold", 6, S.flat_dim(GLOBAL))
    _, s1 = fn(stack, w, GLOBAL, jax.random.PRNGKey(0), state)
    _, s2 = fn(stack, w, GLOBAL, jax.random.PRNGKey(0), s1)
    assert float(jnp.abs(s2["fg_hist"]).sum()) > float(jnp.abs(s1["fg_hist"]).sum())


def test_stacked_wbc_perturbs_after_first_round():
    updates = _make_updates()
    stack, w = _stack(updates)
    fn = S.build_stacked_defense(_Args(wbc_strength=5.0, wbc_lr=0.5), "wbc")
    state = S.init_defense_state("wbc", 6, S.flat_dim(GLOBAL))
    a1, s1 = fn(stack, w, GLOBAL, jax.random.PRNGKey(0), state)
    assert float(s1["wbc_has"]) == 1.0
    # round 1 has no prev: aggregate is the plain weighted mean
    np.testing.assert_allclose(_flat(a1), _flat(weighted_mean(updates)), rtol=1e-5)
    a2, _ = fn(stack, w, GLOBAL, jax.random.PRNGKey(1), s1)
    # identical updates two rounds running = maximally persistent space:
    # noise lands somewhere
    assert np.abs(_flat(a2) - _flat(a1)).max() > 0


ATTACK_CASES = [
    ("byzantine", dict(attack_mode="zero", byzantine_client_num=2)),
    ("byzantine", dict(attack_mode="flip", byzantine_client_num=2)),
    ("model_replacement", dict(attack_scale=5.0, byzantine_client_num=2)),
    ("backdoor", dict(attack_mode="craft", attack_num_std=1.5, byzantine_client_num=2)),
    ("backdoor", dict(attack_mode="clip", attack_num_std=1.5, byzantine_client_num=2)),
    ("edge_case_backdoor", dict(attack_scale=5.0, attack_norm_bound=2.0,
                                byzantine_client_num=2)),
]


@pytest.mark.parametrize("attack,extra", ATTACK_CASES)
def test_stacked_attack_matches_host(attack, extra):
    n = 6
    updates = _make_updates(n)
    a = FedMLAttacker.get_instance()
    a.init(_Args(enable_attack=True, attack_type=attack,
                 client_num_in_total=n, **extra))
    idxs = a.get_byzantine_idxs(n)
    host = a.attack_model(list(updates), GLOBAL)
    host_mat = np.stack([_flat(p) for _, p in host])

    stack, w = _stack(updates)
    mat = S.stack_to_mat(stack)
    g_vec = _flat(GLOBAL)
    mal = jnp.zeros((n,)).at[jnp.asarray(idxs)].set(1.0)
    fn = S.build_stacked_attack(_Args(**extra), attack)
    out = np.asarray(fn(mat, w, jnp.asarray(g_vec), mal, jax.random.PRNGKey(0)))

    np.testing.assert_allclose(out, host_mat, rtol=2e-4, atol=2e-5)


def test_stacked_attack_random_mode_corrupts_only_malicious():
    n = 6
    updates = _make_updates(n)
    stack, w = _stack(updates)
    mat = S.stack_to_mat(stack)
    mal = jnp.zeros((n,)).at[jnp.asarray([1, 4])].set(1.0)
    fn = S.build_stacked_attack(_Args(attack_mode="random"), "byzantine")
    out = np.asarray(fn(mat, w, jnp.asarray(_flat(GLOBAL)), mal, jax.random.PRNGKey(0)))
    benign = [0, 2, 3, 5]
    np.testing.assert_allclose(out[benign], np.asarray(mat)[benign])
    assert np.abs(out[[1, 4]] - np.asarray(mat)[[1, 4]]).max() > 0.5
