"""iOS Swift package (ios/FedMLTpu): drift gates that run everywhere, plus
a `swift build` compile check when a Swift toolchain is present.

The binding surface is the C ABI header native/include/fedml_capi.h —
capi.cpp includes it (definition drift = native compile error), the Swift
package vendors a byte-identical copy, and the gates below keep the header,
the implementation, and the Swift wrapper aligned."""

from __future__ import annotations

import os
import re
import shutil
import subprocess

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CANON = os.path.join(REPO, "native", "include", "fedml_capi.h")
VENDORED = os.path.join(REPO, "ios", "FedMLTpu", "Sources", "CFedML",
                        "fedml_capi.h")
CAPI = os.path.join(REPO, "native", "capi.cpp")
SWIFT_SRC = os.path.join(REPO, "ios", "FedMLTpu", "Sources", "FedMLTpu",
                         "FedMLTrainer.swift")


def _header_functions(path: str) -> set:
    with open(path) as f:
        text = f.read()
    return set(re.findall(r"\b(fedml_\w+)\s*\(", text))


class TestHeaderDriftGates:
    def test_vendored_header_is_byte_identical(self):
        with open(CANON, "rb") as a, open(VENDORED, "rb") as b:
            assert a.read() == b.read(), (
                "ios/FedMLTpu vendored header drifted from "
                "native/include/fedml_capi.h — copy it over")

    def test_capi_defines_every_declared_function(self):
        declared = _header_functions(CANON) - {"fedml_progress_cb"}
        with open(CAPI) as f:
            impl = f.read()
        defined = set(re.findall(r"\b(fedml_\w+)\(", impl))
        missing = declared - defined
        assert not missing, f"declared but not defined: {missing}"

    def test_capi_includes_the_header(self):
        # the compile-time drift gate only exists if capi.cpp includes it
        with open(CAPI) as f:
            assert 'include/fedml_capi.h' in f.read()

    def test_header_compiles_as_c_and_cpp(self, tmp_path):
        gxx = shutil.which("g++")
        gcc = shutil.which("gcc")
        if not (gxx and gcc):
            pytest.skip("no host compiler")
        tu = tmp_path / "tu.c"
        tu.write_text('#include "fedml_capi.h"\nint main(void){return 0;}\n')
        for comp in (gcc, gxx):
            out = subprocess.run(
                [comp, "-fsyntax-only", "-Wall", "-Werror",
                 f"-I{os.path.dirname(CANON)}", str(tu)],
                capture_output=True, text=True)
            assert out.returncode == 0, (comp, out.stderr)

    def test_swift_wrapper_calls_only_declared_functions(self):
        declared = _header_functions(CANON)
        with open(SWIFT_SRC) as f:
            used = set(re.findall(r"\b(fedml_\w+)\s*\(", f.read()))
        unknown = used - declared
        assert not unknown, f"Swift calls undeclared C functions: {unknown}"
        # and the core trainer surface is actually wrapped
        for fn in ("fedml_trainer_create", "fedml_trainer_train",
                   "fedml_trainer_save", "fedml_client_save_masked_model"):
            assert fn in used, f"Swift wrapper misses {fn}"


HAVE_SWIFT = shutil.which("swift") is not None


@pytest.mark.skipif(not HAVE_SWIFT, reason="no Swift toolchain in this image")
class TestSwiftBuild:
    def test_package_compiles(self):
        out = subprocess.run(
            ["swift", "build", "-Xlinker", f"-L{os.path.join(REPO, 'native')}"],
            cwd=os.path.join(REPO, "ios", "FedMLTpu"),
            capture_output=True, text=True, timeout=600)
        assert out.returncode == 0, out.stderr


class TestSwiftProtocolDriftGates:
    """The Swift protocol layer (MessageDefine/BrokerConnection/
    EdgeClientManager) mirrors the Java SDK and the Python wire — same
    parsing gates as tests/test_java_sdk.py, Swift flavored."""

    SWIFT_DIR = os.path.join(REPO, "ios", "FedMLTpu", "Sources", "FedMLTpu")

    def _swift(self, name):
        with open(os.path.join(self.SWIFT_DIR, name)) as f:
            return f.read()

    def test_message_define_matches_python(self):
        from fedml_tpu.cross_device.message_define import MNNMessage

        src = self._swift("MessageDefine.swift")
        ints = dict(re.findall(r"let (MSG_TYPE_\w+) = (\d+)", src))
        strs = dict(re.findall(r'let (\w+) = "([^"]*)"', src))
        assert ints, "no int constants parsed from MessageDefine.swift"
        for name, val in ints.items():
            assert getattr(MNNMessage, name) == int(val), name
        for name, val in strs.items():
            if name == "MSG_TYPE_CONNECTION_READY":
                assert val == "connection_ready"
                continue
            assert getattr(MNNMessage, name) == val, name
        for name in dir(MNNMessage):
            if name.startswith(("MSG_TYPE_", "MSG_ARG_KEY_", "CLIENT_STATUS_")):
                assert name in ints or name in strs, f"missing in Swift: {name}"

    def test_broker_frame_ops_match(self):
        src = self._swift("BrokerConnection.swift")
        for op in ("SUB", "UNSUB", "PUB", "WILL", "DISCONNECT", "MSG"):
            assert f'"{op}"' in src, f"missing broker op {op}"
        # the RST-safe close contract (shared with Java/Python clients)
        assert "SHUT_WR" in src
        assert "onConnectionLost" in src

    def test_client_topic_scheme_matches(self):
        src = self._swift("EdgeClientManager.swift")
        assert 'fedml/\\(runId)/\\(rank)/0' in src
        assert 'fedml/\\(runId)/0/\\(rank)' in src
        assert 'fedml/\\(runId)/status' in src
        assert 'fedml/\\(runId)/#' in src
