"""Edge deployment daemon (reference ``cli/edge_deployment/client_daemon.py``
+ ``server_runner.py``): dispatch-directory and broker run channels,
heartbeat introspection, status publication, stop protocol."""

import json
import os
import textwrap
import time

import pytest

from fedml_tpu.cli.build import build_package
from fedml_tpu.cli.edge_deployment.daemon import FedMLDaemon


@pytest.fixture
def package(tmp_path):
    src = tmp_path / "src"
    src.mkdir()
    (src / "train.py").write_text(textwrap.dedent("""\
        import argparse, sys
        p = argparse.ArgumentParser()
        p.add_argument("--cf"); p.add_argument("--run_id"); p.add_argument("--role")
        a, _ = p.parse_known_args()
        print("trained", a.run_id)
        sys.exit(0)
    """))
    cfg = tmp_path / "fedml_config.yaml"
    cfg.write_text("train_args:\n  epochs: 1\n")
    return build_package(str(src), "train.py", str(cfg), str(tmp_path / "pkg.zip"))


def _wait(pred, timeout=30.0):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if pred():
            return True
        time.sleep(0.1)
    return False


class TestDispatchDir:
    def test_file_dispatch_runs_and_heartbeats(self, package, tmp_path):
        home = tmp_path / "home"
        d = FedMLDaemon(str(home), role="client", account_id="acc1",
                        poll_interval=0.1)
        t = d.serve_async()
        try:
            req = {"run_id": "42", "package": package}
            path = home / "dispatch" / "run_42.json"
            with open(str(path) + ".tmp", "w") as f:
                json.dump(req, f)
            os.replace(str(path) + ".tmp", path)
            assert _wait(lambda: (FedMLDaemon.read_state(str(home)) or {})
                         .get("runs", {}).get("42") == "FINISHED")
            # request file was consumed
            assert not path.exists()
            assert (home / "dispatch" / "run_42.json.accepted").exists()
            state = FedMLDaemon.read_state(str(home))
            assert state["role"] == "client" and state["account_id"] == "acc1"
            log = (home / "runs" / "42" / "run.log").read_text()
            assert "trained 42" in log
        finally:
            d.stop()
            t.join(timeout=10)

    def test_stop_file_ends_serve(self, tmp_path):
        home = tmp_path / "home"
        d = FedMLDaemon(str(home), poll_interval=0.05)
        t = d.serve_async()
        assert _wait(lambda: FedMLDaemon.read_state(str(home)) is not None)
        FedMLDaemon.request_stop(str(home))
        t.join(timeout=10)
        assert not t.is_alive()


class TestBrokerChannel:
    def test_broker_dispatch_and_status_publication(self, package, tmp_path):
        from fedml_tpu.core.distributed.communication.mqtt_s3.broker import (
            BrokerClient, LocalBroker,
        )

        broker = LocalBroker().start()
        statuses = []
        try:
            watcher = BrokerClient(
                "127.0.0.1", broker.port,
                lambda topic, payload: statuses.append(payload["status"]),
            )
            watcher.subscribe("mlops/status/client/#")
            home = tmp_path / "home"
            d = FedMLDaemon(str(home), role="client", account_id="acc2",
                            broker=f"127.0.0.1:{broker.port}", poll_interval=0.1)
            t = d.serve_async()
            try:
                pusher = BrokerClient("127.0.0.1", broker.port, lambda *_: None)
                pusher.publish("mlops/deploy/client/acc2",
                               {"run_id": "7", "package": package})
                assert _wait(lambda: "FINISHED" in statuses)
                assert statuses[0] in ("INITIALIZING", "STARTING")
                pusher.disconnect()
            finally:
                d.stop()
                t.join(timeout=10)
            watcher.disconnect()
        finally:
            broker.stop()


class TestCLISurface:
    def test_dispatch_and_status_commands(self, package, tmp_path, capsys):
        from fedml_tpu.cli.cli import main

        home = tmp_path / "home"
        d = FedMLDaemon(str(home), poll_interval=0.1)
        t = d.serve_async()
        try:
            rc = main(["dispatch", "--package", package, "--run_id", "9",
                       "--daemon_home", str(home)])
            assert rc == 0
            assert _wait(lambda: (FedMLDaemon.read_state(str(home)) or {})
                         .get("runs", {}).get("9") == "FINISHED")
            rc = main(["status", "--daemon_home", str(home)])
            out = capsys.readouterr().out
            assert rc == 0
            assert "run 9: FINISHED" in out
        finally:
            d.stop()
            t.join(timeout=10)
