"""Native C++ edge runtime (native/ + fedml_tpu.native ctypes bindings) —
the rebuild's MobileNN equivalent (SURVEY.md §2.8).  Builds the shared lib
with make, then exercises trainer, FTEM interop, and the LightSecAgg codec
cross-language against core/mpc."""

import os
import struct

import numpy as np
import pytest

from fedml_tpu.cross_device.edge_model import load_edge_model, save_edge_model

native = pytest.importorskip("fedml_tpu.native")


@pytest.fixture(scope="module")
def lib():
    return native.load()  # builds via make when stale


def _separable(n, d=10, classes=4, seed=0):
    centers = np.random.RandomState(7).randn(classes, d) * 3
    rng = np.random.RandomState(seed)
    y = rng.randint(0, classes, n)
    x = centers[y] + rng.randn(n, d) * 0.5
    return x.astype(np.float32), y.astype(np.int32)


def _write_data(tmp_path, x, y, name="data.ftem"):
    path = str(tmp_path / name)
    save_edge_model(path, {"x": x, "y": y.astype(np.int32)})
    return path


def _write_model(tmp_path, d, classes, hidden=0, name="model.ftem", seed=0):
    rng = np.random.RandomState(seed)
    if hidden:
        flat = {
            "params/Dense_0/kernel": (rng.randn(d, hidden) * 0.1).astype(np.float32),
            "params/Dense_0/bias": np.zeros(hidden, np.float32),
            "params/Dense_1/kernel": (rng.randn(hidden, classes) * 0.1).astype(np.float32),
            "params/Dense_1/bias": np.zeros(classes, np.float32),
        }
    else:
        flat = {
            "params/linear/kernel": np.zeros((d, classes), np.float32),
            "params/linear/bias": np.zeros(classes, np.float32),
        }
    path = str(tmp_path / name)
    save_edge_model(path, flat)
    return path


class TestNativeTrainer:
    def test_lr_learns_and_reports_progress(self, lib, tmp_path):
        x, y = _separable(256)
        data = _write_data(tmp_path, x, y)
        model = _write_model(tmp_path, 10, 4)
        t = native.EdgeTrainer(model, data, batch_size=32, lr=0.3, epochs=4, seed=1)
        seen = []
        t.set_progress_callback(lambda e, l: seen.append((e, l)))
        t.train()
        epoch, loss = t.epoch_and_loss()
        assert epoch == 4 and len(seen) == 4
        assert seen[-1][1] < seen[0][1]  # loss decreased
        acc, _ = t.evaluate()
        assert acc > 0.9
        assert t.num_samples == 256

        out = t.save(str(tmp_path / "trained.ftem"))
        flat = load_edge_model(out)  # python reads what C++ wrote
        assert flat["params/linear/kernel"].shape == (10, 4)
        assert np.abs(flat["params/linear/kernel"]).sum() > 0
        t.close()

    def test_mlp_learns(self, lib, tmp_path):
        x, y = _separable(256, seed=2)
        t = native.EdgeTrainer(
            _write_model(tmp_path, 10, 4, hidden=16), _write_data(tmp_path, x, y),
            batch_size=32, lr=0.1, epochs=6, seed=3,
        )
        t.train()
        acc, _ = t.evaluate()
        assert acc > 0.9
        t.close()

    def test_bad_model_error_surfaces(self, lib, tmp_path):
        data = _write_data(tmp_path, *_separable(16))
        path = str(tmp_path / "junk.ftem")
        save_edge_model(path, {"not_a_kernel": np.zeros(3, np.float32)})
        with pytest.raises(RuntimeError, match="kernel"):
            native.EdgeTrainer(path, data)

    def test_mnist_idx_converter(self, lib, tmp_path):
        # craft a 3-image idx pair
        n, rows, cols = 3, 4, 4
        imgs = tmp_path / "imgs"
        labs = tmp_path / "labs"
        pix = np.arange(n * rows * cols, dtype=np.uint8)
        imgs.write_bytes(struct.pack(">IIII", 0x803, n, rows, cols) + pix.tobytes())
        labs.write_bytes(struct.pack(">II", 0x801, n) + bytes([0, 1, 2]))
        out = native.mnist_idx_to_ftem(str(imgs), str(labs), str(tmp_path / "m.ftem"))
        flat = load_edge_model(out)
        assert flat["x"].shape == (3, 16)
        np.testing.assert_allclose(flat["x"][0, 1], 1 / 255.0, rtol=1e-5)
        np.testing.assert_array_equal(flat["y"], [0, 1, 2])


class TestLightSecAggInterop:
    def test_native_encode_python_decode(self, lib):
        """C++ mask encodings must reconstruct with the PYTHON server math."""
        from fedml_tpu.core.mpc.field import FIELD_PRIME
        from fedml_tpu.core.mpc.lightsecagg import (
            aggregate_mask_reconstruction,
            compute_aggregate_encoded_mask,
        )

        d, n, t, u = 23, 4, 1, 3
        rng = np.random.default_rng(5)
        masks = [rng.integers(0, int(FIELD_PRIME), d, dtype=np.int64) for _ in range(n)]
        # each client encodes natively
        rows_per_client = [native.lsa_mask_encoding(d, n, t, u, masks[c], seed=100 + c)
                           for c in range(n)]
        surviving = [1, 2, 3]  # client ids, 1-based; one dropout (4)
        # surviving client j sums the rows addressed to it from surviving peers
        agg = {}
        for j in surviving:
            received = {c + 1: rows_per_client[c][j - 1] for c in range(n) if c + 1 in surviving}
            agg[j] = compute_aggregate_encoded_mask(received, surviving)
        recon = aggregate_mask_reconstruction(agg, t, u, d)
        expected = np.zeros(d, np.int64)
        for c in surviving:
            expected = (expected + masks[c - 1]) % FIELD_PRIME
        np.testing.assert_array_equal(recon, expected)

    def test_python_encode_native_decode(self, lib):
        """And the reverse: python encodings decoded by the native codec."""
        from fedml_tpu.core.mpc.field import FIELD_PRIME
        from fedml_tpu.core.mpc.lightsecagg import mask_encoding

        d, n, t, u = 17, 5, 2, 4
        rng = np.random.default_rng(11)
        masks = [rng.integers(0, int(FIELD_PRIME), d, dtype=np.int64) for _ in range(n)]
        rows_per_client = [mask_encoding(d, n, t, u, masks[c], rng) for c in range(n)]
        surviving = [1, 2, 4, 5]
        agg_rows = []
        for j in surviving:
            s = np.zeros_like(rows_per_client[0][0])
            for c in surviving:
                s = (s + rows_per_client[c - 1][j - 1]) % FIELD_PRIME
            agg_rows.append(s)
        recon = native.lsa_aggregate_decode(np.stack(agg_rows), surviving, t, u, d)
        expected = np.zeros(d, np.int64)
        for c in surviving:
            expected = (expected + masks[c - 1]) % FIELD_PRIME
        np.testing.assert_array_equal(recon, expected)


class TestNativeDeviceProtocol:
    def test_cross_device_round_with_native_devices(self, lib, tmp_path):
        """Full Beehive round where devices train in C++ (use_native=True)."""
        from fedml_tpu.arguments import Arguments
        from fedml_tpu.core.distributed.communication.loopback import LoopbackHub
        from fedml_tpu.cross_device.fake_device import FakeDeviceManager
        from fedml_tpu.cross_device.fedml_aggregator import FedMLAggregator
        from fedml_tpu.cross_device.fedml_server_manager import FedMLServerManager
        from fedml_tpu.models.linear import LogisticRegression

        LoopbackHub.reset()
        args = Arguments.from_dict(
            {
                "common_args": {"training_type": "cross_device", "random_seed": 0,
                                "run_id": "native-proto"},
                "data_args": {"dataset": "synthetic"},
                "model_args": {"model": "lr"},
                "train_args": {
                    "federated_optimizer": "FedAvg",
                    "client_num_in_total": 2,
                    "client_num_per_round": 2,
                    "comm_round": 2,
                    "epochs": 2,
                    "batch_size": 16,
                    "learning_rate": 0.2,
                },
                "validation_args": {"frequency_of_the_test": 1},
                "comm_args": {"backend": "LOOPBACK"},
            }
        ).validate()
        x_test, y_test = _separable(128, seed=9)
        aggregator = FedMLAggregator(args, LogisticRegression(output_dim=4),
                                     (x_test, y_test), worker_num=2,
                                     model_dir=str(tmp_path / "models"))
        server = FedMLServerManager(args, aggregator, client_rank=0, client_num=2)
        devices = [
            FakeDeviceManager(args, r, _separable(96, seed=r), client_num=2,
                              upload_dir=str(tmp_path / f"dev{r}"), use_native=True)
            for r in (1, 2)
        ]
        threads = [server.run_async()] + [d.run_async() for d in devices]
        for t in threads:
            t.join(timeout=60)
        assert all(not t.is_alive() for t in threads)
        assert aggregator.eval_history[-1]["test_acc"] > 0.8


class TestNativeClientManager:
    def test_full_lightsecagg_round(self, lib, tmp_path):
        """3 native clients -> masked uploads + encoded sub-masks; python
        server unmasks the aggregate and matches the true quantized average
        (the C++ LightSecAggForMNN flow, SURVEY.md §2.8)."""
        from fedml_tpu.core.mpc.field import FIELD_PRIME
        from fedml_tpu.core.mpc.lightsecagg import (
            aggregate_mask_reconstruction,
            compute_aggregate_encoded_mask,
        )
        from fedml_tpu.core.mpc.secagg import transform_finite_to_tensor

        n, t, u, q_bits = 3, 1, 3, 16
        clients = []
        for c in range(n):
            x, y = _separable(96, seed=c)
            cm = native.EdgeClientManager(
                _write_model(tmp_path, 10, 4, name=f"m{c}.ftem"),
                _write_data(tmp_path, x, y, name=f"d{c}.ftem"),
                batch_size=32, lr=0.2, epochs=2, seed=c,
            )
            cm.train()
            clients.append(cm)
        d = clients[0].mask_dim

        masked, enc_rows, plains = [], [], []
        for c, cm in enumerate(clients):
            mpath = cm.save_masked_model(q_bits, mask_seed=500 + c,
                                         out_path=str(tmp_path / f"masked{c}.ftem"))
            masked.append(load_edge_model(mpath)["masked_params"].astype(np.int64))
            enc_rows.append(cm.encode_mask(n, t, u, mask_seed=500 + c))
            # ground truth: the unmasked trained params
            ppath = cm.save_model(str(tmp_path / f"plain{c}.ftem"))
            flat = load_edge_model(ppath)
            plains.append(np.concatenate([flat[k].ravel() for k in sorted(flat)]))

        surviving = [1, 2, 3]
        agg = {}
        for j in surviving:
            received = {c + 1: enc_rows[c][j - 1] for c in range(n)}
            agg[j] = compute_aggregate_encoded_mask(received, surviving)
        agg_mask = aggregate_mask_reconstruction(agg, t, u, d)

        summed = np.zeros(d, np.int64)
        for m in masked:
            summed = (summed + m) % FIELD_PRIME
        unmasked = (summed - agg_mask) % FIELD_PRIME
        avg = transform_finite_to_tensor(unmasked, q_bits=q_bits) / n

        expected = np.mean(plains, axis=0)
        np.testing.assert_allclose(avg, expected, atol=2e-4)
        for cm in clients:
            cm.close()
