"""Child process for the multi-host (2-process jax.distributed) round test.

Run as:  python multihost_child.py <rank> <coordinator_port>
Env must set JAX_PLATFORMS=cpu and XLA_FLAGS device-count BEFORE jax loads
(the parent test does this via the subprocess env).  Prints one final line
``MHOK <padded_norm> <packed_norm> <defended_norm>`` consumed by the parent.
"""

import os
import sys


def main(rank: int, port: str) -> None:
    os.environ["FEDML_JAX_COORDINATOR"] = f"127.0.0.1:{port}"
    os.environ["FEDML_JAX_NUM_PROCESSES"] = "2"
    os.environ["FEDML_JAX_PROCESS_ID"] = str(rank)

    import numpy as np

    import fedml_tpu
    from fedml_tpu.arguments import Arguments

    def build_args(**over):
        args = Arguments.from_dict({
            "common_args": {"training_type": "simulation", "random_seed": 0,
                            "run_id": "mh"},
            "data_args": {"dataset": "mnist", "data_cache_dir": "",
                          "partition_method": "homo",
                          "synthetic_train_size": 128},
            "model_args": {"model": "lr"},
            "train_args": {"federated_optimizer": "FedAvg",
                           "client_num_in_total": 16,
                           "client_num_per_round": 16, "comm_round": 2,
                           "epochs": 1, "batch_size": 16,
                           "client_optimizer": "sgd", "learning_rate": 0.1},
            "validation_args": {"frequency_of_the_test": 0},
            "comm_args": {"backend": "XLA"},
        })
        for k, v in over.items():
            setattr(args, k, v)
        return args.validate()

    args = fedml_tpu.init(build_args(), should_init_logs=False)
    import jax

    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 8, len(jax.devices())

    from fedml_tpu import data, models
    from fedml_tpu.simulation.xla.fed_sim import XLASimulator

    def norm(sim):
        return sum(float(np.sum(np.abs(np.asarray(l))))
                   for l in jax.tree_util.tree_leaves(sim.variables))

    dataset, out_dim = data.load(args)
    model = models.create(args, out_dim)
    sim = XLASimulator(args, dataset, model)
    sim.train()
    padded = norm(sim)

    args2 = fedml_tpu.init(build_args(xla_pack=True), should_init_logs=False)
    sim2 = XLASimulator(args2, dataset, model)
    sim2.train()
    packed = norm(sim2)

    # the security path: the per-client update stack stays P('client')-
    # sharded (NOT fully addressable from either process) and the stacked
    # attack + robust-aggregation program consumes it with global
    # semantics — the multi-host-safety claim, executed for real
    from fedml_tpu.core.security.fedml_attacker import FedMLAttacker
    from fedml_tpu.core.security.fedml_defender import FedMLDefender

    args3 = build_args(xla_pack=True, enable_attack=True,
                       attack_type="byzantine", attack_mode="random",
                       byzantine_client_num=2, enable_defense=True,
                       defense_type="krum")
    FedMLAttacker._attacker_instance = None
    FedMLDefender._defender_instance = None
    args3 = fedml_tpu.init(args3, should_init_logs=False)
    try:
        sim3 = XLASimulator(args3, dataset, model)
        sim3.train()
        defended = norm(sim3)
    finally:
        FedMLAttacker._attacker_instance = None
        FedMLDefender._defender_instance = None

    print(f"MHOK {padded:.6f} {packed:.6f} {defended:.6f}", flush=True)


if __name__ == "__main__":
    main(int(sys.argv[1]), sys.argv[2])
