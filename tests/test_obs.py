"""The round-trace observability layer (``fedml_tpu.core.obs``).

Three strata, mirroring the layer's own contract:

* **Unit** — deterministic trace/span ids, W3C traceparent round-trips,
  tracer record shapes (incl. crash-adoption ends), the metrics registry's
  bucket math and cardinality cap, and the no-op guarantees of the
  disabled facade (with ``obs_trace`` off the wire must stay byte-identical
  to the pre-obs wire).
* **Report** — ``tools/trace_report.py`` against golden record sets:
  critical-path walk, straggler flagging, orphan/unclosed detection and
  the ``--assert-closed`` exit contract.
* **Trace integrity under chaos** — the acceptance claim: a topology
  absorbing drop + duplicate + delay + reset + crash-and-rejoin (and,
  separately, a server kill + restart) must still reconstruct every
  completed round as ONE closed span tree, with retransmit attempts
  visible as child spans and every fault as a span event.  Reuses the
  chaos harness from ``test_fault_tolerance`` — same plans, same
  topologies, now traced.
"""

from __future__ import annotations

import contextlib
import json
import os
import sys
import time

import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))

import trace_report

import test_fault_tolerance as _ft
from fedml_tpu.core import mlops, obs
from fedml_tpu.core.distributed.communication.loopback import LoopbackHub
from fedml_tpu.core.distributed.communication.message import Message
from fedml_tpu.core.mlops import FanoutSink, InMemorySink
from fedml_tpu.core.mlops.mlops_profiler_event import MLOpsProfilerEvent
from fedml_tpu.core.mlops.sinks import JsonlFileSink
from fedml_tpu.core.obs import MetricsRegistry, SpanContext, Tracer
from fedml_tpu.core.obs.exposition import (
    DROPPED_SERIES_METRIC, MetricsExporter, parse_openmetrics,
    render_openmetrics, sanitize_metric_name)
from fedml_tpu.core.obs.flight import FlightRecorder, frame_line, parse_line
from fedml_tpu.core.obs.trace import round_root_ctx, span_id_for, trace_id_for


@pytest.fixture(autouse=True)
def _obs_hygiene():
    """obs state is process-global: every test leaves it disabled and the
    registry empty so no other module inherits a live tracer."""
    yield
    obs.shutdown()
    obs.registry().reset()


# ---------------------------------------------------------------------------
# Unit: deterministic ids + propagation header
# ---------------------------------------------------------------------------

class TestDeterministicIds:
    def test_trace_id_is_pure_function_of_run_and_round(self):
        a = trace_id_for("run-7", 3)
        assert a == trace_id_for("run-7", 3)
        assert len(a) == 32 and int(a, 16) >= 0
        assert a != trace_id_for("run-7", 4)
        assert a != trace_id_for("run-8", 3)

    def test_span_id_is_pure_function_of_coordinates(self):
        tid = trace_id_for("r", 0)
        a = span_id_for(tid, "upload", 2, 0)
        assert a == span_id_for(tid, "upload", 2, 0)
        assert len(a) == 16 and int(a, 16) >= 0
        assert a != span_id_for(tid, "upload", 3, 0)
        assert a != span_id_for(tid, "upload", 2, 1)
        assert a != span_id_for(tid, "invite", 2, 0)

    def test_every_incarnation_agrees_on_the_round_root(self):
        # the property crash adoption rests on: any process, any time
        assert round_root_ctx("r", 5) == round_root_ctx("r", 5)

    def test_traceparent_roundtrip(self):
        ctx = round_root_ctx("run-x", 2)
        back = SpanContext.from_traceparent(ctx.to_traceparent())
        assert back == ctx

    @pytest.mark.parametrize("header", [
        None, "", "00", "00-short-short-01", 12345,
        "00-" + "a" * 31 + "-" + "b" * 16 + "-01",   # trace id too short
        "00-" + "a" * 32 + "-" + "b" * 15 + "-01",   # span id too short
    ])
    def test_malformed_traceparent_is_none(self, header):
        assert SpanContext.from_traceparent(header) is None


# ---------------------------------------------------------------------------
# Unit: tracer record shapes
# ---------------------------------------------------------------------------

def _collecting_tracer(run_id="t"):
    out = []
    return Tracer(run_id, lambda topic, rec: out.append((topic, dict(rec)))), out


class TestTracer:
    def test_round_tree_start_end_parenting(self):
        tr, out = _collecting_tracer()
        root = tr.round_span(0, fanout=3)
        with tr.span("select", root.ctx, round_idx=0) as sel:
            pass
        root.end(reason="closed")
        topics = [t for t, _ in out]
        assert topics == ["span_start", "span_start", "span_end", "span_end"]
        root_start, sel_start, sel_end, root_end = [r for _, r in out]
        assert root_start["name"] == "round" and root_start["fanout"] == 3
        assert "parent_span_id" not in root_start
        assert sel_start["parent_span_id"] == root.ctx.span_id
        assert sel_start["trace_id"] == root.ctx.trace_id
        assert sel_end["duration_s"] >= 0
        assert root_end["reason"] == "closed"
        assert sel.ctx.span_id == span_id_for(root.ctx.trace_id, "select", 0, 0)

    def test_end_is_idempotent(self):
        tr, out = _collecting_tracer()
        sp = tr.round_span(0)
        sp.end()
        sp.end()
        assert [t for t, _ in out].count("span_end") == 1

    def test_adopted_end_carries_no_duration(self):
        # a crash-restarted server never saw the start's monotonic origin
        tr, out = _collecting_tracer()
        sp = tr.adopt_round_span(4)
        sp.end(reason="closed")
        assert [t for t, _ in out] == ["span_end"]  # no re-emitted start
        rec = out[0][1]
        assert rec["adopted"] is True and "duration_s" not in rec
        assert rec["span_id"] == round_root_ctx("t", 4).span_id

    def test_unique_span_ids_differ_per_attempt(self):
        tr, out = _collecting_tracer()
        parent = round_root_ctx("t", 0)
        a = tr.unique_span("retransmit", parent, node=1)
        b = tr.unique_span("retransmit", parent, node=1)
        assert a.ctx.span_id != b.ctx.span_id
        assert a.ctx.trace_id == b.ctx.trace_id == parent.trace_id

    def test_span_event_falls_back_to_round_root(self):
        tr, out = _collecting_tracer()
        tr.span_event("drop", None, round_idx=1, msg_type=2)
        assert out[0][1]["span_id"] == round_root_ctx("t", 1).span_id
        # with neither ctx nor round the event is dropped, never mis-filed
        tr.span_event("drop", None)
        assert len(out) == 1

    def test_emit_failure_is_swallowed(self):
        def boom(topic, rec):
            raise RuntimeError("sink down")

        tr = Tracer("t", boom)
        sp = tr.round_span(0)
        sp.event("x")
        sp.end()  # telemetry must never take the run down


# ---------------------------------------------------------------------------
# Unit: metrics registry
# ---------------------------------------------------------------------------

class TestMetricsRegistry:
    def test_histogram_bucket_edges(self):
        r = MetricsRegistry()
        buckets = (0.1, 1.0, 10.0)
        for v in (0.05, 0.1, 0.5, 10.0, 50.0):
            r.histogram_observe("lat", v, buckets=buckets)
        h = r.get_histogram("lat")
        assert h["buckets"] == [0.1, 1.0, 10.0]
        # v <= upper_bound: 0.05 and 0.1 land in the first bucket, 10.0 in
        # the last finite one, 50.0 in the implicit +Inf slot
        assert h["bucket_counts"] == [2, 1, 1, 1]
        assert h["count"] == 5
        assert h["sum"] == pytest.approx(60.65)

    def test_counter_and_gauge_semantics(self):
        r = MetricsRegistry()
        r.counter_inc("c")
        r.counter_inc("c", 2, {"node": 1})
        r.gauge_set("g", 3.0)
        r.gauge_set("g", 1.5)  # last write wins
        assert r.get_counter("c") == 1
        assert r.get_counter("c", {"node": 1}) == 2
        assert r.get_gauge("g") == 1.5

    def test_kind_conflict_raises(self):
        r = MetricsRegistry()
        r.counter_inc("m")
        with pytest.raises(ValueError):
            r.gauge_set("m", 1.0)

    def test_cardinality_cap_collapses_to_overflow(self):
        r = MetricsRegistry(max_series_per_metric=3)
        for i in range(5):
            r.counter_inc("c", 1, {"client": i})
        # 3 real series + the shared overflow series; 2 increments collapsed
        assert r.series_count("c") == 4
        assert r.dropped_series("c") == 2
        assert r.get_counter("c", {"overflow": "true"}) == 2
        # existing series keep incrementing normally past the cap
        r.counter_inc("c", 1, {"client": 0})
        assert r.get_counter("c", {"client": 0}) == 2
        recs = [x for x in r.export() if x["metric"] == "c"]
        assert all(x["dropped_series"] == 2 for x in recs)

    def test_export_record_shape(self):
        r = MetricsRegistry()
        r.counter_inc("comm.retransmits", 3, {"node": 0})
        r.histogram_observe("round.seconds", 0.2, buckets=(1.0,))
        recs = {x["metric"]: x for x in r.export()}
        c = recs["comm.retransmits"]
        assert c["kind"] == "counter" and c["value"] == 3
        assert c["labels"] == {"node": "0"}
        h = recs["round.seconds"]
        assert h["kind"] == "histogram"
        assert h["bucket_counts"] == [1, 0] and h["count"] == 1

    def test_maybe_export_rate_limit(self):
        r = MetricsRegistry()
        r.counter_inc("x")
        emitted = []
        emit = lambda t, rec: emitted.append((t, rec))
        assert r.maybe_export(emit, 0) is False      # 0 = shutdown-only
        time.sleep(0.02)
        assert r.maybe_export(emit, 0.01) is True
        assert emitted and emitted[0][0] == "metrics"
        assert r.maybe_export(emit, 10.0) is False   # inside the window


# ---------------------------------------------------------------------------
# Unit: the facade's disabled guarantees + satellite mlops fixes
# ---------------------------------------------------------------------------

class _ObsArgs:
    rank = 0

    def __init__(self, run_id, obs_trace=True, **extra):
        self.run_id = run_id
        self.obs_trace = obs_trace
        for k, v in extra.items():
            setattr(self, k, v)


class TestFacade:
    def test_disabled_everything_is_noop(self):
        assert obs.enabled() is False
        sp = obs.span("upload", round_root_ctx("r", 0))
        assert sp is obs.NULL_SPAN and sp.ctx is None
        sp.event("x")
        sp.end()
        obs.span_event("drop", round_idx=0)
        assert obs.round_span(0) is obs.NULL_SPAN

    def test_disabled_inject_leaves_wire_byte_identical(self):
        m = Message(3, 1, 0)
        before = dict(m.get_params())
        obs.inject(m, round_root_ctx("r", 0))
        assert m.get_params() == before
        assert m.get(Message.MSG_ARG_KEY_TRACEPARENT) is None
        assert obs.extract(m) is None

    def test_enabled_inject_extract_roundtrip(self):
        emitted = []
        obs.configure(_ObsArgs("rt"), lambda t, rec: emitted.append(t))
        try:
            with obs.span("upload", round_root_ctx("rt", 0),
                          round_idx=0, node=2) as up:
                m = Message(3, 2, 0)
                obs.inject(m, up.ctx)
            assert obs.extract(m) == up.ctx
            assert emitted == ["span_start", "span_end"]
        finally:
            obs.shutdown()
        assert obs.enabled() is False

    def test_metrics_helpers_live_even_when_tracing_off(self):
        # counters mirror unconditionally: obs_trace gates spans, not metrics
        obs.counter_inc("comm.test_metric", 2, {"node": 1})
        assert obs.registry().get_counter("comm.test_metric",
                                          {"node": 1}) == 2


class TestMlopsSatellites:
    def test_profiler_durations_survive_wall_clock_step(self, monkeypatch):
        # an NTP step back mid-event must not yield a negative duration:
        # the profiler measures with time.monotonic, wall time is metadata
        mem = InMemorySink()
        ev = MLOpsProfilerEvent("r", 0, FanoutSink([mem]))
        walls = iter([1000.0, 500.0, 400.0, 300.0])
        monkeypatch.setattr(time, "time", lambda: next(walls, 300.0))
        ev.log_event_started("train")
        ev.log_event_ended("train")
        ended = [r for r in mem.by_topic("event") if r["phase"] == "ended"]
        assert ended and ended[0]["duration_s"] >= 0

    def test_jsonl_sink_close_is_idempotent(self, tmp_path):
        path = str(tmp_path / "run.jsonl")
        sink = JsonlFileSink(path)
        sink.emit("metrics", {"metric": "x", "value": 1})
        sink.close()
        sink.close()  # second close: no-op, no raise
        sink.emit("metrics", {"metric": "y", "value": 2})  # dropped, no raise
        with open(path) as f:
            lines = [json.loads(l) for l in f if l.strip()]
        assert len(lines) == 1 and lines[0]["metric"] == "x"


# ---------------------------------------------------------------------------
# Report: tools/trace_report.py on golden record sets
# ---------------------------------------------------------------------------

def _golden_round(run_id="golden", slow_node=3):
    """One closed round: root > invite > 3 client.train legs, one slow."""
    tid = trace_id_for(run_id, 0)
    root = span_id_for(tid, "round", 0, 0)
    inv = span_id_for(tid, "invite", 0, 0)
    recs = [
        {"topic": "span_start", "trace_id": tid, "span_id": root,
         "name": "round", "node": 0, "round_idx": 0, "ts": 10.0},
        {"topic": "span_start", "trace_id": tid, "span_id": inv,
         "name": "invite", "node": 0, "parent_span_id": root, "ts": 10.05},
        {"topic": "span_end", "trace_id": tid, "span_id": inv,
         "name": "invite", "duration_s": 0.05, "ts": 10.1},
    ]
    for node, dur in ((1, 0.2), (2, 0.21), (slow_node, 1.5)):
        sid = span_id_for(tid, "client.train", node, 0)
        recs.append({"topic": "span_start", "trace_id": tid, "span_id": sid,
                     "name": "client.train", "node": node,
                     "parent_span_id": inv, "ts": 10.1})
        recs.append({"topic": "span_end", "trace_id": tid, "span_id": sid,
                     "name": "client.train", "duration_s": dur,
                     "ts": 10.1 + dur})
    recs.append({"topic": "span_event", "trace_id": tid,
                 "span_id": span_id_for(tid, "client.train", slow_node, 0),
                 "event": "gc_pause", "node": slow_node})
    recs.append({"topic": "span_end", "trace_id": tid, "span_id": root,
                 "name": "round", "duration_s": 2.0, "ts": 12.0})
    return tid, recs


class TestTraceReport:
    def test_golden_round_is_closed_and_critical_path_finds_the_slow_leg(self):
        tid, recs = _golden_round()
        tr = trace_report.build_traces(recs)[tid]
        assert tr.problems() == []
        path = tr.critical_path()
        assert [sn.name for sn in path] == ["round", "invite", "client.train"]
        assert path[-1].node == 3  # the leg the round actually waited on

    def test_straggler_ranking_flags_past_factor_x_median(self):
        tid, recs = _golden_round()
        ranked = trace_report.build_traces(recs)[tid].stragglers(2.0)
        assert [sn.node for sn, _, _ in ranked] == [3, 2, 1]
        assert [slow for _, _, slow in ranked] == [True, False, False]

    def test_duplicate_records_collapse_first_wins(self):
        # retransmitted frames can re-deliver span records; deterministic
        # ids make the copies collapse instead of corrupting the tree
        tid, recs = _golden_round()
        tr = trace_report.build_traces(recs + [dict(r) for r in recs])[tid]
        assert tr.problems() == []
        assert len([sn for sn in tr.spans.values()
                    if sn.name == "client.train"]) == 3

    def test_orphan_and_unclosed_and_multiroot_detection(self):
        tid, recs = _golden_round()
        recs.append({"topic": "span_start", "trace_id": tid,
                     "span_id": "feedfeedfeedfeed", "name": "upload",
                     "node": 9, "parent_span_id": "beefbeefbeefbeef"})
        problems = trace_report.build_traces(recs)[tid].problems()
        assert any("orphan" in p for p in problems)
        assert any("never closed" in p for p in problems)
        # adopted close pairing: an end with no start is also a violation
        lone = [{"topic": "span_end", "trace_id": "x" * 32,
                 "span_id": "c" * 16, "name": "round"}]
        p2 = trace_report.build_traces(lone)["x" * 32].problems()
        assert any("ended without starting" in p for p in p2)
        assert any("root" in p for p in p2)

    def test_assert_closed_exit_codes(self, tmp_path, capsys):
        _, recs = _golden_round()
        good = tmp_path / "good.jsonl"
        good.write_text("\n".join(json.dumps(r) for r in recs) + "\n"
                        + "{torn json tail\n")  # unparseable tail is skipped
        assert trace_report.main([str(good), "--assert-closed"]) == 0
        # drop the root's end: the trace is no longer closed
        bad = tmp_path / "bad.jsonl"
        bad.write_text("\n".join(
            json.dumps(r) for r in recs
            if not (r["topic"] == "span_end" and r["name"] == "round")) + "\n")
        assert trace_report.main([str(bad)]) == 0  # report-only: informative
        assert trace_report.main([str(bad), "--assert-closed"]) == 2
        out = capsys.readouterr().out
        assert "never closed" in out


# ---------------------------------------------------------------------------
# Trace integrity under chaos (the acceptance layer)
# ---------------------------------------------------------------------------

@contextlib.contextmanager
def _traced(run_id, **extra):
    """Process-wide tracing through an in-memory sink: obs is configured by
    ``mlops.init`` (the production seam) and covers every in-process node
    thread of the topology.  ``extra`` lands as attributes on the args
    (e.g. ``obs_flight_dir`` for flight-recorder tests)."""
    mem = InMemorySink()
    mlops.init(_ObsArgs(run_id, **extra), FanoutSink([mem]))
    try:
        yield mem
    finally:
        mlops.finish()


def _span_records(mem):
    return [dict(rec, topic=t) for t, rec in list(mem.records)
            if t in trace_report.SPAN_TOPICS]


def _assert_rounds_closed(mem, run_id, n_rounds):
    """Every completed round reconstructs as exactly one CLOSED span tree —
    zero orphans, zero unclosed spans — and returns {round_idx: Trace}."""
    traces = trace_report.build_traces(_span_records(mem))
    out = {}
    for r in range(n_rounds):
        tid = trace_id_for(run_id, r)
        assert tid in traces, f"round {r}: no trace emitted"
        tr = traces[tid]
        assert tr.problems() == [], (r, tr.problems())
        out[r] = tr
    return out


def _names(tr):
    return {sn.name for sn in tr.spans.values()}


def _events(traces):
    return {ev["event"] for tr in traces.values()
            for sn in tr.spans.values() for ev in sn.events}


def test_trace_integrity_chaos_loopback():
    """Full chaos plan (drop + reset + duplicate + delay) + a client
    crash-and-rejoin: both rounds close as single span trees, the healed
    drop is visible as a retransmit child span, and every injected fault
    surfaces as a span event on the round it hit."""
    LoopbackHub.reset()
    run_id = "obs-chaos"
    with _traced(run_id) as mem:
        history, final, stats = _ft._run_chaos_topology(
            run_id, fault_plan=_ft._full_chaos_plan(), crash_rank=1)
        assert len(history) == 2
    traces = _assert_rounds_closed(mem, run_id, 2)
    # the round protocol's full phase vocabulary, per round
    for r, tr in traces.items():
        assert {"round", "select", "invite", "client.train", "upload",
                "journal.append", "aggregate", "broadcast"} <= _names(tr), r
        path = tr.critical_path()
        assert path and path[0].name == "round" and len(path) >= 2
    # the dropped round-1 sync was healed by retransmit — as a child span
    retx = [sn for sn in traces[1].spans.values() if sn.name == "retransmit"]
    assert retx and all(sn.end is not None for sn in retx)
    events = _events(traces)
    assert {"drop", "reset", "dup", "delay", "rejoin"} <= events, events
    # legacy topic keeps emitting alongside the registry export
    assert mem.by_topic("comm_stats")
    metric_names = {r["metric"] for r in mem.by_topic("metrics")}
    assert "comm.retransmits" in metric_names
    assert "comm.dup_dropped" in metric_names
    assert "population.reported" in metric_names


def test_trace_integrity_server_kill_loopback(tmp_path):
    """A server killed mid-round-0 and restarted from durable state ADOPTS
    the dead incarnation's round span: the restart closes the span its
    predecessor opened (deterministic ids), so even the killed round reads
    as one closed tree with the recovery milestones attached."""
    LoopbackHub.reset()
    run_id = "obs-kill"
    with _traced(run_id) as mem:
        history, final, stats, restarts, killed, server = \
            _ft._run_server_kill_topology(run_id, tmp_path / "srv")
        assert restarts >= 1 and len(history) == 2
    traces = _assert_rounds_closed(mem, run_id, 2)
    root0 = traces[0].roots()[0]
    assert root0.end is not None and root0.end.get("adopted") is True
    events = _events(traces)
    assert {"server_kill", "server_restore", "epoch_bump"} <= events, events
    metric_names = {r["metric"] for r in mem.by_topic("metrics")}
    assert "journal.appends" in metric_names
    assert "journal.replay_records" in metric_names
    assert "checkpoint.saves" in metric_names


def test_tracing_off_and_on_converge_bit_identical():
    """The <2%-overhead claim's correctness half: enabling ``obs_trace``
    must not perturb the round flow — a traced fault-free run produces the
    BIT-IDENTICAL final model of an untraced one (and the untraced run
    emits no span records at all)."""
    LoopbackHub.reset()
    _, final_off, _ = _ft._run_chaos_topology("obs-off", knobs={})
    assert obs.enabled() is False
    with _traced("obs-on") as mem:
        history, final_on, _ = _ft._run_chaos_topology("obs-on", knobs={})
        assert len(history) == 2
    assert _ft._trees_bit_identical(final_off, final_on)
    # the traced clean run is also fully closed (no chaos required)
    _assert_rounds_closed(mem, "obs-on", 2)


@pytest.mark.slow
@pytest.mark.parametrize("backend", ["TRPC", "GRPC", "MQTT_S3"])
def test_trace_integrity_all_backends(backend, tmp_path):
    """The cross-backend acceptance sweep: drop + duplicate + delay + reset
    + server_kill over every socketed transport, and every completed round
    still reconstructs as one closed tree — the traceparent header survives
    JSON and pickled transports alike."""
    run_id = f"obs-{backend.lower()}"
    comm_extra = {}
    broker = None
    if backend == "TRPC":
        comm_extra = {"trpc_base_port": 29710, "trpc_connect_retries": 3,
                      "trpc_retry_interval_s": 0.1}
    elif backend == "GRPC":
        comm_extra = {"grpc_base_port": 29810, "grpc_send_retries": 3,
                      "grpc_send_backoff_base_s": 0.05}
    else:
        from fedml_tpu.core.distributed.communication.mqtt_s3.broker import LocalBroker

        broker = LocalBroker().start()
        comm_extra = {"mqtt_host": "127.0.0.1", "mqtt_port": broker.port,
                      "s3_blob_root": str(tmp_path / "blobs"),
                      "mqtt_reconnect_retries": 10,
                      "mqtt_reconnect_base_s": 0.05}
    plan = _ft._server_kill_plan(extra_rules=_ft._full_chaos_plan()["rules"])
    try:
        with _traced(run_id) as mem:
            history, final, stats, restarts, killed, server = \
                _ft._run_server_kill_topology(
                    run_id, tmp_path / "srv", backend=backend,
                    fault_plan=plan, comm_extra=comm_extra)
            assert restarts >= 1 and len(history) == 2
        traces = _assert_rounds_closed(mem, run_id, 2)
        root0 = traces[0].roots()[0]
        assert root0.end is not None and root0.end.get("adopted") is True
        events = _events(traces)
        assert "server_kill" in events, events
    finally:
        if broker is not None:
            broker.stop()


# ---------------------------------------------------------------------------
# Exposition: OpenMetrics rendering + pull endpoint
# ---------------------------------------------------------------------------

class TestExposition:
    def test_golden_fixture_render(self):
        """The exact wire text for one registry with every kind — any
        rendering change must consciously update this golden."""
        r = MetricsRegistry()
        r.counter_inc("comm.retransmits", 3, {"node": 0})
        r.gauge_set("async.buffer_bytes", 1024.0)
        r.histogram_observe("round.seconds", 0.5, buckets=(1.0, 10.0))
        assert render_openmetrics(r) == (
            "# TYPE async_buffer_bytes gauge\n"
            "async_buffer_bytes 1024.0\n"
            "# TYPE comm_retransmits counter\n"
            'comm_retransmits_total{node="0"} 3\n'
            "# TYPE round_seconds histogram\n"
            'round_seconds_bucket{le="1.0"} 1\n'
            'round_seconds_bucket{le="10.0"} 1\n'
            'round_seconds_bucket{le="+Inf"} 1\n'
            "round_seconds_sum 0.5\n"
            "round_seconds_count 1\n"
            "# EOF\n"
        )

    def test_round_trip_every_kind(self):
        r = MetricsRegistry()
        r.counter_inc("c", 7, {"node": 3})
        r.counter_inc("c", 1)
        r.gauge_set("g", 0.1 + 0.2)  # repr() must round-trip exactly
        for v in (0.05, 0.1, 5.0, 50.0):
            r.histogram_observe("h", v, buckets=(0.1, 10.0))
        parsed = parse_openmetrics(render_openmetrics(r))
        assert parsed["types"] == {"c": "counter", "g": "gauge",
                                   "h": "histogram"}
        s = parsed["samples"]
        assert s[("c_total", (("node", "3"),))] == 7
        assert s[("c_total", ())] == 1
        assert s[("g", ())] == 0.1 + 0.2  # exact, not approx
        # wire buckets are CUMULATIVE; le="+Inf" equals the count
        assert s[("h_bucket", (("le", "0.1"),))] == 2
        assert s[("h_bucket", (("le", "10.0"),))] == 3
        assert s[("h_bucket", (("le", "+Inf"),))] == 4
        assert s[("h_count", ())] == 4
        assert s[("h_sum", ())] == pytest.approx(55.15)

    def test_label_escaping_round_trips(self):
        hostile = 'quote:" backslash:\\ newline:\nend'
        r = MetricsRegistry()
        r.counter_inc("c", 1, {"path": hostile})
        text = render_openmetrics(r)
        assert "\\n" in text and '\\"' in text  # escaped on the wire
        parsed = parse_openmetrics(text)
        assert parsed["samples"][("c_total", (("path", hostile),))] == 1

    def test_name_sanitization(self):
        assert sanitize_metric_name("agg.step_seconds") == "agg_step_seconds"
        assert sanitize_metric_name("7rounds") == "_7rounds"
        assert sanitize_metric_name("a b/c") == "a_b_c"

    def test_overflow_series_and_dropped_gauge(self):
        r = MetricsRegistry(max_series_per_metric=2)
        for i in range(4):
            r.counter_inc("c", 1, {"client": i})
        text = render_openmetrics(r)
        parsed = parse_openmetrics(text)
        # the overflow series renders like any other, marker label intact
        assert parsed["samples"][("c_total", (("overflow", "true"),))] == 2
        # and the per-family drop count surfaces as the synthetic gauge
        assert parsed["types"][DROPPED_SERIES_METRIC] == "gauge"
        assert parsed["samples"][
            (DROPPED_SERIES_METRIC, (("metric", "c"),))] == 2

    def test_render_ends_with_eof_terminator(self):
        assert render_openmetrics(MetricsRegistry()).endswith("# EOF\n")


class TestMetricsExporter:
    def test_http_pull_on_ephemeral_port(self):
        import urllib.error
        import urllib.request

        r = MetricsRegistry()
        r.counter_inc("scrapes.test", 5)
        exp = MetricsExporter(r, port=0).start()
        try:
            assert exp.url and exp.port
            with urllib.request.urlopen(exp.url, timeout=5) as resp:
                body = resp.read().decode("utf-8")
                assert resp.headers["Content-Type"].startswith(
                    "application/openmetrics-text")
            assert parse_openmetrics(body)["samples"][
                ("scrapes_test_total", ())] == 5
            # the endpoint renders LIVE state, not a start()-time copy
            r.counter_inc("scrapes.test", 1)
            with urllib.request.urlopen(exp.url, timeout=5) as resp:
                live = parse_openmetrics(resp.read().decode("utf-8"))
            assert live["samples"][("scrapes_test_total", ())] == 6
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(
                    exp.url.replace("/metrics", "/secrets"), timeout=5)
        finally:
            exp.shutdown()

    def test_shutdown_is_idempotent_and_writes_final_snapshot(self, tmp_path):
        snap = tmp_path / "metrics.prom"
        r = MetricsRegistry()
        r.gauge_set("g", 2.5)
        exp = MetricsExporter(r, port=0, snapshot_path=str(snap)).start()
        exp.shutdown()
        exp.shutdown()  # second shutdown: no-op, no raise
        text = snap.read_text()
        assert text.endswith("# EOF\n")
        assert parse_openmetrics(text)["samples"][("g", ())] == 2.5

    def test_shutdown_without_start_is_safe(self):
        MetricsExporter(MetricsRegistry(), port=0).shutdown()

    def test_snapshot_only_mode_never_binds(self, tmp_path):
        snap = tmp_path / "m.prom"
        exp = MetricsExporter(MetricsRegistry(),
                              snapshot_path=str(snap)).start()
        assert exp.url is None and exp.port is None
        assert exp.snapshot() == str(snap)
        exp.shutdown()


# ---------------------------------------------------------------------------
# Flight recorder: ring, framing, dump/reload
# ---------------------------------------------------------------------------

class TestFlightRecorder:
    def test_frame_parse_round_trip_and_corruption(self):
        rec = {"topic": "span_end", "name": "round", "duration_s": 1.5}
        line = frame_line(rec)
        assert parse_line(line) == rec
        assert parse_line("") is None
        assert parse_line("zzzzzzzz " + line[9:]) is None   # non-hex crc
        assert parse_line(line[:-3]) is None                # torn tail
        assert parse_line(line.replace('"round"', '"r0und"')) is None
        # framed non-dict payloads are rejected on load
        import zlib as _zlib
        payload = "[1, 2]"
        crc = _zlib.crc32(payload.encode()) & 0xFFFFFFFF
        assert parse_line(f"{crc:08x} {payload}") is None

    def test_ring_wraparound_keeps_newest_and_counts_dropped(self):
        fr = FlightRecorder(capacity=3)
        for i in range(5):
            fr.record("metrics", {"i": i})
        snap = fr.snapshot()
        assert [r["i"] for r in snap] == [2, 3, 4]
        assert fr.dropped == 2

    def test_trigger_events_return_reason(self):
        fr = FlightRecorder(capacity=4)
        assert fr.record("span_event", {"event": "server_kill"}) == "server_kill"
        assert fr.record("span_event", {"event": "slow_round"}) == "slow_round"
        # the elastic topology fault is a dump trigger: the ring around a
        # lost chip is the forensic window a remesh post-mortem needs
        assert fr.record("span_event", {"event": "device_loss"}) == "device_loss"
        assert fr.record("span_event", {"event": "mesh_shrink"}) is None
        assert fr.record("span_event", {"event": "drop"}) is None
        assert fr.record("span_start", {"name": "round"}) is None

    def test_dump_and_tolerant_reload(self, tmp_path):
        fr = FlightRecorder(capacity=8, directory=str(tmp_path), run_id="r1")
        for i in range(3):
            fr.record("span_start", {"name": "round", "i": i})
        path = fr.dump("server_kill")
        assert path and os.path.basename(path).endswith("server_kill.jsonl")
        records, n_bad = FlightRecorder.load(path)
        assert n_bad == 0
        assert records[0]["topic"] == "flight_meta"
        assert records[0]["reason"] == "server_kill"
        assert records[0]["n_records"] == 3
        assert [r.get("i") for r in records[1:]] == [0, 1, 2]

    def test_truncated_tail_reload_drops_only_the_torn_line(self, tmp_path):
        fr = FlightRecorder(capacity=8, directory=str(tmp_path), run_id="r2")
        for i in range(4):
            fr.record("metrics", {"i": i})
        path = fr.dump("manual")
        text = open(path, "r", encoding="utf-8").read()
        torn = text.rstrip("\n")[:-7]  # tear the last record mid-payload
        open(path, "w", encoding="utf-8").write(torn)
        records, n_bad = FlightRecorder.load(path)
        assert n_bad == 1
        assert [r.get("i") for r in records[1:]] == [0, 1, 2]

    def test_dump_budget_and_no_directory(self, tmp_path):
        fr = FlightRecorder(capacity=2, directory=str(tmp_path),
                            run_id="r3", max_dumps=1)
        fr.record("metrics", {"x": 1})
        assert fr.dump("one") is not None
        assert fr.dump("two") is None  # budget exhausted
        assert FlightRecorder(capacity=2).dump("nowhere") is None

    def test_facade_wires_flight_and_dumps_on_trigger_event(self, tmp_path):
        emitted = []
        obs.configure(_ObsArgs("fl", obs_flight_dir=str(tmp_path)),
                      lambda t, rec: emitted.append(t))
        try:
            assert obs.flight_recorder() is not None
            with obs.round_span(0):
                obs.span_event("server_kill", round_idx=0)
        finally:
            obs.shutdown()
        assert "span_event" in emitted  # the tap forwards, never swallows
        dumps = list(tmp_path.glob("flight-fl-*-server_kill.jsonl"))
        assert len(dumps) == 1
        records, n_bad = FlightRecorder.load(str(dumps[0]))
        assert n_bad == 0
        assert any(r.get("event") == "server_kill" for r in records)

    def test_flight_dump_accessor_never_raises(self, tmp_path):
        assert obs.flight_dump("manual") is None  # disabled: no-op
        obs.configure(_ObsArgs("fd", obs_flight_dir=str(tmp_path)),
                      lambda t, rec: None)
        try:
            path = obs.flight_dump("unhandled_exception")
            assert path and "unhandled_exception" in path
        finally:
            obs.shutdown()

    def test_flight_capacity_zero_disables(self):
        obs.configure(_ObsArgs("off", obs_flight_capacity=0),
                      lambda t, rec: None)
        try:
            assert obs.flight_recorder() is None
        finally:
            obs.shutdown()


def test_flight_dump_on_server_kill_chaos(tmp_path):
    """The acceptance leg: a server killed mid-round triggers an automatic
    flight dump whose crc-framed snapshot reloads cleanly and contains the
    killed round's span records — the post-mortem an operator actually
    needs after a crash."""
    LoopbackHub.reset()
    run_id = "obs-flight-kill"
    fdir = tmp_path / "flight"
    with _traced(run_id, obs_flight_dir=str(fdir)) as mem:
        history, final, stats, restarts, killed, server = \
            _ft._run_server_kill_topology(run_id, tmp_path / "srv")
        assert restarts >= 1 and len(history) == 2
    kill_dumps = sorted(fdir.glob("flight-*-server_kill.jsonl"))
    assert kill_dumps, "server_kill must trigger a flight dump"
    records, n_bad = FlightRecorder.load(str(kill_dumps[0]))
    assert n_bad == 0, "an atomic dump reloads with zero bad lines"
    assert records[0]["topic"] == "flight_meta"
    assert records[0]["reason"] == "server_kill"
    assert any(r.get("event") == "server_kill" for r in records)
    # the killed round's spans are in the ring: round 0's trace was live
    tid0 = trace_id_for(run_id, 0)
    killed_round = [r for r in records if r.get("trace_id") == tid0
                    and r.get("topic") in trace_report.SPAN_TOPICS]
    assert any(r["topic"] == "span_start" and r.get("name") == "round"
               for r in killed_round)
    # the sink records and the flight ring agree (same emit fan)
    assert mem.by_topic("span_start")


# ---------------------------------------------------------------------------
# Resource attribution: gauges, compile split, trace_report views
# ---------------------------------------------------------------------------

class TestResourceAttribution:
    def test_resource_gauges_sampled(self):
        obs.sample_resource_gauges()
        assert obs.registry().get_gauge("proc.max_rss_bytes") > 0

    def test_compile_seconds_total_monotonic(self):
        before = obs.compile_seconds_total()
        assert before >= 0.0
        assert obs.compile_seconds_total() >= before

    def test_attribution_self_seconds_with_clamp(self):
        tid, recs = _golden_round()
        att = trace_report.build_traces(recs)[tid].attribution()
        assert att["round_s"] == pytest.approx(2.0)
        # self = duration minus children, clamped at 0: invite's children
        # (0.2 + 0.21 + 1.5 = 1.91) exceed its own 0.05s wall
        assert att["self_seconds"]["invite"] == 0.0
        assert att["self_seconds"]["client.train"] == pytest.approx(1.91)
        assert att["self_seconds"]["round"] == pytest.approx(1.95)
        # no compile split in the golden records: the keys stay absent
        assert "compile_s" not in att

    def test_attribution_copies_compile_split_from_root_end(self):
        tid, recs = _golden_round()
        for r in recs:
            if r["topic"] == "span_end" and r["name"] == "round":
                r["compile_s"] = 0.8
                r["execute_s"] = 1.2
        att = trace_report.build_traces(recs)[tid].attribution()
        assert att["compile_s"] == 0.8 and att["execute_s"] == 1.2

    def test_report_attribution_view(self, tmp_path, capsys):
        _, recs = _golden_round()
        p = tmp_path / "t.jsonl"
        p.write_text("\n".join(json.dumps(r) for r in recs) + "\n")
        assert trace_report.main([str(p), "--attribution"]) == 0
        out = capsys.readouterr().out
        assert "attribution:" in out and "client.train" in out

    def test_report_format_json(self, tmp_path, capsys):
        _, recs = _golden_round()
        p = tmp_path / "t.jsonl"
        p.write_text("\n".join(json.dumps(r) for r in recs) + "\n")
        assert trace_report.main([str(p), "--format", "json"]) == 0
        payload = json.loads(capsys.readouterr().out)
        assert payload["n_traces"] == 1 and payload["n_problems"] == 0
        (tr,) = payload["traces"]
        assert tr["attribution"]["self_seconds"]["client.train"] == \
            pytest.approx(1.91)
        assert [s["name"] for s in tr["critical_path"]][0] == "round"

    def test_report_format_json_assert_closed_still_exits_2(
            self, tmp_path, capsys):
        _, recs = _golden_round()
        p = tmp_path / "bad.jsonl"
        p.write_text("\n".join(
            json.dumps(r) for r in recs
            if not (r["topic"] == "span_end" and r["name"] == "round"))
            + "\n")
        rc = trace_report.main([str(p), "--format", "json",
                                "--assert-closed"])
        assert rc == 2
        payload = json.loads(capsys.readouterr().out)  # stdout stays JSON
        assert payload["n_problems"] >= 1


def _knob_args(**over):
    from fedml_tpu.arguments import Arguments

    args = Arguments.from_dict({
        "common_args": {"training_type": "simulation", "random_seed": 0,
                        "run_id": "knobs"},
        "data_args": {"dataset": "mnist", "data_cache_dir": "",
                      "partition_method": "hetero", "partition_alpha": 0.5,
                      "synthetic_train_size": 100},
        "model_args": {"model": "lr"},
        "train_args": {"federated_optimizer": "FedAvg",
                       "client_num_in_total": 2, "client_num_per_round": 2,
                       "comm_round": 1, "epochs": 1, "batch_size": 16,
                       "learning_rate": 0.1},
        "validation_args": {"frequency_of_the_test": 1},
        "comm_args": {"backend": "sp"},
    })
    for k, v in over.items():
        setattr(args, k, v)
    return args


class TestExportKnobValidation:
    def test_export_knobs_accepted(self):
        _knob_args(obs_export_port=9464, obs_flight_capacity=0,
                   obs_export_path="/tmp/m.prom").validate()

    def test_bad_export_port_rejected(self):
        with pytest.raises(ValueError):
            _knob_args(obs_export_port=99999).validate()

    def test_negative_flight_capacity_rejected(self):
        with pytest.raises(ValueError):
            _knob_args(obs_flight_capacity=-1).validate()

    def test_exporter_configured_from_args(self, tmp_path):
        import socket
        import urllib.request

        # configure() treats port 0 as "HTTP off", so reserve a real one
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        snap = tmp_path / "m.prom"
        obs.configure(_ObsArgs("exp", obs_export_port=port,
                               obs_export_path=str(snap)),
                      lambda t, rec: None)
        try:
            exp = obs.exporter()
            assert exp is not None and exp.port == port
            obs.counter_inc("exp.test", 2)
            with urllib.request.urlopen(exp.url, timeout=5) as resp:
                body = resp.read().decode("utf-8")
            assert parse_openmetrics(body)["samples"][
                ("exp_test_total", ())] == 2
        finally:
            obs.shutdown()
        # shutdown wrote the final snapshot and tore the server down
        assert snap.exists() and snap.read_text().endswith("# EOF\n")
        assert obs.exporter() is None
