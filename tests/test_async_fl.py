"""Buffered-async FL (``fedml_tpu/core/async_fl``) — the FedBuff-style
execution mode layered on PRs 3-6's substrates.

Four strata:

* **Golden** — the staleness-weight policies' closed forms (FedBuff,
  arXiv:2106.06639 §3.2), scalar and jit-traceable array forms agreeing,
  and the UpdateBuffer invariants (canonical drain order, per-sender
  slots, insertion-order-invariant flushes).
* **Scheduler** — heterogeneity-aware dispatch decisions driven purely by
  the injected clock and the registry's ``ema_seconds`` column: fast
  clients re-dispatch immediately, slow clients are paced, hopeless
  clients are deferred at the flush wave.
* **Simulators** — sp + XLA async runs are bit-reproducible from the seed
  alone (deterministic virtual-arrival queue), and under full
  participation with ``async_buffer_size == cohort``, ``constant``
  weighting and zero staleness budget they reproduce the sync FedAvg loop
  BIT-EXACTLY (the equivalence guarantee from docs/ASYNC.md).
* **Message plane + chaos** — ``fl_mode=async`` end-to-end over LOOPBACK
  (cross-silo and cross-device), sync-equivalence through the compiled
  aggregation plane, every ``buffer.flush`` span closed under
  ``trace_report --assert-closed``, and the crash-safety contract: a
  ``server_kill`` mid-buffer replays journaled deltas with per-sender
  dedup and converges bit-identically with exactly-once accounting.
"""

from __future__ import annotations

import json
import os
import sys
import threading

import numpy as np
import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))

import trace_report

import test_fault_tolerance as _ft
import fedml_tpu
from fedml_tpu.arguments import Arguments
from fedml_tpu.core import mlops, obs
from fedml_tpu.core.async_fl import (
    ManualClock,
    StalenessScheduler,
    UpdateBuffer,
    VirtualArrivalQueue,
    staleness_weight,
    staleness_weights,
)
from fedml_tpu.core.distributed.communication.loopback import LoopbackHub
from fedml_tpu.core.mlops import FanoutSink, InMemorySink
from fedml_tpu.core.obs.trace import trace_id_for

# the FedAvg-equivalence knob set: buffer == cohort, no staleness budget,
# staleness ignored — every cycle collects the full cohort exactly like a
# synchronous round (see docs/ASYNC.md "Sync equivalence")
_EQ2 = dict(fl_mode="async", async_buffer_size=2,
            async_staleness_policy="constant", async_max_staleness=0)
_EQ3 = dict(fl_mode="async", async_buffer_size=3,
            async_staleness_policy="constant", async_max_staleness=0)


class _TraceArgs:
    """Minimal args for ``mlops.init``: tracing on, server-side identity."""
    rank = 0

    def __init__(self, run_id):
        self.run_id = run_id
        self.obs_trace = True


@pytest.fixture(autouse=True)
def _obs_hygiene():
    """obs state is process-global: every test leaves it disabled and the
    registry empty so no other module inherits a live tracer."""
    yield
    obs.shutdown()
    obs.registry().reset()


# ---------------------------------------------------------------------------
# Golden: staleness-weight closed forms
# ---------------------------------------------------------------------------

class TestStalenessWeightsGolden:
    def test_constant_is_always_one(self):
        for s in range(6):
            assert staleness_weight("constant", s) == 1.0

    def test_polynomial_closed_form(self):
        # FedBuff's s(t) = 1/(1+t)^a
        assert staleness_weight("polynomial", 0, alpha=0.5) == 1.0
        assert staleness_weight("polynomial", 3, alpha=0.5) == pytest.approx(0.5)
        assert staleness_weight("polynomial", 1, alpha=1.0) == pytest.approx(0.5)
        assert staleness_weight("polynomial", 8, alpha=0.5) == pytest.approx(1 / 3)

    def test_hinge_closed_form(self):
        for s in range(5):  # grace window: s <= b keeps full weight
            assert staleness_weight("hinge", s, alpha=0.5, hinge_b=4) == 1.0
        assert staleness_weight("hinge", 6, alpha=0.5, hinge_b=4) == pytest.approx(0.5)
        assert staleness_weight("hinge", 5, alpha=1.0, hinge_b=4) == pytest.approx(0.5)
        assert staleness_weight("hinge", 8, alpha=1.0, hinge_b=4) == pytest.approx(0.2)

    def test_array_form_matches_scalar_form(self):
        s = np.arange(8, dtype=np.float32)
        for policy in ("constant", "polynomial", "hinge"):
            arr = np.asarray(staleness_weights(policy, s, alpha=0.7, hinge_b=2))
            ref = np.asarray(
                [staleness_weight(policy, float(v), alpha=0.7, hinge_b=2)
                 for v in s], np.float32)
            np.testing.assert_allclose(arr, ref, rtol=1e-6)

    def test_bad_policy_and_negative_staleness_raise(self):
        with pytest.raises(ValueError):
            staleness_weight("exponential", 1)
        with pytest.raises(ValueError):
            staleness_weight("constant", -1)


# ---------------------------------------------------------------------------
# Golden: buffer invariants + flush bit-determinism
# ---------------------------------------------------------------------------

class TestUpdateBuffer:
    @staticmethod
    def _fill(order):
        buf = UpdateBuffer(capacity=4, policy="polynomial", alpha=0.5)
        for sender in order:
            buf.add(sender, {"w": np.full(3, float(sender), np.float32)},
                    n_samples=10 + sender, version=sender % 2,
                    staleness=sender % 3)
        return buf

    def test_flush_is_insertion_order_invariant(self):
        """Canonical (version, sender) drain: the weighted list the agg
        plane folds is bit-identical no matter the upload interleaving."""
        buf_a, buf_b = self._fill([2, 0, 3, 1]), self._fill([1, 3, 0, 2])
        a, b = buf_a.drain(), buf_b.drain()
        assert [(e.version, e.sender) for e in a] == \
            [(e.version, e.sender) for e in b]
        assert [(e.version, e.sender) for e in a] == \
            sorted((e.version, e.sender) for e in a)
        wa, wb = buf_a.weighted(a), buf_b.weighted(b)
        assert [w for w, _ in wa] == [w for w, _ in wb]
        for (_, pa), (_, pb) in zip(wa, wb):
            assert np.array_equal(pa["w"], pb["w"])

    def test_weights_are_n_samples_times_policy(self):
        buf = self._fill([0, 1, 2, 3])
        entries = buf.drain()
        for (w, _), e in zip(buf.weighted(entries), entries):
            assert w == pytest.approx(
                (10 + e.sender) * staleness_weight("polynomial", e.staleness,
                                                   alpha=0.5))

    def test_duplicate_sender_and_negative_staleness_raise(self):
        buf = UpdateBuffer(capacity=2)
        buf.add(1, {}, 8.0, version=0, staleness=0)
        with pytest.raises(ValueError):
            buf.add(1, {}, 8.0, version=0, staleness=0)
        with pytest.raises(ValueError):
            buf.add(2, {}, 8.0, version=3, staleness=-1)

    def test_ready_occupancy_and_stats(self):
        buf = UpdateBuffer(capacity=2)
        assert not buf.ready() and buf.occupancy == 0
        buf.add(5, {}, 1.0, version=0, staleness=2)
        assert not buf.ready()
        buf.add(3, {}, 1.0, version=1, staleness=1)
        assert buf.ready() and buf.senders() == [3, 5]
        stats = UpdateBuffer.staleness_stats(buf.drain())
        assert stats == {"staleness_min": 1.0, "staleness_mean": 1.5,
                         "staleness_max": 2.0}
        assert len(buf) == 0

    def test_bad_capacity_raises(self):
        with pytest.raises(ValueError):
            UpdateBuffer(capacity=0)


# ---------------------------------------------------------------------------
# Scheduler: EMA-driven dispatch decisions on the injected clock
# ---------------------------------------------------------------------------

class _FakeRegistry:
    """positions() == identity; just the ema_seconds column the scheduler
    reads (the real registry is exercised by the topology tests below)."""

    def __init__(self, emas):
        self.ema_seconds = np.asarray(emas, np.float64)

    def positions(self, ids):
        return np.asarray(ids, np.int64)


class TestStalenessScheduler:
    def test_fast_clients_redispatch_slow_clients_wait(self):
        reg = _FakeRegistry([0.1, 1.0, 10.0, 0.0])
        sched = StalenessScheduler(reg, max_staleness=2, clock=ManualClock())
        assert sched.redispatch_now(0) is True    # strictly below median (1.0)
        assert sched.redispatch_now(1) is False   # at the median: hold
        assert sched.redispatch_now(2) is False   # straggler: hold
        assert sched.redispatch_now(3) is False   # unobserved: hold

    def test_no_staleness_budget_means_no_early_redispatch(self):
        reg = _FakeRegistry([0.1, 1.0, 10.0])
        sched = StalenessScheduler(reg, max_staleness=0, clock=ManualClock())
        assert sched.redispatch_now(0) is False

    def test_defer_at_flush_uses_flush_period_ema(self):
        reg = _FakeRegistry([0.1, 5.0])
        clock = ManualClock()
        sched = StalenessScheduler(reg, max_staleness=1, clock=clock)
        assert sched.defer_at_flush(1) is False  # no period observed yet
        sched.note_flush()
        clock.advance(1.0)
        sched.note_flush()
        assert sched.flush_period_ema == pytest.approx(1.0)
        # 5.0s EMA > (max_staleness + 1) * 1.0s: training it now is wasted
        assert sched.defer_at_flush(1) is True
        assert sched.defer_at_flush(0) is False
        # the period EMA keeps moving — the decision is re-evaluated
        clock.advance(8.0)
        sched.note_flush()
        assert sched.flush_period_ema > 1.0

    def test_manual_clock_rejects_going_backwards(self):
        with pytest.raises(ValueError):
            ManualClock().advance(-1.0)

    def test_virtual_queue_tie_break_is_push_order(self):
        q = VirtualArrivalQueue()
        q.push(5, 1.0)
        q.push(2, 1.0)
        q.push(9, 0.5)
        assert q.clients() == [2, 5, 9]
        assert q.pop() == (0.5, 9)
        assert q.pop() == (1.0, 5)  # same finish time: dispatch order wins
        assert q.pop() == (1.0, 2)
        assert not q


# ---------------------------------------------------------------------------
# sp simulator: seed-determinism + bit-exact sync equivalence
# ---------------------------------------------------------------------------

def _sp_args(**over):
    base = {
        "common_args": {"training_type": "simulation", "random_seed": 0,
                        "run_id": over.pop("run_id", "async-sp")},
        "data_args": {"dataset": "mnist", "data_cache_dir": "",
                      "partition_method": "hetero", "partition_alpha": 0.5,
                      "synthetic_train_size": 600},
        "model_args": {"model": "lr"},
        "train_args": {
            "federated_optimizer": "FedAvg",
            "client_num_in_total": 4,
            "client_num_per_round": 4,
            "comm_round": 2,
            "epochs": 1,
            "batch_size": 32,
            "client_optimizer": "sgd",
            "learning_rate": 0.1,
        },
        "validation_args": {"frequency_of_the_test": 1},
        "comm_args": {"backend": "sp"},
    }
    args = Arguments.from_dict(base)
    for k, v in over.items():
        setattr(args, k, v)
    return args.validate()


def _sp_build(args):
    args = fedml_tpu.init(args, should_init_logs=False)
    dataset, out_dim = fedml_tpu.data.load(args)
    model = fedml_tpu.models.create(args, out_dim)
    return args, dataset, model


def _sp_fedbuff(**over):
    from fedml_tpu.simulation.sp.async_fedavg.fedbuff_api import FedBuffAPI

    args, dataset, model = _sp_build(_sp_args(**over))
    return FedBuffAPI(args, None, dataset, model)


class TestSPFedBuff:
    def test_sp_sync_equivalence_bit_exact(self):
        """Full participation + buffer == cohort + constant weighting + zero
        staleness budget == the sync FedAvg loop, bit for bit."""
        from fedml_tpu.simulation.sp.fedavg.fedavg_api import FedAvgAPI

        args, dataset, model = _sp_build(_sp_args())
        sync = FedAvgAPI(args, None, dataset, model)
        m_sync = sync.train()
        asyn = _sp_fedbuff(fl_mode="async", async_buffer_size=4,
                           async_max_staleness=0,
                           async_staleness_policy="constant")
        m_async = asyn.train()
        assert m_sync == m_async
        assert _ft._trees_bit_identical(sync.w_global, asyn.w_global)

    def test_sp_async_dispatch_raises_on_non_fedavg(self):
        from fedml_tpu.simulation.sp import create_sp_algorithm

        args, dataset, model = _sp_build(_sp_args(fl_mode="async"))
        with pytest.raises(ValueError, match="fedavg"):
            create_sp_algorithm("FedProx", args, None, dataset, model)

    def test_sp_deterministic_traced_and_report_closed(self, tmp_path, capsys):
        """One buffered run (cohort 4, buffer 2, no staleness budget — late
        reports are DROPPED and re-dispatched) traced + one untraced: the
        final models are bit-identical (tracing never perturbs the math),
        every cycle reconstructs as a closed span tree with its
        ``buffer.flush`` span, the dropped-stale counter surfaces in the
        exported metrics, and ``trace_report --assert-closed`` passes while
        printing the async flush/staleness columns."""
        knobs = dict(fl_mode="async", async_buffer_size=2,
                     async_max_staleness=0,
                     async_staleness_policy="constant", run_id="async-sp-tr")
        plain = _sp_fedbuff(**knobs)
        plain.train()
        mem = InMemorySink()
        mlops.init(_TraceArgs("async-sp-tr"), FanoutSink([mem]))
        try:
            traced = _sp_fedbuff(**knobs)
            traced.train()
        finally:
            mlops.finish()
        assert _ft._trees_bit_identical(plain.w_global, traced.w_global)

        recs = [dict(rec, topic=t) for t, rec in list(mem.records)
                if t in trace_report.SPAN_TOPICS]
        traces = trace_report.build_traces(recs)
        for r in range(2):
            tr = traces[trace_id_for("async-sp-tr", r)]
            assert tr.problems() == [], tr.problems()
            assert tr.is_async()
            flushes = tr.flushes()
            assert len(flushes) == 1
            assert flushes[0].start["n_deltas"] == 2
            assert flushes[0].start["capacity"] == 2
        metric_names = {r["metric"] for r in mem.by_topic("metrics")}
        assert "async.staleness" in metric_names
        assert "async.buffer_occupancy" in metric_names
        assert "async.dropped_stale" in metric_names  # late v0 reports died

        path = tmp_path / "trace.jsonl"
        path.write_text("\n".join(json.dumps(r) for r in recs) + "\n")
        assert trace_report.main([str(path), "--assert-closed"]) == 0
        out = capsys.readouterr().out
        assert "flush round=" in out
        assert "time_to_report=" in out  # async straggler metric, not dur


# ---------------------------------------------------------------------------
# XLA simulator: one-program async flush on the virtual mesh
# ---------------------------------------------------------------------------

def _xla_args(**over):
    over.setdefault("backend", "XLA")
    return _sp_args(**over)


@pytest.mark.heavy
class TestXLAAsyncFL:
    def test_xla_sync_equivalence_bit_exact(self):
        """Full participation + constant weighting + zero staleness budget:
        the async virtual-arrival driver collects the whole (id-sorted)
        cohort every cycle, so the in-mesh flush is schedule-identical to
        the sync round — bit for bit."""
        from fedml_tpu.simulation.xla.fed_sim import XLASimulator

        args_s, ds_s, m_s = _sp_build(_xla_args(partition_method="homo"))
        sim_sync = XLASimulator(args_s, ds_s, m_s)
        sim_sync.train()
        args_a, ds_a, m_a = _sp_build(_xla_args(
            partition_method="homo", fl_mode="async", async_buffer_size=4,
            async_max_staleness=0, async_staleness_policy="constant"))
        sim_async = XLASimulator(args_a, ds_a, m_a)
        sim_async.train()
        assert _ft._trees_bit_identical(sim_sync.variables,
                                        sim_async.variables)

    def test_xla_async_deterministic_traced_and_report_closed(
            self, tmp_path, capsys):
        """A genuinely-async XLA config (partial cohorts, staleness budget,
        polynomial discount) run untraced then traced: bit-identical final
        models, every cycle's span tree closed with a ``buffer.flush``
        record, and ``trace_report --assert-closed`` green."""
        from fedml_tpu.simulation.xla.fed_sim import XLASimulator

        knobs = dict(client_num_in_total=8, client_num_per_round=4,
                     fl_mode="async", async_buffer_size=2,
                     async_max_staleness=2,
                     async_staleness_policy="polynomial",
                     run_id="async-xla-tr")
        args1, ds1, m1 = _sp_build(_xla_args(**knobs))
        sim1 = XLASimulator(args1, ds1, m1)
        sim1.train()
        mem = InMemorySink()
        mlops.init(_TraceArgs("async-xla-tr"), FanoutSink([mem]))
        try:
            args2, ds2, m2 = _sp_build(_xla_args(**knobs))
            sim2 = XLASimulator(args2, ds2, m2)
            sim2.train()
        finally:
            mlops.finish()
        assert _ft._trees_bit_identical(sim1.variables, sim2.variables)

        recs = [dict(rec, topic=t) for t, rec in list(mem.records)
                if t in trace_report.SPAN_TOPICS]
        traces = trace_report.build_traces(recs)
        for r in range(2):
            tr = traces[trace_id_for("async-xla-tr", r)]
            assert tr.problems() == [], tr.problems()
            assert tr.is_async()
            assert len(tr.flushes()) == 1
        path = tmp_path / "trace.jsonl"
        path.write_text("\n".join(json.dumps(r) for r in recs) + "\n")
        assert trace_report.main([str(path), "--assert-closed"]) == 0
        assert "flush round=" in capsys.readouterr().out

    def test_xla_async_with_checkpointing_is_loudly_unsupported(self,
                                                                tmp_path):
        from fedml_tpu.simulation.xla.fed_sim import XLASimulator

        args, ds, m = _sp_build(_xla_args(
            fl_mode="async", async_buffer_size=2,
            checkpoint_dir=str(tmp_path / "ckpt")))
        with pytest.raises(NotImplementedError):
            XLASimulator(args, ds, m)


# ---------------------------------------------------------------------------
# Cross-silo message plane over LOOPBACK
# ---------------------------------------------------------------------------

def _run_silo_topology(run_id, n=2, **extra):
    """1 server + ``n`` silos to completion; returns (history, final
    params, server)."""
    from fedml_tpu.cross_silo.server.server import Server

    args_s = _ft._args(run_id, n, **extra)
    args_s.role, args_s.rank = "server", 0
    args_s = fedml_tpu.init(args_s, should_init_logs=False)
    ds, out_dim = fedml_tpu.data.load(args_s)
    server = Server(args_s, None, ds, fedml_tpu.models.create(args_s, out_dim))
    clients = [_ft._build_client(run_id, r, n, **extra)
               for r in range(1, n + 1)]
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    history = _ft._run_server_bounded(server)
    _ft._join_all(threads)
    final = server.server_manager.aggregator.get_global_model_params()
    return history, final, server


class TestCrossSiloAsync:
    def test_async_loopback_sync_equivalence_through_compiled_plane(self):
        """The acceptance check: buffer == cohort + constant weighting,
        both runs flushing through ``agg_plane=compiled`` — async must
        reproduce the sync FedAvg result bit-exactly."""
        LoopbackHub.reset()
        h_sync, f_sync, _ = _run_silo_topology(
            "async-eq-sync", agg_plane="compiled")
        LoopbackHub.reset()
        h_async, f_async, _ = _run_silo_topology(
            "async-eq-async", agg_plane="compiled", **_EQ2)
        assert len(h_sync) == len(h_async) == 2
        assert _ft._trees_bit_identical(f_sync, f_async)

    def test_async_loopback_buffered_run_traced_and_closed(self, tmp_path,
                                                           capsys):
        """A genuinely-buffered LOOPBACK run (buffer of 1, staleness budget
        1: the second silo's delta lands one flush late and is still
        aggregated, discounted): completes, evals every flush, and every
        cycle + buffer.flush span closes under --assert-closed."""
        LoopbackHub.reset()
        run_id = "async-loop-tr"
        mem = InMemorySink()
        mlops.init(_TraceArgs(run_id), FanoutSink([mem]))
        try:
            history, final, _ = _run_silo_topology(
                run_id, fl_mode="async", async_buffer_size=1,
                async_max_staleness=1, async_staleness_policy="polynomial")
        finally:
            mlops.finish()
        assert len(history) == 2
        assert 0.0 <= history[-1]["test_acc"] <= 1.0

        recs = [dict(rec, topic=t) for t, rec in list(mem.records)
                if t in trace_report.SPAN_TOPICS]
        traces = trace_report.build_traces(recs)
        for r in range(2):
            tr = traces[trace_id_for(run_id, r)]
            assert tr.problems() == [], (r, tr.problems())
            assert tr.is_async()
            flushes = tr.flushes()
            assert len(flushes) == 1
            assert flushes[0].start["n_deltas"] >= 1
        metric_names = {r["metric"] for r in mem.by_topic("metrics")}
        assert "async.staleness" in metric_names
        assert "async.flushes" in metric_names
        path = tmp_path / "trace.jsonl"
        path.write_text("\n".join(json.dumps(r) for r in recs) + "\n")
        assert trace_report.main([str(path), "--assert-closed"]) == 0
        assert "flush round=" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# Cross-device (Beehive) file plane
# ---------------------------------------------------------------------------

def _run_device_topology(tmp_path, tag, **extra):
    from fedml_tpu.cross_device.fake_device import FakeDeviceManager
    from fedml_tpu.cross_device.fedml_aggregator import FedMLAggregator
    from fedml_tpu.cross_device.fedml_server_manager import FedMLServerManager
    from fedml_tpu.models.linear import LogisticRegression

    LoopbackHub.reset()
    args = Arguments.from_dict({
        "common_args": {"training_type": "cross_device", "random_seed": 0,
                        "run_id": f"async-beehive-{tag}"},
        "data_args": {"dataset": "synthetic"},
        "model_args": {"model": "lr"},
        "train_args": {
            "federated_optimizer": "FedAvg",
            "client_num_in_total": 2, "client_num_per_round": 2,
            "comm_round": 3, "epochs": 2, "batch_size": 16,
            "learning_rate": 0.2, **extra,
        },
        "validation_args": {"frequency_of_the_test": 1},
        "comm_args": {"backend": "LOOPBACK"},
    }).validate()
    sep = __import__("test_cross_device")._separable
    x_test, y_test = sep(128, seed=9)
    aggregator = FedMLAggregator(
        args, LogisticRegression(output_dim=4), (x_test, y_test),
        worker_num=2, model_dir=str(tmp_path / f"models-{tag}"))
    server = FedMLServerManager(args, aggregator, client_rank=0, client_num=2)
    devices = [
        FakeDeviceManager(args, rank, sep(96, seed=rank), client_num=2,
                          upload_dir=str(tmp_path / f"dev{rank}-{tag}"))
        for rank in (1, 2)
    ]
    threads = [server.run_async()] + [d.run_async() for d in devices]
    for t in threads:
        t.join(timeout=60)
    for t in threads:
        assert not t.is_alive(), "protocol did not terminate"
    return aggregator, devices, server


class TestCrossDeviceAsync:
    def test_async_file_plane_matches_sync_and_releases_uploads(self,
                                                                tmp_path):
        """The equivalence config on the device file plane: bit-identical
        final model, every device trained every cycle, and every flushed
        upload file was released after its cycle's snapshot went durable."""
        agg_s, dev_s, _ = _run_device_topology(tmp_path, "sync")
        agg_a, dev_a, server = _run_device_topology(tmp_path, "async", **_EQ2)
        assert [d.rounds_trained for d in dev_a] == \
            [d.rounds_trained for d in dev_s] == [3, 3]
        assert agg_a.eval_history and \
            agg_a.eval_history[-1] == agg_s.eval_history[-1]
        assert _ft._trees_bit_identical(agg_s.variables, agg_a.variables)
        assert server._async_files == {}, "flushed upload files not released"


# ---------------------------------------------------------------------------
# Chaos: transport faults + server_kill mid-buffer (exactly-once accounting)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def async_fault_free_final():
    """The fault-free async reference model every chaos/kill run must
    bit-match (same claim as the sync chaos suite: the final model is a
    pure function of config, never of transport weather)."""
    LoopbackHub.reset()
    history, final, _ = _ft._run_chaos_topology(
        "async-ff", knobs=dict(_ft._CHAOS_KNOBS, **_EQ3))
    assert len(history) == 2
    return final


def test_async_fl_chaos_drop_dup_delay_bit_identical(async_fault_free_final):
    """The full scripted fault plan (drop + reset + duplicate + delay)
    against the buffered server: every fault is healed or deduped and the
    run converges to the bit-identical fault-free async model."""
    LoopbackHub.reset()
    history, final, stats = _ft._run_chaos_topology(
        "async-chaos", fault_plan=_ft._full_chaos_plan(),
        knobs=dict(_ft._CHAOS_KNOBS, **_EQ3))
    assert len(history) == 2
    assert _ft._trees_bit_identical(final, async_fault_free_final)
    assert stats[2]["faults_reset"] >= 1  # the scripted RST actually fired


def test_async_fl_server_kill_mid_buffer_replays_journal(
        async_fault_free_final, tmp_path):
    """The crash-safety contract, mid-buffer: the server dies after
    journaling the first delta of a 3-deep buffer; the restarted
    incarnation replays the journal INTO the buffer (per-sender dedup),
    collects the re-sent + still-pending deltas, and finishes with the
    bit-identical model — no delta applied twice across the restore."""
    LoopbackHub.reset()
    history, final, stats, restarts, killed_stats, server = \
        _ft._run_server_kill_topology("async-kill", tmp_path / "srv",
                                      knobs=_EQ3)
    assert restarts >= 1
    assert len(history) == 2
    assert _ft._trees_bit_identical(final, async_fault_free_final), \
        "restarted async run diverged from the fault-free model"
    assert sum(s.get("faults_killed", 0) for s in killed_stats) >= 1
    srv = stats[0]
    assert srv["server_restores"] >= 1
    assert srv["journal_replays"] >= 1
    # exactly-once accounting: journal replay + retransmits must not
    # double-count any report (3 silos x 2 flushes, each counted once)
    reg = server.server_manager.population.registry.snapshot()
    assert reg["reported_total"] == 3 * 2, reg
