"""Fast default-run model smoke (the full model-zoo forward matrix lives in
test_models.py, marker heavy)."""

import jax
import jax.numpy as jnp

from fedml_tpu.ml.engine.train import init_variables


def test_lr_and_cnn_forward():
    from fedml_tpu.models.cnn import CNN_DropOut
    from fedml_tpu.models.linear import LogisticRegression

    x = jnp.zeros((2, 28, 28, 1))
    for model in (LogisticRegression(output_dim=10), CNN_DropOut(only_digits=True, num_classes=10)):
        variables = init_variables(model, x, seed=0)
        out = model.apply(variables, x, train=False)
        assert out.shape == (2, 10)


def test_resnet_bf16_params_stay_fp32():
    from fedml_tpu.models.resnet import resnet20

    model = resnet20(num_classes=10, dtype=jnp.bfloat16)
    x = jnp.zeros((2, 32, 32, 3))
    variables = init_variables(model, x, seed=0)
    leaves = jax.tree_util.tree_leaves(variables["params"])
    assert all(l.dtype == jnp.float32 for l in leaves)
    out = model.apply(variables, x, train=False)
    assert out.shape == (2, 10)
