"""In-mesh split-computation algorithms (simulation/xla/split.py) on the
8-device virtual CPU mesh: VFL feature sharding, SplitNN compiled activation
exchange, FedGKT sharded knowledge transfer.  Thresholds mirror the sp twins
(tests/test_algorithms.py, tests/test_gkt_nas_seg.py)."""

import jax
import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.arguments import Arguments

pytestmark = pytest.mark.heavy


def _args(optimizer, **over):
    args = Arguments.from_dict(
        {
            "common_args": {"training_type": "simulation", "random_seed": 0, "run_id": "xsp"},
            "data_args": {
                "dataset": "mnist",
                "data_cache_dir": "",
                "partition_method": "hetero",
                "partition_alpha": 0.5,
                "synthetic_train_size": 800,
            },
            "model_args": {"model": "lr"},
            "train_args": {
                "federated_optimizer": optimizer,
                "client_num_in_total": 6,
                "client_num_per_round": 3,
                "comm_round": 3,
                "epochs": 1,
                "batch_size": 32,
                "client_optimizer": "sgd",
                "learning_rate": 0.1,
            },
            "validation_args": {"frequency_of_the_test": 2},
            "comm_args": {"backend": "XLA"},
        }
    )
    for k, v in over.items():
        setattr(args, k, v)
    return args.validate()


def _run(args):
    args = fedml_tpu.init(args, should_init_logs=False)
    dataset, out_dim = fedml_tpu.data.load(args)
    try:
        model = fedml_tpu.models.create(args, out_dim)
    except ValueError:
        model = None
    runner = fedml_tpu.FedMLRunner(args, None, dataset, model)
    return runner.run()


class TestVFLInMesh:
    def test_learns_on_mesh(self):
        metrics = _run(_args("classical_vertical", comm_round=60, dataset="synthetic"))
        assert metrics["test_acc"] > 0.5, metrics

    def test_matches_sp_trajectory(self):
        """Feature-sharded psum round == the sp host loop (same full-batch
        GD math, so the aggregates must agree to float tolerance)."""
        from fedml_tpu.simulation.sp.classical_vertical_fl.vfl_api import VerticalFLAPI
        from fedml_tpu.simulation.xla.split import VFLInMeshAPI

        args = fedml_tpu.init(
            _args("classical_vertical", comm_round=10, dataset="synthetic"),
            should_init_logs=False,
        )
        dataset, _ = fedml_tpu.data.load(args)
        mesh_m = VFLInMeshAPI(args, None, dataset).train()
        sp_m = VerticalFLAPI(args, None, dataset).train()
        # different init draws (sharded vs per-slice keys) -> compare quality
        assert abs(mesh_m["test_acc"] - sp_m["test_acc"]) < 0.15, (mesh_m, sp_m)

    def test_only_logit_sized_tensors_cross_parties(self):
        """The privacy property: weights/features stay party-sharded."""
        from fedml_tpu.simulation.xla.split import VFLInMeshAPI

        args = fedml_tpu.init(
            _args("classical_vertical", comm_round=1, dataset="synthetic"),
            should_init_logs=False,
        )
        dataset, _ = fedml_tpu.data.load(args)
        api = VFLInMeshAPI(args, None, dataset)
        api.train()
        # the weight matrix stays sharded over the party axis after training
        spec = api.w.sharding.spec
        assert tuple(spec)[0] == "party", spec


class TestSplitNNInMesh:
    def test_learns_on_mesh(self):
        metrics = _run(_args("split_nn", comm_round=2, client_num_in_total=3))
        assert metrics["test_acc"] > 0.4, metrics

    def test_relay_halves_stay_split(self):
        from fedml_tpu.simulation.xla.split import SplitNNInMeshAPI

        args = fedml_tpu.init(
            _args("split_nn", comm_round=1, client_num_in_total=3),
            should_init_logs=False,
        )
        dataset, _ = fedml_tpu.data.load(args)
        api = SplitNNInMeshAPI(args, None, dataset)
        before = jax.tree_util.tree_leaves(api.front_params)[0].copy()
        api.train()
        after = jax.tree_util.tree_leaves(api.front_params)[0]
        assert not np.allclose(np.asarray(before), np.asarray(after))
        # front and back remain separate param trees (the split boundary)
        front_keys = set(api.front_params["params"])
        back_keys = set(api.back_params["params"])
        assert front_keys.isdisjoint(back_keys)


class TestGKTInMesh:
    def _gkt_args(self, **over):
        return _args(
            "FedGKT", dataset="cifar10", model="resnet8_gkt",
            client_num_in_total=4, client_num_per_round=2, comm_round=2,
            epochs=1, batch_size=16, learning_rate=0.05,
            synthetic_train_size=256, frequency_of_the_test=1,
            # small tower: the CPU-mesh suite runs the protocol, not the
            # full ResNet-55-grade server (see models/gkt.py defaults)
            gkt_server_width=32, gkt_server_blocks=1, **over,
        )

    def test_round_runs_and_knowledge_flows(self):
        metrics = _run(self._gkt_args())
        assert "test_acc" in metrics and metrics["test_acc"] > 0.0

    def test_edge_nets_stay_local_and_knowledge_updates(self):
        from fedml_tpu.simulation.xla.split import GKTInMeshAPI

        args = fedml_tpu.init(self._gkt_args(), should_init_logs=False)
        dataset, _ = fedml_tpu.data.load(args)
        api = GKTInMeshAPI(args, None, dataset)
        api.train()
        has = np.asarray(api.has_kd)
        # sampling rotated through some participants; each got knowledge
        assert 2 <= int(has.sum()) <= 4
        # participating clients' edge nets diverged from each other
        cids = np.where(has > 0)[0][:2]
        a = jax.tree_util.tree_leaves(
            jax.tree_util.tree_map(lambda t: t[int(cids[0])], api.edge_table))
        b = jax.tree_util.tree_leaves(
            jax.tree_util.tree_map(lambda t: t[int(cids[1])], api.edge_table))
        assert any(not np.allclose(np.asarray(x), np.asarray(y))
                   for x, y in zip(a, b))
