"""Tests for DP mechanisms/accountant and attack/defense dispatchers."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.core.dp.budget_accountant import BudgetAccountant
from fedml_tpu.core.dp.fedml_differential_privacy import FedMLDifferentialPrivacy
from fedml_tpu.core.dp.mechanisms import Gaussian, Laplace
from fedml_tpu.core.security import defense_funcs as F
from fedml_tpu.core.security.fedml_attacker import FedMLAttacker
from fedml_tpu.core.security.fedml_defender import FedMLDefender


def _params(scale):
    return {"w": jnp.full((4, 4), float(scale)), "b": jnp.full((4,), float(scale))}


class TestDP:
    def test_gaussian_sigma_formula(self):
        g = Gaussian(epsilon=1.0, delta=1e-5, sensitivity=2.0)
        assert g.sigma == pytest.approx(np.sqrt(2 * np.log(1.25 / 1e-5)) * 2.0, rel=1e-9)

    def test_noise_changes_params_reproducibly(self):
        g = Gaussian(epsilon=1.0, delta=1e-5)
        k = jax.random.PRNGKey(0)
        a = g.add_noise(_params(0.0), k)
        b = g.add_noise(_params(0.0), k)
        assert float(jnp.abs(a["w"]).sum()) > 0
        np.testing.assert_allclose(a["w"], b["w"])

    def test_laplace_scale(self):
        l = Laplace(epsilon=2.0, sensitivity=1.0)
        assert l.scale == 0.5

    def test_accountant_exhausts(self):
        acc = BudgetAccountant(epsilon=1.0, delta=1e-4)
        acc.spend(0.5, 1e-5)
        acc.spend(0.5, 1e-5)
        with pytest.raises(RuntimeError):
            acc.spend(0.1, 0.0)

    def test_singleton_ldp_gate(self):
        class Args:
            enable_dp = True
            dp_type = "ldp"
            epsilon = 1.0
            delta = 1e-5
            mechanism_type = "gaussian"
            random_seed = 0

        dp = FedMLDifferentialPrivacy.get_instance()
        dp.init(Args())
        assert dp.is_local_dp_enabled() and not dp.is_global_dp_enabled()
        noised = dp.add_local_noise(_params(1.0))
        assert float(jnp.abs(noised["w"] - 1.0).sum()) > 0


class TestDefenses:
    def _updates(self, n=6, bad=None):
        ups = [(1.0, _params(1.0 + 0.01 * i)) for i in range(n)]
        if bad is not None:
            ups[bad] = (1.0, _params(100.0))
        return ups

    def test_krum_excludes_outlier(self):
        ups = self._updates(bad=2)
        kept = F.krum(ups, byzantine_num=1)
        assert len(kept) == 1
        assert float(kept[0][1]["w"][0, 0]) < 10

    def test_median_robust_to_outlier(self):
        med = F.coordinate_wise_median(self._updates(bad=0))
        assert float(med["w"][0, 0]) < 2

    def test_trimmed_mean(self):
        tm = F.coordinate_wise_trimmed_mean(self._updates(bad=1), trim_ratio=0.2)
        assert float(tm["w"][0, 0]) < 2

    def test_geometric_median_close_to_cluster(self):
        gm = F.geometric_median(self._updates(bad=5), max_iter=50)
        assert abs(float(gm["w"][0, 0]) - 1.0) < 0.5

    def test_norm_clipping_bounds_delta(self):
        glob = _params(0.0)
        clipped = F.norm_diff_clipping(self._updates(bad=3), glob, norm_bound=1.0)
        for _, p in clipped:
            vec = jnp.concatenate([p["w"].ravel(), p["b"].ravel()])
            assert float(jnp.linalg.norm(vec)) <= 1.0 + 1e-4

    def test_bulyan_robust(self):
        out = F.bulyan(self._updates(n=9, bad=4), byzantine_num=1)
        assert float(out["w"][0, 0]) < 2

    def test_defender_dispatch_krum(self):
        class Args:
            enable_defense = True
            defense_type = "krum"
            byzantine_client_num = 1
            random_seed = 0

        d = FedMLDefender.get_instance()
        d.init(Args())
        assert d.is_defense_enabled() and d.is_defense_before_aggregation()
        kept = d.defend_before_aggregation(self._updates(bad=1), _params(0.0))
        assert len(kept) == 1

    def test_defender_dispatch_geo_median(self):
        class Args:
            enable_defense = True
            defense_type = "geometric_median"
            random_seed = 0

        d = FedMLDefender.get_instance()
        d.init(Args())
        out = d.defend_on_aggregation(self._updates(bad=0), extra_auxiliary_info=_params(0.0))
        assert abs(float(out["w"][0, 0]) - 1.0) < 0.5


class TestAttacks:
    def test_byzantine_zero_mode(self):
        class Args:
            enable_attack = True
            attack_type = "byzantine"
            attack_mode = "zero"
            byzantine_client_num = 2
            random_seed = 0

        a = FedMLAttacker.get_instance()
        a.init(Args())
        assert a.is_model_attack()
        ups = [(1.0, _params(1.0)) for _ in range(5)]
        out = a.attack_model(ups, _params(0.0))
        zeroed = sum(1 for _, p in out if float(jnp.abs(p["w"]).sum()) == 0)
        assert zeroed == 2

    def test_label_flip(self):
        class Args:
            enable_attack = True
            attack_type = "label_flipping"
            original_class = 1
            target_class = 7
            random_seed = 0

        a = FedMLAttacker.get_instance()
        a.init(Args())
        y = np.array([0, 1, 2, 1])
        np.testing.assert_array_equal(a.poison_data(y), [0, 7, 2, 7])
