"""Shared test helpers for multi-process/networked tests."""

import socket


def free_port() -> int:
    """An ephemeral localhost port (bind 0, read, release)."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def force_child_cpu() -> None:
    """Force a SPAWNED child onto the CPU backend.  Spawned children don't
    run conftest: the axon sitecustomize registers the TPU backend in EVERY
    python process, and jax would otherwise init (and possibly hang on) the
    tunnel inside the child.  Call FIRST in every spawn target."""
    import os

    os.environ["JAX_PLATFORMS"] = "cpu"
    from fedml_tpu.utils.platform import force_cpu_backend

    force_cpu_backend()
