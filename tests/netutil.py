"""Shared test networking helpers."""

import socket


def free_port() -> int:
    """An ephemeral localhost port (bind 0, read, release)."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port
