"""Population subsystem (core/population/): registry accounting, selection
policies (with bit-exact legacy-schedule parity on BOTH historical RNG
styles), the over-commit pacer's arithmetic, vectorized stacked selection
at Parrot fleet sizes, knob validation, and the cohort_stats sink record.

The parity tests are the PR's contract: with policy=uniform and no pacing
knobs, every backend's cohort schedule is bit-identical to the pre-population
code — and the draw no longer stomps the process-global NumPy RNG."""

from __future__ import annotations

import numpy as np
import pytest

from fedml_tpu.arguments import Arguments
from fedml_tpu.core.population import (
    ClientRegistry,
    ImportancePolicy,
    PopulationManager,
    RoundPacer,
    StratifiedBySpeedPolicy,
    UniformPolicy,
    make_policy,
    stacked_cohorts,
    uniform_id_choice,
)
from fedml_tpu.core.sampling import client_sampling


def _sim_args(**over):
    base = {
        "common_args": {"training_type": "simulation", "random_seed": 0, "run_id": "pop"},
        "data_args": {"dataset": "mnist", "data_cache_dir": "",
                      "partition_method": "hetero", "partition_alpha": 0.5,
                      "synthetic_train_size": 320},
        "model_args": {"model": "lr"},
        "train_args": {"federated_optimizer": "FedAvg", "client_num_in_total": 16,
                       "client_num_per_round": 4, "comm_round": 3, "epochs": 1,
                       "batch_size": 32, "client_optimizer": "sgd",
                       "learning_rate": 0.1},
        "validation_args": {"frequency_of_the_test": 2},
        "comm_args": {"backend": "sp"},
    }
    args = Arguments.from_dict(base)
    for k, v in over.items():
        setattr(args, k, v)
    return args


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------

class TestClientRegistry:
    def test_counters_and_snapshot(self):
        reg = ClientRegistry(np.arange(10), num_samples=np.arange(10) * 10)
        reg.note_invited([1, 2, 3], round_idx=0)
        reg.note_reports([1, 2], round_idx=0, seconds=2.0)
        reg.note_failures([3], round_idx=0)
        reg.note_rejected_late(3)
        reg.note_rejoin(3)
        snap = reg.snapshot()
        assert snap["fleet"] == 10 and snap["eligible"] == 10
        assert snap["invited_total"] == 3 and snap["reported_total"] == 2
        assert snap["failures_total"] == 1
        assert snap["rejected_late_total"] == 1 and snap["rejoins_total"] == 1
        rec = reg.record(3)
        assert rec["invites"] == 1 and rec["failures"] == 1
        assert rec["rejected_late"] == 1 and rec["rejoins"] == 1

    def test_ema_latency_and_speed_scores(self):
        reg = ClientRegistry(np.arange(4))
        # first observation seeds the EMA; later ones blend with alpha=0.3
        reg.note_report(0, 0, seconds=10.0)
        reg.note_report(0, 1, seconds=20.0)
        assert reg.record(0)["ema_seconds"] == pytest.approx(13.0)
        reg.note_report(1, 0, seconds=1.0)
        scores = reg.speed_scores()
        # unseen clients (2, 3) sit at the fleet median of observed EMAs
        assert scores[0] == pytest.approx(13.0) and scores[1] == pytest.approx(1.0)
        assert scores[2] == scores[3] == pytest.approx(np.median([13.0, 1.0]))

    def test_runtime_estimator_feed(self):
        reg = ClientRegistry(np.arange(3))
        for n, s in ((10, 1.0), (20, 2.0), (30, 3.0)):
            reg.note_report(1, 0, n_samples=n, seconds=s)
        pred = reg.predicted_seconds(1, 40)
        assert pred == pytest.approx(4.0, rel=0.2)

    def test_blocklist_round_trip(self):
        reg = ClientRegistry(np.arange(6))
        reg.blocklist([0, 5])
        assert reg.is_blocklisted(0) and not reg.is_blocklisted(1)
        assert reg.eligible_count() == 4
        assert set(map(int, reg.eligible_ids())) == {1, 2, 3, 4}
        reg.unblocklist([0])
        assert reg.eligible_count() == 5

    def test_non_contiguous_ids(self):
        # message-plane fleets are 1-based (and could be sparse): the
        # id->position map must round-trip counters correctly
        reg = ClientRegistry([7, 11, 42])
        reg.note_invited([42], round_idx=0)
        reg.note_report(42, 0, seconds=1.0)
        assert reg.record(42)["reports"] == 1 and reg.record(7)["reports"] == 0

    def test_absorb_comm_stats(self):
        reg = ClientRegistry(np.arange(2))
        reg.absorb_comm_stats({"retries": 3, "rejoins": 1})
        assert reg.comm_stats.get("retries") == 3
        reg.absorb_comm_stats({"retries": 2})
        assert reg.comm_stats.get("retries") == 5


# ---------------------------------------------------------------------------
# Uniform policy: bit-exact legacy parity, no global-RNG stomp
# ---------------------------------------------------------------------------

class TestUniformParity:
    def test_client_sampling_matches_legacy_mt19937_schedule(self):
        """The fixed seam reproduces the historical global-seeded draw."""
        for r in range(6):
            np.random.seed(r)  # the schedule the old code produced
            legacy = np.random.choice(range(20), 5, replace=False)
            assert np.array_equal(client_sampling(r, 20, 5), legacy)

    def test_client_sampling_no_longer_stomps_global_rng(self):
        """The historical bug: sampling reseeded np.random, so every other
        consumer of the global stream became a function of round_idx."""
        np.random.seed(1234)
        expect = np.random.rand(4)
        np.random.seed(1234)
        client_sampling(0, 20, 5)  # must not touch the global stream
        assert np.array_equal(np.random.rand(4), expect)

    def test_uniform_policy_mt19937_matches_client_sampling(self):
        reg = ClientRegistry(np.arange(20))
        pol = UniformPolicy(reg, rng_style="mt19937")
        for r in range(4):
            assert np.array_equal(pol.select(r, 5), client_sampling(r, 20, 5))

    def test_uniform_policy_pcg64_matches_legacy_message_plane_draw(self):
        """Cross-silo/cross-device historically drew with
        default_rng(round_idx) over the literal id list."""
        ids = list(range(1, 13))
        reg = ClientRegistry(ids)
        pol = UniformPolicy(reg, rng_style="pcg64")
        for r in range(4):
            legacy = np.random.default_rng(r).choice(ids, 5, replace=False).tolist()
            assert list(map(int, pol.select(r, 5))) == legacy
            assert uniform_id_choice(r, ids, 5) == legacy

    def test_full_cohort_when_k_covers_pool(self):
        reg = ClientRegistry([1, 2, 3])
        assert list(UniformPolicy(reg, "pcg64").select(0, 3)) == [1, 2, 3]
        assert list(UniformPolicy(reg, "mt19937").select(0, 7)) == [1, 2, 3]

    def test_blocklist_respected(self):
        reg = ClientRegistry(np.arange(30))
        reg.blocklist([0, 1, 2])
        for r in range(5):
            cohort = UniformPolicy(reg, "mt19937").select(r, 10)
            assert not set(map(int, cohort)) & {0, 1, 2}


# ---------------------------------------------------------------------------
# Stratified / importance policies
# ---------------------------------------------------------------------------

class TestStatefulPolicies:
    def _seeded_registry(self, n=40):
        reg = ClientRegistry(np.arange(n), num_samples=(np.arange(n) + 1) * 5)
        # observed speeds: client i takes i+1 seconds
        reg.note_reports(np.arange(n), 0, seconds=None)
        for i in range(n):
            reg.note_report(i, 0, seconds=float(i + 1))
        return reg

    def test_stratified_deterministic_and_spans_speed_spectrum(self):
        reg = self._seeded_registry()
        pol = StratifiedBySpeedPolicy(reg, num_strata=4)
        a, b = pol.select(3, 8), pol.select(3, 8)
        assert np.array_equal(a, b)  # deterministic in round_idx
        assert not np.array_equal(pol.select(4, 8), a)
        assert len(set(map(int, a))) == 8
        # largest-remainder quota: 2 clients from each decile of the
        # speed-sorted pool (speeds here are client_id + 1 seconds)
        assert pol.last_strata_sizes == [10, 10, 10, 10]  # stratum pool sizes
        for lo in (0, 10, 20, 30):
            assert sum(lo <= int(c) < lo + 10 for c in a) == 2

    def test_stratified_blocklist(self):
        reg = self._seeded_registry()
        reg.blocklist(list(range(10)))
        cohort = StratifiedBySpeedPolicy(reg, num_strata=3).select(0, 9)
        assert all(int(c) >= 10 for c in cohort)

    def test_importance_weights_toward_large_clients(self):
        reg = self._seeded_registry(n=50)
        pol = ImportancePolicy(reg, alpha=2.0)
        picks = np.concatenate([pol.select(r, 10) for r in range(30)])
        assert len(set(map(int, pol.select(0, 10)))) == 10
        assert np.array_equal(pol.select(5, 10), pol.select(5, 10))
        # (num_samples+1)^2 weighting: the big half must dominate the draws
        big = np.count_nonzero(picks >= 25)
        assert big > 0.6 * picks.size

    def test_importance_staleness_boost(self):
        reg = ClientRegistry(np.arange(20), num_samples=np.full(20, 10))
        # everyone equal except client 7, unseen since round 0
        reg.note_reports(np.delete(np.arange(20), 7), 99, seconds=1.0)
        pol = ImportancePolicy(reg, alpha=0.0, staleness_weight=50.0)
        hits = sum(7 in set(map(int, pol.select(r, 5))) for r in range(100, 140))
        base = sum(3 in set(map(int, pol.select(r, 5))) for r in range(100, 140))
        assert hits > base

    def test_make_policy_dispatch_and_unknown_name(self):
        reg = ClientRegistry(np.arange(4))
        assert make_policy("uniform", reg, rng_style="pcg64").name == "uniform"
        assert make_policy("stratified", reg, rng_style="mt19937",
                           num_strata=2).name == "stratified"
        assert make_policy("importance", reg, rng_style="mt19937",
                           importance_alpha=1.0).name == "importance"
        with pytest.raises(ValueError):
            make_policy("bogus", reg, rng_style="mt19937")


# ---------------------------------------------------------------------------
# Pacer arithmetic
# ---------------------------------------------------------------------------

class TestRoundPacer:
    def test_invite_count_ceil_with_float_guard(self):
        p = RoundPacer(overcommit=1.1)
        # 10 * 1.1 is 11.000000000000002 in floats: must not ceil to 12
        assert p.invite_count(10) == 11
        assert RoundPacer(overcommit=1.5).invite_count(2) == 3
        assert RoundPacer(overcommit=1.0).invite_count(7) == 7

    def test_quorum_for(self):
        assert RoundPacer().quorum_for(4, 4) == 4          # default: target K
        assert RoundPacer(quorum=2).quorum_for(4, 6) == 2  # explicit quorum
        assert RoundPacer(quorum=9).quorum_for(4, 3) == 3  # clamped to invited
        assert RoundPacer().quorum_for(0, 0) == 1          # never zero

    def test_enabled_flag(self):
        assert not RoundPacer().enabled
        assert RoundPacer(overcommit=1.2).enabled
        assert RoundPacer(quorum=3).enabled

    def test_validation(self):
        with pytest.raises(ValueError):
            RoundPacer(overcommit=0.9)
        with pytest.raises(ValueError):
            RoundPacer(quorum=-1)

    def test_from_args(self):
        args = _sim_args(pacing_overcommit=1.5, pacing_quorum=3).validate()
        p = RoundPacer.from_args(args)
        assert p.overcommit == 1.5 and p.quorum == 3


# ---------------------------------------------------------------------------
# Stacked (vectorized whole-run) selection
# ---------------------------------------------------------------------------

class TestStackedCohorts:
    def test_draws_cohort_from_100k_fleet_in_one_call(self):
        """The acceptance bar: a Parrot-scale fleet (>= 1e5 virtual clients)
        scheduled in ONE vectorized call — no Python loop over clients."""
        n, k, rounds = 120_000, 64, 8
        sched = stacked_cohorts(n, k, rounds, seed=3)
        assert sched.shape == (rounds, k) and sched.dtype == np.int64
        for row in sched:
            assert len(set(map(int, row))) == k  # no replacement
        assert sched.min() >= 0 and sched.max() < n
        # rounds differ (astronomically unlikely to collide)
        assert not np.array_equal(sched[0], sched[1])

    def test_deterministic_in_seed(self):
        a = stacked_cohorts(1000, 10, 5, seed=11)
        b = stacked_cohorts(1000, 10, 5, seed=11)
        c = stacked_cohorts(1000, 10, 5, seed=12)
        assert np.array_equal(a, b) and not np.array_equal(a, c)

    def test_blocked_never_drawn(self):
        blocked = np.arange(50)
        sched = stacked_cohorts(200, 40, 20, seed=0, blocked=blocked)
        assert sched.min() >= 50

    def test_weighted_draw_biases_heavy_clients(self):
        w = np.ones(1000)
        w[:100] = 200.0
        sched = stacked_cohorts(1000, 50, 40, seed=5, weights=w)
        heavy = np.count_nonzero(sched < 100)
        assert heavy > 0.5 * sched.size

    def test_validation(self):
        with pytest.raises(ValueError):
            stacked_cohorts(10, 0, 5)
        with pytest.raises(ValueError):
            stacked_cohorts(10, 11, 5)
        with pytest.raises(ValueError):
            stacked_cohorts(10, 5, 0)
        with pytest.raises(ValueError):
            stacked_cohorts(10, 8, 2, blocked=np.arange(5))  # leaves 5 < k=8


# ---------------------------------------------------------------------------
# Knob validation (fail at config time, not mid-run)
# ---------------------------------------------------------------------------

class TestArgumentValidation:
    def test_per_round_must_fit_fleet(self):
        with pytest.raises(ValueError, match="client_num_per_round"):
            _sim_args(client_num_per_round=32).validate()

    def test_overcommit_floor(self):
        with pytest.raises(ValueError, match="pacing_overcommit"):
            _sim_args(pacing_overcommit=0.5).validate()

    def test_quorum_floor(self):
        with pytest.raises(ValueError, match="pacing_quorum"):
            _sim_args(pacing_quorum=-2).validate()

    def test_policy_enum(self):
        with pytest.raises(ValueError, match="selection_policy"):
            _sim_args(selection_policy="fastest_first").validate()

    def test_strata_floor(self):
        with pytest.raises(ValueError, match="population_strata"):
            _sim_args(population_strata=0).validate()

    def test_blocklist_must_leave_a_cohort(self):
        with pytest.raises(ValueError, match="population_blocklist"):
            _sim_args(population_blocklist=list(range(14))).validate()

    def test_valid_knobs_pass(self):
        args = _sim_args(selection_policy="stratified", pacing_overcommit=1.25,
                         pacing_quorum=2, population_strata=3,
                         population_blocklist=[0, 1]).validate()
        assert args.pacing_overcommit == 1.25


# ---------------------------------------------------------------------------
# Manager + cohort_stats observability
# ---------------------------------------------------------------------------

class TestPopulationManager:
    def test_invite_report_close_cycle(self):
        args = _sim_args(pacing_overcommit=1.5).validate()
        emitted = []
        mgr = PopulationManager.from_args(args, list(range(1, 9)),
                                          rng_style="pcg64", emit=emitted.append)
        invited = mgr.invite(0, 4)
        assert len(invited) == 6  # ceil(4 * 1.5)
        assert mgr.quorum == 4
        for cid in invited[:3]:
            assert mgr.note_report(cid, round_idx=0, seconds=1.0)
        assert not mgr.note_report(invited[0], round_idx=0)  # idempotent
        assert not mgr.quorum_reached()
        assert mgr.note_report(invited[3], round_idx=0)
        assert mgr.quorum_reached()
        mgr.note_rejected_late(invited[5])
        stats = mgr.close_round(reason="quorum", seconds=2.5)
        assert stats is emitted[-1]
        assert stats["invited"] == 6 and stats["reported"] == 4
        assert stats["failed"] == 2 and stats["rejected_late"] == 1
        assert stats["close_reason"] == "quorum" and stats["target_k"] == 4
        assert stats["round_seconds"] == pytest.approx(2.5)
        assert stats["rejected_late_total"] == 1

    def test_observe_round_vectorized_surface(self):
        args = _sim_args().validate()
        emitted = []
        mgr = PopulationManager.from_args(args, np.arange(1000),
                                          emit=emitted.append)
        inv = np.arange(100)
        stats = mgr.observe_round(0, inv, reported_ids=inv[:90], seconds=1.0)
        assert stats["invited"] == 100 and stats["reported"] == 90
        assert stats["failed"] == 10
        assert mgr.registry.snapshot()["failures_total"] == 10
        assert emitted == [stats] and mgr.history == [stats]

    def test_cohort_stats_lands_in_inmemory_sink(self):
        """The default emit path goes through the core/mlops bus: one
        cohort_stats record per round close, visible to any attached sink."""
        from fedml_tpu.core import mlops
        from fedml_tpu.core.mlops import FanoutSink, InMemorySink

        args = _sim_args().validate()
        mem = InMemorySink()
        mlops.init(args, FanoutSink([mem]))
        try:
            mgr = PopulationManager.from_args(args, list(range(1, 7)),
                                              rng_style="pcg64")
            mgr.invite(0, 4)
            for cid in mgr._invited:
                mgr.note_report(cid, round_idx=0)
            mgr.close_round(reason="complete")
            records = mem.by_topic("cohort_stats")
            assert len(records) == 1
            rec = records[0]
            assert rec["round_idx"] == 0 and rec["policy"] == "uniform"
            assert rec["close_reason"] == "complete"
            assert rec["invited"] == rec["reported"] == 4
        finally:
            mlops.finish()

    def test_from_args_applies_blocklist(self):
        args = _sim_args(population_blocklist=[1, 2]).validate()
        mgr = PopulationManager.from_args(args, list(range(16)))
        assert mgr.registry.eligible_count() == 14
        for r in range(4):
            assert not set(map(int, mgr.select(r, 6))) & {1, 2}


# ---------------------------------------------------------------------------
# Cross-backend determinism: one seed, one policy -> one schedule
# ---------------------------------------------------------------------------

class TestCrossBackendDeterminism:
    def _build(self, backend):
        import fedml_tpu

        args = fedml_tpu.init(_sim_args(backend=backend).validate(),
                              should_init_logs=False)
        dataset, out_dim = fedml_tpu.data.load(args)
        model = fedml_tpu.models.create(args, out_dim)
        return args, dataset, model

    def test_sp_and_xla_share_the_legacy_schedule(self):
        """Same seed + uniform policy -> bit-identical cohorts on the sp and
        XLA simulators, both equal to the historical global-seeded draw."""
        from fedml_tpu.simulation.sp.fedavg.fedavg_api import FedAvgAPI
        from fedml_tpu.simulation.xla.fed_sim import XLASimulator

        args, dataset, model = self._build("sp")
        sp = FedAvgAPI(args, None, dataset, model)
        args_x, dataset_x, model_x = self._build("XLA")
        xla = XLASimulator(args_x, dataset_x, model_x)
        for r in range(3):
            legacy = client_sampling(r, 16, 4)
            assert list(map(int, sp._client_sampling(r))) == list(map(int, legacy))
            assert np.array_equal(np.asarray(xla._client_sampling(r)), legacy)

    def test_message_plane_managers_share_the_pcg64_schedule(self):
        """The cross-silo aggregator seam and a pcg64 PopulationManager draw
        the identical legacy default_rng(round_idx) cohort."""
        from fedml_tpu.cross_silo.server.fedml_aggregator import FedMLAggregator

        ids = list(range(1, 9))
        args = _sim_args().validate()
        mgr = PopulationManager.from_args(args, ids, rng_style="pcg64")
        for r in range(4):
            legacy = np.random.default_rng(r).choice(ids, 3, replace=False).tolist()
            assert FedMLAggregator.client_selection(None, r, ids, 3) == legacy
            assert [int(c) for c in mgr.select(r, 3)] == legacy

    def test_stacked_schedule_is_pure_function_of_config(self):
        from fedml_tpu.simulation.xla.fed_sim import XLASimulator

        args, dataset, model = self._build("XLA")
        args.population_stacked = True
        sim = XLASimulator(args, dataset, model)
        expect = stacked_cohorts(16, 4, 3, seed=0)
        for r in range(3):
            assert np.array_equal(sim._client_sampling(r), expect[r])
