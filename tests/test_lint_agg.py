"""tools/lint_agg.py wired into tier-1: with ``core/aggregate.py`` (host)
and ``parallel/agg_plane.py`` (compiled) as the only two aggregation
surfaces, library code must not grow new hand-rolled star-lambda
``tree_map`` aggregation loops — and the linter itself must actually catch
violations, because a lint that can't fail is not a gate."""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))

import lint_agg


def test_library_tree_is_clean():
    """The machine-enforced contract: every multi-client fold in fedml_tpu/
    routes through core/aggregate or the compiled agg plane."""
    assert lint_agg.main([]) == 0


def test_catches_star_lambda_tree_map(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import jax\n"
        "def my_agg(models):\n"
        "    return jax.tree_util.tree_map(lambda *xs: sum(xs), *models)\n"
    )
    violations = lint_agg.lint_file(str(bad))
    assert [(lineno, kind) for _, lineno, kind, _ in violations] == [
        (3, "host tree_map aggregation loop"),
    ]
    assert lint_agg.main(["--root", str(tmp_path)]) == 1


def test_single_tree_maps_are_fine(tmp_path):
    f = tmp_path / "good.py"
    f.write_text(
        "import jax\n"
        "def scale(tree, s):\n"
        "    return jax.tree_util.tree_map(lambda x: x * s, tree)\n"
        "def pairwise(a, b):\n"
        "    return jax.tree_util.tree_map(lambda x, y: x + y, a, b)\n"
    )
    assert lint_agg.lint_file(str(f)) == []


def test_pragma_allows_approved_seam(tmp_path):
    f = tmp_path / "seam.py"
    f.write_text(
        "import jax\n"
        "agg = jax.tree_util.tree_map(lambda *xs: sum(xs), *ts)  # lint_agg: allow\n"
    )
    assert lint_agg.lint_file(str(f)) == []
    assert lint_agg.main(["--root", str(tmp_path)]) == 0


def test_core_aggregate_is_exempt(tmp_path):
    d = tmp_path / "core"
    d.mkdir()
    f = d / "aggregate.py"
    f.write_text(
        "import jax\n"
        "def tree_sum(trees):\n"
        "    return jax.tree_util.tree_map(lambda *xs: sum(xs), *trees)\n"
    )
    assert lint_agg.lint_file(str(f)) == []
    assert lint_agg.main(["--root", str(tmp_path)]) == 0


def test_docstrings_and_comments_do_not_false_positive(tmp_path):
    f = tmp_path / "prose.py"
    f.write_text(
        '"""Never write tree_map(lambda *xs: ...) aggregation by hand."""\n'
        "# the old loop was tree_map(lambda *w: np.mean(w), *models)\n"
        "MSG = 'use core.aggregate, not tree_map(lambda *xs: sum(xs))'\n"
    )
    assert lint_agg.lint_file(str(f)) == []
