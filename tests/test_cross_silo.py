"""End-to-end cross-silo (Octopus) tests: 1 server + 2 client silos running the
full ONLINE-handshake / init / train / aggregate / sync / FINISH protocol
(reference smoke_test_cross_silo_ho_linux.yml runs the same topology as
co-located processes; here threads + loopback/gRPC/MQTT-S3 backends)."""

from __future__ import annotations

import threading

import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.arguments import Arguments
from fedml_tpu.core.distributed.communication.loopback import LoopbackHub


def _make_args(backend: str, run_id: str, **extra):
    cfg = {
        "common_args": {"training_type": "cross_silo", "random_seed": 0, "run_id": run_id},
        "data_args": {"dataset": "synthetic", "data_cache_dir": "", "partition_method": "homo",
                      "synthetic_train_size": 240},
        "model_args": {"model": "lr"},
        "train_args": {
            "federated_optimizer": "FedAvg",
            "client_num_in_total": 2,
            "client_num_per_round": 2,
            "comm_round": 2,
            "epochs": 1,
            "batch_size": 16,
            "client_optimizer": "sgd",
            "learning_rate": 0.1,
        },
        "validation_args": {"frequency_of_the_test": 1},
        "comm_args": {"backend": backend, **extra},
    }
    return Arguments.from_dict(cfg).validate()


def _run_topology(backend: str, run_id: str, comm_extra=None):
    """Run server + 2 clients to completion; return server eval history."""
    comm_extra = comm_extra or {}
    args_s = _make_args(backend, run_id, **comm_extra)
    args_s.role = "server"
    args_s.rank = 0
    args_s = fedml_tpu.init(args_s, should_init_logs=False)
    dataset_s, out_dim = fedml_tpu.data.load(args_s)
    model_s = fedml_tpu.models.create(args_s, out_dim)

    from fedml_tpu.cross_silo.server.server import Server

    server = Server(args_s, None, dataset_s, model_s)

    clients = []
    for rank in (1, 2):
        args_c = _make_args(backend, run_id, **comm_extra)
        args_c.role = "client"
        args_c.rank = rank
        args_c = fedml_tpu.init(args_c, should_init_logs=False)
        dataset_c, out_dim_c = fedml_tpu.data.load(args_c)
        model_c = fedml_tpu.models.create(args_c, out_dim_c)
        from fedml_tpu.cross_silo.client.client import Client

        clients.append(Client(args_c, None, dataset_c, model_c))

    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    history = server.run()  # blocks until FINISH
    for t in threads:
        t.join(timeout=30)
        assert not t.is_alive(), "client did not shut down after FINISH"
    return history


def test_cross_silo_loopback():
    LoopbackHub.reset()
    history = _run_topology("LOOPBACK", "cs-loop")
    assert len(history) == 2  # eval each round (freq=1)
    assert 0.0 <= history[-1]["test_acc"] <= 1.0
    # training on separable synthetic data should beat chance (10 classes)
    assert history[-1]["test_acc"] > 0.2


def test_cross_silo_grpc():
    history = _run_topology("GRPC", "cs-grpc", comm_extra={"grpc_base_port": 29110})
    assert len(history) == 2
    assert history[-1]["test_acc"] > 0.2


def test_cross_silo_mqtt_s3(tmp_path):
    from fedml_tpu.core.distributed.communication.mqtt_s3.broker import LocalBroker

    broker = LocalBroker().start()
    try:
        history = _run_topology(
            "MQTT_S3", "cs-mqtt",
            comm_extra={"mqtt_host": "127.0.0.1", "mqtt_port": broker.port,
                        "s3_blob_root": str(tmp_path / "blobs")},
        )
        assert len(history) == 2
        assert history[-1]["test_acc"] > 0.2
    finally:
        broker.stop()


def test_broker_pubsub_and_lastwill():
    """Broker unit semantics: wildcard subs, delivery, last-will on dirty exit."""
    from fedml_tpu.core.distributed.communication.mqtt_s3.broker import BrokerClient, LocalBroker

    broker = LocalBroker().start()
    got = []
    done = threading.Event()

    def on_msg(topic, payload):
        got.append((topic, payload))
        done.set()

    sub = BrokerClient("127.0.0.1", broker.port, on_msg)
    sub.subscribe("fedml_run1_#")  # prefix wildcard
    pub = BrokerClient("127.0.0.1", broker.port, lambda *a: None)
    import time

    time.sleep(0.2)  # let SUB land
    pub.publish("fedml_run1_0_1", {"hello": 1})
    assert done.wait(5), "message not delivered"
    assert got[0] == ("fedml_run1_0_1", {"hello": 1})

    # last will fires on unclean close
    done.clear()
    will = BrokerClient("127.0.0.1", broker.port, lambda *a: None)
    will.set_last_will("fedml_run1_lastwill", {"rank": 9, "status": "OFFLINE"})
    time.sleep(0.2)
    import socket as _socket

    # dirty death: FIN without a DISCONNECT frame (shutdown, not close —
    # close() defers the FIN while the client's recv thread holds the fd)
    will._sock.shutdown(_socket.SHUT_RDWR)
    assert done.wait(5), "last-will not delivered"
    assert got[-1][0] == "fedml_run1_lastwill"

    sub.disconnect()
    pub.disconnect()
    broker.stop()
