"""Unit tests for the core kernel: config, message, loopback comm, aggregation."""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.arguments import Arguments
from fedml_tpu.core.aggregate import (
    FedMLAggOperator,
    stacked_weighted_mean,
    tree_stack,
    unweighted_sum,
    weighted_mean,
)
from fedml_tpu.core.data.noniid_partition import (
    homo_partition,
    non_iid_partition_with_dirichlet_distribution,
)
from fedml_tpu.core.distributed.comm_manager import FedMLCommManager
from fedml_tpu.core.distributed.communication.loopback import LoopbackHub
from fedml_tpu.core.distributed.communication.message import Message


def _params(scale):
    return {"dense": {"w": jnp.full((3, 2), scale), "b": jnp.full((2,), scale)}}


class TestConfig:
    def test_from_dict_flattens_sections(self):
        args = Arguments.from_dict(
            {
                "common_args": {"training_type": "simulation", "random_seed": 0},
                "train_args": {
                    "federated_optimizer": "FedAvg",
                    "client_num_in_total": 10,
                    "client_num_per_round": 4,
                    "comm_round": 5,
                },
                "data_args": {"dataset": "mnist"},
                "model_args": {"model": "lr"},
            }
        )
        assert args.training_type == "simulation"
        assert args.client_num_per_round == 4
        args.validate()

    def test_validate_rejects_oversampling(self):
        args = Arguments.from_dict(
            {
                "training_type": "simulation",
                "dataset": "mnist",
                "model": "lr",
                "federated_optimizer": "FedAvg",
                "client_num_in_total": 2,
                "client_num_per_round": 4,
                "comm_round": 1,
            }
        )
        with pytest.raises(ValueError):
            args.validate()


class TestMessage:
    def test_roundtrip_json(self):
        m = Message(type="sync", sender_id=0, receiver_id=3)
        m.add_params("round_idx", 7)
        m.add_params(Message.MSG_ARG_KEY_MODEL_PARAMS, _params(1.0))  # tensor: excluded from json
        m2 = Message()
        m2.init_from_json_string(m.to_json())
        assert m2.get_type() == "sync"
        assert m2.get_receiver_id() == 3
        assert m2.get("round_idx") == 7
        assert m2.get(Message.MSG_ARG_KEY_MODEL_PARAMS) is None


class TestAggregation:
    def test_weighted_mean_matches_manual(self):
        updates = [(1.0, _params(1.0)), (3.0, _params(2.0))]
        avg = weighted_mean(updates)
        np.testing.assert_allclose(avg["dense"]["w"], np.full((3, 2), 1.75), rtol=1e-6)

    def test_seq_mode_is_sum(self):
        class A:
            federated_optimizer = "FedAvg_seq"

        out = FedMLAggOperator.agg(A(), [(1.0, _params(1.0)), (1.0, _params(2.0))])
        np.testing.assert_allclose(out["dense"]["b"], np.full((2,), 3.0), rtol=1e-6)

    def test_stacked_matches_list_form(self):
        updates = [(2.0, _params(1.0)), (1.0, _params(4.0)), (1.0, _params(0.0))]
        listform = weighted_mean(updates)
        stacked = tree_stack([p for _, p in updates])
        stackform = stacked_weighted_mean(stacked, jnp.asarray([2.0, 1.0, 1.0]))
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-6), listform, stackform
        )


class TestPartition:
    def test_homo_covers_all(self):
        m = homo_partition(103, 7, seed=1)
        all_idx = np.concatenate([m[i] for i in range(7)])
        assert sorted(all_idx.tolist()) == list(range(103))

    def test_dirichlet_covers_all_and_skews(self):
        y = np.repeat(np.arange(10), 100)
        m = non_iid_partition_with_dirichlet_distribution(y, 5, 10, alpha=0.5, seed=3)
        all_idx = np.concatenate([m[i] for i in range(5)])
        assert sorted(all_idx.tolist()) == list(range(1000))
        # alpha=0.5 should produce visibly non-uniform class histograms
        h0 = np.bincount(y[m[0]], minlength=10)
        assert h0.max() > 2 * max(h0.min(), 1) or h0.min() == 0


class TestLoopbackComm:
    def test_two_node_round_trip(self):
        LoopbackHub.reset()

        class Args:
            run_id = "t1"

        got = threading.Event()
        received = {}

        class Server(FedMLCommManager):
            def register_message_receive_handlers(self):
                self.register_message_receive_handler("client_result", self._on)

            def _on(self, msg):
                received["value"] = msg.get("value")
                got.set()
                self.finish()

        server = Server(Args(), rank=0, size=2, backend="LOOPBACK")
        t = server.run_async()
        client = FedMLCommManager(Args(), rank=1, size=2, backend="LOOPBACK")
        msg = Message(type="client_result", sender_id=1, receiver_id=0)
        msg.add_params("value", 42)
        client.send_message(msg)
        assert got.wait(timeout=5)
        t.join(timeout=5)
        assert received["value"] == 42


class TestMultiHostInit:
    def test_coordinator_args_plumb_into_jax_distributed(self, monkeypatch):
        """init() joins the jax.distributed cluster when a coordinator is
        configured (the reference's multi-host NCCL pg init role)."""
        import fedml_tpu
        from fedml_tpu.arguments import Arguments

        calls = {}

        def fake_initialize(coordinator_address=None, num_processes=None,
                            process_id=None):
            calls.update(addr=coordinator_address, n=num_processes, pid=process_id)

        import jax

        monkeypatch.setattr(jax.distributed, "initialize", fake_initialize)
        args = Arguments.from_dict({"common_args": {"random_seed": 0},
                                    "train_args": {}})
        args.jax_coordinator_address = "10.0.0.1:1234"
        args.jax_num_processes = 4
        args.jax_process_id = 2
        fedml_tpu.init(args, should_init_logs=False)
        assert calls == {"addr": "10.0.0.1:1234", "n": 4, "pid": 2}

    def test_no_coordinator_no_distributed_init(self, monkeypatch):
        import fedml_tpu
        from fedml_tpu.arguments import Arguments

        import jax

        def boom(*a, **k):
            raise AssertionError("must not initialize without a coordinator")

        monkeypatch.setattr(jax.distributed, "initialize", boom)
        monkeypatch.delenv("FEDML_JAX_COORDINATOR", raising=False)
        args = Arguments.from_dict({"common_args": {"random_seed": 0},
                                    "train_args": {}})
        fedml_tpu.init(args, should_init_logs=False)
