"""core/schedule: runtime fitting + makespan scheduling (reference
core/schedule/seq_train_scheduler.py + runtime_estimate.py parity)."""

import numpy as np

from fedml_tpu.core.schedule import RuntimeEstimator, SeqTrainScheduler, linear_fit


def test_linear_fit_recovers_line():
    x = np.array([10, 20, 40, 80])
    y = 0.5 * x + 3
    a, b, err = linear_fit(x, y)
    assert abs(a - 0.5) < 1e-9 and abs(b - 3) < 1e-6 and err < 1e-9


def test_linear_fit_degenerate():
    a, b, err = linear_fit([5.0], [2.0])
    assert a == 0.0 and b == 2.0


def test_estimator_predict():
    est = RuntimeEstimator(4)
    assert est.predict(0, 100) is None and not est.has_model()
    for n in (100, 200, 400):
        est.record(0, n, 0.01 * n + 1.0)
    assert abs(est.predict(2, 300) - 4.0) < 1e-6  # uniform devices pool obs
    assert est.fit_error() < 1e-9


def test_schedule_balances_makespan():
    sched = SeqTrainScheduler(4)
    sizes = [100, 100, 100, 100, 1, 1, 1, 1]
    ids, mask, makespan = sched.schedule(list(range(8)), sizes)
    assert ids.shape == (4, 2) and mask.sum() == 8
    loads = (np.vectorize(lambda c: sizes[c])(ids) * mask).sum(1)
    assert loads.max() == 101  # one big + one small per slot is optimal

    # every client appears exactly once
    assert sorted(ids[mask.astype(bool)].tolist()) == list(range(8))


def test_schedule_pads_uneven():
    sched = SeqTrainScheduler(4)
    ids, mask, _ = sched.schedule([7, 9, 11], [5, 6, 7])
    assert ids.shape == (4, 1)
    assert mask.sum() == 3  # one padding slot


def test_schedule_uses_runtime_model():
    # per-client fixed cost dominates -> balanced COUNTS beat balanced samples
    est = RuntimeEstimator(2)
    for n in (10, 1000):
        est.record(0, n, 10.0 + 0.001 * n)  # b=10s, a=1ms/sample
    sched = SeqTrainScheduler(2, estimator=est)
    sizes = [1000, 500, 500, 1, 1, 1]
    ids, mask, makespan = sched.schedule(list(range(6)), sizes)
    counts_per_dev = mask.sum(1)
    assert counts_per_dev.max() == 3  # 3+3 split, not samples-only 1+5
