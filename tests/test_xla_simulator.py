"""Parrot-XLA simulator tests on the 8-device virtual CPU mesh."""

import jax
import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.arguments import Arguments
from fedml_tpu.parallel.mesh import create_fl_mesh
from fedml_tpu.simulation.xla.fed_sim import XLASimulator

pytestmark = pytest.mark.heavy  # long XLA compiles; see pytest.ini


def _args(**over):
    args = Arguments.from_dict(
        {
            "common_args": {"training_type": "simulation", "random_seed": 0, "run_id": "xt"},
            "data_args": {
                "dataset": "mnist",
                "data_cache_dir": "",
                "partition_method": "hetero",
                "partition_alpha": 0.5,
                "synthetic_train_size": 1600,
            },
            "model_args": {"model": "lr"},
            "train_args": {
                "federated_optimizer": "FedAvg",
                "client_num_in_total": 16,
                "client_num_per_round": 8,
                "comm_round": 4,
                "epochs": 1,
                "batch_size": 32,
                "client_optimizer": "sgd",
                "learning_rate": 0.1,
            },
            "validation_args": {"frequency_of_the_test": 2},
            "comm_args": {"backend": "XLA"},
        }
    )
    for k, v in over.items():
        setattr(args, k, v)
    return args.validate()


def _build(args):
    args = fedml_tpu.init(args, should_init_logs=False)
    dataset, out_dim = fedml_tpu.data.load(args)
    model = fedml_tpu.models.create(args, out_dim)
    return args, dataset, model


class TestXLASimulator:
    def test_learns_on_8dev_mesh(self):
        args, dataset, model = _build(_args())
        sim = XLASimulator(args, dataset, model)
        assert sim.n_dev == 8
        metrics = sim.train()
        assert metrics["test_acc"] > 0.5

    def test_uneven_clients_pad_with_dummies(self):
        # 6 clients per round over 8 devices -> 2 dummy slots
        args, dataset, model = _build(_args(client_num_per_round=6, comm_round=2))
        sim = XLASimulator(args, dataset, model)
        metrics = sim.train()
        assert "test_acc" in metrics

    def test_matches_host_aggregation(self):
        """One XLA round == host-side weighted average of per-client results."""
        args, dataset, model = _build(
            _args(client_num_in_total=4, client_num_per_round=4, comm_round=1,
                  partition_method="homo", synthetic_train_size=640)
        )
        mesh = create_fl_mesh(4)
        sim = XLASimulator(args, dataset, model, mesh=mesh)
        w0 = sim.variables

        # replicate the round on the host path using the same engine fn + rngs
        import jax.numpy as jnp

        from fedml_tpu.core.aggregate import weighted_mean
        from fedml_tpu.ml.engine.train import build_local_train, pad_to

        sampled = sim._client_sampling(0)
        ids, real = sim._schedule(sampled)
        counts = np.where(real > 0, np.asarray(sim.client_counts)[ids], 0)
        rng = jax.random.PRNGKey(int(args.random_seed) + 11)
        _, sub = jax.random.split(rng)
        rngs = jax.random.split(jax.random.fold_in(sub, 0), len(ids))

        fn = build_local_train(model, args, int(args.batch_size), sim.padded_n)
        updates = []
        for slot, cid in enumerate(ids):
            if counts[slot] == 0:
                continue
            idx_row = np.asarray(sim.client_idx[cid])
            x = jnp.asarray(np.asarray(sim.x_all)[idx_row])
            y = jnp.asarray(np.asarray(sim.y_all)[idx_row])
            res = fn(w0, x, y, int(counts[slot]), rngs[slot])
            updates.append((float(counts[slot]), res.variables))
        expected = weighted_mean(updates)

        sim.train()
        got = sim.variables
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5),
            expected,
            got,
        )

    def test_throughput_reported(self):
        args, dataset, model = _build(_args(comm_round=3))
        sim = XLASimulator(args, dataset, model)
        sim.train()
        tp = sim.throughput()
        assert tp["rounds_per_sec"] > 0 and tp["samples_per_sec"] > 0


class TestGraftEntry:
    def test_entry_compiles(self):
        import __graft_entry__ as ge

        fn, example_args = ge.entry()
        out = jax.jit(fn)(*example_args)
        assert out.shape == (8, 10)

    def test_dryrun_multichip_8(self):
        import __graft_entry__ as ge

        ge.dryrun_multichip(8)


class TestDeterministicReplay:
    """SURVEY §5 race-detection rebuild note: JAX's functional model replaces
    sanitizers with determinism guarantees — same seed, bitwise-same round
    outputs, for both execution strategies."""

    @pytest.mark.parametrize("pack", [False, True])
    def test_two_runs_bitwise_identical(self, pack):
        outs = []
        for _ in range(2):
            args, dataset, model = _build(_args(comm_round=2, xla_pack=pack))
            sim = XLASimulator(args, dataset, model)
            sim.train()
            outs.append([np.asarray(l) for l in jax.tree_util.tree_leaves(sim.variables)])
        for a, b in zip(*outs):
            np.testing.assert_array_equal(a, b)


class TestInMeshLocalDP:
    """Local DP rides the compiled round: per-client noise before
    aggregation (the mechanism's add_noise is jax-pure), budget accounted
    host-side per participating client."""

    @pytest.mark.parametrize("pack", [False, True])
    def test_ldp_noises_and_accounts(self, pack):
        from fedml_tpu.core.dp.fedml_differential_privacy import (
            FedMLDifferentialPrivacy,
        )

        results = {}
        for enable in (False, True):
            args, dataset, model = _build(_args(comm_round=2, xla_pack=pack))
            args.enable_dp = enable
            args.dp_type = "ldp"
            args.mechanism_type = "gaussian"
            args.epsilon = 50.0
            args.delta = 1e-5
            FedMLDifferentialPrivacy._instance = None
            dp = FedMLDifferentialPrivacy.get_instance()
            dp.init(args)
            sim = XLASimulator(args, dataset, model)
            sim.train()
            results[enable] = [np.asarray(l) for l in
                               jax.tree_util.tree_leaves(sim.variables)]
            if enable:
                # 2 rounds x all sampled clients must be accounted
                assert len(dp.accountant) == 2 * int(args.client_num_per_round)
        # noise changed the trajectory
        diffs = [np.abs(a - b).max() for a, b in zip(results[False], results[True])]
        assert max(diffs) > 1e-6


class TestInMeshDefense:
    """Robust aggregation on the XLA backend: clients train in the compiled
    round, which ships the per-client update stack out; the defender's jnp
    math replaces the weighted mean."""

    def _run(self, defense=None, **dargs):
        from fedml_tpu.core.security.fedml_defender import FedMLDefender

        args, dataset, model = _build(_args(comm_round=2))
        if defense:
            args.enable_defense = True
            args.defense_type = defense
            for k, v in dargs.items():
                setattr(args, k, v)
        FedMLDefender._defender_instance = None
        FedMLDefender.get_instance().init(args)
        sim = XLASimulator(args, dataset, model)
        metrics = sim.train()
        return sim, metrics

    @pytest.mark.parametrize("defense,extra", [
        ("coordinate_wise_median", {}),
        ("krum", {"byzantine_client_num": 1}),
        ("norm_diff_clipping", {"norm_bound": 5.0}),
    ])
    def test_defended_round_learns(self, defense, extra):
        sim, metrics = self._run(defense, **extra)
        assert metrics["test_acc"] > 0.5, (defense, metrics)

    def test_defense_changes_aggregate(self):
        _, clean = self._run(None)
        _, defended = self._run("coordinate_wise_median")
        # median != weighted mean on heterogeneous clients
        assert clean["test_loss"] != defended["test_loss"]

    def test_packed_defense_fails_loud(self):
        from fedml_tpu.core.security.fedml_defender import FedMLDefender

        args, dataset, model = _build(_args(comm_round=1, xla_pack=True))
        args.enable_defense = True
        args.defense_type = "krum"
        args.byzantine_client_num = 1
        FedMLDefender._defender_instance = None
        FedMLDefender.get_instance().init(args)
        with pytest.raises(NotImplementedError, match="padded round"):
            XLASimulator(args, dataset, model)
