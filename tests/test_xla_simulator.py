"""Parrot-XLA simulator tests on the 8-device virtual CPU mesh."""

import jax
import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.arguments import Arguments
from fedml_tpu.parallel.mesh import create_fl_mesh
from fedml_tpu.simulation.xla.fed_sim import XLASimulator

pytestmark = pytest.mark.heavy  # long XLA compiles; see pytest.ini


def _args(**over):
    args = Arguments.from_dict(
        {
            "common_args": {"training_type": "simulation", "random_seed": 0, "run_id": "xt"},
            "data_args": {
                "dataset": "mnist",
                "data_cache_dir": "",
                "partition_method": "hetero",
                "partition_alpha": 0.5,
                "synthetic_train_size": 1600,
            },
            "model_args": {"model": "lr"},
            "train_args": {
                "federated_optimizer": "FedAvg",
                "client_num_in_total": 16,
                "client_num_per_round": 8,
                "comm_round": 4,
                "epochs": 1,
                "batch_size": 32,
                "client_optimizer": "sgd",
                "learning_rate": 0.1,
            },
            "validation_args": {"frequency_of_the_test": 2},
            "comm_args": {"backend": "XLA"},
        }
    )
    for k, v in over.items():
        setattr(args, k, v)
    return args.validate()


def _build(args):
    args = fedml_tpu.init(args, should_init_logs=False)
    dataset, out_dim = fedml_tpu.data.load(args)
    model = fedml_tpu.models.create(args, out_dim)
    return args, dataset, model


class TestXLASimulator:
    def test_learns_on_8dev_mesh(self):
        args, dataset, model = _build(_args())
        sim = XLASimulator(args, dataset, model)
        assert sim.n_dev == 8
        metrics = sim.train()
        assert metrics["test_acc"] > 0.5

    def test_uneven_clients_pad_with_dummies(self):
        # 6 clients per round over 8 devices -> 2 dummy slots
        args, dataset, model = _build(_args(client_num_per_round=6, comm_round=2))
        sim = XLASimulator(args, dataset, model)
        metrics = sim.train()
        assert "test_acc" in metrics

    def test_matches_host_aggregation(self):
        """One XLA round == host-side weighted average of per-client results."""
        args, dataset, model = _build(
            _args(client_num_in_total=4, client_num_per_round=4, comm_round=1,
                  partition_method="homo", synthetic_train_size=640)
        )
        mesh = create_fl_mesh(4)
        sim = XLASimulator(args, dataset, model, mesh=mesh)
        w0 = sim.variables

        # replicate the round on the host path using the same engine fn + rngs
        import jax.numpy as jnp

        from fedml_tpu.core.aggregate import weighted_mean
        from fedml_tpu.ml.engine.train import build_local_train, pad_to

        sampled = sim._client_sampling(0)
        ids, real = sim._schedule(sampled)
        counts = np.where(real > 0, np.asarray(sim.client_counts)[ids], 0)
        rng = jax.random.PRNGKey(int(args.random_seed) + 11)
        _, sub = jax.random.split(rng)
        rngs = jax.random.split(jax.random.fold_in(sub, 0), len(ids))

        fn = build_local_train(model, args, int(args.batch_size), sim.padded_n)
        updates = []
        for slot, cid in enumerate(ids):
            if counts[slot] == 0:
                continue
            idx_row = np.asarray(sim.client_idx[cid])
            x = jnp.asarray(np.asarray(sim.x_all)[idx_row])
            y = jnp.asarray(np.asarray(sim.y_all)[idx_row])
            res = fn(w0, x, y, int(counts[slot]), rngs[slot])
            updates.append((float(counts[slot]), res.variables))
        expected = weighted_mean(updates)

        sim.train()
        got = sim.variables
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5),
            expected,
            got,
        )

    def test_throughput_reported(self):
        args, dataset, model = _build(_args(comm_round=3))
        sim = XLASimulator(args, dataset, model)
        sim.train()
        tp = sim.throughput()
        assert tp["rounds_per_sec"] > 0 and tp["samples_per_sec"] > 0


class TestGraftEntry:
    def test_entry_compiles(self):
        import __graft_entry__ as ge

        fn, example_args = ge.entry()
        out = jax.jit(fn)(*example_args)
        assert out.shape == (8, 10)

    def test_dryrun_multichip_8(self):
        import __graft_entry__ as ge

        ge.dryrun_multichip(8)


class TestDeterministicReplay:
    """SURVEY §5 race-detection rebuild note: JAX's functional model replaces
    sanitizers with determinism guarantees — same seed, bitwise-same round
    outputs, for both execution strategies."""

    @pytest.mark.parametrize("pack", [False, True])
    def test_two_runs_bitwise_identical(self, pack):
        outs = []
        for _ in range(2):
            args, dataset, model = _build(_args(comm_round=2, xla_pack=pack))
            sim = XLASimulator(args, dataset, model)
            sim.train()
            outs.append([np.asarray(l) for l in jax.tree_util.tree_leaves(sim.variables)])
        for a, b in zip(*outs):
            np.testing.assert_array_equal(a, b)


class TestInMeshLocalDP:
    """Local DP rides the compiled round: per-client noise before
    aggregation (the mechanism's add_noise is jax-pure), budget accounted
    host-side per participating client."""

    @pytest.mark.parametrize("pack", [False, True])
    def test_ldp_noises_and_accounts(self, pack):
        from fedml_tpu.core.dp.fedml_differential_privacy import (
            FedMLDifferentialPrivacy,
        )

        results = {}
        for enable in (False, True):
            args, dataset, model = _build(_args(comm_round=2, xla_pack=pack))
            args.enable_dp = enable
            args.dp_type = "ldp"
            args.mechanism_type = "gaussian"
            args.epsilon = 50.0
            args.delta = 1e-5
            FedMLDifferentialPrivacy._instance = None
            dp = FedMLDifferentialPrivacy.get_instance()
            dp.init(args)
            sim = XLASimulator(args, dataset, model)
            sim.train()
            results[enable] = [np.asarray(l) for l in
                               jax.tree_util.tree_leaves(sim.variables)]
            if enable:
                # 2 rounds x all sampled clients must be accounted
                assert len(dp.accountant) == 2 * int(args.client_num_per_round)
        # noise changed the trajectory
        diffs = [np.abs(a - b).max() for a, b in zip(results[False], results[True])]
        assert max(diffs) > 1e-6


def _reset_security():
    from fedml_tpu.core.security.fedml_attacker import FedMLAttacker
    from fedml_tpu.core.security.fedml_defender import FedMLDefender

    FedMLAttacker._attacker_instance = None
    FedMLDefender._defender_instance = None
    return FedMLAttacker.get_instance(), FedMLDefender.get_instance()


def _run_security(attack=None, defense=None, pack=False, comm_round=2, **extra):
    """One XLA run with the given attack/defense config; returns (sim, metrics)."""
    args, dataset, model = _build(_args(comm_round=comm_round, xla_pack=pack))
    for k, v in extra.items():
        setattr(args, k, v)
    if attack:
        args.enable_attack = True
        args.attack_type = attack
    if defense:
        args.enable_defense = True
        args.defense_type = defense
    attacker, defender = _reset_security()
    try:
        attacker.init(args)
        defender.init(args)
        sim = XLASimulator(args, dataset, model)
        metrics = sim.train()
    finally:
        _reset_security()  # even on expected raises: singletons are global
    return sim, metrics


class TestInMeshDefense:
    """Robust aggregation on the XLA backend: the compiled round returns the
    sharded per-client update stack; a second jitted program substitutes the
    robust aggregate (core/security/stacked.py) — both execution strategies,
    every aggregates_via_acc algorithm."""

    @pytest.mark.parametrize("defense,extra", [
        ("coordinate_wise_median", {}),
        ("krum", {"byzantine_client_num": 1}),
        ("norm_diff_clipping", {"norm_bound": 5.0}),
    ])
    def test_defended_round_learns(self, defense, extra):
        sim, metrics = _run_security(defense=defense, **extra)
        assert metrics["test_acc"] > 0.5, (defense, metrics)

    def test_defense_changes_aggregate(self):
        _, clean = _run_security()
        _, defended = _run_security(defense="coordinate_wise_median")
        # median != weighted mean on heterogeneous clients
        assert clean["test_loss"] != defended["test_loss"]

    @pytest.mark.parametrize("defense,extra", [
        ("krum", {"byzantine_client_num": 1}),
        ("geometric_median", {}),
    ])
    def test_packed_defended_round_learns(self, defense, extra):
        sim, metrics = _run_security(defense=defense, pack=True, **extra)
        assert metrics["test_acc"] > 0.5, (defense, metrics)

    @pytest.mark.parametrize("pack", [False, True])
    def test_defense_composes_with_scaffold(self, pack):
        _, metrics = _run_security(
            defense="coordinate_wise_median", pack=pack,
            federated_optimizer="SCAFFOLD",
        )
        assert metrics["test_acc"] > 0.5, metrics

    @pytest.mark.parametrize("optimizer", ["FedNova", "async_fedavg"])
    @pytest.mark.parametrize("defense,extra", [
        ("krum", {"byzantine_client_num": 1}),          # before: selection
        ("coordinate_wise_median", {}),                 # on: aggregate-replacing
        # on: trust-reweighting — rows mode must broadcast its aggregate
        # (normalized trust weights would collapse async's relative factor)
        ("foolsgold", {}),
    ])
    def test_ext_aggregators_compose_with_defense(self, optimizer, defense, extra):
        """FedNova/async aggregate through ext, not the weighted acc — the
        security tail recomputes their per-client contributions from the
        defended row space (ext_from_rows; sp composition for before-
        defenses, consensus-row semantics for aggregate-replacers)."""
        _, metrics = _run_security(
            defense=defense, federated_optimizer=optimizer, **extra
        )
        assert metrics["test_acc"] > 0.5, (optimizer, defense, metrics)

    @pytest.mark.parametrize("optimizer,defense,extra", [
        ("FedOpt", "norm_diff_clipping", {"norm_bound": 5.0}),
        ("FedNova", "krum", {"byzantine_client_num": 1}),
    ])
    def test_sharded_state_composes_with_defense_bitwise(
            self, optimizer, defense, extra):
        """The defended + model-sharded composition (the old fed_sim gate
        silently degraded sharded_state to replicated whenever the security
        tail was active): the security program now ends at the psum'd
        accumulator and the model-sharded GSPMD tail applies the server
        step — and the run is BITWISE the replicated defended run, for
        both the via-acc and the rows (ext2) security branches."""
        knobs = dict(defense=defense, federated_optimizer=optimizer,
                     server_optimizer="adam", **extra)
        sim_r, m_r = _run_security(**knobs)
        sim_s, m_s = _run_security(server_state="sharded", **knobs)
        assert sim_s.sharded_state and not sim_r.sharded_state
        for a, b in zip(jax.tree_util.tree_leaves(sim_r.variables),
                        jax.tree_util.tree_leaves(sim_s.variables)):
            assert np.asarray(a).tobytes() == np.asarray(b).tobytes()
        assert m_r["test_acc"] == m_s["test_acc"]

    def test_fednova_byzantine_degrades_and_krum_recovers(self):
        _, clean = _run_security(comm_round=3, federated_optimizer="FedNova")
        _, attacked = _run_security(
            attack="byzantine", comm_round=3, federated_optimizer="FedNova",
            attack_mode="random", byzantine_client_num=8,
        )
        _, defended = _run_security(
            attack="byzantine", defense="krum", comm_round=3,
            federated_optimizer="FedNova",
            attack_mode="random", byzantine_client_num=8,
        )
        assert attacked["test_acc"] < clean["test_acc"] - 0.1, (clean, attacked)
        assert defended["test_acc"] > attacked["test_acc"] + 0.1, (attacked, defended)


class TestDefenseStateCheckpoint:
    def test_foolsgold_history_survives_resume(self, tmp_path):
        """Cross-round defense state (foolsgold similarity history) must ride
        the checkpoint: a resumed run that re-zeroed it would silently
        re-pardon already-attenuated sybils."""
        from fedml_tpu.core.security.fedml_defender import FedMLDefender

        def build(rounds):
            args, dataset, model = _build(_args(
                comm_round=rounds, client_num_per_round=16,
                client_num_in_total=16,  # full participation: stable slots
            ))
            args.enable_defense = True
            args.defense_type = "foolsgold"
            args.checkpoint_dir = str(tmp_path / "ckpt")
            FedMLDefender._defender_instance = None
            FedMLDefender.get_instance().init(args)
            return XLASimulator(args, dataset, model)

        try:
            sim = build(2)
            sim.train()
            hist_before = np.asarray(sim._defense_state["fg_hist"])
            assert np.abs(hist_before).sum() > 0
            # resume into a fresh simulator: state must come back from disk
            sim2 = build(3)
            sim2.train()  # restores round 0-1, runs round 2
            assert sim2._defense_n == 16
            hist_after = np.asarray(sim2._defense_state["fg_hist"])
            # history kept accumulating from the restored value, not from zero
            assert np.abs(hist_after).sum() > np.abs(hist_before).sum()
        finally:
            FedMLDefender._defender_instance = None


class TestInMeshAttack:
    """The sp security matrix reproduced on the XLA backend: data poisoning
    stamps at pack time, model attacks run in the stacked security program
    (reference fedml_attacker.py:28-30 — one simulator runs the whole
    matrix)."""

    @pytest.mark.parametrize("pack", [False, True])
    def test_byzantine_degrades_and_krum_recovers(self, pack):
        _, clean = _run_security(pack=pack, comm_round=3)
        _, attacked = _run_security(
            attack="byzantine", pack=pack, comm_round=3,
            attack_mode="random", byzantine_client_num=8,
        )
        _, defended = _run_security(
            attack="byzantine", defense="krum", pack=pack, comm_round=3,
            attack_mode="random", byzantine_client_num=8,
        )
        # 8/16 random-garbage clients wreck plain FedAvg; krum survives
        assert attacked["test_acc"] < clean["test_acc"] - 0.1, (clean, attacked)
        assert defended["test_acc"] > attacked["test_acc"] + 0.1, (attacked, defended)

    def test_label_flip_poisons_pack(self):
        sim, _ = _run_security(
            attack="label_flipping", comm_round=1,
            original_class=1, target_class=7, byzantine_client_num=16,
        )
        clean_sim, _ = _run_security(comm_round=1)
        # every client malicious: no label-1 row survives in the packed data
        assert not bool((np.asarray(sim.y_all) == 1).any())
        assert bool((np.asarray(clean_sim.y_all) == 1).any())

    def test_model_replacement_mitigated_by_clipping(self):
        """The scaled push drags the aggregate away from the clean trajectory;
        norm clipping pulls it back (parameter-space distances — the LR task
        is too easy for accuracy to separate the runs)."""
        def _vec(sim):
            from jax.flatten_util import ravel_pytree

            return np.asarray(ravel_pytree(sim.variables)[0])

        clean_sim, _ = _run_security(comm_round=2)
        atk_sim, _ = _run_security(
            attack="model_replacement", comm_round=2,
            attack_scale=25.0, byzantine_client_num=4,
        )
        def_sim, _ = _run_security(
            attack="model_replacement", defense="norm_diff_clipping",
            comm_round=2, attack_scale=25.0, byzantine_client_num=4,
            norm_bound=0.5,
        )
        d_atk = np.linalg.norm(_vec(atk_sim) - _vec(clean_sim))
        d_def = np.linalg.norm(_vec(def_sim) - _vec(clean_sim))
        assert d_atk > 2.0 * d_def, (d_atk, d_def)

    def test_dlg_reconstruction_runs_in_round(self):
        args, dataset, model = _build(_args(comm_round=1))
        args.enable_attack = True
        args.attack_type = "dlg"
        args.dlg_steps = 20
        attacker, _ = _reset_security()
        attacker.init(args)
        sim = XLASimulator(args, dataset, model)
        sim.train()
        x_rec, y_soft = attacker.last_reconstruction
        assert np.all(np.isfinite(np.asarray(x_rec)))
        assert x_rec.shape[1:] == sim.x_all.shape[1:]
        _reset_security()

    def test_invert_gradient_reconstruction_runs_in_round(self):
        """The second analysis primitive (cosine matching + TV prior,
        reference invert_gradient_attack.py) runs in-mesh off the same
        intercepted-update stack dlg uses."""
        args, dataset, model = _build(_args(comm_round=1))
        args.enable_attack = True
        args.attack_type = "invert_gradient"
        args.dlg_steps = 20
        attacker, _ = _reset_security()
        attacker.init(args)
        sim = XLASimulator(args, dataset, model)
        sim.train()
        x_rec, _ = attacker.last_reconstruction
        assert np.all(np.isfinite(np.asarray(x_rec)))
        assert x_rec.shape[1:] == sim.x_all.shape[1:]
        _reset_security()

    def test_revealing_labels_reveals_victim_classes(self):
        """iDLG bias-sign revelation on the intercepted in-mesh update: the
        classes flagged present must actually appear in the victim client's
        local label set."""
        args, dataset, model = _build(_args(comm_round=1))
        args.enable_attack = True
        args.attack_type = "revealing_labels_from_gradients"
        attacker, _ = _reset_security()
        attacker.init(args)
        sim = XLASimulator(args, dataset, model)
        sim.train()
        order, present = attacker.last_revealed_labels
        assert present.shape == (sim.class_num,)
        # the round's victim: first malicious client in schedule order, else
        # the first real slot (mirrors the train() victim pick)
        sampled = sim._client_sampling(0)
        ids, real = sim._schedule(sampled)
        counts = np.where(real > 0, np.asarray(sim.client_counts)[ids], 0)
        real_sel = np.where(counts > 0)[0]
        bad = set(attacker.get_byzantine_idxs(sim.num_clients))
        victims = [int(i) for i in real_sel if int(ids[i]) in bad] or [int(real_sel[0])]
        vid = int(ids[victims[0]])
        vrows = np.asarray(sim._client_rows[vid])[: sim.local_num_dict[vid]]
        vlabels = set(np.asarray(sim.y_all)[vrows].tolist())
        # top-ranked class is one the victim actually holds
        assert int(np.asarray(order)[0]) in vlabels, (vlabels, np.asarray(order)[:3])
        _reset_security()
