"""Seq2seq, link-prediction (ego + bipartite recsys), multi-task molecule,
and SpreadGNN task families (reference app/fednlp/seq2seq,
app/fedgraphnn/{ego_networks_link_pred,recsys_subgraph_link_pred},
research/SpreadGNN)."""

import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.arguments import Arguments

pytestmark = pytest.mark.heavy  # transformer/GCN XLA compiles


def _cfg(dataset, model, **over):
    d = {
        "common_args": {"training_type": "simulation", "random_seed": 0,
                        "run_id": f"task-{dataset}"},
        "data_args": {"dataset": dataset, "data_cache_dir": "",
                      "partition_method": "homo", "synthetic_train_size": 512},
        "model_args": {"model": model},
        "train_args": {"federated_optimizer": "FedAvg", "client_num_in_total": 4,
                       "client_num_per_round": 4, "comm_round": 3, "epochs": 1,
                       "batch_size": 32, "client_optimizer": "adam",
                       "learning_rate": 0.002},
        "validation_args": {"frequency_of_the_test": 2},
        "comm_args": {"backend": "sp"},
    }
    args = Arguments.from_dict(d)
    for k, v in over.items():
        setattr(args, k, v)
    return args.validate()


def _run(args):
    args = fedml_tpu.init(args, should_init_logs=False)
    device = fedml_tpu.device.get_device(args)
    dataset, out_dim = fedml_tpu.data.load(args)
    model = fedml_tpu.models.create(args, out_dim)
    from fedml_tpu.simulation.simulator import create_simulator

    return create_simulator(args, device, dataset, model).run()


class TestSeq2Seq:
    def test_corpus_shape(self):
        from fedml_tpu.data.synthetic import make_seq2seq

        x, y = make_seq2seq(16, 8, 8, 32, seed=0)
        assert x.shape == (16, 16) and y.shape == (16, 16)
        assert (y[:, :8] == -1).all()  # source positions unlabeled
        assert (y[:, 8:] >= 2).all()   # targets are real tokens
        # teacher forcing: input after SEP is the shifted target
        assert (x[:, 8] == 1).all()
        assert (x[:, 9:] == y[:, 8:-1]).all()

    def test_learns_successor_copy(self):
        metrics = _run(_cfg("synthetic_s2s", "transformer_s2s", comm_round=4,
                            epochs=3, learning_rate=0.01,
                            synthetic_train_size=2048))
        # masked token accuracy: well above 1/62 chance on held-out sequences
        assert metrics["test_acc"] > 0.5, metrics


class TestLinkPrediction:
    def test_labels_balanced_and_disjoint(self):
        from fedml_tpu.data.synthetic import make_link_prediction

        x, y = make_link_prediction(8, 16, 8, seed=0)
        assert x.shape == (8, 16, 24) and y.shape == (8, 16, 16)
        pos, neg = (y == 1).sum(), (y == 0).sum()
        assert pos > 0 and neg > 0
        # held-out positives are NOT in the observed adjacency
        adj = x[..., 8:]
        assert (adj[y == 1] == 0).all()

    def test_learns_links(self):
        metrics = _run(_cfg("ego_linkpred", "gcn_linkpred", comm_round=4,
                            epochs=3, learning_rate=0.01))
        assert metrics["test_acc"] > 0.62, metrics  # balanced pairs: 0.5 chance

    def test_learns_bipartite_recsys(self):
        metrics = _run(_cfg("recsys_linkpred", "gcn_linkpred", comm_round=4,
                            epochs=3, learning_rate=0.01))
        assert metrics["test_acc"] > 0.62, metrics


class TestMultiTask:
    def test_partial_labels(self):
        from fedml_tpu.data.synthetic import make_multitask_graphs

        x, y = make_multitask_graphs(32, 16, 8, 8, seed=0)
        assert y.shape == (32, 8)
        frac = (y >= 0).mean()
        assert 0.5 < frac < 0.9  # partial observation
        assert set(np.unique(y)) <= {-1.0, 0.0, 1.0}

    def test_learns_multitask(self):
        metrics = _run(_cfg("moleculenet_mtl", "gcn_mtl", comm_round=4,
                            epochs=3, learning_rate=0.01))
        assert metrics["test_acc"] > 0.62, metrics  # per-task binary, 0.5 chance


class TestSpreadGNN:
    def test_decentralized_multitask(self):
        args = _cfg("moleculenet_mtl", "gcn_mtl", comm_round=3, epochs=2,
                    learning_rate=0.01, topology_neighbor_num=2)
        args.federated_optimizer = "SpreadGNN"
        args.client_num_in_total = args.client_num_per_round = 4
        metrics = _run(args)
        assert metrics["test_acc"] > 0.55, metrics

    def test_heads_stay_local_encoder_mixes(self):
        import jax
        import jax.numpy as jnp

        from fedml_tpu.simulation.sp.spreadgnn.spreadgnn_api import SpreadGNNAPI

        args = _cfg("moleculenet_mtl", "gcn_mtl", comm_round=1, epochs=1,
                    synthetic_train_size=128, topology_neighbor_num=2)
        args.federated_optimizer = "SpreadGNN"
        args.client_num_in_total = args.client_num_per_round = 4
        args = fedml_tpu.init(args, should_init_logs=False)
        device = fedml_tpu.device.get_device(args)
        dataset, out_dim = fedml_tpu.data.load(args)
        model = fedml_tpu.models.create(args, out_dim)
        api = SpreadGNNAPI(args, device, dataset, model)

        # distinct per-node models: head leaf i = i, encoder leaf i = i
        def make_node(i):
            return jax.tree_util.tree_map(
                lambda x: jnp.full_like(x, float(i)), api.w_global
            )

        stacked = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs, 0), *[make_node(i) for i in range(4)]
        )
        mixed = api._gossip(stacked, api.mix)
        flat = jax.tree_util.tree_flatten_with_path(mixed)[0]
        saw_head = saw_enc = False
        for path, leaf in flat:
            keys = {getattr(k, "key", getattr(k, "name", None)) for k in path}
            if "readout" in keys:
                saw_head = True  # untouched: node i keeps value i
                for i in range(4):
                    assert float(leaf[i].ravel()[0]) == float(i)
            else:
                saw_enc = True  # mixed: neighbor average != own value
                mixed_vals = [float(leaf[i].ravel()[0]) for i in range(4)]
                assert mixed_vals != [0.0, 1.0, 2.0, 3.0]
        assert saw_head and saw_enc


class TestIoTAnomaly:
    def test_benign_manifold_and_flags(self):
        from fedml_tpu.data.synthetic import make_iot_traffic

        x, flags = make_iot_traffic(256, 24, seed=0, anomaly_frac=0.1)
        assert x.shape == (256, 24)
        assert 20 <= flags.sum() <= 40
        xb, fb = make_iot_traffic(256, 24, seed=1, anomaly_frac=0.0)
        assert fb.sum() == 0

    def test_autoencoder_detects_anomalies(self):
        metrics = _run(_cfg("iot_anomaly", "autoencoder", comm_round=4,
                            epochs=3, learning_rate=0.01,
                            synthetic_train_size=2048))
        # benign reconstructs, anomalies don't: both overall accuracy and
        # recall on the anomalous tail must beat guessing
        assert metrics["test_acc"] > 0.85, metrics
        assert metrics["test_anomaly_recall"] > 0.7, metrics


class TestGraphNodeClf:
    def test_learns_node_communities(self):
        metrics = _run(_cfg("ego_nodeclf", "gcn_nodeclf", comm_round=4,
                            epochs=3, learning_rate=0.01))
        # per-node accuracy above 1/3 chance (community structure + features)
        assert metrics["test_acc"] > 0.6, metrics


class TestGraphRegression:
    def test_learns_property(self):
        metrics = _run(_cfg("freesolv", "gcn_reg", comm_round=4, epochs=3,
                            learning_rate=0.01,
                            partition_method="hetero"))
        # RMSE well below the target's std (signal = w.mean_feats + density)
        assert metrics["test_rmse"] < 0.6, metrics


class TestTasksOnXLABackend:
    """Task-specific losses now ride the compiled in-mesh round: the loss
    key is plumbed into both engines and eval goes through the task-aware
    aggregator (previously fail-loud -> sp only)."""

    @pytest.mark.parametrize("dataset,model,gate,extra", [
        ("synthetic_det", "tiny_detector", 0.5, {}),
        ("ego_linkpred", "gcn_linkpred", 0.62, {}),
        ("iot_anomaly", "autoencoder", 0.85, {}),
        ("synthetic_s2s", "transformer_s2s", 0.5, {"synthetic_train_size": 2048}),
    ])
    @pytest.mark.parametrize("pack", [False, True])
    def test_task_learns_in_mesh(self, dataset, model, gate, extra, pack):
        args = _cfg(dataset, model, comm_round=4, epochs=3, learning_rate=0.01,
                    **extra)
        args.backend = "XLA"
        args.xla_pack = pack
        metrics = _run(args)
        assert metrics["test_acc"] > gate, (dataset, pack, metrics)

    def test_tag_prediction_in_mesh(self):
        """Int class ids are one-hot'd host-side at pack time so the bce
        loss (and tag eval probe) run in the compiled round."""
        args = _cfg("stackoverflow_lr", "lr", comm_round=6, epochs=3,
                    learning_rate=0.1, synthetic_train_size=1024)
        args.backend = "XLA"
        metrics = _run(args)
        # per-label-position accuracy; multi-hot is sparse so the floor is
        # high — require real learning via the F1 extra
        assert metrics["test_f1"] > 0.3, metrics
