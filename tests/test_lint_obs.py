"""tools/lint_obs.py wired into tier-1: with the unified metrics registry
and the obs facade in place, library code must not grow new bare counter
bags (``defaultdict(int)``) or bypass the mlops seam with direct
``<sink>.emit(...)`` calls — and the linter itself must actually catch
violations, because a lint that can't fail is not a gate."""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))

import lint_obs


def test_library_tree_is_clean():
    """The machine-enforced contract: every fedml_tpu/ counter reaches the
    registry and every record rides the sink fan."""
    assert lint_obs.main([]) == 0


def test_catches_counter_bag_and_direct_emit(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "from collections import defaultdict\n"
        "class Stats:\n"
        "    def __init__(self, sink):\n"
        "        self.counts = defaultdict(int)\n"
        "        self.sink = sink\n"
        "    def flush(self):\n"
        "        self.sink.emit('stats', dict(self.counts))\n"
    )
    violations = lint_obs.lint_file(str(bad))
    assert [(lineno, kind) for _, lineno, kind, _ in violations] == [
        (4, "bare counter bag"),
        (7, "direct sink emit"),
    ]
    assert lint_obs.main(["--root", str(tmp_path)]) == 1


def test_fan_alias_is_covered(tmp_path):
    f = tmp_path / "alias.py"
    f.write_text(
        "def ship(fan, mem_sink, record):\n"
        "    fan.emit('x', record)\n"
        "    mem_sink.emit('x', record)\n"
    )
    assert len(lint_obs.lint_file(str(f))) == 2


def test_pragma_allows_approved_seam(tmp_path):
    f = tmp_path / "seam.py"
    f.write_text(
        "from collections import defaultdict\n"
        "counts = defaultdict(int)  # lint_obs: allow\n"
    )
    assert lint_obs.lint_file(str(f)) == []
    assert lint_obs.main(["--root", str(tmp_path)]) == 0


def test_obs_and_mlops_layers_are_exempt(tmp_path):
    # the two layers that ARE the seam may touch sinks/registries freely
    for part in (("core", "obs"), ("core", "mlops")):
        d = tmp_path.joinpath(*part)
        d.mkdir(parents=True)
        f = d / "impl.py"
        f.write_text("def flush(self):\n    self.sink.emit('x', {})\n")
        assert lint_obs.lint_file(str(f)) == []
    assert lint_obs.main(["--root", str(tmp_path)]) == 0


def test_docstrings_and_comments_do_not_false_positive(tmp_path):
    f = tmp_path / "prose.py"
    f.write_text(
        '"""Never call sink.emit(...) directly; defaultdict(int) is banned."""\n'
        "# the old code kept a defaultdict(int) and called fan.emit() here\n"
        "MSG = 'route counters through obs, not sink.emit(topic, rec)'\n"
    )
    assert lint_obs.lint_file(str(f)) == []


def test_registry_and_facade_calls_are_not_flagged(tmp_path):
    f = tmp_path / "good.py"
    f.write_text(
        "from fedml_tpu.core import obs\n"
        "def record(n):\n"
        "    obs.counter_inc('comm.retransmits', n, {'node': 0})\n"
        "    obs.histogram_observe('round.seconds', 0.5)\n"
    )
    assert lint_obs.lint_file(str(f)) == []


def test_catches_printed_metric_json(tmp_path):
    # stdout JSON emission is the bench driver's contract line and nobody
    # else's — a library print(json.dumps(...)) races the exactly-one-
    # metric-line guarantee
    f = tmp_path / "printer.py"
    f.write_text(
        "import json\n"
        "def report(stats):\n"
        "    print(json.dumps({'metric': 'x', 'value': stats}))\n"
        "    blob = json.dumps(stats)\n"          # dumps alone is fine
        "    print('round done')\n"               # print alone is fine
    )
    violations = lint_obs.lint_file(str(f))
    assert [(lineno, kind) for _, lineno, kind, _ in violations] == [
        (3, "printed metric json"),
    ]


def test_catches_direct_registry_render(tmp_path):
    # exposition belongs to the exporter inside core/obs — a stray
    # render_openmetrics() call forks the export seam
    f = tmp_path / "renderer.py"
    f.write_text(
        "from fedml_tpu.core.obs.exposition import render_openmetrics\n"
        "def scrape(reg):\n"
        "    return render_openmetrics(reg)\n"
    )
    violations = lint_obs.lint_file(str(f))
    kinds = [kind for _, _, kind, _ in violations]
    assert kinds == ["direct registry render"]


def test_catches_telemetry_wire_key_outside_seam(tmp_path):
    # the piggybacked blob rides messages under ONE param key owned by
    # core/obs/telemetry.py — any other module spelling it builds or reads
    # telemetry params off-seam, dodging the seq/dedup protocol
    f = tmp_path / "manager.py"
    f.write_text(
        "def upload(msg, blob):\n"
        "    msg.add_params('__obs_telemetry__', blob)\n"
    )
    violations = lint_obs.lint_file(str(f))
    assert [(lineno, kind) for _, lineno, kind, _ in violations] == [
        (2, "telemetry wire key"),
    ]
    assert lint_obs.main(["--root", str(tmp_path)]) == 1


def test_telemetry_wire_key_seam_and_pragma(tmp_path):
    # the owning module spells the key freely...
    d = tmp_path / "core" / "obs"
    d.mkdir(parents=True)
    seam = d / "telemetry.py"
    seam.write_text("TELEMETRY_KEY = '__obs_telemetry__'\n")
    assert lint_obs.lint_file(str(seam)) == []
    # ...but the rule pierces the core/obs blanket exemption: a SIBLING
    # module in the exempt layer is still flagged
    sibling = d / "helpers.py"
    sibling.write_text("KEY = '__obs_telemetry__'\n")
    kinds = [kind for _, _, kind, _ in lint_obs.lint_file(str(sibling))]
    assert kinds == ["telemetry wire key"]
    # and the pragma still grants an approved exception
    allowed = tmp_path / "approved.py"
    allowed.write_text("KEY = '__obs_telemetry__'  # lint_obs: allow\n")
    assert lint_obs.lint_file(str(allowed)) == []


def test_exposition_rules_respect_pragma_and_exemption(tmp_path):
    allowed = tmp_path / "allowed.py"
    allowed.write_text(
        "import json\n"
        "print(json.dumps({'v': 1}))  # lint_obs: allow\n"
        "body = render_openmetrics(reg)  # lint_obs: allow\n"
    )
    assert lint_obs.lint_file(str(allowed)) == []
    # core/obs itself (the exporter) renders freely
    d = tmp_path / "core" / "obs"
    d.mkdir(parents=True)
    f = d / "exposition.py"
    f.write_text("def snapshot(reg):\n    return render_openmetrics(reg)\n")
    assert lint_obs.lint_file(str(f)) == []
