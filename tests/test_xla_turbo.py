"""In-mesh Turbo-Aggregate (simulation/xla/turbo.py): training + the
multi-group masked-ring aggregation compile into one XLA program; gated by
exact equivalence against the sp twin (the telescoping masks cancel, so the
round output must equal the sp protocol's)."""

import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.arguments import Arguments
from fedml_tpu.parallel.mesh import create_fl_mesh

pytestmark = pytest.mark.heavy


def _args(**over):
    base = {
        "common_args": {"training_type": "simulation", "random_seed": 0, "run_id": "ta"},
        "data_args": {
            "dataset": "mnist",
            "data_cache_dir": "",
            # homo => identical padded shapes on both backends (the
            # exact-equality precondition)
            "partition_method": "homo",
            "synthetic_train_size": 512,
        },
        "model_args": {"model": "lr"},
        "train_args": {
            "federated_optimizer": "turbo_aggregate",
            "client_num_in_total": 8,
            "client_num_per_round": 8,
            "comm_round": 3,
            "epochs": 1,
            "batch_size": 16,
            "client_optimizer": "sgd",
            "learning_rate": 0.1,
            "ta_group_num": 3,
        },
        "validation_args": {"frequency_of_the_test": 1},
        "comm_args": {"backend": "XLA"},
    }
    args = Arguments.from_dict(base)
    for k, v in over.items():
        setattr(args, k, v)
    return args.validate()


def _build(**over):
    args = fedml_tpu.init(_args(**over), should_init_logs=False)
    dataset, out_dim = fedml_tpu.data.load(args)
    model = fedml_tpu.models.create(args, out_dim)
    return args, dataset, model


class TestTurboInMesh:
    def test_matches_sp_twin(self):
        """Same sampling, grouping-by-position, per-(round, client) keys,
        and engine; the ring masks cancel — the compiled protocol must land
        on the sp twin's global model (small fp slack: mask add/subtract
        cancellation)."""
        import jax

        from fedml_tpu.simulation.sp.turboaggregate.ta_api import TurboAggregateAPI
        from fedml_tpu.simulation.xla.turbo import TurboAggregateInMeshAPI

        args, dataset, model = _build()
        sp = TurboAggregateAPI(args, None, dataset, model)
        sp.train()

        args2, dataset2, model2 = _build()
        api = TurboAggregateInMeshAPI(args2, None, dataset2, model2,
                                      mesh=create_fl_mesh(4))
        api.train()

        for a, b in zip(
            jax.tree_util.tree_leaves(api.variables),
            jax.tree_util.tree_leaves(sp.w_global),
        ):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    def test_masks_cancel_to_weighted_mean(self):
        """The protocol must be transparent: identical final model to plain
        sp FedAvg on the same config (same trainer key chain; the masks
        telescope to zero, leaving the weighted mean)."""
        import jax

        from fedml_tpu.simulation.sp.fedavg.fedavg_api import FedAvgAPI
        from fedml_tpu.simulation.xla.turbo import TurboAggregateInMeshAPI

        args, dataset, model = _build(comm_round=2)
        api = TurboAggregateInMeshAPI(args, None, dataset, model,
                                      mesh=create_fl_mesh(4))
        api.train()

        args2, dataset2, model2 = _build(comm_round=2,
                                         federated_optimizer="FedAvg")
        sp = FedAvgAPI(args2, None, dataset2, model2)
        sp.train()

        for a, b in zip(
            jax.tree_util.tree_leaves(api.variables),
            jax.tree_util.tree_leaves(sp.w_global),
        ):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    def test_runner_dispatch(self):
        from fedml_tpu.simulation.simulator import SimulatorXLA
        from fedml_tpu.simulation.xla.turbo import TurboAggregateInMeshAPI

        args, dataset, model = _build()
        sim = SimulatorXLA(args, None, dataset, model)
        assert isinstance(sim.sim, TurboAggregateInMeshAPI)

    def test_padded_slots_with_unsampled_client_zero(self):
        """cpr < total and not a multiple of the mesh: padding slots carry
        id 0 even when client 0 was not sampled — they must stay inert, not
        KeyError (regression)."""
        from fedml_tpu.simulation.xla.turbo import TurboAggregateInMeshAPI

        args, dataset, model = _build(client_num_in_total=16,
                                      client_num_per_round=10, comm_round=3)
        api = TurboAggregateInMeshAPI(args, None, dataset, model,
                                      mesh=create_fl_mesh(4))
        out = api.train()
        assert out["test_acc"] > 0.8

    def test_ta_args_section_flattens(self):
        """The example's ta_args section must land on args (an unlisted
        section would silently fall back to the in-code default)."""
        import os

        import yaml

        cfg = os.path.join(os.path.dirname(__file__), os.pardir, "examples",
                           "simulation", "xla_turbo_aggregate_mnist_lr",
                           "fedml_config.yaml")
        with open(cfg) as f:
            args = Arguments.from_dict(yaml.safe_load(f))
        assert args.ta_group_num == 2
        assert not isinstance(getattr(args, "ta_args", None), dict) or "ta_group_num" not in args.ta_args
