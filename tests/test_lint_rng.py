"""tools/lint_rng.py wired into tier-1: the library tree must stay free of
global-NumPy-RNG use (the reproducibility contract behind every selection
policy's round-seeded local generator), and the linter itself must actually
catch violations — a lint that can't fail is not a gate."""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))

import lint_rng


def test_library_tree_is_clean():
    """The machine-enforced contract: fedml_tpu/ has no global-RNG draws
    outside the one pragma-marked run-entry seam."""
    assert lint_rng.main([]) == 0


def test_catches_a_violation(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import numpy as np\n"
        "def sample(n, k):\n"
        "    np.random.seed(0)\n"
        "    return np.random.choice(n, k, replace=False)\n"
    )
    violations = lint_rng.lint_file(str(bad))
    assert [lineno for _, lineno, _ in violations] == [3, 4]
    assert lint_rng.main(["--root", str(tmp_path)]) == 1


def test_alias_and_method_coverage(tmp_path):
    f = tmp_path / "alias.py"
    f.write_text(
        "import numpy as _np\n"
        "_np.random.shuffle([1, 2])\n"       # alias form is covered
        "x = _np.random.permutation(4)\n"
    )
    assert len(lint_rng.lint_file(str(f))) == 2


def test_pragma_allows_approved_seam(tmp_path):
    f = tmp_path / "seam.py"
    f.write_text(
        "import numpy as np\n"
        "np.random.seed(0)  # lint_rng: allow\n"
    )
    assert lint_rng.lint_file(str(f)) == []
    assert lint_rng.main(["--root", str(tmp_path)]) == 0


def test_docstrings_and_comments_do_not_false_positive(tmp_path):
    f = tmp_path / "prose.py"
    f.write_text(
        '"""Module about np.random.seed(round_idx) and np.random.choice()."""\n'
        "# the old code called np.random.seed(0) here\n"
        "MSG = 'never call np.random.shuffle(x) in library code'\n"
    )
    assert lint_rng.lint_file(str(f)) == []


def test_local_generators_are_not_flagged(tmp_path):
    f = tmp_path / "good.py"
    f.write_text(
        "import numpy as np\n"
        "rs = np.random.RandomState(3)\n"
        "rng = np.random.default_rng(3)\n"
        "x = rs.choice(10, 2, replace=False)\n"
        "y = rng.random(4)\n"
    )
    assert lint_rng.lint_file(str(f)) == []
