"""TRUE multi-process execution of the compiled FL round: two
``jax.distributed`` processes (4 virtual CPU devices each) form ONE global
8-device mesh and run the SAME XLASimulator program — psum/all_gather ride
gloo across the process boundary, exactly how a multi-host TPU pod run is
wired (``fedml_tpu.init`` does the ``jax.distributed`` bootstrap from the
FEDML_JAX_* env).  This upgrades the multi-host story from "compiles with
global semantics" (the driver dryrun) to "executes across processes with
identical results"."""

from __future__ import annotations

import os
import subprocess
import sys

import pytest

from netutil import free_port

pytestmark = pytest.mark.heavy

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.abspath(os.path.join(HERE, os.pardir))


def _spawn(rank: int, port: int) -> subprocess.Popen:
    env = {
        **{k: v for k, v in os.environ.items() if k not in ("PYTHONPATH", "XLA_FLAGS")},
        # PYTHONPATH excludes the axon sitecustomize dir: the children must
        # init the CPU backend with the forced device count
        "PYTHONPATH": REPO,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
    }
    return subprocess.Popen(
        [sys.executable, os.path.join(HERE, "multihost_child.py"), str(rank), str(port)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )


def test_two_process_round_executes_and_agrees():
    port = free_port()
    procs = [_spawn(r, port) for r in (0, 1)]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        assert p.returncode == 0, f"rank failed:\n{out}\n{err}"
        line = [l for l in out.splitlines() if l.startswith("MHOK")]
        assert line, f"no MHOK line:\n{out}\n{err}"
        outs.append(tuple(float(x) for x in line[0].split()[1:]))

    # both processes computed the identical global model (padded, packed,
    # AND the defended round whose P('client') update stack is not fully
    # addressable from either process)
    assert len(outs[0]) == 3, outs
    assert outs[0] == outs[1], outs


# Single-process oracle in its own test so a multihost failure is
# distinguishable from an oracle failure.
def test_single_process_oracle_matches_two_process():
    port = free_port()
    procs = [_spawn(r, port) for r in (0, 1)]
    mh = None
    for p in procs:
        out, err = p.communicate(timeout=420)
        assert p.returncode == 0, f"rank failed:\n{out}\n{err}"
        line = [l for l in out.splitlines() if l.startswith("MHOK")][0]
        mh = tuple(float(x) for x in line.split()[1:])

    import jax
    import numpy as np

    import fedml_tpu
    from fedml_tpu.arguments import Arguments
    from fedml_tpu.simulation.xla.fed_sim import XLASimulator

    def build(**over):
        args = Arguments.from_dict({
            "common_args": {"training_type": "simulation", "random_seed": 0,
                            "run_id": "mh-oracle"},
            "data_args": {"dataset": "mnist", "data_cache_dir": "",
                          "partition_method": "homo",
                          "synthetic_train_size": 128},
            "model_args": {"model": "lr"},
            "train_args": {"federated_optimizer": "FedAvg",
                           "client_num_in_total": 16,
                           "client_num_per_round": 16, "comm_round": 2,
                           "epochs": 1, "batch_size": 16,
                           "client_optimizer": "sgd", "learning_rate": 0.1},
            "validation_args": {"frequency_of_the_test": 0},
            "comm_args": {"backend": "XLA"},
        })
        for k, v in over.items():
            setattr(args, k, v)
        return args.validate()

    def norm(sim):
        return sum(float(np.sum(np.abs(np.asarray(l))))
                   for l in jax.tree_util.tree_leaves(sim.variables))

    args = fedml_tpu.init(build(), should_init_logs=False)
    dataset, out_dim = fedml_tpu.data.load(args)
    model = fedml_tpu.models.create(args, out_dim)
    sim = XLASimulator(args, dataset, model)  # conftest's 8 local devices
    sim.train()
    np.testing.assert_allclose(norm(sim), mh[0], rtol=1e-6)

    args2 = fedml_tpu.init(build(xla_pack=True), should_init_logs=False)
    sim2 = XLASimulator(args2, dataset, model)
    sim2.train()
    np.testing.assert_allclose(norm(sim2), mh[1], rtol=1e-6)

    # defended (stacked attack + krum) oracle: cross-process agreement alone
    # would also pass for an identically-wrong result — pin it to the
    # single-process run of the same program
    from fedml_tpu.core.security.fedml_attacker import FedMLAttacker
    from fedml_tpu.core.security.fedml_defender import FedMLDefender

    args3 = build(xla_pack=True, enable_attack=True, attack_type="byzantine",
                  attack_mode="random", byzantine_client_num=2,
                  enable_defense=True, defense_type="krum")
    FedMLAttacker._attacker_instance = None
    FedMLDefender._defender_instance = None
    args3 = fedml_tpu.init(args3, should_init_logs=False)
    try:
        sim3 = XLASimulator(args3, dataset, model)
        sim3.train()
        np.testing.assert_allclose(norm(sim3), mh[2], rtol=1e-6)
    finally:
        FedMLAttacker._attacker_instance = None
        FedMLDefender._defender_instance = None
