"""MLOps platform wire protocol against the loopback fake
(core/mlops/platform_fake.py): config fetch hands out transport credentials,
the log daemon ships chunks through the HTTP log sink, uploads land keyed by
run.  Reference: mlops_configs.py + mlops_runtime_log_daemon.py:276-346."""

import pytest

from fedml_tpu.core.mlops.mlops_configs import MLOpsConfigs, post_log_chunk
from fedml_tpu.core.mlops.platform_fake import MLOpsPlatformFake
from fedml_tpu.core.mlops.sinks import FanoutSink, HttpLogSink


@pytest.fixture
def platform():
    fake = MLOpsPlatformFake(mqtt_port=18830).start()
    yield fake
    fake.stop()


class TestConfigFetch:
    def test_fetch_all_hands_out_credentials(self, platform):
        cfg = MLOpsConfigs(platform.url).fetch_configs()
        assert cfg["mqtt_config"]["BROKER_PORT"] == 18830
        assert cfg["ml_ops_config"]["LOG_SERVER_URL"].endswith("/logs/update")
        assert platform.config_fetches == [list(MLOpsConfigs.ALL)]

    def test_fetch_subset(self, platform):
        mqtt = MLOpsConfigs(platform.url).fetch_mqtt_config()
        assert mqtt["BROKER_HOST"] == "127.0.0.1"
        assert platform.config_fetches[-1] == ["mqtt_config"]

    def test_unknown_path_fails_loud(self, platform):
        c = MLOpsConfigs(platform.url)
        with pytest.raises(Exception):
            c._post("/nope", {})


class TestLogUpload:
    def test_post_log_chunk(self, platform):
        url = MLOpsConfigs(platform.url).fetch_configs()["ml_ops_config"]["LOG_SERVER_URL"]
        post_log_chunk(url, run_id="42", rank=1, lines=["a", "b"])
        assert platform.logs_for_run("42") == ["a", "b"]
        assert platform.log_uploads[0]["edge_id"] == 1

    def test_log_daemon_ships_through_http_sink(self, platform, tmp_path):
        from fedml_tpu.core.mlops.mlops_runtime_log_daemon import MLOpsRuntimeLogDaemon

        log = tmp_path / "run.log"
        log.write_text("line-0\nline-1\nline-2\n")
        url = platform.configs["ml_ops_config"]["LOG_SERVER_URL"]
        sink = FanoutSink([HttpLogSink(url)])
        daemon = MLOpsRuntimeLogDaemon(str(log), sink=sink, run_id="7", rank=0)
        daemon.flush()
        assert platform.logs_for_run("7") == ["line-0", "line-1", "line-2"]
        # tail continues from the shipped offset
        with open(log, "a") as f:
            f.write("line-3\n")
        daemon.flush()
        assert platform.logs_for_run("7")[-1] == "line-3"

    def test_ship_failure_does_not_raise(self, tmp_path):
        sink = HttpLogSink("http://127.0.0.1:9/nope", timeout_s=0.2)
        sink.emit("log_chunk", {"run_id": "1", "rank": 0, "lines": ["x"]})
        assert sink.ship_failures == 1
