"""MLOps platform wire protocol against the loopback fake
(core/mlops/platform_fake.py): config fetch hands out transport credentials,
the log daemon ships chunks through the HTTP log sink, uploads land keyed by
run.  Reference: mlops_configs.py + mlops_runtime_log_daemon.py:276-346."""

import pytest

from fedml_tpu.core.mlops.mlops_configs import MLOpsConfigs, post_log_chunk
from fedml_tpu.core.mlops.platform_fake import MLOpsPlatformFake
from fedml_tpu.core.mlops.sinks import FanoutSink, HttpLogSink


@pytest.fixture
def platform():
    fake = MLOpsPlatformFake(mqtt_port=18830).start()
    yield fake
    fake.stop()


class TestConfigFetch:
    def test_fetch_all_hands_out_credentials(self, platform):
        cfg = MLOpsConfigs(platform.url).fetch_configs()
        assert cfg["mqtt_config"]["BROKER_PORT"] == 18830
        assert cfg["ml_ops_config"]["LOG_SERVER_URL"].endswith("/logs/update")
        assert platform.config_fetches == [list(MLOpsConfigs.ALL)]

    def test_fetch_subset(self, platform):
        mqtt = MLOpsConfigs(platform.url).fetch_mqtt_config()
        assert mqtt["BROKER_HOST"] == "127.0.0.1"
        assert platform.config_fetches[-1] == ["mqtt_config"]

    def test_unknown_path_fails_loud(self, platform):
        c = MLOpsConfigs(platform.url)
        with pytest.raises(Exception):
            c._post("/nope", {})


class TestLogUpload:
    def test_post_log_chunk(self, platform):
        url = MLOpsConfigs(platform.url).fetch_configs()["ml_ops_config"]["LOG_SERVER_URL"]
        post_log_chunk(url, run_id="42", rank=1, lines=["a", "b"])
        assert platform.logs_for_run("42") == ["a", "b"]
        assert platform.log_uploads[0]["edge_id"] == 1

    def test_log_daemon_ships_through_http_sink(self, platform, tmp_path):
        from fedml_tpu.core.mlops.mlops_runtime_log_daemon import MLOpsRuntimeLogDaemon

        log = tmp_path / "run.log"
        log.write_text("line-0\nline-1\nline-2\n")
        url = platform.configs["ml_ops_config"]["LOG_SERVER_URL"]
        sink = FanoutSink([HttpLogSink(url)])
        daemon = MLOpsRuntimeLogDaemon(str(log), sink=sink, run_id="7", rank=0)
        daemon.flush()
        assert platform.logs_for_run("7") == ["line-0", "line-1", "line-2"]
        # tail continues from the shipped offset
        with open(log, "a") as f:
            f.write("line-3\n")
        daemon.flush()
        assert platform.logs_for_run("7")[-1] == "line-3"

    def test_ship_failure_does_not_raise(self, tmp_path):
        sink = HttpLogSink("http://127.0.0.1:9/nope", timeout_s=0.2)
        sink.emit("log_chunk", {"run_id": "1", "rank": 0, "lines": ["x"]})
        assert sink.ship_failures == 1


class TestSimRegistration:
    """createSim project/run registration RPCs (reference
    core/mlops/__init__.py create_project :438 / create_run :466)."""

    def test_create_project_and_run(self, platform):
        cfg = MLOpsConfigs(platform.url)
        pid = cfg.create_project("exp-1", api_key="k")
        assert pid == 1
        rid = cfg.create_run(pid, api_key="k", edge_ids=[0, 1], run_name="r0")
        assert rid == 1
        assert platform.projects[0]["name"] == "exp-1"
        assert platform.projects[0]["platform_type"] == "simulation"
        assert platform.runs[0]["projectid"] == "1"
        assert platform.runs[0]["edgeids"] == [0, 1]
        assert platform.runs[0]["name"] == "r0"

    def test_second_project_gets_next_id(self, platform):
        cfg = MLOpsConfigs(platform.url)
        assert cfg.create_project("a") == 1
        assert cfg.create_project("b") == 2


class TestWandbSink:
    """enable_wandb must never be a silent dead flag: with wandb importable
    the sink logs metric rows; without it init() warns loudly and runs on."""

    class _Args:
        run_id = "w1"
        rank = 0
        log_file_dir = None
        enable_wandb = True

    def test_missing_wandb_warns_not_crashes(self, caplog, monkeypatch):
        import logging
        import sys

        from fedml_tpu.core import mlops

        # force the ImportError path even where wandb IS installed
        monkeypatch.setitem(sys.modules, "wandb", None)
        with caplog.at_level(logging.WARNING, "fedml_tpu.core.mlops"):
            mlops.init(self._Args())
        try:
            assert mlops.enabled()
            assert any("enable_wandb" in r.message for r in caplog.records)
        finally:
            mlops.finish()

    def test_fake_wandb_receives_metric_rows(self, monkeypatch):
        import sys
        import types

        rows = []
        fake = types.SimpleNamespace(
            run=None,
            init=lambda **kw: setattr(fake, "run", object()),
            log=lambda row: rows.append(row),
            finish=lambda: setattr(fake, "run", None),
        )
        monkeypatch.setitem(sys.modules, "wandb", fake)
        from fedml_tpu.core import mlops

        mlops.init(self._Args())
        try:
            mlops.log({"round": 1, "train_loss": 0.5})
            mlops.log_round_info(10, 1)
            mlops.event("train", event_started=False, event_value=1.25)
            assert {"round": 1, "train_loss": 0.5} in [
                {k: r[k] for k in ("round", "train_loss") if k in r} for r in rows
            ]
            assert any("round_idx" in r for r in rows)
            assert any("event/train" in r for r in rows)
        finally:
            mlops.finish()
