// fedml_edge — native edge runtime for the TPU-native FedML rebuild.
//
// Role of the reference's MobileNN C++ SDK (android/fedmlsdk/MobileNN/):
//   * FedMLBaseTrainer      (includes/train/FedMLBaseTrainer.h:13-46)
//   * dataset readers       (src/MNN/{mnist,cifar10}.cpp)
//   * LightSecAgg LCC codec (includes/security/LightSecAgg.h:11-33)
//   * FedMLClientManager    (includes/FedMLClientManager.h:6-41)
//
// The model/data interchange format is FTEM (fedml_tpu/cross_device/
// edge_model.py) — the same file the Python server writes/reads, so a native
// device and the TPU server speak one format.

#pragma once

#include <atomic>
#include <cstdint>
#include <map>
#include <memory>
#include <string>
#include <utility>
#include <vector>

namespace fedml {

// ---------------------------------------------------------------------------
// FTEM container
// ---------------------------------------------------------------------------
struct Tensor {
  std::vector<uint32_t> dims;
  int dtype = 0;  // 0 = f32, 1 = i32
  std::vector<float> f32;
  std::vector<int32_t> i32;
  size_t size() const;
};

using TensorMap = std::map<std::string, Tensor>;  // sorted: canonical order

bool ftem_read(const std::string& path, TensorMap& out, std::string& err);
bool ftem_write(const std::string& path, const TensorMap& tensors, std::string& err);

// MNIST idx pair -> FTEM {"x": [n, 784] f32 in [0,1], "y": [n] i32}
// (role of reference MobileNN/src/MNN/mnist.cpp). limit <= 0 means all.
bool mnist_idx_to_ftem(const std::string& images_path, const std::string& labels_path,
                       const std::string& out_path, int limit, std::string& err);

// CIFAR-10 binary batch (1 label byte + 3072 RGB-plane bytes per record) ->
// FTEM {"x": [n, 32, 32, 3] f32 in [0,1] NHWC, "y": [n] i32} (role of
// reference MobileNN/src/MNN/cifar10.cpp). limit <= 0 means all.
bool cifar10_bin_to_ftem(const std::string& bin_path, const std::string& out_path,
                         int limit, std::string& err);

// ---------------------------------------------------------------------------
// Trainer (reference FedMLBaseTrainer contract)
// ---------------------------------------------------------------------------
using ProgressCallback = void (*)(int epoch, double loss);

class FedMLBaseTrainer {
 public:
  virtual ~FedMLBaseTrainer() = default;

  // reference init(model_path, data_path, batch, lr, epochs)
  virtual bool init(const std::string& model_path, const std::string& data_path,
                    int batch_size, double lr, int epochs, uint64_t seed,
                    std::string& err) = 0;
  virtual bool train(std::string& err) = 0;             // full local run
  virtual bool save(const std::string& out_path, std::string& err) = 0;
  virtual bool evaluate(double* acc, double* loss, std::string& err) = 0;

  // reference getEpochAndLoss() — both fields atomic: polled cross-thread
  // while train() runs
  std::pair<int, double> epoch_and_loss() const { return {epoch_.load(), loss_.load()}; }
  // reference stopTraining()
  void stop_training() { stop_requested_ = true; }
  void set_progress_callback(ProgressCallback cb) { progress_cb_ = cb; }
  int64_t num_samples() const { return num_samples_; }

  // flatten trained params in name-sorted order (the masking order the
  // Python side uses: sorted(flat) — edge_model.py writes sorted too)
  std::vector<float> flat_params() const;
  int64_t flat_size() const;

 protected:
  std::atomic<int> epoch_{0};
  std::atomic<double> loss_{0.0};
  std::atomic<bool> stop_requested_{false};
  ProgressCallback progress_cb_ = nullptr;
  int64_t num_samples_ = 0;
  TensorMap model_;
};

// Factory: picks FedMLConvTrainer when the model has any 4-D kernel, else
// FedMLDenseTrainer.  Returns nullptr + err on a malformed model file.
FedMLBaseTrainer* create_trainer(const std::string& model_path, std::string& err);

// Dense-stack (LR / MLP) softmax-CE SGD trainer — the edge model family
// (reference MobileNN trains LeNet-class models; dense stacks are the FTEM
// models the Python hub marks edge-capable).
class FedMLDenseTrainer : public FedMLBaseTrainer {
 public:
  bool init(const std::string& model_path, const std::string& data_path,
            int batch_size, double lr, int epochs, uint64_t seed,
            std::string& err) override;
  bool train(std::string& err) override;
  bool save(const std::string& out_path, std::string& err) override;
  bool evaluate(double* acc, double* loss, std::string& err) override;

 private:
  // chained dense layers: indices into names
  std::vector<std::pair<std::string, std::string>> layers_;  // (kernel, bias)
  std::vector<float> x_;  // [n, d] row-major
  std::vector<int32_t> y_;
  int64_t dim_ = 0, classes_ = 0;
  int batch_ = 32, epochs_ = 1;
  double lr_ = 0.01;
  uint64_t seed_ = 0;
};

// LeNet-grade conv trainer (role of reference MobileNN's conv graphs,
// includes/train/FedMLBaseTrainer.h:13-46 + src/MNN/{mnist,cifar10}.cpp).
// Model convention (inferred from the FTEM tensor map, name-sorted):
//   * 4-D kernels [kh, kw, cin, cout] (flax NHWC Conv layout) + "/bias":
//     conv blocks — VALID padding, stride 1, ReLU, then 2x2 max-pool —
//     chained by cin(i+1) == cout(i);
//   * 2-D kernels: the dense head on the flattened (H*W*C row-major) conv
//     output, ReLU between layers, softmax-CE at the end.
// Data: x must be [n, H, W, C] f32, y [n] i32.
class FedMLConvTrainer : public FedMLBaseTrainer {
 public:
  bool init(const std::string& model_path, const std::string& data_path,
            int batch_size, double lr, int epochs, uint64_t seed,
            std::string& err) override;
  bool train(std::string& err) override;
  bool save(const std::string& out_path, std::string& err) override;
  bool evaluate(double* acc, double* loss, std::string& err) override;

 private:
  struct ConvLayer { std::string kernel, bias; };
  bool forward_backward(const std::vector<int64_t>& batch_rows, bool update,
                        double* loss_sum, int64_t* correct, std::string& err);
  std::vector<ConvLayer> convs_;
  std::vector<std::pair<std::string, std::string>> dense_;  // (kernel, bias)
  std::vector<float> x_;  // [n, H, W, C]
  std::vector<int32_t> y_;
  int64_t H_ = 0, W_ = 0, C_ = 0, classes_ = 0;
  int batch_ = 32, epochs_ = 1;
  double lr_ = 0.01;
  uint64_t seed_ = 0;
};

// ---------------------------------------------------------------------------
// LightSecAgg (reference includes/security/LightSecAgg.h)
// ---------------------------------------------------------------------------
namespace lsa {

constexpr int64_t kPrime = 2147483647;  // M31, matches core/mpc/field.py

int64_t mod_pow(int64_t base, int64_t exp, int64_t p = kPrime);
int64_t mod_inverse(int64_t a, int64_t p = kPrime);  // Fermat a^(p-2)

// U[t*k + j] = prod_{l!=j} (targets[t]-interp[l]) / (interp[j]-interp[l])
std::vector<int64_t> lagrange_basis_at(const std::vector<int64_t>& interp,
                                       const std::vector<int64_t>& targets,
                                       int64_t p = kPrime);

// X: [K, chunk] -> [N, chunk] evaluated at betas (alphas are 1..K interp pts)
std::vector<int64_t> lcc_encode(const std::vector<int64_t>& X, int K, int chunk,
                                const std::vector<int64_t>& alphas,
                                const std::vector<int64_t>& betas, int64_t p = kPrime);
// F: [R, chunk] known at eval_betas -> values at target_alphas
std::vector<int64_t> lcc_decode(const std::vector<int64_t>& F, int chunk,
                                const std::vector<int64_t>& eval_betas,
                                const std::vector<int64_t>& target_alphas,
                                int64_t p = kPrime);

// Returns -1 when parameters are invalid (u <= t or d <= 0) — callers must
// check; a bare division by (u - t) here would SIGFPE through the C ABI.
inline int chunk_size(int d, int t, int u) {
  int k = u - t;
  if (k <= 0 || d <= 0) return -1;
  return (d + k - 1) / k;
}

// Encode a length-d mask into n sub-masks [n, chunk]; matches
// fedml_tpu/core/mpc/lightsecagg.py mask_encoding (alphas 1..u, betas u+1..u+n).
std::vector<int64_t> mask_encoding(int d, int n, int t, int u,
                                   const std::vector<int64_t>& mask, uint64_t seed,
                                   int64_t p = kPrime);

// Server side: aggregate-encoded rows (keyed by 1-based client id) -> sum of
// masks; matches lightsecagg.py aggregate_mask_reconstruction.
std::vector<int64_t> aggregate_mask_reconstruction(
    const std::vector<std::pair<int, std::vector<int64_t>>>& agg_encoded,
    int t, int u, int d, int64_t p = kPrime);

// fixed-point quantization (reference my_q / secagg.py:19-35)
std::vector<int64_t> quantize(const std::vector<float>& x, int q_bits, int64_t p = kPrime);
std::vector<double> dequantize(const std::vector<int64_t>& z, int q_bits, int64_t p = kPrime);

}  // namespace lsa

// ---------------------------------------------------------------------------
// Client manager (reference FedMLClientManager.h:6-41): trainer + LightSecAgg
// ---------------------------------------------------------------------------
class FedMLClientManager {
 public:
  bool init(const std::string& model_path, const std::string& data_path,
            int batch_size, double lr, int epochs, uint64_t seed, std::string& err);
  bool train(std::string& err);
  bool save_model(const std::string& out_path, std::string& err);
  // LightSecAgg upload pair: masked quantized params (FTEM "masked_params"
  // i32 [D] + "num_samples") and the LCC-encoded sub-masks of the local mask.
  bool save_masked_model(int q_bits, uint64_t mask_seed, const std::string& out_path,
                         std::string& err);
  std::vector<int64_t> encode_mask(int n, int t, int u, uint64_t mask_seed,
                                   std::string& err);

  FedMLBaseTrainer& trainer() { return *trainer_; }

 private:
  std::unique_ptr<FedMLBaseTrainer> trainer_;  // dense or conv (create_trainer)
  int64_t mask_dim_ = 0;
};

}  // namespace fedml
