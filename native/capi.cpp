// C ABI for the native edge runtime (consumed from Python via ctypes —
// pybind11 is deliberately not a dependency; role of the reference JNI bridge
// android/fedmlsdk/src/main/jni/JniFedMLClientManager.cpp).

#include <cstring>
#include <string>

// the canonical C ABI header: including it here makes any drift between
// declaration (what bindings see) and definition a compile error
#include "include/fedml_capi.h"

#include "fedml_edge.hpp"

using fedml::FedMLClientManager;
using fedml::FedMLBaseTrainer;

namespace {
thread_local std::string g_last_error;
int fail(const std::string& err) {
  g_last_error = err;
  return -1;
}

// C++ exceptions must not cross the C ABI (ctypes cannot catch them — the
// process would abort). Every entry point that can allocate/throw runs
// through one of these guards.
template <typename F>
int guarded(F&& f) {
  try {
    return f();
  } catch (const std::exception& e) {
    return fail(e.what());
  } catch (...) {
    return fail("unknown native error");
  }
}

template <typename F>
void* guarded_ptr(F&& f) {
  try {
    return f();
  } catch (const std::exception& e) {
    g_last_error = e.what();
    return nullptr;
  } catch (...) {
    g_last_error = "unknown native error";
    return nullptr;
  }
}
}  // namespace

extern "C" {

const char* fedml_last_error() { return g_last_error.c_str(); }

// -- data ------------------------------------------------------------------
int fedml_mnist_idx_to_ftem(const char* images, const char* labels, const char* out,
                            int limit) {
  return guarded([&] {
    std::string err;
    return fedml::mnist_idx_to_ftem(images, labels, out, limit, err) ? 0 : fail(err);
  });
}

int fedml_cifar10_bin_to_ftem(const char* bin_path, const char* out, int limit) {
  return guarded([&] {
    std::string err;
    return fedml::cifar10_bin_to_ftem(bin_path, out, limit, err) ? 0 : fail(err);
  });
}

// -- trainer (reference FedMLBaseTrainer contract) -------------------------
void* fedml_trainer_create(const char* model_path, const char* data_path, int batch,
                           double lr, int epochs, unsigned long long seed) {
  // auto-detects dense vs conv (LeNet-grade) from the model's kernel ranks
  return guarded_ptr([&]() -> void* {
    std::string err;
    FedMLBaseTrainer* t = fedml::create_trainer(model_path, err);
    if (!t) { g_last_error = err; return nullptr; }
    if (!t->init(model_path, data_path, batch, lr, epochs, seed, err)) {
      g_last_error = err;
      delete t;
      return nullptr;
    }
    return t;
  });
}

void fedml_trainer_set_callback(void* h, fedml_progress_cb cb) {
  static_cast<FedMLBaseTrainer*>(h)->set_progress_callback(cb);
}

int fedml_trainer_train(void* h) {
  return guarded([&] {
    std::string err;
    return static_cast<FedMLBaseTrainer*>(h)->train(err) ? 0 : fail(err);
  });
}

void fedml_trainer_epoch_loss(void* h, int* epoch, double* loss) {
  auto el = static_cast<FedMLBaseTrainer*>(h)->epoch_and_loss();
  *epoch = el.first;
  *loss = el.second;
}

void fedml_trainer_stop(void* h) { static_cast<FedMLBaseTrainer*>(h)->stop_training(); }

long long fedml_trainer_num_samples(void* h) {
  return static_cast<FedMLBaseTrainer*>(h)->num_samples();
}

int fedml_trainer_save(void* h, const char* out_path) {
  return guarded([&] {
    std::string err;
    return static_cast<FedMLBaseTrainer*>(h)->save(out_path, err) ? 0 : fail(err);
  });
}

int fedml_trainer_eval(void* h, double* acc, double* loss) {
  return guarded([&] {
    std::string err;
    return static_cast<FedMLBaseTrainer*>(h)->evaluate(acc, loss, err) ? 0 : fail(err);
  });
}

void fedml_trainer_destroy(void* h) { delete static_cast<FedMLBaseTrainer*>(h); }

// -- LightSecAgg ------------------------------------------------------------
int fedml_lsa_chunk(int d, int t, int u) { return fedml::lsa::chunk_size(d, t, u); }

// out: [n * chunk] int64
int fedml_lsa_mask_encoding(int d, int n, int t, int u, const long long* mask,
                            unsigned long long seed, long long* out) {
  return guarded([&] {
    if (u <= t || n < u || d <= 0) return fail("need d > 0 and t < u <= n");
    std::vector<int64_t> m(mask, mask + d);
    auto rows = fedml::lsa::mask_encoding(d, n, t, u, m, seed);
    memcpy(out, rows.data(), rows.size() * sizeof(int64_t));
    return 0;
  });
}

// rows: [n_ids * chunk] (sorted by id), ids: 1-based; out: [d]
int fedml_lsa_aggregate_decode(const long long* rows, const int* ids, int n_ids, int t,
                               int u, int d, int chunk, long long* out) {
  return guarded([&] {
    if (n_ids < u) return fail("need >= u surviving aggregate-encoded rows");
    std::vector<std::pair<int, std::vector<int64_t>>> agg;
    for (int i = 0; i < n_ids; ++i)
      agg.emplace_back(ids[i],
                       std::vector<int64_t>(rows + (size_t)i * chunk, rows + (size_t)(i + 1) * chunk));
    auto mask = fedml::lsa::aggregate_mask_reconstruction(agg, t, u, d);
    memcpy(out, mask.data(), (size_t)d * sizeof(int64_t));
    return 0;
  });
}

// -- client manager ---------------------------------------------------------
void* fedml_client_create(const char* model_path, const char* data_path, int batch,
                          double lr, int epochs, unsigned long long seed) {
  return guarded_ptr([&]() -> void* {
    auto* c = new FedMLClientManager();
    std::string err;
    if (!c->init(model_path, data_path, batch, lr, epochs, seed, err)) {
      g_last_error = err;
      delete c;
      return nullptr;
    }
    return c;
  });
}

int fedml_client_train(void* h) {
  return guarded([&] {
    std::string err;
    return static_cast<FedMLClientManager*>(h)->train(err) ? 0 : fail(err);
  });
}

int fedml_client_save_model(void* h, const char* out_path) {
  return guarded([&] {
    std::string err;
    return static_cast<FedMLClientManager*>(h)->save_model(out_path, err) ? 0 : fail(err);
  });
}

int fedml_client_save_masked_model(void* h, int q_bits, unsigned long long mask_seed,
                                   const char* out_path) {
  return guarded([&] {
    std::string err;
    return static_cast<FedMLClientManager*>(h)->save_masked_model(q_bits, mask_seed, out_path, err)
               ? 0
               : fail(err);
  });
}

long long fedml_client_mask_dim(void* h) {
  return static_cast<FedMLClientManager*>(h)->trainer().flat_size();
}

// out: [n * chunk] int64
int fedml_client_encode_mask(void* h, int n, int t, int u, unsigned long long mask_seed,
                             long long* out) {
  return guarded([&] {
    if (u <= t || n < u) return fail("need t < u <= n");
    std::string err;
    auto rows = static_cast<FedMLClientManager*>(h)->encode_mask(n, t, u, mask_seed, err);
    if (rows.empty()) return fail(err.empty() ? "encode_mask failed" : err);
    memcpy(out, rows.data(), rows.size() * sizeof(int64_t));
    return 0;
  });
}

void fedml_client_destroy(void* h) { delete static_cast<FedMLClientManager*>(h); }

}  // extern "C"
