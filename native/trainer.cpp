// Dense-stack SGD trainer (role of reference FedMLMNNTrainer/FedMLTorchTrainer,
// android/fedmlsdk/MobileNN/src/train/): softmax-CE, per-epoch shuffling,
// progress callbacks, cooperative stopTraining.

#include <algorithm>
#include <cmath>
#include <random>

#include "fedml_edge.hpp"

namespace fedml {

static bool ends_with(const std::string& s, const std::string& suf) {
  return s.size() >= suf.size() && s.compare(s.size() - suf.size(), suf.size(), suf) == 0;
}

// order kernel/bias pairs by chaining out-dim(i) == in-dim(i+1)
// (same logic as cross_device/fake_device.py _dense_stack)
static std::vector<std::pair<std::string, std::string>> dense_stack(
    const TensorMap& m, std::string& err) {
  std::vector<std::pair<std::string, std::string>> pairs;
  for (const auto& kv : m) {
    if (ends_with(kv.first, "/kernel") && kv.second.dims.size() == 2) {
      std::string bias = kv.first.substr(0, kv.first.size() - 6) + "bias";
      if (m.count(bias)) pairs.emplace_back(kv.first, bias);
    }
  }
  if (pairs.empty()) { err = "no kernel/bias dense pairs in model"; return {}; }
  std::vector<std::pair<std::string, std::string>> ordered{pairs.front()};
  pairs.erase(pairs.begin());
  bool changed = true;
  while (!pairs.empty() && changed) {
    changed = false;
    for (auto it = pairs.begin(); it != pairs.end(); ++it) {
      uint32_t in0 = m.at(it->first).dims[0], out0 = m.at(it->first).dims[1];
      if (in0 == m.at(ordered.back().first).dims[1]) {
        ordered.push_back(*it); pairs.erase(it); changed = true; break;
      }
      if (out0 == m.at(ordered.front().first).dims[0]) {
        ordered.insert(ordered.begin(), *it); pairs.erase(it); changed = true; break;
      }
    }
  }
  for (auto& p : pairs) ordered.push_back(p);
  return ordered;
}

bool FedMLDenseTrainer::init(const std::string& model_path, const std::string& data_path,
                             int batch_size, double lr, int epochs, uint64_t seed,
                             std::string& err) {
  if (!ftem_read(model_path, model_, err)) return false;
  layers_ = dense_stack(model_, err);
  if (layers_.empty()) return false;

  TensorMap data;
  if (!ftem_read(data_path, data, err)) return false;
  auto xi = data.find("x");
  auto yi = data.find("y");
  if (xi == data.end() || yi == data.end() || xi->second.dims.size() != 2 ||
      xi->second.dtype != 0 || yi->second.dtype != 1 || yi->second.dims.size() != 1) {
    err = "data file needs x [n, d] f32 and y [n] i32";
    return false;
  }
  if (yi->second.dims[0] != xi->second.dims[0]) {
    err = "x and y row counts differ";
    return false;
  }
  x_ = xi->second.f32;
  y_ = yi->second.i32;
  num_samples_ = yi->second.dims[0];
  dim_ = xi->second.dims[1];
  if ((int64_t)x_.size() != num_samples_ * dim_ || (int64_t)y_.size() != num_samples_) {
    err = "tensor payload size mismatch";
    return false;
  }
  classes_ = model_.at(layers_.back().first).dims[1];
  if (model_.at(layers_.front().first).dims[0] != (uint32_t)dim_) {
    err = "model input dim != data dim";
    return false;
  }
  for (int64_t i = 0; i < num_samples_; ++i) {
    if (y_[i] < 0 || y_[i] >= classes_) {
      err = "label out of range [0, classes)";
      return false;
    }
  }
  batch_ = batch_size;
  lr_ = lr;
  epochs_ = epochs;
  seed_ = seed;
  return true;
}

bool FedMLDenseTrainer::train(std::string& err) {
  (void)err;
  std::mt19937_64 rng(seed_);
  const int64_t n = num_samples_;
  const int L = (int)layers_.size();
  std::vector<int64_t> order(n);
  for (int64_t i = 0; i < n; ++i) order[i] = i;

  // activations per layer for one batch (acts[0] = input)
  for (int e = 0; e < epochs_ && !stop_requested_; ++e) {
    std::shuffle(order.begin(), order.end(), rng);
    double loss_sum = 0.0;
    int64_t seen = 0;
    for (int64_t s = 0; s < n && !stop_requested_; s += batch_) {
      int64_t bs = std::min<int64_t>(batch_, n - s);
      std::vector<std::vector<double>> acts(L + 1);
      acts[0].resize(bs * dim_);
      for (int64_t i = 0; i < bs; ++i)
        for (int64_t j = 0; j < dim_; ++j)
          acts[0][i * dim_ + j] = x_[order[s + i] * dim_ + j];

      // forward
      for (int li = 0; li < L; ++li) {
        const Tensor& W = model_.at(layers_[li].first);
        const Tensor& b = model_.at(layers_[li].second);
        int64_t din = W.dims[0], dout = W.dims[1];
        acts[li + 1].assign(bs * dout, 0.0);
        for (int64_t i = 0; i < bs; ++i) {
          for (int64_t k = 0; k < din; ++k) {
            double a = acts[li][i * din + k];
            if (a == 0.0) continue;
            const float* wrow = &W.f32[k * dout];
            double* orow = &acts[li + 1][i * dout];
            for (int64_t j = 0; j < dout; ++j) orow[j] += a * wrow[j];
          }
          for (int64_t j = 0; j < dout; ++j) {
            double z = acts[li + 1][i * dout + j] + b.f32[j];
            acts[li + 1][i * dout + j] = (li < L - 1) ? std::max(z, 0.0) : z;
          }
        }
      }

      // softmax CE + grad at logits
      int64_t dout = classes_;
      std::vector<double> g(bs * dout);
      for (int64_t i = 0; i < bs; ++i) {
        double* logit = &acts[L][i * dout];
        double mx = logit[0];
        for (int64_t j = 1; j < dout; ++j) mx = std::max(mx, logit[j]);
        double sum = 0.0;
        for (int64_t j = 0; j < dout; ++j) sum += std::exp(logit[j] - mx);
        int32_t lab = y_[order[s + i]];
        loss_sum += -(logit[lab] - mx - std::log(sum));
        for (int64_t j = 0; j < dout; ++j)
          g[i * dout + j] = (std::exp(logit[j] - mx) / sum - (j == lab ? 1.0 : 0.0)) / bs;
      }
      seen += bs;

      // backward + SGD update
      for (int li = L - 1; li >= 0; --li) {
        Tensor& W = model_.at(layers_[li].first);
        Tensor& b = model_.at(layers_[li].second);
        int64_t din = W.dims[0], dcur = W.dims[1];
        std::vector<double> gprev;
        if (li > 0) {
          gprev.assign(bs * din, 0.0);
          for (int64_t i = 0; i < bs; ++i)
            for (int64_t k = 0; k < din; ++k) {
              double acc = 0.0;
              const float* wrow = &W.f32[k * dcur];
              for (int64_t j = 0; j < dcur; ++j) acc += g[i * dcur + j] * wrow[j];
              // relu mask of the input activation
              gprev[i * din + k] = acts[li][i * din + k] > 0.0 ? acc : 0.0;
            }
        }
        for (int64_t k = 0; k < din; ++k) {
          float* wrow = &W.f32[k * dcur];
          for (int64_t j = 0; j < dcur; ++j) {
            double gw = 0.0;
            for (int64_t i = 0; i < bs; ++i) gw += acts[li][i * din + k] * g[i * dcur + j];
            wrow[j] -= (float)(lr_ * gw);
          }
        }
        for (int64_t j = 0; j < dcur; ++j) {
          double gb = 0.0;
          for (int64_t i = 0; i < bs; ++i) gb += g[i * dcur + j];
          b.f32[j] -= (float)(lr_ * gb);
        }
        if (li > 0) g.swap(gprev);
      }
    }
    loss_ = seen ? loss_sum / seen : 0.0;
    epoch_ = e + 1;
    if (progress_cb_) progress_cb_(e + 1, loss_);
  }
  return true;
}

bool FedMLDenseTrainer::evaluate(double* acc, double* loss, std::string& err) {
  (void)err;
  const int L = (int)layers_.size();
  int64_t correct = 0;
  double loss_sum = 0.0;
  std::vector<double> a, nxt;
  for (int64_t i = 0; i < num_samples_; ++i) {
    a.assign(x_.begin() + i * dim_, x_.begin() + (i + 1) * dim_);
    for (int li = 0; li < L; ++li) {
      const Tensor& W = model_.at(layers_[li].first);
      const Tensor& b = model_.at(layers_[li].second);
      int64_t din = W.dims[0], dout = W.dims[1];
      nxt.assign(dout, 0.0);
      for (int64_t k = 0; k < din; ++k) {
        if (a[k] == 0.0) continue;
        const float* wrow = &W.f32[k * dout];
        for (int64_t j = 0; j < dout; ++j) nxt[j] += a[k] * wrow[j];
      }
      for (int64_t j = 0; j < dout; ++j) {
        double z = nxt[j] + b.f32[j];
        nxt[j] = (li < L - 1) ? std::max(z, 0.0) : z;
      }
      a.swap(nxt);
    }
    double mx = a[0];
    int64_t arg = 0;
    for (int64_t j = 1; j < (int64_t)a.size(); ++j)
      if (a[j] > mx) { mx = a[j]; arg = j; }
    double sum = 0.0;
    for (double z : a) sum += std::exp(z - mx);
    loss_sum += -(a[y_[i]] - mx - std::log(sum));
    if (arg == y_[i]) ++correct;
  }
  *acc = num_samples_ ? (double)correct / num_samples_ : 0.0;
  *loss = num_samples_ ? loss_sum / num_samples_ : 0.0;
  return true;
}

bool FedMLDenseTrainer::save(const std::string& out_path, std::string& err) {
  return ftem_write(out_path, model_, err);
}

std::vector<float> FedMLBaseTrainer::flat_params() const {
  std::vector<float> out;
  for (const auto& kv : model_)  // sorted-name order == Python sorted(flat)
    if (kv.second.dtype == 0)
      out.insert(out.end(), kv.second.f32.begin(), kv.second.f32.end());
  return out;
}

int64_t FedMLBaseTrainer::flat_size() const {
  int64_t n = 0;
  for (const auto& kv : model_)
    if (kv.second.dtype == 0) n += (int64_t)kv.second.f32.size();
  return n;
}

FedMLBaseTrainer* create_trainer(const std::string& model_path, std::string& err) {
  TensorMap probe;
  if (!ftem_read(model_path, probe, err)) return nullptr;
  for (const auto& kv : probe)
    if (ends_with(kv.first, "/kernel") && kv.second.dims.size() == 4)
      return new FedMLConvTrainer();
  return new FedMLDenseTrainer();
}

}  // namespace fedml
