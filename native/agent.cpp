// fedml_edge_agent — standalone on-device client process.
//
// Role of the reference Android client's native core driven by its Java
// service (android/fedmlsdk/FedMLClientManager + MobileNN trainers): a real
// DEVICE-SIDE process, separate from any Python runtime, that executes
// local training jobs.  The WAN leg (MQTT in the reference, the in-repo
// comm backends here) stays with the host bridge
// (fedml_tpu/cross_device/device_agent.py), which drives this agent through
// a directory protocol — the same split as Java-service + C++-trainer.
//
// Protocol (all under --dir):
//   inbox/job_r<k>.meta   key=value lines: model=<ftem> data=<ftem>
//                         batch=<int> lr=<float> epochs=<int> seed=<u64>
//   outbox/update_r<k>.ftem   trained model (written first)
//   outbox/update_r<k>.done   key=value: num_samples, train_acc, train_loss
//   status                heartbeat: state=idle|training round=<k> pid=<pid>
//   stop                  -> agent exits 0
//
// A job is processed once: presence of the .done marker makes restarts
// idempotent.  Malformed jobs produce update_r<k>.err instead of .done.

#include <unistd.h>

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <filesystem>
#include <fstream>
#include <map>
#include <memory>
#include <sstream>
#include <string>
#include <thread>
#include <vector>

#include "fedml_edge.hpp"

namespace fs = std::filesystem;

namespace {

std::map<std::string, std::string> read_meta(const fs::path& p) {
  std::map<std::string, std::string> kv;
  std::ifstream f(p);
  std::string line;
  while (std::getline(f, line)) {
    auto eq = line.find('=');
    if (eq == std::string::npos) continue;
    kv[line.substr(0, eq)] = line.substr(eq + 1);
  }
  return kv;
}

void write_text(const fs::path& p, const std::string& body) {
  // write-then-rename: watchers never see a partial file
  fs::path tmp = p;
  tmp += ".tmp";
  {
    std::ofstream f(tmp);
    f << body;
  }
  fs::rename(tmp, p);
}

void write_status(const fs::path& dir, const std::string& state, int round) {
  std::ostringstream ss;
  ss << "state=" << state << "\nround=" << round << "\npid=" << getpid() << "\n";
  write_text(dir / "status", ss.str());
}

// "job_r<k>.meta" -> k, or -1
int job_round(const std::string& name) {
  if (name.rfind("job_r", 0) != 0) return -1;
  auto dot = name.find(".meta");
  if (dot == std::string::npos) return -1;
  try {
    return std::stoi(name.substr(5, dot - 5));
  } catch (...) {
    return -1;
  }
}

bool process_job(const fs::path& dir, int round, const fs::path& meta_path) {
  fs::path outbox = dir / "outbox";
  char buf[64];
  std::snprintf(buf, sizeof(buf), "update_r%d", round);
  fs::path update = outbox / (std::string(buf) + ".ftem");
  fs::path done = outbox / (std::string(buf) + ".done");
  fs::path errf = outbox / (std::string(buf) + ".err");
  if (fs::exists(done) || fs::exists(errf)) return false;  // already handled

  auto kv = read_meta(meta_path);
  std::string err;
  auto fail = [&](const std::string& why) {
    write_text(errf, "error=" + why + "\n");
    std::fprintf(stderr, "job r%d failed: %s\n", round, why.c_str());
    return true;
  };
  if (!kv.count("model") || !kv.count("data")) return fail("meta missing model/data");

  int batch = kv.count("batch") ? std::stoi(kv["batch"]) : 32;
  double lr = kv.count("lr") ? std::stod(kv["lr"]) : 0.01;
  int epochs = kv.count("epochs") ? std::stoi(kv["epochs"]) : 1;
  uint64_t seed = kv.count("seed") ? std::stoull(kv["seed"]) : 0;

  std::unique_ptr<fedml::FedMLBaseTrainer> t(fedml::create_trainer(kv["model"], err));
  if (!t) return fail(err);
  if (!t->init(kv["model"], kv["data"], batch, lr, epochs, seed, err)) return fail(err);
  if (!t->train(err)) return fail(err);
  if (!t->save(update.string(), err)) return fail(err);
  double acc = 0.0, loss = 0.0;
  if (!t->evaluate(&acc, &loss, err)) return fail(err);

  std::ostringstream ss;
  ss << "num_samples=" << t->num_samples() << "\ntrain_acc=" << acc
     << "\ntrain_loss=" << loss << "\n";
  write_text(done, ss.str());  // .done written LAST: update is complete
  return true;
}

}  // namespace

int main(int argc, char** argv) {
  std::string dir;
  int poll_ms = 100;
  for (int i = 1; i < argc - 1; ++i) {
    std::string a = argv[i];
    if (a == "--dir") dir = argv[++i];
    else if (a == "--poll-ms") poll_ms = std::stoi(argv[++i]);
  }
  if (dir.empty()) {
    std::fprintf(stderr, "usage: fedml_edge_agent --dir DIR [--poll-ms N]\n");
    return 2;
  }
  fs::path root(dir);
  fs::create_directories(root / "inbox");
  fs::create_directories(root / "outbox");
  write_status(root, "idle", -1);

  while (!fs::exists(root / "stop")) {
    std::vector<std::pair<int, fs::path>> jobs;
    for (auto& e : fs::directory_iterator(root / "inbox")) {
      int r = job_round(e.path().filename().string());
      if (r >= 0) jobs.emplace_back(r, e.path());
    }
    std::sort(jobs.begin(), jobs.end());
    bool worked = false;
    for (auto& [r, p] : jobs) {
      write_status(root, "training", r);
      worked = process_job(root, r, p) || worked;
      write_status(root, "idle", r);
    }
    if (!worked) {
      write_status(root, "idle", jobs.empty() ? -1 : jobs.back().first);
      std::this_thread::sleep_for(std::chrono::milliseconds(poll_ms));
    }
  }
  fs::remove(root / "stop");
  write_status(root, "stopped", -1);
  return 0;
}
