// LightSecAgg LCC codec over the M31 prime field (role of reference
// MobileNN/src/security/LightSecAgg.cpp, includes/security/LightSecAgg.h:11-33:
// LCC encode/decode with points, Lagrange coefficient generation, modular
// inverse).  Bit-compatible with fedml_tpu/core/mpc/{field,lightsecagg}.py —
// the Python server reconstructs masks encoded by this code.

#include <cmath>
#include <random>
#include <stdexcept>

#include "fedml_edge.hpp"

namespace fedml {
namespace lsa {

int64_t mod_pow(int64_t base, int64_t exp, int64_t p) {
  base %= p;
  if (base < 0) base += p;
  int64_t result = 1;
  while (exp > 0) {
    if (exp & 1) result = (__int128)result * base % p;
    base = (__int128)base * base % p;
    exp >>= 1;
  }
  return result;
}

int64_t mod_inverse(int64_t a, int64_t p) { return mod_pow(a, p - 2, p); }

std::vector<int64_t> lagrange_basis_at(const std::vector<int64_t>& interp,
                                       const std::vector<int64_t>& targets,
                                       int64_t p) {
  size_t k = interp.size(), m = targets.size();
  std::vector<int64_t> U(m * k);
  for (size_t j = 0; j < k; ++j) {
    int64_t den = 1;
    for (size_t l = 0; l < k; ++l) {
      if (l == j) continue;
      int64_t diff = (interp[j] - interp[l]) % p;
      if (diff < 0) diff += p;
      den = (__int128)den * diff % p;
    }
    int64_t den_inv = mod_inverse(den, p);
    for (size_t t = 0; t < m; ++t) {
      int64_t num = 1;
      for (size_t l = 0; l < k; ++l) {
        if (l == j) continue;
        int64_t diff = (targets[t] - interp[l]) % p;
        if (diff < 0) diff += p;
        num = (__int128)num * diff % p;
      }
      U[t * k + j] = (__int128)num * den_inv % p;
    }
  }
  return U;
}

std::vector<int64_t> lcc_encode(const std::vector<int64_t>& X, int K, int chunk,
                                const std::vector<int64_t>& alphas,
                                const std::vector<int64_t>& betas, int64_t p) {
  auto U = lagrange_basis_at(alphas, betas, p);  // [N, K]
  int N = (int)betas.size();
  std::vector<int64_t> out((size_t)N * chunk, 0);
  for (int i = 0; i < N; ++i)
    for (int j = 0; j < K; ++j) {
      int64_t u = U[(size_t)i * K + j];
      if (!u) continue;
      for (int c = 0; c < chunk; ++c) {
        int64_t x = X[(size_t)j * chunk + c] % p;
        if (x < 0) x += p;
        out[(size_t)i * chunk + c] =
            (out[(size_t)i * chunk + c] + (__int128)u * x % p) % p;
      }
    }
  return out;
}

std::vector<int64_t> lcc_decode(const std::vector<int64_t>& F, int chunk,
                                const std::vector<int64_t>& eval_betas,
                                const std::vector<int64_t>& target_alphas,
                                int64_t p) {
  return lcc_encode(F, (int)eval_betas.size(), chunk, eval_betas, target_alphas, p);
}

std::vector<int64_t> mask_encoding(int d, int n, int t, int u,
                                   const std::vector<int64_t>& mask, uint64_t seed,
                                   int64_t p) {
  int k = u - t;
  int chunk = chunk_size(d, t, u);
  if (chunk < 0) throw std::invalid_argument("mask_encoding: need d > 0 and t < u");
  std::vector<int64_t> X((size_t)u * chunk, 0);
  for (int i = 0; i < d; ++i) {
    int64_t v = mask[i] % p;
    if (v < 0) v += p;
    X[i] = v;  // row-major [k, chunk] fill, data chunks first
  }
  std::mt19937_64 rng(seed);
  std::uniform_int_distribution<int64_t> dist(0, p - 1);
  for (size_t i = (size_t)k * chunk; i < X.size(); ++i) X[i] = dist(rng);  // t noise chunks

  std::vector<int64_t> alphas(u), betas(n);
  for (int i = 0; i < u; ++i) alphas[i] = i + 1;
  for (int i = 0; i < n; ++i) betas[i] = u + 1 + i;
  return lcc_encode(X, u, chunk, alphas, betas, p);  // [n, chunk]
}

std::vector<int64_t> aggregate_mask_reconstruction(
    const std::vector<std::pair<int, std::vector<int64_t>>>& agg_encoded,
    int t, int u, int d, int64_t p) {
  int k = u - t;
  int chunk = chunk_size(d, t, u);
  if (chunk < 0) throw std::invalid_argument("aggregate_mask_reconstruction: need d > 0 and t < u");
  // take the first u ids in sorted order (caller passes sorted), evaluate at
  // betas[id-1] = u + id
  std::vector<int64_t> eval_betas;
  std::vector<int64_t> F;
  for (int i = 0; i < u && i < (int)agg_encoded.size(); ++i) {
    eval_betas.push_back(u + agg_encoded[i].first);
    F.insert(F.end(), agg_encoded[i].second.begin(), agg_encoded[i].second.end());
  }
  std::vector<int64_t> target_alphas(k);
  for (int i = 0; i < k; ++i) target_alphas[i] = i + 1;
  auto decoded = lcc_decode(F, chunk, eval_betas, target_alphas, p);  // [k, chunk]
  decoded.resize(d);
  return decoded;
}

std::vector<int64_t> quantize(const std::vector<float>& x, int q_bits, int64_t p) {
  double scale = (double)((int64_t)1 << q_bits);
  std::vector<int64_t> out(x.size());
  for (size_t i = 0; i < x.size(); ++i) {
    int64_t q = (int64_t)std::llround((double)x[i] * scale);
    q %= p;
    if (q < 0) q += p;
    out[i] = q;
  }
  return out;
}

std::vector<double> dequantize(const std::vector<int64_t>& z, int q_bits, int64_t p) {
  double scale = (double)((int64_t)1 << q_bits);
  int64_t half = (p - 1) / 2;
  std::vector<double> out(z.size());
  for (size_t i = 0; i < z.size(); ++i) {
    int64_t v = z[i] % p;
    if (v < 0) v += p;
    out[i] = (v > half ? (double)(v - p) : (double)v) / scale;
  }
  return out;
}

}  // namespace lsa

// ---------------------------------------------------------------------------
// FedMLClientManager
// ---------------------------------------------------------------------------

bool FedMLClientManager::init(const std::string& model_path, const std::string& data_path,
                              int batch_size, double lr, int epochs, uint64_t seed,
                              std::string& err) {
  trainer_.reset(create_trainer(model_path, err));  // dense or conv
  if (!trainer_) return false;
  if (!trainer_->init(model_path, data_path, batch_size, lr, epochs, seed, err)) return false;
  mask_dim_ = trainer_->flat_size();
  return true;
}

bool FedMLClientManager::train(std::string& err) { return trainer_->train(err); }

bool FedMLClientManager::save_model(const std::string& out_path, std::string& err) {
  return trainer_->save(out_path, err);
}

static std::vector<int64_t> local_mask(int64_t dim, uint64_t mask_seed) {
  std::mt19937_64 rng(mask_seed);
  std::uniform_int_distribution<int64_t> dist(0, lsa::kPrime - 1);
  std::vector<int64_t> mask(dim);
  for (auto& m : mask) m = dist(rng);
  return mask;
}

bool FedMLClientManager::save_masked_model(int q_bits, uint64_t mask_seed,
                                           const std::string& out_path, std::string& err) {
  auto flat = trainer_->flat_params();
  auto z = lsa::quantize(flat, q_bits);
  auto mask = local_mask((int64_t)z.size(), mask_seed);
  Tensor masked;
  masked.dtype = 1;  // residues < p = 2^31 - 1 fit int32 exactly
  masked.dims = {(uint32_t)z.size()};
  masked.i32.resize(z.size());
  for (size_t i = 0; i < z.size(); ++i)
    masked.i32[i] = (int32_t)((z[i] + mask[i]) % lsa::kPrime);
  Tensor ns;
  ns.dtype = 1;
  ns.dims = {1};
  ns.i32 = {(int32_t)trainer_->num_samples()};
  TensorMap out;
  out["masked_params"] = std::move(masked);
  out["num_samples"] = std::move(ns);
  return ftem_write(out_path, out, err);
}

std::vector<int64_t> FedMLClientManager::encode_mask(int n, int t, int u,
                                                     uint64_t mask_seed, std::string& err) {
  (void)err;
  auto mask = local_mask(mask_dim_, mask_seed);
  // noise seed derived from mask seed (distinct stream)
  return lsa::mask_encoding((int)mask_dim_, n, t, u, mask, mask_seed ^ 0x9e3779b97f4a7c15ull);
}

}  // namespace fedml
