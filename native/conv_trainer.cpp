// LeNet-grade conv SGD trainer (role of reference MobileNN conv training,
// android/fedmlsdk/MobileNN/includes/train/FedMLBaseTrainer.h:13-46 and the
// mnist/cifar10 conv paths in src/MNN/): VALID-padding stride-1 convs with
// ReLU + 2x2 max-pool, a dense softmax-CE head on the flattened output,
// per-epoch shuffling, progress callbacks, cooperative stopTraining.
//
// Naive double-accumulator loops on purpose: the edge runtime optimizes for
// portability + exactness, not throughput — the TPU path is the fast path.

#include <algorithm>
#include <cmath>
#include <random>

#include "fedml_edge.hpp"

namespace fedml {

namespace {

bool ends_with_(const std::string& s, const std::string& suf) {
  return s.size() >= suf.size() && s.compare(s.size() - suf.size(), suf.size(), suf) == 0;
}

std::string bias_of(const std::string& kernel) {
  return kernel.substr(0, kernel.size() - 6) + "bias";
}

}  // namespace

bool FedMLConvTrainer::init(const std::string& model_path, const std::string& data_path,
                            int batch_size, double lr, int epochs, uint64_t seed,
                            std::string& err) {
  if (!ftem_read(model_path, model_, err)) return false;

  // collect conv (4-D kernel) and dense (2-D kernel) layers in sorted order
  std::vector<std::string> conv_k, dense_k;
  for (const auto& kv : model_) {
    if (!ends_with_(kv.first, "/kernel")) continue;
    if (!model_.count(bias_of(kv.first))) {
      err = "kernel without bias: " + kv.first;
      return false;
    }
    if (kv.second.dims.size() == 4) conv_k.push_back(kv.first);
    else if (kv.second.dims.size() == 2) dense_k.push_back(kv.first);
  }
  if (conv_k.empty()) { err = "conv trainer needs at least one 4-D kernel"; return false; }
  if (dense_k.empty()) { err = "conv trainer needs a dense head"; return false; }
  for (const auto& k : conv_k) convs_.push_back({k, bias_of(k)});
  for (const auto& k : dense_k) dense_.emplace_back(k, bias_of(k));

  // conv chain must link cin(i+1) == cout(i)
  for (size_t i = 1; i < convs_.size(); ++i) {
    if (model_.at(convs_[i].kernel).dims[2] != model_.at(convs_[i - 1].kernel).dims[3]) {
      err = "conv channel chain broken at " + convs_[i].kernel;
      return false;
    }
  }
  // dense head (name-sorted) must chain din(i+1) == dout(i) — indexing below
  // assumes it, so a broken chain must fail init, not corrupt memory
  for (size_t i = 1; i < dense_.size(); ++i) {
    if (model_.at(dense_[i].first).dims[0] != model_.at(dense_[i - 1].first).dims[1]) {
      err = "dense head chain broken at " + dense_[i].first +
            " (layers must chain in name-sorted order)";
      return false;
    }
  }

  TensorMap data;
  if (!ftem_read(data_path, data, err)) return false;
  auto xi = data.find("x");
  auto yi = data.find("y");
  if (xi == data.end() || yi == data.end() || xi->second.dims.size() != 4 ||
      xi->second.dtype != 0 || yi->second.dtype != 1 || yi->second.dims.size() != 1) {
    err = "conv data file needs x [n, H, W, C] f32 and y [n] i32";
    return false;
  }
  num_samples_ = xi->second.dims[0];
  H_ = xi->second.dims[1];
  W_ = xi->second.dims[2];
  C_ = xi->second.dims[3];
  if (yi->second.dims[0] != (uint32_t)num_samples_) { err = "x and y row counts differ"; return false; }
  if (model_.at(convs_[0].kernel).dims[2] != (uint32_t)C_) {
    err = "first conv cin != data channels";
    return false;
  }
  x_ = xi->second.f32;
  y_ = yi->second.i32;

  // validate spatial chain and dense-head input dim
  int64_t h = H_, w = W_;
  for (const auto& c : convs_) {
    const auto& d = model_.at(c.kernel).dims;
    h = (h - d[0] + 1) / 2;  // VALID conv then 2x2 pool
    w = (w - d[1] + 1) / 2;
    if (h <= 0 || w <= 0) { err = "conv chain shrinks spatial dims below 1"; return false; }
  }
  int64_t flat = h * w * model_.at(convs_.back().kernel).dims[3];
  if (model_.at(dense_.front().first).dims[0] != (uint32_t)flat) {
    err = "dense head input dim != flattened conv output (" + std::to_string(flat) + ")";
    return false;
  }
  classes_ = model_.at(dense_.back().first).dims[1];
  for (int64_t i = 0; i < num_samples_; ++i)
    if (y_[i] < 0 || y_[i] >= classes_) { err = "label out of range"; return false; }

  batch_ = batch_size;
  lr_ = lr;
  epochs_ = epochs;
  seed_ = seed;
  return true;
}

bool FedMLConvTrainer::forward_backward(const std::vector<int64_t>& rows, bool update,
                                        double* loss_sum, int64_t* correct,
                                        std::string& err) {
  (void)err;
  const int64_t bs = (int64_t)rows.size();
  const int nc = (int)convs_.size();
  const int nd = (int)dense_.size();

  // per-conv-stage buffers (index 0 = input)
  std::vector<std::vector<double>> act(nc + 1);      // pooled outputs per stage
  std::vector<std::vector<double>> pre(nc);          // pre-pool ReLU outputs
  std::vector<std::vector<int64_t>> argmax(nc);      // pool argmax flat index
  std::vector<int64_t> hs(nc + 1), ws(nc + 1), cs(nc + 1);
  hs[0] = H_; ws[0] = W_; cs[0] = C_;

  act[0].resize(bs * H_ * W_ * C_);
  for (int64_t i = 0; i < bs; ++i)
    for (int64_t j = 0; j < H_ * W_ * C_; ++j)
      act[0][i * H_ * W_ * C_ + j] = x_[rows[i] * H_ * W_ * C_ + j];

  // ---- conv forward ----
  for (int s = 0; s < nc; ++s) {
    const Tensor& K = model_.at(convs_[s].kernel);
    const Tensor& B = model_.at(convs_[s].bias);
    int64_t kh = K.dims[0], kw = K.dims[1], ci = K.dims[2], co = K.dims[3];
    int64_t oh = hs[s] - kh + 1, ow = ws[s] - kw + 1;
    int64_t ph = oh / 2, pw = ow / 2;
    hs[s + 1] = ph; ws[s + 1] = pw; cs[s + 1] = co;
    pre[s].assign(bs * oh * ow * co, 0.0);
    for (int64_t i = 0; i < bs; ++i) {
      const double* in = &act[s][i * hs[s] * ws[s] * ci];
      double* out = &pre[s][i * oh * ow * co];
      for (int64_t oy = 0; oy < oh; ++oy)
        for (int64_t ox = 0; ox < ow; ++ox)
          for (int64_t c = 0; c < co; ++c) {
            double acc = B.f32[c];
            for (int64_t ky = 0; ky < kh; ++ky)
              for (int64_t kx = 0; kx < kw; ++kx) {
                const double* irow = &in[((oy + ky) * ws[s] + (ox + kx)) * ci];
                const float* krow = &K.f32[((ky * kw + kx) * ci) * co + c];
                for (int64_t z = 0; z < ci; ++z) acc += irow[z] * krow[z * co];
              }
            out[(oy * ow + ox) * co + c] = std::max(acc, 0.0);  // ReLU
          }
    }
    // 2x2 max-pool, stride 2 (record argmax for backward)
    act[s + 1].assign(bs * ph * pw * co, 0.0);
    argmax[s].assign(bs * ph * pw * co, 0);
    for (int64_t i = 0; i < bs; ++i)
      for (int64_t py = 0; py < ph; ++py)
        for (int64_t px = 0; px < pw; ++px)
          for (int64_t c = 0; c < co; ++c) {
            double best = -1.0;
            int64_t best_idx = 0;
            for (int64_t dy = 0; dy < 2; ++dy)
              for (int64_t dx = 0; dx < 2; ++dx) {
                int64_t idx = (i * oh + (py * 2 + dy)) * ow + (px * 2 + dx);
                double v = pre[s][idx * co + c];
                if (v > best) { best = v; best_idx = idx; }
              }
            act[s + 1][((i * ph + py) * pw + px) * co + c] = best;
            argmax[s][((i * ph + py) * pw + px) * co + c] = best_idx;
          }
  }

  // ---- dense forward (on flattened act[nc]) ----
  int64_t flat = hs[nc] * ws[nc] * cs[nc];
  std::vector<std::vector<double>> dact(nd + 1);
  dact[0] = act[nc];  // already row-major [bs, flat]
  for (int li = 0; li < nd; ++li) {
    const Tensor& Wt = model_.at(dense_[li].first);
    const Tensor& bt = model_.at(dense_[li].second);
    int64_t din = Wt.dims[0], dout = Wt.dims[1];
    dact[li + 1].assign(bs * dout, 0.0);
    for (int64_t i = 0; i < bs; ++i) {
      for (int64_t k = 0; k < din; ++k) {
        double a = dact[li][i * din + k];
        if (a == 0.0) continue;
        const float* wrow = &Wt.f32[k * dout];
        double* orow = &dact[li + 1][i * dout];
        for (int64_t j = 0; j < dout; ++j) orow[j] += a * wrow[j];
      }
      for (int64_t j = 0; j < dout; ++j) {
        double z = dact[li + 1][i * dout + j] + bt.f32[j];
        dact[li + 1][i * dout + j] = (li < nd - 1) ? std::max(z, 0.0) : z;
      }
    }
  }

  // ---- softmax CE ----
  std::vector<double> g(bs * classes_);
  for (int64_t i = 0; i < bs; ++i) {
    double* logit = &dact[nd][i * classes_];
    double mx = logit[0];
    for (int64_t j = 1; j < classes_; ++j) mx = std::max(mx, logit[j]);
    double sum = 0.0;
    for (int64_t j = 0; j < classes_; ++j) sum += std::exp(logit[j] - mx);
    int32_t lab = y_[rows[i]];
    if (loss_sum) *loss_sum += -(logit[lab] - mx - std::log(sum));
    if (correct) {
      int64_t arg = 0;
      for (int64_t j = 1; j < classes_; ++j) if (logit[j] > logit[arg]) arg = j;
      if (arg == lab) ++*correct;
    }
    for (int64_t j = 0; j < classes_; ++j)
      g[i * classes_ + j] = (std::exp(logit[j] - mx) / sum - (j == lab ? 1.0 : 0.0)) / bs;
  }
  if (!update) return true;

  // ---- dense backward + SGD ----
  for (int li = nd - 1; li >= 0; --li) {
    Tensor& Wt = model_.at(dense_[li].first);
    Tensor& bt = model_.at(dense_[li].second);
    int64_t din = Wt.dims[0], dcur = Wt.dims[1];
    std::vector<double> gprev(bs * din, 0.0);
    for (int64_t i = 0; i < bs; ++i)
      for (int64_t k = 0; k < din; ++k) {
        double acc = 0.0;
        const float* wrow = &Wt.f32[k * dcur];
        for (int64_t j = 0; j < dcur; ++j) acc += g[i * dcur + j] * wrow[j];
        // ReLU mask (layer 0's input is the pooled conv output — its
        // gradient flows through the pool, masked at the conv ReLU below)
        gprev[i * din + k] = (li > 0 && dact[li][i * din + k] <= 0.0) ? 0.0 : acc;
      }
    for (int64_t k = 0; k < din; ++k) {
      float* wrow = &Wt.f32[k * dcur];
      for (int64_t j = 0; j < dcur; ++j) {
        double gw = 0.0;
        for (int64_t i = 0; i < bs; ++i) gw += dact[li][i * din + k] * g[i * dcur + j];
        wrow[j] -= (float)(lr_ * gw);
      }
    }
    for (int64_t j = 0; j < dcur; ++j) {
      double gb = 0.0;
      for (int64_t i = 0; i < bs; ++i) gb += g[i * dcur + j];
      bt.f32[j] -= (float)(lr_ * gb);
    }
    g.swap(gprev);
  }

  // ---- conv backward (g is now grad wrt flattened act[nc]) ----
  for (int s = nc - 1; s >= 0; --s) {
    Tensor& K = model_.at(convs_[s].kernel);
    Tensor& B = model_.at(convs_[s].bias);
    int64_t kh = K.dims[0], kw = K.dims[1], ci = K.dims[2], co = K.dims[3];
    int64_t oh = hs[s] - kh + 1, ow = ws[s] - kw + 1;
    int64_t ph = hs[s + 1], pw = ws[s + 1];
    // un-pool: route pooled grads to the argmax positions of pre[s]
    std::vector<double> gpre(bs * oh * ow * co, 0.0);
    for (int64_t i = 0; i < bs; ++i)
      for (int64_t py = 0; py < ph; ++py)
        for (int64_t px = 0; px < pw; ++px)
          for (int64_t c = 0; c < co; ++c) {
            int64_t pidx = ((i * ph + py) * pw + px) * co + c;
            double gv = g[pidx];
            if (gv == 0.0) continue;
            // ReLU mask on the pre-pool activation
            if (pre[s][argmax[s][pidx] * co + c] > 0.0)
              gpre[argmax[s][pidx] * co + c] += gv;
          }
    // grads wrt kernel/bias/input
    std::vector<double> gin;
    if (s > 0) gin.assign(bs * hs[s] * ws[s] * ci, 0.0);
    std::vector<double> gK(kh * kw * ci * co, 0.0), gB(co, 0.0);
    for (int64_t i = 0; i < bs; ++i) {
      const double* in = &act[s][i * hs[s] * ws[s] * ci];
      for (int64_t oy = 0; oy < oh; ++oy)
        for (int64_t ox = 0; ox < ow; ++ox)
          for (int64_t c = 0; c < co; ++c) {
            double gv = gpre[((i * oh + oy) * ow + ox) * co + c];
            if (gv == 0.0) continue;
            gB[c] += gv;
            for (int64_t ky = 0; ky < kh; ++ky)
              for (int64_t kx = 0; kx < kw; ++kx) {
                const double* irow = &in[((oy + ky) * ws[s] + (ox + kx)) * ci];
                for (int64_t z = 0; z < ci; ++z) {
                  gK[((ky * kw + kx) * ci + z) * co + c] += irow[z] * gv;
                  if (s > 0)
                    gin[(i * hs[s] * ws[s] + (oy + ky) * ws[s] + (ox + kx)) * ci + z] +=
                        K.f32[((ky * kw + kx) * ci + z) * co + c] * gv;
                }
              }
          }
    }
    for (size_t j = 0; j < gK.size(); ++j) K.f32[j] -= (float)(lr_ * gK[j]);
    for (int64_t c = 0; c < co; ++c) B.f32[c] -= (float)(lr_ * gB[c]);
    if (s > 0) g.swap(gin);
  }
  return true;
}

bool FedMLConvTrainer::train(std::string& err) {
  std::mt19937_64 rng(seed_);
  std::vector<int64_t> order(num_samples_);
  for (int64_t i = 0; i < num_samples_; ++i) order[i] = i;
  for (int e = 0; e < epochs_ && !stop_requested_; ++e) {
    std::shuffle(order.begin(), order.end(), rng);
    double loss_sum = 0.0;
    int64_t seen = 0;
    for (int64_t s = 0; s < num_samples_ && !stop_requested_; s += batch_) {
      int64_t bs = std::min<int64_t>(batch_, num_samples_ - s);
      std::vector<int64_t> rows(order.begin() + s, order.begin() + s + bs);
      if (!forward_backward(rows, /*update=*/true, &loss_sum, nullptr, err)) return false;
      seen += bs;
    }
    loss_ = seen ? loss_sum / seen : 0.0;
    epoch_ = e + 1;
    if (progress_cb_) progress_cb_(e + 1, loss_);
  }
  return true;
}

bool FedMLConvTrainer::evaluate(double* acc, double* loss, std::string& err) {
  double loss_sum = 0.0;
  int64_t correct = 0;
  for (int64_t s = 0; s < num_samples_; s += batch_) {
    int64_t bs = std::min<int64_t>(batch_, num_samples_ - s);
    std::vector<int64_t> rows(bs);
    for (int64_t i = 0; i < bs; ++i) rows[i] = s + i;
    if (!forward_backward(rows, /*update=*/false, &loss_sum, &correct, err)) return false;
  }
  *acc = num_samples_ ? (double)correct / num_samples_ : 0.0;
  *loss = num_samples_ ? loss_sum / num_samples_ : 0.0;
  return true;
}

bool FedMLConvTrainer::save(const std::string& out_path, std::string& err) {
  return ftem_write(out_path, model_, err);
}

}  // namespace fedml
