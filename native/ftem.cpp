// FTEM container I/O + MNIST idx reader (roles of the reference's MNN model
// file handling and MobileNN/src/MNN/mnist.cpp).

#include <cstdio>
#include <cstring>

#include "fedml_edge.hpp"

namespace fedml {

static const char kMagic[4] = {'F', 'T', 'E', 'M'};
static const uint32_t kVersion = 1;

size_t Tensor::size() const {
  size_t n = 1;
  for (auto d : dims) n *= d;
  return n;
}

static bool read_exact(FILE* f, void* buf, size_t n) { return fread(buf, 1, n, f) == n; }

bool ftem_read(const std::string& path, TensorMap& out, std::string& err) {
  FILE* f = fopen(path.c_str(), "rb");
  if (!f) { err = "cannot open " + path; return false; }
  char magic[4];
  uint32_t version = 0, count = 0;
  if (!read_exact(f, magic, 4) || memcmp(magic, kMagic, 4) != 0) {
    err = path + ": not an FTEM file"; fclose(f); return false;
  }
  if (!read_exact(f, &version, 4) || version != kVersion ||
      !read_exact(f, &count, 4)) {
    err = path + ": bad FTEM header"; fclose(f); return false;
  }
  for (uint32_t i = 0; i < count; ++i) {
    uint32_t name_len = 0;
    if (!read_exact(f, &name_len, 4) || name_len > 4096) { err = "bad name"; fclose(f); return false; }
    std::string name(name_len, '\0');
    uint8_t dtype = 0;
    uint32_t ndim = 0;
    if (!read_exact(f, name.data(), name_len) || !read_exact(f, &dtype, 1) ||
        !read_exact(f, &ndim, 4) || ndim > 16) {
      err = "bad tensor header"; fclose(f); return false;
    }
    Tensor t;
    t.dtype = dtype;
    t.dims.resize(ndim);
    if (ndim && !read_exact(f, t.dims.data(), 4 * ndim)) { err = "bad dims"; fclose(f); return false; }
    size_t n = t.size();
    if (n > (size_t(1) << 31)) {  // corrupt header — don't attempt the alloc
      err = path + ": tensor size implausibly large"; fclose(f); return false;
    }
    bool ok;
    if (dtype == 0) { t.f32.resize(n); ok = !n || read_exact(f, t.f32.data(), 4 * n); }
    else if (dtype == 1) { t.i32.resize(n); ok = !n || read_exact(f, t.i32.data(), 4 * n); }
    else { err = "unknown dtype"; fclose(f); return false; }
    if (!ok) { err = "truncated tensor data"; fclose(f); return false; }
    out[name] = std::move(t);
  }
  fclose(f);
  return true;
}

bool ftem_write(const std::string& path, const TensorMap& tensors, std::string& err) {
  std::string tmp = path + ".tmp";
  FILE* f = fopen(tmp.c_str(), "wb");
  if (!f) { err = "cannot open " + tmp; return false; }
  uint32_t count = (uint32_t)tensors.size();
  fwrite(kMagic, 1, 4, f);
  fwrite(&kVersion, 4, 1, f);
  fwrite(&count, 4, 1, f);
  for (const auto& kv : tensors) {  // std::map iterates sorted — canonical
    uint32_t name_len = (uint32_t)kv.first.size();
    uint8_t dtype = (uint8_t)kv.second.dtype;
    uint32_t ndim = (uint32_t)kv.second.dims.size();
    fwrite(&name_len, 4, 1, f);
    fwrite(kv.first.data(), 1, name_len, f);
    fwrite(&dtype, 1, 1, f);
    fwrite(&ndim, 4, 1, f);
    if (ndim) fwrite(kv.second.dims.data(), 4, ndim, f);
    if (dtype == 0) fwrite(kv.second.f32.data(), 4, kv.second.f32.size(), f);
    else fwrite(kv.second.i32.data(), 4, kv.second.i32.size(), f);
  }
  if (fclose(f) != 0) { err = "write failed"; return false; }
  if (rename(tmp.c_str(), path.c_str()) != 0) { err = "rename failed"; return false; }
  return true;
}

// -- MNIST idx --------------------------------------------------------------

static uint32_t be32(const unsigned char* b) {
  return ((uint32_t)b[0] << 24) | ((uint32_t)b[1] << 16) | ((uint32_t)b[2] << 8) | b[3];
}

bool mnist_idx_to_ftem(const std::string& images_path, const std::string& labels_path,
                       const std::string& out_path, int limit, std::string& err) {
  FILE* fi = fopen(images_path.c_str(), "rb");
  if (!fi) { err = "cannot open " + images_path; return false; }
  FILE* fl = fopen(labels_path.c_str(), "rb");
  if (!fl) { err = "cannot open " + labels_path; fclose(fi); return false; }

  unsigned char ih[16], lh[8];
  if (!read_exact(fi, ih, 16) || be32(ih) != 0x803 ||
      !read_exact(fl, lh, 8) || be32(lh) != 0x801) {
    err = "bad idx magic"; fclose(fi); fclose(fl); return false;
  }
  uint32_t n = be32(ih + 4), rows = be32(ih + 8), cols = be32(ih + 12);
  uint32_t nl = be32(lh + 4);
  if (nl < n) n = nl;
  if (limit > 0 && (uint32_t)limit < n) n = (uint32_t)limit;
  size_t d = (size_t)rows * cols;

  Tensor x, y;
  x.dtype = 0; x.dims = {n, (uint32_t)d}; x.f32.resize((size_t)n * d);
  y.dtype = 1; y.dims = {n}; y.i32.resize(n);
  std::vector<unsigned char> row(d);
  for (uint32_t i = 0; i < n; ++i) {
    if (!read_exact(fi, row.data(), d)) { err = "truncated images"; fclose(fi); fclose(fl); return false; }
    for (size_t j = 0; j < d; ++j) x.f32[(size_t)i * d + j] = row[j] / 255.0f;
    unsigned char lab;
    if (!read_exact(fl, &lab, 1)) { err = "truncated labels"; fclose(fi); fclose(fl); return false; }
    y.i32[i] = lab;
  }
  fclose(fi); fclose(fl);
  TensorMap out;
  out["x"] = std::move(x);
  out["y"] = std::move(y);
  return ftem_write(out_path, out, err);
}

// -- CIFAR-10 binary --------------------------------------------------------
// data_batch_N.bin record layout: 1 label byte, then 3072 bytes as three
// 1024-byte color planes (R, G, B) of a 32x32 image, row-major.  Output is
// NHWC [n, 32, 32, 3] f32 in [0,1] — the layout the conv trainer and the
// flax models consume.

bool cifar10_bin_to_ftem(const std::string& bin_path, const std::string& out_path,
                         int limit, std::string& err) {
  constexpr uint32_t kHW = 32, kPlane = kHW * kHW, kRec = 1 + 3 * kPlane;
  FILE* f = fopen(bin_path.c_str(), "rb");
  if (!f) { err = "cannot open " + bin_path; return false; }
  fseek(f, 0, SEEK_END);
  long sz = ftell(f);
  fseek(f, 0, SEEK_SET);
  if (sz <= 0 || sz % kRec != 0) {
    err = "not a CIFAR-10 binary batch (size % 3073 != 0)";
    fclose(f);
    return false;
  }
  uint32_t n = (uint32_t)(sz / kRec);
  if (limit > 0 && (uint32_t)limit < n) n = (uint32_t)limit;

  Tensor x, y;
  x.dtype = 0; x.dims = {n, kHW, kHW, 3}; x.f32.resize((size_t)n * kPlane * 3);
  y.dtype = 1; y.dims = {n}; y.i32.resize(n);
  std::vector<unsigned char> rec(kRec);
  for (uint32_t i = 0; i < n; ++i) {
    if (!read_exact(f, rec.data(), kRec)) { err = "truncated batch"; fclose(f); return false; }
    y.i32[i] = rec[0];
    for (uint32_t p = 0; p < kPlane; ++p)
      for (uint32_t c = 0; c < 3; ++c)  // planes -> interleaved NHWC
        x.f32[((size_t)i * kPlane + p) * 3 + c] = rec[1 + c * kPlane + p] / 255.0f;
  }
  fclose(f);
  TensorMap out;
  out["x"] = std::move(x);
  out["y"] = std::move(y);
  return ftem_write(out_path, out, err);
}

}  // namespace fedml
