// JNI bridge for the native edge runtime — the Android integration surface.
//
// Role of the reference's android/fedmlsdk/src/main/jni/OnLoad.cpp +
// JniFedMLClientManager.cpp: expose the C++ trainer/client-manager to a
// Java/Kotlin service.  This shim is a thin adapter over the stable C ABI
// (../capi.cpp) — every entry point maps 1:1 onto a fedml_* function, so
// the Java layer, the ctypes layer, and any other host binding share one
// runtime surface.
//
// Java side (package ai.fedml.tpu):
//
//   public final class NativeFedMLTrainer {
//     static { System.loadLibrary("fedml_jni"); }
//     public static native long create(String modelPath, String dataPath,
//                                      int batch, double lr, int epochs, long seed);
//     public static native int train(long handle);
//     public static native int save(long handle, String outPath);
//     public static native long[] evaluate(long handle);  // [acc*1e6, loss*1e6], -1 on error
//     public static native long[] epochLoss(long handle);    // [epoch, loss*1e6]
//     public static native long numSamples(long handle);
//     public static native void stop(long handle);
//     public static native void destroy(long handle);
//     public static native String lastError();
//     // LightSecAgg leg (secure aggregation on-device):
//     public static native long clientCreate(String modelPath, String dataPath,
//                                            int batch, double lr, int epochs, long seed);
//     public static native int clientTrain(long handle);
//     public static native int clientSaveMasked(long handle, int qBits,
//                                               long maskSeed, String outPath);
//     public static native long clientMaskDim(long handle);
//     public static native long[] clientEncodeMask(long handle, int n, int t,
//                                                  int u, long maskSeed);
//     public static native void clientDestroy(long handle);
//   }
//
// Build: cmake with the Android toolchain (see CMakeLists.txt next to this
// file); host CI compile-checks against ../android/jni_stub/jni.h (same
// declarations as the NDK header — `make -C native jni_check`).

#include <jni.h>

#include <cstddef>
#include <cstdint>
#include <vector>

// the C ABI from capi.cpp (kept extern "C" so the .so exports one runtime)
extern "C" {
const char* fedml_last_error();
void* fedml_trainer_create(const char*, const char*, int, double, int,
                           unsigned long long);
int fedml_trainer_train(void*);
void fedml_trainer_epoch_loss(void*, int*, double*);
void fedml_trainer_stop(void*);
long long fedml_trainer_num_samples(void*);
int fedml_trainer_save(void*, const char*);
int fedml_trainer_eval(void*, double*, double*);
void fedml_trainer_destroy(void*);
void* fedml_client_create(const char*, const char*, int, double, int,
                          unsigned long long);
int fedml_client_train(void*);
int fedml_client_save_masked_model(void*, int, unsigned long long, const char*);
long long fedml_client_mask_dim(void*);
int fedml_client_encode_mask(void*, int, int, int, unsigned long long, long long*);
void fedml_client_destroy(void*);
int fedml_lsa_chunk(int, int, int);
}

namespace {

// RAII UTF-8 view of a jstring
class Utf {
 public:
  Utf(JNIEnv* env, jstring s) : env_(env), s_(s), c_(nullptr) {
    if (s_ != nullptr) c_ = env_->GetStringUTFChars(s_, nullptr);
  }
  ~Utf() {
    if (c_ != nullptr) env_->ReleaseStringUTFChars(s_, c_);
  }
  const char* get() const { return c_ != nullptr ? c_ : ""; }

 private:
  JNIEnv* env_;
  jstring s_;
  const char* c_;
};

}  // namespace

extern "C" {

JNIEXPORT jlong JNICALL Java_ai_fedml_tpu_NativeFedMLTrainer_create(
    JNIEnv* env, jclass, jstring model, jstring data, jint batch, jdouble lr,
    jint epochs, jlong seed) {
  Utf m(env, model), d(env, data);
  return reinterpret_cast<jlong>(fedml_trainer_create(
      m.get(), d.get(), batch, lr, epochs,
      static_cast<unsigned long long>(seed)));
}

JNIEXPORT jint JNICALL Java_ai_fedml_tpu_NativeFedMLTrainer_train(
    JNIEnv*, jclass, jlong h) {
  return fedml_trainer_train(reinterpret_cast<void*>(h));
}

JNIEXPORT jint JNICALL Java_ai_fedml_tpu_NativeFedMLTrainer_save(
    JNIEnv* env, jclass, jlong h, jstring out) {
  Utf o(env, out);
  return fedml_trainer_save(reinterpret_cast<void*>(h), o.get());
}

JNIEXPORT jlongArray JNICALL Java_ai_fedml_tpu_NativeFedMLTrainer_epochLoss(
    JNIEnv* env, jclass, jlong h) {
  int epoch = 0;
  double loss = 0.0;
  fedml_trainer_epoch_loss(reinterpret_cast<void*>(h), &epoch, &loss);
  jlong out[2] = {epoch, static_cast<jlong>(loss * 1e6)};
  jlongArray arr = env->NewLongArray(2);
  env->SetLongArrayRegion(arr, 0, 2, out);
  return arr;
}

JNIEXPORT jlong JNICALL Java_ai_fedml_tpu_NativeFedMLTrainer_numSamples(
    JNIEnv*, jclass, jlong h) {
  return fedml_trainer_num_samples(reinterpret_cast<void*>(h));
}

JNIEXPORT void JNICALL Java_ai_fedml_tpu_NativeFedMLTrainer_stop(
    JNIEnv*, jclass, jlong h) {
  fedml_trainer_stop(reinterpret_cast<void*>(h));
}

JNIEXPORT void JNICALL Java_ai_fedml_tpu_NativeFedMLTrainer_destroy(
    JNIEnv*, jclass, jlong h) {
  fedml_trainer_destroy(reinterpret_cast<void*>(h));
}

JNIEXPORT jstring JNICALL Java_ai_fedml_tpu_NativeFedMLTrainer_lastError(
    JNIEnv* env, jclass) {
  return env->NewStringUTF(fedml_last_error());
}

// evaluate -> long[2] of fixed-point (acc*1e6, loss*1e6); -1 marker on error
JNIEXPORT jlongArray JNICALL Java_ai_fedml_tpu_NativeFedMLTrainer_evaluate(
    JNIEnv* env, jclass, jlong h) {
  double acc = 0.0, loss = 0.0;
  int rc = fedml_trainer_eval(reinterpret_cast<void*>(h), &acc, &loss);
  jlong out[2] = {rc == 0 ? static_cast<jlong>(acc * 1e6) : -1,
                  rc == 0 ? static_cast<jlong>(loss * 1e6) : -1};
  jlongArray arr = env->NewLongArray(2);
  env->SetLongArrayRegion(arr, 0, 2, out);
  return arr;
}

// -- client manager (LightSecAgg leg) ---------------------------------------
JNIEXPORT jlong JNICALL Java_ai_fedml_tpu_NativeFedMLTrainer_clientCreate(
    JNIEnv* env, jclass, jstring model, jstring data, jint batch, jdouble lr,
    jint epochs, jlong seed) {
  Utf m(env, model), d(env, data);
  return reinterpret_cast<jlong>(fedml_client_create(
      m.get(), d.get(), batch, lr, epochs,
      static_cast<unsigned long long>(seed)));
}

JNIEXPORT jint JNICALL Java_ai_fedml_tpu_NativeFedMLTrainer_clientTrain(
    JNIEnv*, jclass, jlong h) {
  return fedml_client_train(reinterpret_cast<void*>(h));
}

JNIEXPORT jint JNICALL Java_ai_fedml_tpu_NativeFedMLTrainer_clientSaveMasked(
    JNIEnv* env, jclass, jlong h, jint q_bits, jlong mask_seed, jstring out) {
  Utf o(env, out);
  return fedml_client_save_masked_model(
      reinterpret_cast<void*>(h), q_bits,
      static_cast<unsigned long long>(mask_seed), o.get());
}

JNIEXPORT jlong JNICALL Java_ai_fedml_tpu_NativeFedMLTrainer_clientMaskDim(
    JNIEnv*, jclass, jlong h) {
  return fedml_client_mask_dim(reinterpret_cast<void*>(h));
}

JNIEXPORT jlongArray JNICALL Java_ai_fedml_tpu_NativeFedMLTrainer_clientEncodeMask(
    JNIEnv* env, jclass, jlong h, jint n, jint t, jint u, jlong mask_seed) {
  const int d = static_cast<int>(fedml_client_mask_dim(reinterpret_cast<void*>(h)));
  const int chunk = fedml_lsa_chunk(d, t, u);
  std::vector<long long> rows(static_cast<size_t>(n) * chunk);
  int rc = fedml_client_encode_mask(reinterpret_cast<void*>(h), n, t, u,
                                    static_cast<unsigned long long>(mask_seed),
                                    rows.data());
  if (rc != 0) return env->NewLongArray(0);
  jlongArray arr = env->NewLongArray(static_cast<jsize>(rows.size()));
  env->SetLongArrayRegion(arr, 0, static_cast<jsize>(rows.size()),
                          reinterpret_cast<const jlong*>(rows.data()));
  return arr;
}

JNIEXPORT void JNICALL Java_ai_fedml_tpu_NativeFedMLTrainer_clientDestroy(
    JNIEnv*, jclass, jlong h) {
  fedml_client_destroy(reinterpret_cast<void*>(h));
}

JNIEXPORT jint JNICALL JNI_OnLoad(JavaVM*, void*) { return JNI_VERSION_1_6; }

}  // extern "C"
