// Host-compile stub of the JNI ABI (subset used by fedml_jni.cpp).
//
// This image has no JDK/NDK, so CI compile-checks the JNI shim against this
// header; the declarations mirror the real <jni.h> C++ surface exactly
// (same names, same member-function signatures), so the identical
// fedml_jni.cpp builds unmodified against the Android NDK's jni.h — this
// stub never ships to a device.  Member functions are declarations only:
// the shim links as a shared object (undefined symbols are resolved by the
// JVM at load time on-device; the host check builds with -shared, where
// undefined symbols are permitted).
#ifndef FEDML_JNI_STUB_H_
#define FEDML_JNI_STUB_H_

#include <cstdint>

typedef int32_t jint;
typedef int64_t jlong;
typedef int8_t jbyte;
typedef uint8_t jboolean;
typedef uint16_t jchar;
typedef int16_t jshort;
typedef float jfloat;
typedef double jdouble;
typedef jint jsize;

class _jobject {};
class _jclass : public _jobject {};
class _jstring : public _jobject {};
class _jarray : public _jobject {};
class _jlongArray : public _jarray {};
class _jintArray : public _jarray {};

typedef _jobject* jobject;
typedef _jclass* jclass;
typedef _jstring* jstring;
typedef _jarray* jarray;
typedef _jlongArray* jlongArray;
typedef _jintArray* jintArray;

#define JNI_FALSE 0
#define JNI_TRUE 1
#define JNI_VERSION_1_6 0x00010006
#define JNI_OK 0

#define JNIEXPORT __attribute__((visibility("default")))
#define JNIIMPORT
#define JNICALL

struct JNIEnv {
  const char* GetStringUTFChars(jstring str, jboolean* isCopy);
  void ReleaseStringUTFChars(jstring str, const char* chars);
  jstring NewStringUTF(const char* utf);
  jsize GetArrayLength(jarray array);
  jlong* GetLongArrayElements(jlongArray array, jboolean* isCopy);
  void ReleaseLongArrayElements(jlongArray array, jlong* elems, jint mode);
  jint* GetIntArrayElements(jintArray array, jboolean* isCopy);
  void ReleaseIntArrayElements(jintArray array, jint* elems, jint mode);
  jlongArray NewLongArray(jsize length);
  void SetLongArrayRegion(jlongArray array, jsize start, jsize len, const jlong* buf);
  jint ThrowNew(jclass clazz, const char* message);
  jclass FindClass(const char* name);
};

struct JavaVM {
  jint GetEnv(void** env, jint version);
};

#endif  // FEDML_JNI_STUB_H_
